"""Benchmark: training tokens/sec/chip on the flagship model family.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: decoder-LM training throughput (tokens/sec/chip) in bf16 with the
fused train step. ``vs_baseline`` reports achieved MFU relative to the
reference's published 54%-of-peak Ulysses number
(`blogs/deepspeed-ulysses/README.md:81-83` — the only hardware-normalized
efficiency figure the reference publishes), i.e. vs_baseline = MFU / 0.54.
"""

import json
import os
import sys
import time


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, TransformerLM

    # ~124M-param GPT-2-small-shaped llama-style model, seq 1024 — big enough
    # to saturate the MXU on one chip, small enough to fit v5e HBM with Adam.
    if on_tpu:
        cfg = TransformerConfig(vocab_size=32000, hidden_size=768, num_layers=12, num_heads=12,
                                intermediate_size=3072, max_seq_len=1024, norm="rmsnorm", positions="rotary",
                                mlp="swiglu", dtype=jnp.bfloat16, attention_impl="reference", remat=True)
        micro, seq, steps, warmup = 8, 1024, 10, 3
    else:  # CI / CPU smoke mode
        cfg = TransformerConfig(vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
                                intermediate_size=256, max_seq_len=256, dtype=jnp.float32,
                                attention_impl="reference")
        micro, seq, steps, warmup = 2, 256, 3, 1

    model = TransformerLM(cfg)
    n_chips = len(jax.devices())
    config = {
        "train_batch_size": micro * n_chips,
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4, "weight_decay": 0.0}},
        "zero_optimization": {"stage": 1 if n_chips > 1 else 0},
        "bf16": {"enabled": bool(on_tpu)},
        "steps_per_print": 10**9,
        "tpu": {"mesh": {"data": n_chips}},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)

    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, size=(config["train_batch_size"], seq), dtype=np.int32)}

    def _sync():
        # a host fetch is the only reliable barrier on tunneled runtimes
        return float(np.asarray(engine.state["step"]))

    for _ in range(warmup):
        engine.train_batch(batch)
    _sync()
    t0 = time.time()
    for _ in range(steps):
        engine.train_batch(batch)
    _sync()
    dt = time.time() - t0

    tokens = steps * config["train_batch_size"] * seq
    tok_per_sec_per_chip = tokens / dt / n_chips

    n_params = model.num_params()
    # fwd+bwd ≈ 6 FLOPs/param/token + attention term
    attn_flops_per_token = 12 * cfg.num_layers * cfg.hidden_size * seq  # 2*2*3 * L * H * S
    flops_per_token = 6 * n_params + attn_flops_per_token
    peak = 197e12 if on_tpu else 1e12  # v5e bf16 peak
    mfu = tok_per_sec_per_chip * flops_per_token / peak
    print(json.dumps({
        "metric": "train_tokens_per_sec_per_chip",
        "value": round(tok_per_sec_per_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.54, 4),
    }))


if __name__ == "__main__":
    main()
