"""Benchmark: training tokens/sec/chip on the flagship model family.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: decoder-LM training throughput (tokens/sec/chip) in bf16 with the
fused train step on a Llama-2-architecture model (rmsnorm/rotary/swiglu —
the BASELINE.md target workload) at the largest configuration that fits one
v5e chip's HBM with ZeRO-3 + Adam. ``vs_baseline`` reports achieved MFU
relative to the reference's published 54%-of-peak Ulysses number
(`blogs/deepspeed-ulysses/README.md:81-83` — the only hardware-normalized
efficiency figure the reference publishes), i.e. vs_baseline = MFU / 0.54.

Attention runs the Pallas flash kernel (fwd+bwd); the remat policy saves the
attention context (`save_only_these_names(attn_out)`) so the backward never
recomputes the flash kernel; gradient accumulation amortizes the
HBM-bandwidth-bound Adam step over 16 microbatches.
"""

import json
import time


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, TransformerLM

    if on_tpu:
        # 748M-param Llama-arch model: h=2048 x 12 layers, seq 2048 — the
        # largest clean shape that fits v5e HBM (16G) with fp32 Adam states
        # and an f32 grad accumulator.
        cfg = TransformerConfig(vocab_size=32000, hidden_size=2048, num_layers=12,
                                num_heads=16, num_kv_heads=16, intermediate_size=5632,
                                max_seq_len=2048, norm="rmsnorm", positions="rotary",
                                mlp="swiglu", dtype=jnp.bfloat16, attention_impl="flash",
                                remat=True, remat_policy="save_only_these_names(attn_out)")
        micro, gas, seq, steps, warmup = 2, 16, 2048, 8, 3
    else:  # CI / CPU smoke mode
        cfg = TransformerConfig(vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
                                intermediate_size=256, max_seq_len=256, dtype=jnp.float32,
                                attention_impl="reference")
        micro, gas, seq, steps, warmup = 2, 1, 256, 3, 1

    model = TransformerLM(cfg)
    n_chips = len(jax.devices())
    config = {
        "train_batch_size": micro * gas * n_chips,
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4, "weight_decay": 0.0}},
        "zero_optimization": {"stage": 3 if on_tpu else 0},
        "bf16": {"enabled": bool(on_tpu)},
        "steps_per_print": 10**9,
        "tpu": {"mesh": {"data": n_chips}},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)

    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, size=(config["train_batch_size"], seq), dtype=np.int32)}

    def _sync():
        # a host fetch is the only reliable barrier on tunneled runtimes
        return float(np.asarray(engine.state["step"]))

    for _ in range(warmup):
        engine.train_batch(batch)
    _sync()
    t0 = time.time()
    for _ in range(steps):
        engine.train_batch(batch)
    _sync()
    dt = time.time() - t0

    tokens = steps * config["train_batch_size"] * seq
    tok_per_sec_per_chip = tokens / dt / n_chips

    n_params = model.num_params()
    # fwd+bwd ≈ 6 FLOPs/param/token + attention term (PaLM MFU convention)
    attn_flops_per_token = 12 * cfg.num_layers * cfg.hidden_size * seq
    flops_per_token = 6 * n_params + attn_flops_per_token
    peak = 197e12 if on_tpu else 1e12  # v5e bf16 peak
    mfu = tok_per_sec_per_chip * flops_per_token / peak
    print(json.dumps({
        "metric": "train_tokens_per_sec_per_chip",
        "value": round(tok_per_sec_per_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.54, 4),
    }))


if __name__ == "__main__":
    main()
