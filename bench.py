"""Benchmark: training tokens/sec/chip + FastGen-style serving on the
flagship model family.

Prints TWO JSON lines; the LAST is the headline training metric (tracked
round-over-round by the driver), the first is the serving plane:

  {"metric": "fastgen_decode_tokens_per_sec_per_chip", ..., "ttft_p50_ms": ...}
  {"metric": "train_tokens_per_sec_per_chip", ..., "serving": {...}}

Training metric: decoder-LM training throughput (tokens/sec/chip) in bf16
with the fused train step on a Llama-2-architecture model (rmsnorm/rotary/
swiglu — the BASELINE.md target workload) at the largest configuration that
fits one v5e chip's HBM with ZeRO-3 + Adam. ``vs_baseline`` reports achieved
MFU relative to the reference's published 54%-of-peak Ulysses number
(`blogs/deepspeed-ulysses/README.md:81-83` — the only hardware-normalized
efficiency figure the reference publishes), i.e. vs_baseline = MFU / 0.54.

Serving metric (reference methodology `blogs/deepspeed-fastgen/README.md:139-144`:
p50 TTFT + steady-state generation throughput under continuous batching):
InferenceEngineV2.put drives prefill (whole prompt) then batched decode (one
token per tracked sequence per step) through the paged-KV ragged plane.
``vs_baseline`` for serving is achieved decode throughput over the single-chip
HBM roofline (decode is bandwidth-bound: every step re-reads the bf16 params
and each sequence's KV) — a hardware-normalized efficiency comparable across
rounds, with the absolute A100 bar unavailable on one v5e chip.

Attention runs the Pallas flash kernel (fwd+bwd); the remat policy saves the
attention context (`save_only_these_names(attn_out)`) so the backward never
recomputes the flash kernel; gradient accumulation amortizes the
HBM-bandwidth-bound Adam step over 16 microbatches.

Process layout (round-3 lesson: `BENCH_r03.json` died rc=1 on an unguarded
``jax.devices()`` when the TPU plugin failed to initialize, forfeiting the
round's perf evidence): ``python bench.py`` runs a SUPERVISOR that never
imports jax itself. It probes the backend in a subprocess with bounded
retries, runs the real bench in a child process with a timeout, falls back
to ``JAX_PLATFORMS=cpu`` with an explicit ``"on_tpu": false`` disclosure if
the TPU is truly unreachable, and — even if every child dies — emits a
parseable final JSON line and exits 0.
"""

import gc
import json
import os
import subprocess
import sys
import time


def backend_stamp(on_tpu: bool) -> dict:
    """``{'backend': 'tpu'|'cpu', 'chip': <device_kind>}`` — stamped into
    every final JSON line so round-over-round tooling can tell a CPU-fallback
    number from an on-chip one WITHOUT reading prose caveats (the
    BENCH_r04/r05 lesson: r04/r05 ran CPU-only and their headline values are
    not comparable to the r01-r02 on-chip rounds)."""
    chip = "cpu"
    if on_tpu:
        try:
            import jax

            chip = str(jax.devices()[0].device_kind)
        except Exception:
            chip = "tpu-unknown"
    return {"backend": "tpu" if on_tpu else "cpu", "chip": chip}


def backend_of(line: dict):
    """Backend stamp of a bench JSON line: explicit ``backend`` wins, the
    pre-r06 ``on_tpu`` field is the fallback, neither -> None."""
    b = line.get("backend")
    if b is None and "on_tpu" in line:
        b = "tpu" if line.get("on_tpu") else "cpu"
    return b


def comparability_refusal(base: dict, cur: dict):
    """Why a base-vs-cur ratio would be MEANINGLESS (None = comparable):
    missing backend stamps, cross-backend, or cross-chip. The shared
    refusal core of :func:`compare_to_baseline` and
    ``tools/perf_sentinel.py``'s round-trajectory verdicts — the r04/r05
    lesson (CPU-fallback rounds silently ratioed against on-chip rounds)
    machine-checked in one place."""
    b_backend = backend_of(base)
    c_backend = backend_of(cur)
    if b_backend is None:
        return "baseline carries no backend stamp (pre-r06 format without on_tpu)"
    if b_backend != c_backend:
        return f"cross-backend comparison: baseline={b_backend} current={c_backend}"
    if base.get("chip") and cur.get("chip") and base["chip"] != cur["chip"]:
        return f"cross-chip comparison: baseline={base['chip']} current={cur['chip']}"
    return None


def compare_to_baseline(line: dict, baseline_path: str) -> dict:
    """Headline-vs-previous-round comparison that REFUSES cross-backend
    ratios. Accepts a raw bench JSON line or the driver's ``BENCH_rXX.json``
    wrapper (``{"parsed": {...}}``). A baseline without a backend stamp is
    judged by its ``on_tpu`` field; one with neither is refused — an
    unknown-backend ratio is exactly the trap this exists to close."""
    try:
        with open(baseline_path) as f:
            base = json.load(f)
    except (OSError, ValueError) as e:
        return {"refused": f"unreadable baseline: {type(e).__name__}"}
    if isinstance(base, dict) and isinstance(base.get("parsed"), dict):
        base = base["parsed"]
    if not isinstance(base, dict):
        return {"refused": "baseline is not a bench JSON object"}
    refusal = comparability_refusal(base, line)
    if refusal is not None:
        return {"refused": refusal}
    b_backend = backend_of(base)
    if (base.get("metric") and line.get("metric") and base["metric"] != line["metric"]):
        # bench prints TWO stamped lines (serving + train headline) — a
        # ratio across metrics is as meaningless as one across backends
        return {"refused": f"cross-metric comparison: baseline={base['metric']} "
                           f"current={line['metric']}"}
    if not base.get("value"):
        return {"refused": "baseline has no headline value"}
    try:
        return {"ratio": round(float(line["value"]) / float(base["value"]), 4),
                "baseline_value": base["value"], "baseline_backend": b_backend}
    except (TypeError, ValueError, ZeroDivisionError) as e:
        # a malformed baseline must cost this field, never the headline line
        return {"refused": f"non-numeric baseline value: {type(e).__name__}"}


def _free_engine(engine, *attrs):
    """Drop an engine's device buffers (params/state/KV pools) so the next
    benchmark configuration has the chip's HBM to itself."""
    for a in attrs:
        setattr(engine, a, None)
    engine._compiled = {}
    gc.collect()


def bench_serving(on_tpu: bool):
    """FastGen-equivalent serving bench: p50 TTFT (prefill latency) and
    steady-state decode tokens/s/chip under continuous batching."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from deepspeed_tpu.models import TransformerConfig, TransformerLM
    from deepspeed_tpu.inference.v2 import InferenceEngineV2, RaggedInferenceEngineConfig

    if on_tpu:
        cfg = TransformerConfig(vocab_size=32000, hidden_size=2048, num_layers=12,
                                num_heads=16, num_kv_heads=16, intermediate_size=5632,
                                max_seq_len=2048, norm="rmsnorm", positions="rotary",
                                mlp="swiglu", dtype=jnp.bfloat16, attention_impl="flash")
        # int8 KV halves the pool: 64 tracked sequences fit where bf16 fit 32,
        # and the bigger decode batch amortizes the 1.5 GB/step weight stream —
        # the dominant serving-roofline term. DS_TPU_BENCH_NSEQS pins it; the
        # ladder below falls back 64 -> 32 on OOM so a tight chip still
        # produces a number instead of forfeiting the serving line.
        n_seqs = int(os.environ.get("DS_TPU_BENCH_NSEQS", "64"))
        prompt_len, decode_steps, block_size = 512, 192, 128
    else:  # CPU smoke
        cfg = TransformerConfig(vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
                                intermediate_size=256, max_seq_len=512, dtype=jnp.float32,
                                attention_impl="reference")
        n_seqs, prompt_len, decode_steps, block_size = 4, 64, 4, 64

    model = TransformerLM(cfg)
    rng = np.random.default_rng(0)
    warm_prompt = rng.integers(0, cfg.vocab_size, size=prompt_len, dtype=np.int32)

    def build(ns, k8):
        icfg = RaggedInferenceEngineConfig()
        icfg.kv_block_size = block_size
        icfg.num_kv_blocks = ns * (-(-(prompt_len + decode_steps + block_size) // block_size)) + 8
        icfg.kv_dtype = "int8" if k8 else cfg.dtype
        icfg.state_manager.max_tracked_sequences = ns
        icfg.state_manager.max_ragged_sequence_count = ns
        icfg.state_manager.max_ragged_batch_size = max(prompt_len, ns)
        icfg.state_manager.max_context = prompt_len + decode_steps + block_size
        return InferenceEngineV2(model, icfg)

    # int8 KV (FastGen quantized-KV analog) halves the decode KV stream —
    # the serving default on TPU (the on-chip kernel suite validates the int8
    # paged kernel before this bench runs; DS_TPU_BENCH_KV=bf16 reverts).
    # Fallback ladder: batch 64 -> 32, int8 -> bf16 — an OOM or a kernel
    # failure costs one rung, never the serving number (r3 lesson). 64+bf16
    # is omitted: by the sizing model above it cannot fit where 64+int8
    # didn't. Each rung warms the FULL memory-heavy program set (all-seqs
    # prefill + the widest decode scan) so a late OOM can't escape the
    # ladder, and failed rungs drop their tracebacks + collect before the
    # next build so dead buffers don't cascade-OOM the rungs that would fit.
    horizon = 64 if on_tpu else 2
    kv_int8 = on_tpu and os.environ.get("DS_TPU_BENCH_KV", "int8") == "int8"
    ladder = [(n_seqs, kv_int8)]
    if on_tpu and n_seqs > 32:
        ladder.append((32, kv_int8))
    if kv_int8:
        ladder += [(ns, False) for ns, _ in ladder if ns <= 32] or [(32, False)]

    def warm_rung(ns, k8):
        eng = build(ns, k8)
        first = eng.put([0], [warm_prompt], sample="greedy")  # compile prefill bucket
        for uid in range(1, ns):  # full-batch KV residency
            eng.put([uid], [warm_prompt], sample="greedy")
        tok = [np.asarray([int(first[0])], np.int32)] * ns
        # the timed phase's batched 1-token put (all seqs) and the widest
        # decode scan — the recompile sentinel flags any bucket this rung
        # misses as a steady-state recompile below
        eng.put(list(range(ns)), tok, sample="greedy")
        eng.decode(list(range(ns)), tok, horizon)  # compile the widest decode scan
        for uid in range(ns):
            eng.flush(uid)
        return eng

    engine, last_err = None, None
    for ns, k8 in ladder:
        try:
            engine = warm_rung(ns, k8)
            n_seqs, kv_int8 = ns, k8
            # the rung warmed every bucket the timed phases hit with REAL
            # traffic — declare the sentinel boundary and attach the serving
            # ledger so the TTFT/decode phases are wall-clock attributed and
            # any steady-state recompile below is flagged, not silent
            from deepspeed_tpu.monitor.goodput import get_goodput as _gp

            if _gp().enabled:
                engine.goodput_ledger = _gp().serving_ledger("bench")
                engine.declare_gp_warmed()
            break
        except Exception as e:
            print(f"# WARNING: serving config n_seqs={ns} kv={'int8' if k8 else 'bf16'} failed "
                  f"({type(e).__name__}: {str(e)[:200]}); trying next rung", flush=True)
            last_err = e.with_traceback(None)  # frames pin device buffers
            if engine is not None:
                _free_engine(engine, "state_manager", "params")
                engine = None
            gc.collect()
    if engine is None:
        raise last_err

    prompts = [rng.integers(0, cfg.vocab_size, size=prompt_len, dtype=np.int32) for _ in range(n_seqs)]
    # --- prefill / TTFT: one prompt per put (the FastGen TTFT definition:
    # time from request admission to its first generated token on host;
    # on-device greedy sampling so the transfer is the token, not the logits) ---
    ttfts = []
    first_tok = None
    for uid in range(n_seqs):
        t0 = time.time()
        first_tok = engine.put([uid], [prompts[uid]], sample="greedy")
        ttfts.append((time.time() - t0) * 1000.0)
    ttft_p50 = float(np.percentile(ttfts, 50))

    # --- steady-state continuous-batching decode: the multi-step on-device
    # scan (engine.decode) with greedy feedback — one host round-trip per
    # horizon instead of per token, the serving loop's steady-state shape ---
    uids = list(range(n_seqs))
    step_tok = [np.asarray([int(first_tok[0])], np.int32) for _ in uids]
    # horizon 64 (set at the rung ladder, where the scan was pre-compiled):
    # each decode() call pays one host round-trip (~50ms on the axon relay)
    # regardless of length — the steady-state number measures the device
    n_rounds = max(1, (decode_steps - horizon) // horizon)
    last = [np.asarray([int(t)], np.int32) for t in np.asarray(engine.put(
        uids, step_tok, sample="greedy"))]
    t0 = time.time()
    for _ in range(n_rounds):
        out = engine.decode(uids, last, horizon)
        last = [np.asarray([int(t)], np.int32) for t in out[:, -1]]
    dt = time.time() - t0
    decode_tps = n_seqs * n_rounds * horizon / dt

    # --- prefix-cache phase: hit-vs-miss TTFT on a shared-prefix stream.
    # A separate small engine (params SHARED with the main one — no second
    # HBM copy) with ragged.prefix_cache enabled: per shared system prompt,
    # the first request pays full prefill (miss), repeats prefill only their
    # unique suffix (radix hit) — the TTFT gap is the serving win ---
    prefix_line = None
    try:
        from deepspeed_tpu.inference.v2 import PrefixCacheConfig

        if on_tpu:
            n_prefixes, repeats, shared_len, suffix_len = 4, 3, 384, 128
        else:
            n_prefixes, repeats, shared_len, suffix_len = 2, 2, 48, 16
        per_seq = -(-(shared_len + suffix_len + 1) // block_size) + 1
        picfg = RaggedInferenceEngineConfig()
        picfg.kv_block_size = block_size
        picfg.num_kv_blocks = (n_prefixes + 2) * per_seq + 8
        picfg.kv_dtype = "int8" if kv_int8 else cfg.dtype
        picfg.state_manager.max_tracked_sequences = 4
        picfg.state_manager.max_ragged_sequence_count = 4
        picfg.state_manager.max_ragged_batch_size = max(prompt_len, 4)
        picfg.state_manager.max_context = shared_len + suffix_len + block_size
        picfg.use_pallas_kernels = "never" if not on_tpu else "auto"
        picfg.prefix_cache = PrefixCacheConfig(enabled=True)
        peng = InferenceEngineV2(model, picfg, params=engine.params)
        # compile the miss- and hit-shaped buckets before timing
        wp = rng.integers(0, cfg.vocab_size, size=shared_len + suffix_len, dtype=np.int32)
        peng.put([90_000], [wp], sample="greedy")
        peng.put([90_001], [wp[-suffix_len:]], sample="greedy")
        for u in (90_000, 90_001):
            peng.flush(u)
        peng.prefix_cache.clear()
        peng.prefix_cache.stats.update({k: 0 for k in peng.prefix_cache.stats})
        ttft_miss, ttft_hit = [], []
        uid = 91_000
        for p in range(n_prefixes):
            shared = rng.integers(0, cfg.vocab_size, size=shared_len, dtype=np.int32)
            for r in range(repeats + 1):
                suffix = rng.integers(0, cfg.vocab_size, size=suffix_len, dtype=np.int32)
                t0 = time.time()
                peng.put([uid], [np.concatenate([shared, suffix])], sample="greedy")
                (ttft_miss if r == 0 else ttft_hit).append((time.time() - t0) * 1000.0)
                peng.flush(uid)
                uid += 1
        pc = peng.prefix_cache
        prefix_line = {
            "hit_rate": round(pc.hit_rate, 3),
            "cached_tokens": int(pc.stats["cached_tokens"]),
            "ttft_hit_p50_ms": round(float(np.percentile(ttft_hit, 50)), 1),
            "ttft_miss_p50_ms": round(float(np.percentile(ttft_miss, 50)), 1),
        }
        _free_engine(peng, "state_manager")
    except Exception as e:
        # the headline serving numbers never forfeit to the prefix phase
        print(f"# WARNING: prefix-cache bench phase failed "
              f"({type(e).__name__}: {str(e)[:200]})", flush=True)

    # --- HBM roofline for vs_baseline (decode is bandwidth-bound). The KV
    # term uses the bytes ACTUALLY streamed (int8 + fp32 scales in quantized
    # mode) so the ratio stays an honest fraction of the achievable bound ---
    n_params = model.num_params()
    param_bytes = n_params * np.dtype(np.float32 if cfg.dtype == jnp.float32 else np.float16).itemsize
    ctx = prompt_len + decode_steps // 2
    kv_token_bytes = (cfg.head_dim * 1 + 4) if kv_int8 else cfg.head_dim * 2
    kv_bytes_per_seq = 2 * cfg.num_layers * cfg.num_kv_heads * ctx * kv_token_bytes
    hbm_bw = 819e9 if on_tpu else 50e9  # v5e HBM bandwidth
    step_time_roofline = (param_bytes + n_seqs * kv_bytes_per_seq) / hbm_bw
    roofline_tps = n_seqs / step_time_roofline

    out = {
        "metric": "fastgen_decode_tokens_per_sec_per_chip",
        "value": round(decode_tps, 1),
        "unit": "tokens/s/chip",
        "ttft_p50_ms": round(ttft_p50, 1),
        "batch_sequences": n_seqs,
        "prompt_len": prompt_len,
        "kv_cache": "int8" if kv_int8 else "bf16",
        # vs_baseline is a fraction of the TPU HBM roofline; on the CPU
        # fallback it is meaningless (a naive reader would see a 95%
        # "regression" — VERDICT r4), so it is null unless measured on-chip
        "vs_baseline": round(decode_tps / roofline_tps, 4) if on_tpu else None,
    }
    if prefix_line is not None:
        out["prefix_cache"] = prefix_line
    if engine.goodput_ledger is not None:
        # freeze the wall clock: the ledger's report covers the serving
        # phases, not the unrelated bench minutes that follow
        engine.goodput_ledger.stop()
    _free_engine(engine, "state_manager", "params")
    return out


def bench_kernels(on_tpu: bool) -> dict:
    """Raw-speed microbench A/Bs (PR 10): q-tiled vs per-token paged
    attention tok/s, explicit-overlap vs implicit ZeRO-3 step time, tuned vs
    default flash tiles. Each sub-block is independently guarded — a failure
    costs that key only, never the headline. Off-TPU the Pallas arms run in
    interpret mode on tiny shapes (disclosed), so the numbers exercise the
    plumbing, not the chip."""
    import time as _t

    import jax
    import jax.numpy as jnp
    import numpy as np
    from deepspeed_tpu.autotuning.kernel_config import KernelAutotuner

    out = {}
    if not on_tpu:
        out["note"] = "cpu: pallas arms run interpreted on tiny shapes"

    # same warmup/median methodology as the tile sweep, so the A/B block and
    # the autotuner can never quietly measure differently
    timeit = KernelAutotuner(output_dir=".", steps=3, warmup=1).measure

    # --- paged attention: q-tiled vs per-token ---
    try:
        from deepspeed_tpu.ops.pallas.paged_attention import _pallas_paged, _resolve_q_tile

        rng = np.random.default_rng(0)
        if on_tpu:
            nq, nkv, d, bs, chunk, n_seqs = 16, 16, 128, 128, 128, 2
        else:
            nq, nkv, d, bs, chunk, n_seqs = 4, 4, 32, 16, 16, 2
        T = chunk * n_seqs
        NB = n_seqs * (-(-(chunk + bs) // bs))
        dt = jnp.bfloat16 if on_tpu else jnp.float32
        k_pool = jnp.asarray(rng.normal(size=(NB * bs, nkv, d)), dt)
        v_pool = jnp.asarray(rng.normal(size=(NB * bs, nkv, d)), dt)
        tables = jnp.arange(NB, dtype=jnp.int32).reshape(n_seqs, -1)
        q = jnp.asarray(rng.normal(size=(T, nq, d)), dt)
        seq_idx = jnp.asarray(np.repeat(np.arange(n_seqs), chunk), jnp.int32)
        pos = jnp.asarray(np.tile(np.arange(chunk), n_seqs) + bs // 2, jnp.int32)
        qt = _resolve_q_tile(T, n_seqs)
        if qt <= 1:
            qt = 8

        def paged(q_tile):
            return lambda: _pallas_paged(q, k_pool, v_pool, tables, seq_idx, pos,
                                         block_size=bs, q_tile=q_tile, interpret=not on_tpu)

        t1 = timeit(paged(1))
        tq = timeit(paged(qt))
        out["paged_attention"] = {
            "q_tile": qt, "prefill_tokens": T,
            "per_token_tok_s": round(T / t1, 1),
            "q_tiled_tok_s": round(T / tq, 1),
            "speedup": round(t1 / tq, 3),
        }
    except Exception as e:
        print(f"# WARNING: kernels.paged_attention bench failed "
              f"({type(e).__name__}: {str(e)[:160]})", flush=True)

    # --- paged attention decode: flash-decode KV-split on vs off ---
    try:
        from deepspeed_tpu.ops.pallas.paged_attention import (_pallas_paged,
                                                              _resolve_kv_splits)

        # the SHARED decode-shaped case (one token per sequence at the end
        # of a fully-live long context — the shape where the per-token
        # grid's single softmax chain is the latency floor): the bench
        # measures exactly the shape tune_paged_decode records
        n_seqs = 4
        q, k_pool, v_pool, tables, seq_idx, pos, bs, mb = \
            KernelAutotuner.paged_decode_case(on_tpu, n_seqs=n_seqs)
        ks = _resolve_kv_splits(n_seqs, n_seqs, mb)
        if ks <= 1:
            ks = 8

        def decode(kv_splits):
            return lambda: _pallas_paged(q, k_pool, v_pool, tables, seq_idx, pos,
                                         block_size=bs, q_tile=1, kv_splits=kv_splits,
                                         interpret=not on_tpu)

        t1 = timeit(decode(1))
        ts = timeit(decode(ks))
        out["paged_decode_split"] = {
            "kv_splits": ks, "context_tokens": mb * bs, "decode_rows": n_seqs,
            "split_off_tok_s": round(n_seqs / t1, 1),
            "split_on_tok_s": round(n_seqs / ts, 1),
            "speedup": round(t1 / ts, 3),
        }
    except Exception as e:
        print(f"# WARNING: kernels.paged_decode_split bench failed "
              f"({type(e).__name__}: {str(e)[:160]})", flush=True)

    # --- ZeRO-3 overlap_comm: explicit vs implicit step time ---
    try:
        import deepspeed_tpu
        from deepspeed_tpu.models import TransformerConfig, TransformerLM
        from deepspeed_tpu.parallel import groups

        if on_tpu:
            mcfg = TransformerConfig(vocab_size=8192, hidden_size=1024, num_layers=8,
                                     num_heads=8, intermediate_size=2816, max_seq_len=512,
                                     dtype=jnp.bfloat16, attention_impl="flash")
            micro, seq, steps = 2, 512, 4
        else:
            mcfg = TransformerConfig(vocab_size=256, hidden_size=64, num_layers=4, num_heads=4,
                                     intermediate_size=128, max_seq_len=64, dtype=jnp.float32,
                                     attention_impl="reference")
            micro, seq, steps = 2, 64, 3
        step_ms = {}
        for overlap in (False, True):
            groups.reset()
            n = len(jax.devices())
            cfgd = {
                "train_batch_size": micro * n,
                "train_micro_batch_size_per_gpu": micro,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
                "zero_optimization": {"stage": 3, "overlap_comm": overlap},
                "bf16": {"enabled": bool(on_tpu)},
                "steps_per_print": 10**9,
                "tpu": {"mesh": {"data": n}},
            }
            eng, _, _, _ = deepspeed_tpu.initialize(model=TransformerLM(mcfg), config=cfgd)
            rng = np.random.default_rng(0)
            batch = {"input_ids": rng.integers(0, mcfg.vocab_size, size=(micro * n, seq),
                                               dtype=np.int32)}
            eng.train_batch(batch)  # compile
            float(np.asarray(eng.state["step"]))
            t0 = _t.perf_counter()
            for _ in range(steps):
                eng.train_batch(batch)
            float(np.asarray(eng.state["step"]))
            step_ms["overlap_on" if overlap else "overlap_off"] = round(
                (_t.perf_counter() - t0) / steps * 1e3, 3)
            _free_engine(eng, "state")
        out["zero3_overlap"] = {
            "step_ms_off": step_ms["overlap_off"], "step_ms_on": step_ms["overlap_on"],
            "speedup": round(step_ms["overlap_off"] / max(step_ms["overlap_on"], 1e-9), 3),
        }
    except Exception as e:
        print(f"# WARNING: kernels.zero3_overlap bench failed "
              f"({type(e).__name__}: {str(e)[:160]})", flush=True)

    # --- flash attention: tuned vs default tiles (only meaningful on-chip) ---
    if on_tpu:
        try:
            from deepspeed_tpu.ops.pallas.flash_attention import (_default_tile, _pallas_flash,
                                                                  _resolve_tiles)

            S, nq, d = 2048, 16, 128
            k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
            qf = jax.random.normal(k1, (1, S, nq, d), jnp.bfloat16)
            kf = jax.random.normal(k2, (1, S, nq, d), jnp.bfloat16)
            vf = jax.random.normal(k3, (1, S, nq, d), jnp.bfloat16)
            dflt = _default_tile()
            bq, bk = _resolve_tiles(S, d)
            td = timeit(lambda: _pallas_flash(qf, kf, vf, causal=True, block_q=dflt,
                                              block_k=dflt))
            tt = timeit(lambda: _pallas_flash(qf, kf, vf, causal=True, block_q=bq, block_k=bk))
            out["flash_tiles"] = {
                "default": [dflt, dflt], "tuned": [bq, bk],
                "default_ms": round(td * 1e3, 3), "tuned_ms": round(tt * 1e3, 3),
                "speedup": round(td / tt, 3),
                "untuned": (bq, bk) == (dflt, dflt),  # no kernel_config.json for this topo
            }
        except Exception as e:
            print(f"# WARNING: kernels.flash_tiles bench failed "
                  f"({type(e).__name__}: {str(e)[:160]})", flush=True)
    return out


def trace_demo(seq=128, micro=2):
    """Drive the eager 3-call engine API and one eager collective under the
    live tracer: the fwd/bwd/step phase spans only exist as separate host
    calls on this path (the fused train_batch is ONE compiled program and is
    traced as its own span), and the eager all_reduce exercises @timed_op's
    wall-timed regime with real payload bytes."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import deepspeed_tpu
    from deepspeed_tpu import dist
    from deepspeed_tpu.models import TransformerConfig, TransformerLM
    from deepspeed_tpu.parallel import groups

    groups.reset()
    cfg = TransformerConfig(vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
                            intermediate_size=256, max_seq_len=seq, dtype=jnp.float32,
                            attention_impl="reference")
    model = TransformerLM(cfg)
    n_chips = len(jax.devices())
    config = {
        "train_batch_size": micro * n_chips,
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "steps_per_print": 10**9,
        "tpu": {"mesh": {"data": n_chips}},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, size=(micro * n_chips, seq),
                                       dtype=np.int32)}
    loss = engine.forward(batch)
    engine.backward(loss)
    engine.step()
    x = np.ones((256, 1024), np.float32)  # 1 MiB payload
    # the first call compiles the eager executable; timed_op tags that span
    # `compiled` and keeps it out of the comms bandwidth stats automatically
    for _ in range(4):
        dist.all_reduce(x)
    _free_engine(engine, "state")


def run_bench():
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the image's sitecustomize registers the axon PJRT plugin and sets
        # jax_platforms="axon,cpu" at the CONFIG level, which beats the env
        # var — without this the "CPU fallback" child still initializes the
        # (possibly hung) TPU tunnel (the __graft_entry__ round-1 lesson)
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    # persistent compile cache: repeat bench runs skip the ~40s-per-program
    # XLA compiles (first run in a fresh container still pays them)
    try:
        cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    # --trace OUT.jsonl: enable the unified observability bus (monitor/trace.py)
    # BEFORE any compile so jax_compile events land in the artifact; the same
    # switch turns on the metrics registry and real comms byte accounting
    trace_path = os.environ.get("DS_TPU_BENCH_TRACE")
    if trace_path:
        from deepspeed_tpu.monitor.trace import configure_tracer
        from deepspeed_tpu.monitor.metrics import configure_metrics
        from deepspeed_tpu.comm import comm as _dist

        try:  # fresh artifact per child (TPU/CPU children share the path)
            os.remove(trace_path)
        except OSError:
            pass
        configure_tracer(enabled=True, path=trace_path)
        configure_metrics(enabled=True)
        _dist.configure(enabled=True, prof_all=True)

    # goodput ledger + recompile sentinel (monitor/goodput.py): armed for
    # every bench child — the final JSON's `goodput` block attributes the
    # bench's own wall clock (compile vs compute vs input wait) and proves
    # the steady-state phases recompiled nothing
    from deepspeed_tpu.monitor.goodput import configure_goodput

    configure_goodput(enabled=True)

    # roofline plane (monitor/roofline.py): cost-vs-wall verdict for every
    # post-warmup compiled bucket; the final JSON's `roofline` block is what
    # perf_sentinel trends MFU/MBU over. DS_TPU_BENCH_ROOFLINE=0 skips.
    if os.environ.get("DS_TPU_BENCH_ROOFLINE", "1") != "0":
        from deepspeed_tpu.monitor.roofline import configure_roofline

        configure_roofline(enabled=True)

    try:
        on_tpu = any(d.platform == "tpu" for d in jax.devices())
    except Exception as e:  # backend init died mid-child: disclose, run CPU
        print(f"# WARNING: jax.devices() failed ({type(e).__name__}); forcing CPU", flush=True)
        # config-level update + backend-cache clear — the env var alone is
        # beaten by the sitecustomize's jax_platforms='axon,cpu' config
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
        from jax._src import xla_bridge

        xla_bridge._clear_backends()
        on_tpu = False
    tpu_error = os.environ.get("DS_TPU_BENCH_TPU_ERROR", "")
    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, TransformerLM

    # on-chip kernel numerics gate (VERDICT r2: interpret-mode CI can't see
    # Mosaic miscompiles): run the real-TPU kernel suite before timing.
    # TWO-TIER response (r3 lesson — never forfeit the round's perf number
    # to an unrelated failure): a failure in a kernel the bench's own paths
    # exercise (flash / paged / quant / fused adam) aborts LOUDLY; a failure
    # in any other on-chip test (evoformer, sparse, ...) is disclosed on
    # stdout and in the JSON line but the bench still runs — its numbers
    # don't depend on those kernels. DS_TPU_BENCH_VALIDATE=0 skips.
    gate_note = None
    if on_tpu and os.environ.get("DS_TPU_BENCH_VALIDATE", "1") != "0":
        import re
        import subprocess
        import sys

        # bench-critical = kernels the bench's own paths execute. Prefixes of
        # the actual tests_tpu function names (the r4 bare-substring match
        # made test_evoformer_biased_flash_on_chip match "flash" and abort
        # the bench on a kernel its paths never run — ADVICE r4; the explicit
        # noncritical markers keep evoformer/sparse out even if future names
        # collide again).
        critical = ("test_flash", "test_paged", "test_quant", "test_fused_adam",
                    "test_v1_fused_decode", "test_v2_engine_serving")
        noncritical_markers = ("evoformer", "sparse")
        suite = os.path.join(os.path.dirname(os.path.abspath(__file__)), "tests_tpu")
        if not os.path.isdir(suite):
            print("# WARNING: tests_tpu/ missing — on-TPU kernel numerics gate SKIPPED", flush=True)
        else:
            env = dict(os.environ)
            env["JAX_COMPILATION_CACHE_DIR"] = cache_dir  # children share the warm cache

            def run_pytest(args, timeout):
                return subprocess.run([sys.executable, "-m", "pytest", suite, "-q"] + args,
                                      capture_output=True, text=True, timeout=timeout, env=env)

            # STAGE 1 — bench-critical kernels only. Runs first so a slow
            # cold compile of a kernel the bench never executes (evoformer's
            # 4-pass bwd, block-sparse) can't eat the whole gate budget
            # (a cold cache blew the single 900s pytest run this replaced).
            kexpr = " or ".join(critical)
            try:
                proc = run_pytest(["-k", kexpr], timeout=1200)
            except subprocess.TimeoutExpired as e:
                raise RuntimeError(f"on-TPU CRITICAL kernel validation timed out after "
                                   f"{e.timeout}s") from e
            failed1 = re.findall(r"FAILED (\S+)", proc.stdout)
            # criticality is judged on the FUNCTION name, not on -k's sweep
            # (-k also matches module/class keywords, so a future
            # tests_tpu/test_quant_*.py FILE would ride in — the r4
            # false-abort class): only a genuinely critical-named test aborts
            crit_failed = [
                f for f in failed1
                if any(c in f.split("::")[-1] for c in critical)
                and not any(m in f for m in noncritical_markers)
            ]
            if crit_failed:
                raise RuntimeError("on-TPU kernel validation FAILED on bench-critical kernels "
                                   f"{crit_failed}:\n" + proc.stdout[-3000:] + "\n"
                                   + proc.stderr[-2000:])
            if failed1:
                gate_note = f"non-critical on-chip kernel tests FAILED: {failed1}"
                print(f"# WARNING: {gate_note} — bench paths unaffected, continuing", flush=True)
            if " passed" not in proc.stdout:
                # e.g. a locked single-process TPU: the child saw no device
                # and skipped everything — disclose in the JSON too, not
                # just stdout (coverage must not be claimed silently)
                gate_note = "critical kernel stage ran NO tests — numerics gate ineffective"
                print(f"# WARNING: on-TPU {gate_note} (device not visible to subprocess?)",
                      flush=True)
            else:
                tail = proc.stdout.strip().splitlines()
                print(f"# on-TPU critical kernels: {tail[-1] if tail else 'ok'}", flush=True)

            # STAGE 2 — everything else (evoformer, sparse, grouped, ...):
            # disclose-only. A failure OR timeout here never forfeits the
            # perf number (r3 lesson); it lands in the JSON as a warning.
            def add_note(note):
                combined = f"{gate_note}; {note}" if gate_note else note
                print(f"# WARNING: {note} — bench paths unaffected, continuing", flush=True)
                return combined

            try:
                proc2 = run_pytest(["-k", f"not ({kexpr})"], timeout=900)
                failed2 = re.findall(r"FAILED (\S+)", proc2.stdout)
                if failed2:
                    gate_note = add_note(f"non-critical on-chip kernel tests FAILED: {failed2}")
                else:
                    tail2 = proc2.stdout.strip().splitlines()
                    print(f"# on-TPU non-critical kernels: {tail2[-1] if tail2 else 'ok'}", flush=True)
            except subprocess.TimeoutExpired:
                gate_note = add_note("non-critical on-chip kernel stage timed out (cold compiles?)")

    serving = bench_serving(on_tpu)
    # gateway plane (PR 6): latency-under-load curves through the HTTP/SSE
    # request plane + the prefix-router vs random-placement A/B. Small-engine
    # config by design (two production replicas do not share one chip), so it
    # rides every bench run; DS_TPU_BENCH_GATEWAY=0 skips, and a failure
    # costs this block only — never the headline serving numbers.
    if os.environ.get("DS_TPU_BENCH_GATEWAY", "1") != "0":
        try:
            from tools.serving_load import gateway_bench

            serving["gateway"] = gateway_bench(on_tpu)
            # request-scoped tracing (PR 8): surface the p99-TTFT attribution
            # and the measured trace-on-vs-off throughput tax as one readable
            # line — the full table rides the serving JSON below
            tr = serving["gateway"].get("tracing", {})
            attr = tr.get("attribution", {})
            if attr.get("stages_p99_ms"):
                stages = " ".join(f"{k.removesuffix('_ms')}={v}ms"
                                  for k, v in attr["stages_p99_ms"].items())
                print(f"# p99 TTFT attribution: ttft_p99={attr.get('ttft_p99_ms')}ms "
                      f"[{stages}] breakdown_ok={attr.get('breakdown_ok_frac')} "
                      f"trace_overhead={tr.get('overhead_pct')}%", flush=True)
        except Exception as e:
            print(f"# WARNING: gateway bench phase failed "
                  f"({type(e).__name__}: {str(e)[:200]})", flush=True)
    # speculative decoding (PR 9/13): spec-on/off A/B on the shared-prefix
    # workload — acceptance rate + decode tok/s both arms + greedy token
    # parity — plus the K × tree-width sweep grid with per-drafter-mode
    # accept rates. DS_TPU_BENCH_SPEC=0 skips; a failure costs this block
    # only, never the headline serving numbers.
    if os.environ.get("DS_TPU_BENCH_SPEC", "1") != "0":
        try:
            from tools.serving_load import speculative_ab

            sp = speculative_ab(on_tpu)
            serving["speculative"] = {k: sp[k] for k in
                                      ("accept_rate", "decode_tok_s_on", "decode_tok_s_off",
                                       "speedup", "k", "min_match", "tree_width",
                                       "spec_rounds", "drafted_tokens", "token_parity")
                                      if k in sp}
            print(f"# speculative: accept_rate={sp.get('accept_rate')} decode_tok_s "
                  f"on/off={sp.get('decode_tok_s_on')}/{sp.get('decode_tok_s_off')} "
                  f"(k={sp.get('k')}, width={sp.get('tree_width')}, "
                  f"parity={sp.get('token_parity')})", flush=True)
        except Exception as e:
            print(f"# WARNING: speculative bench phase failed "
                  f"({type(e).__name__}: {str(e)[:200]})", flush=True)
        # the sweep is its own failure domain: the headline A/B above must
        # survive a sweep-only regression (and vice versa)
        try:
            from tools.serving_load import speculative_sweep

            sw = speculative_sweep(on_tpu)
            serving.setdefault("speculative", {})["sweep"] = {
                "grid": sw["grid"], "decode_tok_s_off": sw["decode_tok_s_off"],
                "best_accept_rate_by_mode": sw["best_accept_rate_by_mode"],
                "all_parity": sw["all_parity"]}
            best = max(sw["grid"], key=lambda c: c["decode_tok_s"], default=None)
            if best:
                print(f"# speculative sweep: best cell mode={best['mode']} k={best['k']} "
                      f"width={best['tree_width']} accept={best['accept_rate']} "
                      f"tok/s={best['decode_tok_s']} (parity={sw['all_parity']})", flush=True)
        except Exception as e:
            print(f"# WARNING: speculative sweep phase failed "
                  f"({type(e).__name__}: {str(e)[:200]})", flush=True)
    serving.update(backend_stamp(on_tpu))
    print(json.dumps(serving))

    def train_tps(cfg, micro, gas, seq, steps, warmup, data="batch"):
        """One training-throughput measurement. ``data`` selects the input
        path: "batch" re-feeds one host batch (zero assembly cost — the
        headline metric, unchanged round-over-round); "iter" assembles a
        fresh batch per microbatch on the host, synchronously; "prefetch"
        runs the same assembly through ``engine.prefetching_loader`` (the
        async input pipeline). Returns (tokens/s/chip, model,
        input_wait_ms p50 over the timed steps)."""
        from deepspeed_tpu.parallel import groups
        from deepspeed_tpu.monitor.metrics import configure_metrics, get_metrics

        groups.reset()
        configure_metrics(enabled=True)  # train/input_wait_ms rides the registry
        model = TransformerLM(cfg)
        n_chips = len(jax.devices())
        config = {
            "train_batch_size": micro * gas * n_chips,
            "train_micro_batch_size_per_gpu": micro,
            "gradient_accumulation_steps": gas,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4, "weight_decay": 0.0}},
            "zero_optimization": {"stage": 3 if on_tpu else 0},
            "bf16": {"enabled": bool(on_tpu)},
            "steps_per_print": 10**9,
            "tpu": {"mesh": {"data": n_chips}},
        }
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
        rng = np.random.default_rng(0)
        prefetcher = None
        if data == "batch":
            batch = {"input_ids": rng.integers(0, cfg.vocab_size, size=(config["train_batch_size"], seq),
                                               dtype=np.int32)}
            feed = lambda: engine.train_batch(batch)
        else:
            rows = config["train_batch_size"] // gas  # per-microbatch rows (single process)

            def mb_gen():
                # per-sample sequence packing + collate — the standard LM
                # input-pipeline shape (draw short documents, concatenate,
                # truncate, stack), identical for the sync and prefetch arms
                while True:
                    samples = []
                    for _ in range(rows):
                        lens = rng.integers(16, 64, size=-(-seq // 16))
                        toks = rng.integers(0, cfg.vocab_size, size=int(lens.sum()), dtype=np.int32)
                        # document-boundary resets, then truncate to one row
                        samples.append(np.concatenate(np.split(toks, np.cumsum(lens)[:-1]))[:seq])
                    yield {"input_ids": np.stack(samples)}

            it = mb_gen()
            if data == "prefetch":
                it = prefetcher = engine.prefetching_loader(it, depth=2)
            # per-step host sync: the A/B arms model a device-bound training
            # loop (the loop waits on the step each iteration), which is what
            # the prefetch worker overlaps — async dispatch would let the
            # consumer outrun assembly and measure worker throughput instead
            feed = lambda: float(np.asarray(engine.train_batch(data_iter=it)))
        for _ in range(warmup):
            feed()
        float(np.asarray(engine.state["step"]))  # host fetch = real barrier
        get_metrics().reset()  # timed-window stats only (warmup pays the compiles)
        t0 = time.time()
        for _ in range(steps):
            feed()
        float(np.asarray(engine.state["step"]))
        tps = steps * config["train_batch_size"] * seq / (time.time() - t0) / n_chips
        input_wait_p50 = get_metrics().histogram("train/input_wait_ms").percentile(50)
        if prefetcher is not None:
            prefetcher.close()
        _free_engine(engine, "state")
        return tps, model, input_wait_p50

    if on_tpu:
        # 748M-param Llama-arch model: h=2048 x 12 layers, seq 2048 — the
        # largest clean shape that fits v5e HBM (16G) with fp32 Adam states
        # and an f32 grad accumulator.
        cfg = TransformerConfig(vocab_size=32000, hidden_size=2048, num_layers=12,
                                num_heads=16, num_kv_heads=16, intermediate_size=5632,
                                max_seq_len=2048, norm="rmsnorm", positions="rotary",
                                mlp="swiglu", dtype=jnp.bfloat16, attention_impl="flash",
                                remat=True, remat_policy="save_only_these_names(attn_out)")
        micro, gas, seq, steps, warmup = 2, 16, 2048, 6, 2
    else:  # CI / CPU smoke mode
        cfg = TransformerConfig(vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
                                intermediate_size=256, max_seq_len=256, dtype=jnp.float32,
                                attention_impl="reference")
        micro, gas, seq, steps, warmup = 2, 1, 256, 3, 1

    tok_per_sec_per_chip, model, input_wait_p50 = train_tps(cfg, micro, gas, seq, steps, warmup)
    # low-accumulation point (the optimizer step un-amortized): the update
    # chain must stay near the HBM roofline, not hide behind gas=16
    gas4_tps, _, _ = train_tps(cfg, micro, 4 if on_tpu else 1, seq, 3 * steps if on_tpu else 2, 2)

    # --prefetch: same workload, same per-microbatch host assembly, with and
    # without the async device-prefetching pipeline — the sync arm's input
    # wait should collapse to ~0 under prefetch while throughput holds (the
    # headline `value` above stays the zero-assembly batch= measurement, so
    # round-over-round tracking is not perturbed by this comparison)
    prefetch_line = None
    if os.environ.get("DS_TPU_BENCH_PREFETCH") == "1":
        # the A/B arms run with gradient accumulation (the real training
        # shape — the sync path stalls once per microbatch pull): headline
        # gas on TPU; the CPU smoke raises its gas=1 to 4 so the sync arm's
        # stall is actually representative
        ab_gas = gas if on_tpu else 4
        ab_steps = steps if on_tpu else 12  # p50 over 3 CPU-smoke steps is noise
        sync_tps, _, sync_wait = train_tps(cfg, micro, ab_gas, seq, ab_steps, warmup, data="iter")
        pf_tps, _, pf_wait = train_tps(cfg, micro, ab_gas, seq, ab_steps, warmup, data="prefetch")
        prefetch_line = {
            "gas": ab_gas,
            "input_wait_ms_p50": round(pf_wait, 3),
            "sync_input_wait_ms_p50": round(sync_wait, 3),
            "tokens_per_sec_per_chip": round(pf_tps, 1),
            "sync_tokens_per_sec_per_chip": round(sync_tps, 1),
            "depth": 2,
        }
        if not on_tpu:
            # the "device" compute runs on the same host cores as the worker,
            # so the CPU fallback understates the throughput side of overlap
            prefetch_line["note"] = "CPU fallback: device compute shares host cores"

    # --ckpt: checkpoint-plane A/B — per-save step-loop blocked time, sync
    # full-write vs async (host-snapshot + background writer). The async
    # number should collapse toward the snapshot cost while the durable
    # write overlaps the next training steps (runtime/resilience/).
    ckpt_line = None
    if os.environ.get("DS_TPU_BENCH_CKPT") == "1":
        import shutil
        import tempfile
        from deepspeed_tpu.parallel import groups
        from deepspeed_tpu.monitor.metrics import configure_metrics, get_metrics

        n_saves = 4
        ckpt_line = {"n_saves": n_saves}
        for mode in ("sync", "async"):
            groups.reset()
            configure_metrics(enabled=True)
            get_metrics().reset()
            n_chips = len(jax.devices())
            ck_config = {
                "train_batch_size": micro * n_chips,
                "train_micro_batch_size_per_gpu": micro,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-4, "weight_decay": 0.0}},
                "zero_optimization": {"stage": 3 if on_tpu else 0},
                "bf16": {"enabled": bool(on_tpu)},
                "steps_per_print": 10**9,
                "tpu": {"mesh": {"data": n_chips}},
                "checkpoint": {"async_save": mode == "async"},
            }
            ck_engine, _, _, _ = deepspeed_tpu.initialize(model=TransformerLM(cfg),
                                                          config=ck_config)
            ck_rng = np.random.default_rng(0)
            ck_batch = {"input_ids": ck_rng.integers(0, cfg.vocab_size,
                                                     size=(ck_config["train_batch_size"], seq),
                                                     dtype=np.int32)}
            ck_engine.train_batch(ck_batch)  # compile outside the timed window
            ck_dir = tempfile.mkdtemp(prefix=f"ds_bench_ckpt_{mode}_")
            try:
                for i in range(n_saves):
                    ck_engine.save_checkpoint(ck_dir, tag=f"bench_save{i}")
                    # the async writer persists while these steps run — the
                    # overlap the sync arm cannot have
                    ck_engine.train_batch(ck_batch)
                    ck_engine.train_batch(ck_batch)
                ck_engine.flush_checkpoints()
                reg = get_metrics()
                ckpt_line[f"ckpt_blocked_ms_p50_{mode}"] = round(
                    reg.histogram("train/ckpt_blocked_ms").percentile(50), 3)
                ckpt_line[f"write_ms_p50_{mode}"] = round(
                    reg.histogram("checkpoint/write_ms").percentile(50), 3)
            finally:
                shutil.rmtree(ck_dir, ignore_errors=True)
                ck_engine.destroy()
        if ckpt_line.get("ckpt_blocked_ms_p50_sync"):
            ckpt_line["blocked_ratio_async_vs_sync"] = round(
                ckpt_line["ckpt_blocked_ms_p50_async"] / ckpt_line["ckpt_blocked_ms_p50_sync"], 4)

    # --health: live-health-plane micro-bench — a short health-armed run
    # (flight recorder + watchdog + in-process exporter) on a deliberately
    # tiny model: proves the watchdog stays silent on a healthy loop and
    # prices a /metrics scrape. Runs OUTSIDE the headline timed window (the
    # headline arms no health plane at all, per the zero-overhead contract).
    health_line = None
    if os.environ.get("DS_TPU_BENCH_HEALTH", "1") != "0":
        import urllib.request
        from deepspeed_tpu.parallel import groups
        from deepspeed_tpu.monitor.health import get_health
        from deepspeed_tpu.monitor.metrics import configure_metrics, get_metrics

        groups.reset()
        configure_metrics(enabled=True)
        get_metrics().reset()
        n_chips = len(jax.devices())
        h_cfg = TransformerConfig(vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
                                  intermediate_size=256, max_seq_len=256, dtype=jnp.float32,
                                  attention_impl="reference")
        h_config = {
            "train_batch_size": 2 * n_chips,
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4, "weight_decay": 0.0}},
            "zero_optimization": {"stage": 0},
            "steps_per_print": 10**9,
            "tpu": {"mesh": {"data": n_chips}},
            "health": {"export_port": 0, "deadline_train_step_s": 300.0,
                       "dump_on_destroy": False},
        }
        h_engine, _, _, _ = deepspeed_tpu.initialize(model=TransformerLM(h_cfg),
                                                     config=h_config)
        h = get_health()
        h_rng = np.random.default_rng(0)
        h_batch = {"input_ids": h_rng.integers(0, h_cfg.vocab_size,
                                               size=(h_config["train_batch_size"], 64),
                                               dtype=np.int32)}
        for _ in range(4):
            h_engine.train_batch(h_batch)
        scrape_ms, body = [], b""
        url = h.server.url + "/metrics"
        for _ in range(20):
            t_s = time.perf_counter()
            body = urllib.request.urlopen(url, timeout=10).read()
            scrape_ms.append((time.perf_counter() - t_s) * 1e3)
        skew_hist = get_metrics().histogram("train/straggler_skew_ms_hist")
        health_line = {
            # a healthy loop must produce ZERO watchdog trips
            "stalls": h.stall_count,
            # cross-rank skew rides the multi-host resilience vote; a
            # single-host run has no samples, disclosed as null
            "straggler_skew_ms_p50": (round(skew_hist.percentile(50), 3)
                                      if skew_hist.count else None),
            "export_scrape_ms_p50": round(sorted(scrape_ms)[len(scrape_ms) // 2], 3),
            "scrape_bytes": len(body),
        }
        if skew_hist.count == 0:
            health_line["note"] = "single-host run: no cross-rank skew samples"
        h_engine.destroy()
        h.shutdown()
        _free_engine(h_engine, "state")

    # --cache: memory & KV-cache observability plane (ISSUE 11) — the
    # cache_pressure workload runs a Zipf corpus ~4x an undersized block
    # pool and reports the measured hit rate against the MRC estimator's 1x
    # prediction (its live accuracy check), block-lifecycle percentiles and
    # fragmentation, plus the process-wide HBM attribution captured while
    # the engine is live. Outside the headline timed window;
    # DS_TPU_BENCH_CACHE=0 skips, failure never costs the headline.
    cache_line = memory_line = None
    if os.environ.get("DS_TPU_BENCH_CACHE", "1") != "0":
        try:
            from tools.serving_load import cache_pressure_bench

            cp = cache_pressure_bench(on_tpu)
            snap = cp["telemetry"]
            cache_line = {
                "mrc": cp["mrc"],
                "mrc_predicted_1x": cp["mrc_predicted_1x"],
                "measured_hit_rate": cp["measured_hit_rate"],
                "mrc_abs_err_1x": cp["mrc_abs_err_1x"],
                "block_age_p50_s": snap["block_age_s"]["p50"],
                "evicted_block_age_p50_s": snap["evicted_block_age_s"]["p50"],
                "reuse_interval_p50_s": snap["reuse_interval_s"]["p50"],
                "fragmentation": snap["fragmentation"],
                "evictions": cp["evictions"],
                "evicted_tokens": cp["evicted_tokens"],
                "cow_bytes": cp["cow_bytes"],
            }
            memory_line = cp["memory"]
            mrc_line = " ".join(f"{k}={v}" for k, v in cp["mrc"].items())
            print(f"# cache: measured_hit={cp['measured_hit_rate']} "
                  f"mrc[{mrc_line}] err_1x={cp['mrc_abs_err_1x']} "
                  f"evicted_age_p50={cache_line['evicted_block_age_p50_s']}s", flush=True)
            sect = memory_line.get("sections", {})
            print("# memory: " + " ".join(f"{k}={v / 2**20:.1f}MiB"
                                          for k, v in sorted(sect.items()))
                  + (f" unattributed={memory_line['unattributed_bytes'] / 2**20:.1f}MiB"
                     if memory_line.get("unattributed_bytes") is not None else ""),
                  flush=True)
        except Exception as e:
            print(f"# WARNING: cache bench phase failed "
                  f"({type(e).__name__}: {str(e)[:200]})", flush=True)

    # --cache.host_tier: tiered KV-cache A/B (ISSUE 17) — the Zipf corpus
    # resized to ~10x the HBM pool, run HBM-only vs with the pinned host
    # tier armed. The leaves perf_sentinel trends: hierarchy_hit_rate vs
    # hbm_hit_rate (higher-better), promote_p50/p99_ms and the TTFT split
    # (lower-better). Outside the headline window; DS_TPU_BENCH_HOST_TIER=0
    # skips, failure never costs the headline.
    if cache_line is not None and os.environ.get("DS_TPU_BENCH_HOST_TIER", "1") != "0":
        try:
            from tools.serving_load import host_tier_ab

            ht = host_tier_ab(on_tpu)
            on, off = ht["host_tier"], ht["hbm_only"]
            cache_line["host_tier"] = {
                "hierarchy_hit_rate": on["hierarchy_hit_rate"],
                "hbm_hit_rate": off["hbm_hit_rate"],
                "hit_rate_gain": ht["hit_rate_gain"],
                "token_parity": ht["token_parity"],
                "promote_p50_ms": on.get("promote_p50_ms"),
                "promote_p99_ms": on.get("promote_p99_ms"),
                "ttft_promoted_hit_p50_ms": (on["ttft_promoted_hit_ms"] or {}).get("p50_ms"),
                "ttft_miss_p50_ms": (on["ttft_miss_ms"] or {}).get("p50_ms"),
                "demotions": on["demotions"],
                "promotions": on["promotions"],
            }
            print(f"# host_tier: hierarchy_hit={on['hierarchy_hit_rate']} "
                  f"hbm_hit={off['hbm_hit_rate']} gain={ht['hit_rate_gain']} "
                  f"parity={ht['token_parity']} promote_p99={on.get('promote_p99_ms')}ms",
                  flush=True)
        except Exception as e:
            print(f"# WARNING: host_tier bench phase failed "
                  f"({type(e).__name__}: {str(e)[:200]})", flush=True)

    # --disagg: disaggregated prefill/decode A/B (ISSUE 18) — a decode-heavy
    # foreground stream measured under a pure-prefill background storm,
    # co-located mixed fleet vs ("prefill","decode") pools with the
    # host-tier KV handoff. The leaves perf_sentinel trends: foreground
    # TPOT/TTFT percentiles (lower-better), handoff_p50_ms and
    # handoff_fallback_rate (explicitly lower-better in its direction
    # table). Outside the headline window; DS_TPU_BENCH_DISAGG=0 skips,
    # failure never costs the headline.
    disagg_line = None
    if os.environ.get("DS_TPU_BENCH_DISAGG", "1") != "0":
        try:
            from tools.serving_load import disagg_ab

            da = disagg_ab(on_tpu)
            co, dg = da["colocated"], da["disagg"]
            disagg_line = {
                "fg_tpot_p99_colocated_ms": co["fg_tpot"].get("p99_ms"),
                "fg_tpot_p99_disagg_ms": dg["fg_tpot"].get("p99_ms"),
                "fg_ttft_p99_colocated_ms": co["fg_ttft"].get("p99_ms"),
                "fg_ttft_p99_disagg_ms": dg["fg_ttft"].get("p99_ms"),
                "tpot_p99_improved": da["tpot_p99_improved"],
                "token_parity": da["token_parity"],
                "migrated": dg["migrated"],
                "fallbacks": dg["fallbacks"],
                "blocks_moved": dg["blocks_moved"],
                "handoff_p50_ms": dg["handoff_p50_ms"],
                "handoff_fallback_rate": dg["handoff_fallback_rate"],
            }
            print(f"# disagg: fg_tpot_p99 {co['fg_tpot'].get('p99_ms')}ms -> "
                  f"{dg['fg_tpot'].get('p99_ms')}ms parity={da['token_parity']} "
                  f"migrated={dg['migrated']} fallbacks={dg['fallbacks']} "
                  f"handoff_p50={dg['handoff_p50_ms']}ms", flush=True)
        except Exception as e:
            print(f"# WARNING: disagg bench phase failed "
                  f"({type(e).__name__}: {str(e)[:200]})", flush=True)

    # --chaos: resilience drills (ISSUE 12) — the seeded training storm
    # (kill/stall/straggle/preempt/collective-delay with warm-remesh
    # restarts) and the serving replica-kill drill, reporting the drill
    # VERDICTS plus recovery-time p50 per arm. Outside the headline timed
    # window (the headline arms no chaos at all — the fire() points are
    # no-ops); DS_TPU_BENCH_CHAOS=0 skips, failure never costs the headline.
    chaos_line = None
    if os.environ.get("DS_TPU_BENCH_CHAOS", "1") != "0":
        try:
            from deepspeed_tpu.parallel import groups as _groups
            from tools.chaos_drill import serving_drill, training_drill

            _groups.reset()
            tr = training_drill(seed=7, steps=6)
            _groups.reset()
            sv = serving_drill(seed=3, n_requests=12, n_replicas=2)
            _groups.reset()
            chaos_line = {
                "training": {
                    "verdicts": {k: tr[k] for k in ("loss_parity", "resumed_tags_valid",
                                                    "stall_dumps_match")},
                    "events": tr["events"],
                    "restarts": tr["restarts"],
                    "warm_resumes": tr["warm_resumes"],
                    "recovery_ms_p50": tr["recovery_ms_p50"],
                },
                "serving": {
                    "verdicts": {k: sv[k] for k in ("zero_unreported", "retry_after_on_503",
                                                    "replica_failure_counted",
                                                    "readyz_flipped", "recovered")},
                    "recovery_ms": sv["recovery_ms"],
                },
            }
            print(f"# chaos: train[parity={tr['loss_parity']} tags_valid="
                  f"{tr['resumed_tags_valid']} dumps={tr['stall_dumps_match']} "
                  f"recover_p50={tr['recovery_ms_p50']}ms] serve[unreported="
                  f"{0 if sv['zero_unreported'] else 'SOME'} "
                  f"recover={sv['recovery_ms']}ms]", flush=True)
        except Exception as e:
            print(f"# WARNING: chaos bench phase failed "
                  f"({type(e).__name__}: {str(e)[:200]})", flush=True)

    # --tenants: tenant-scoped metering & fairness (ISSUE 15) — the
    # multi-tenant closed-loop HTTP workload (Zipf tenant shares + one
    # adversarial hot tenant) with the metering plane armed: fairness
    # index (higher-better for the sentinel), per-tenant hit rates and
    # spend, hot-tenant compute share, starvation count. Per-tenant rows
    # are ACCOUNTING fields (perf_sentinel treats the block as neutral
    # except fairness_index). Outside the headline timed window;
    # DS_TPU_BENCH_TENANTS=0 skips, failure never costs the headline.
    tenants_line = None
    if os.environ.get("DS_TPU_BENCH_TENANTS", "1") != "0":
        try:
            from tools.serving_load import multi_tenant_bench

            mt = multi_tenant_bench(on_tpu)
            tenants_line = {k: mt[k] for k in
                            ("fairness_index", "starvations", "tenants_seen",
                             "hot_tenant_compute_share", "rest_ttft_p99_ms",
                             "achieved_rps", "shed_rate", "per_tenant")}
            print(f"# tenants: fairness={mt['fairness_index']} "
                  f"hot_compute_share={mt['hot_tenant_compute_share']} "
                  f"starvations={mt['starvations']} "
                  f"rest_ttft_p99={mt['rest_ttft_p99_ms']}ms "
                  f"(n={mt['tenants_seen']} tenants)", flush=True)
        except Exception as e:
            print(f"# WARNING: tenants bench phase failed "
                  f"({type(e).__name__}: {str(e)[:200]})", flush=True)

    # --control: self-driving serving A/B (ISSUE 19) — the same interactive
    # stream under a batch prefill storm, controller-off vs controller-on
    # (admission policy sheds the batch victim class live). The leaves
    # perf_sentinel trends: fg_{off,on}_miss_rate carry the _miss_rate
    # lower-better suffix; actuations is a neutral accounting field.
    # Outside the headline timed window; DS_TPU_BENCH_CONTROL=0 skips,
    # failure never costs the headline.
    control_line = None
    if os.environ.get("DS_TPU_BENCH_CONTROL", "1") != "0":
        try:
            from tools.serving_load import control_ab

            ca = control_ab(on_tpu)
            off, on = ca["control_off"], ca["control_on"]
            control_line = {
                "ttft_target_ms": ca["ttft_target_ms"],
                "fg_off_miss_rate": off["fg_miss_rate"],
                "fg_on_miss_rate": on["fg_miss_rate"],
                "fg_ttft_p99_off_ms": off["fg_ttft"].get("p99_ms"),
                "fg_ttft_p99_on_ms": on["fg_ttft"].get("p99_ms"),
                "slo_miss_improved": ca["slo_miss_improved"],
                "token_parity": ca["token_parity"],
                "actuations": on["actuations"],
                "deferred": on["deferred"],
                "controller_errors": on["errors"],
                "decisions_justified": on["decisions_justified"],
            }
            print(f"# control: fg_miss_rate {off['fg_miss_rate']} -> "
                  f"{on['fg_miss_rate']} (target {ca['ttft_target_ms']}ms) "
                  f"improved={ca['slo_miss_improved']} parity={ca['token_parity']} "
                  f"actuations={on['actuations']}", flush=True)
        except Exception as e:
            print(f"# WARNING: control bench phase failed "
                  f"({type(e).__name__}: {str(e)[:200]})", flush=True)

    # --timeline: causal timeline rounds (ISSUE 20) — the same disagg
    # workload captured clean vs under a seeded 80ms handoff stall, each
    # round's assembled timelines written to disk and diffed with
    # tools/trace_explain.py. The leaves perf_sentinel trends are neutral
    # accounting fields (timeline. prefix); the attribution verdict
    # (dominant stage = broker_verify) is the honesty check. Outside the
    # headline window; DS_TPU_BENCH_TIMELINE=0 skips, failure never costs
    # the headline.
    timeline_line = None
    if os.environ.get("DS_TPU_BENCH_TIMELINE", "1") != "0":
        try:
            from tools.serving_load import timeline_rounds

            tr = timeline_rounds(on_tpu)
            base, stalled = tr["rounds"]["base"], tr["rounds"]["stalled"]
            timeline_line = {
                "n_timelines_base": base["n_timelines"],
                "n_timelines_stalled": stalled["n_timelines"],
                "migrated_base": base["migrated"],
                "migrated_stalled": stalled["migrated"],
                "migrated_coverage_ok_frac": base["migrated_coverage_ok_frac"],
                "chaos_stalls": stalled["chaos_stalls"],
                "delta_e2e_ms": tr["explain"]["delta_e2e_ms"],
                "dominant_stage": tr["explain"]["dominant_stage"],
                "dominant_cause": tr["explain"]["dominant_cause"],
                "rounds_dir": tr["out_dir"],
            }
            print(f"# timeline: {base['n_timelines']}/{stalled['n_timelines']} "
                  f"timelines (migrated {base['migrated']}/{stalled['migrated']}, "
                  f"coverage {base['migrated_coverage_ok_frac']}); stall delta "
                  f"{tr['explain']['delta_e2e_ms']}ms -> "
                  f"{tr['explain']['dominant_stage']}/"
                  f"{tr['explain']['dominant_cause']}", flush=True)
        except Exception as e:
            print(f"# WARNING: timeline bench phase failed "
                  f"({type(e).__name__}: {str(e)[:200]})", flush=True)

    # --kernels: raw-speed microbench A/Bs (q-tiled paged attention, explicit
    # ZeRO-3 overlap, tuned-vs-default flash tiles). Outside the headline
    # timed window; DS_TPU_BENCH_KERNELS=0 skips, failure never costs the
    # headline (each sub-block is guarded inside bench_kernels).
    kernels_line = None
    if os.environ.get("DS_TPU_BENCH_KERNELS", "1") != "0":
        try:
            kernels_line = bench_kernels(on_tpu)
            if kernels_line.get("paged_attention"):
                pa = kernels_line["paged_attention"]
                print(f"# kernels: paged q_tile={pa['q_tile']} speedup={pa['speedup']}x; "
                      f"overlap={kernels_line.get('zero3_overlap', {}).get('speedup')}x",
                      flush=True)
        except Exception as e:
            print(f"# WARNING: kernels bench phase failed "
                  f"({type(e).__name__}: {str(e)[:200]})", flush=True)

    if trace_path:
        # eager 3-call path demo: genuine fwd/bwd/step spans plus an eager
        # device collective (comm/all_reduce span with real bytes + bandwidth)
        try:
            trace_demo(seq=128)
        except Exception as e:
            print(f"# WARNING: trace demo failed ({type(e).__name__}: {e}); "
                  "trace keeps the train_batch/serving/compile spans", flush=True)

    n_params = model.num_params()
    # fwd+bwd ≈ 6 FLOPs/param/token + attention term (PaLM MFU convention)
    from deepspeed_tpu.profiling.flops_profiler import training_flops_per_token

    flops_per_token = training_flops_per_token(n_params, num_layers=cfg.num_layers,
                                               hidden_size=cfg.hidden_size, seq_len=seq)
    peak = 197e12 if on_tpu else 1e12  # v5e bf16 peak
    mfu = tok_per_sec_per_chip * flops_per_token / peak
    mfu4 = gas4_tps * flops_per_token / peak
    line = {
        "metric": "train_tokens_per_sec_per_chip",
        "value": round(tok_per_sec_per_chip, 1),
        "unit": "tokens/s/chip",
        # MFU ratios are v5e-peak-relative: null on the CPU fallback so the
        # JSON cannot be misread as a perf regression (VERDICT r4)
        "vs_baseline": round(mfu / 0.54, 4) if on_tpu else None,
        "gas4_vs_baseline": round(mfu4 / 0.54, 4) if on_tpu else None,
        # single-chip proxy disclosure (round-2 advisor): the 7B/70B-class
        # BASELINE workloads need a pod; this measures MFU on the largest
        # llama-arch model one v5e chip fits, against the same 54% bar
        "workload": f"{n_params/1e6:.1f}M llama-arch, seq {seq}, ZeRO-3, single v5e chip",
        "serving": {k: serving[k] for k in ("value", "ttft_p50_ms", "vs_baseline")
                    if k in serving} | ({"prefix_cache": serving["prefix_cache"]}
                                       if "prefix_cache" in serving else {})
                                     | ({"gateway": serving["gateway"]}
                                        if "gateway" in serving else {})
                                     | ({"speculative": serving["speculative"]}
                                        if "speculative" in serving else {}),
        # achieved MFU fraction (null on the CPU fallback — the v5e-peak
        # denominator would read as a 99.9% regression, the VERDICT r4 trap)
        "mfu": round(mfu, 4) if on_tpu else None,
        # p50 host time train_batch blocked on data during the timed window
        # (stack+reshape+H2D placement on the batch= path)
        "input_wait_ms_p50": round(input_wait_p50, 3),
        "on_tpu": on_tpu,
        # machine-checkable comparability stamp (BENCH_r04/r05 lesson):
        # cross-round tooling compares `value` ONLY within one backend+chip
        **backend_stamp(on_tpu),
    }
    if kernels_line is not None:
        line["kernels"] = kernels_line
    # DS_TPU_BENCH_BASELINE=<prior BENCH_rXX.json or raw line>: attach the
    # round-over-round ratio — or the refusal — computed by the same rules
    baseline_path = os.environ.get("DS_TPU_BENCH_BASELINE")
    if baseline_path:
        try:
            line["vs_prev"] = compare_to_baseline(line, baseline_path)
        except Exception as e:  # belt-and-braces: the headline always prints
            line["vs_prev"] = {"refused": f"comparison failed: {type(e).__name__}"}
    if prefetch_line is not None:
        line["prefetch"] = prefetch_line
    if ckpt_line is not None:
        line["checkpoint"] = ckpt_line
    if health_line is not None:
        line["health"] = health_line
    if chaos_line is not None:
        line["chaos"] = chaos_line
    if cache_line is not None:
        line["cache"] = cache_line
    if disagg_line is not None:
        line["disagg"] = disagg_line
    if memory_line is not None:
        line["memory"] = memory_line
    if tenants_line is not None:
        line["tenants"] = tenants_line
    if control_line is not None:
        line["control"] = control_line
    if timeline_line is not None:
        line["timeline"] = timeline_line
    if not on_tpu:
        line["tpu_unavailable_reason"] = tpu_error or "no TPU device visible"
    if gate_note:
        line["kernel_gate_warning"] = gate_note
    # goodput block: every bench second attributed (training ledger spans
    # the whole child; the serving ledger covers the timed serving phases),
    # plus the sentinel's steady-state-recompile verdict
    try:
        from deepspeed_tpu.monitor.goodput import conservation_ok, get_goodput

        rep = get_goodput().report()
        gp_line = {"unexpected_compiles": {
            src: sc["unexpected_compiles"] for src, sc in rep["sentinel"].items()}}
        for scope, led_rep in [("train", rep["train"])] + sorted(rep["serving"].items()):
            if led_rep is None:
                continue
            gp_line[scope] = {
                "wall_s": led_rep["wall_s"],
                "fractions": led_rep["fractions"],
                "unattributed_s": led_rep["unattributed_s"],
                "conserved": conservation_ok(led_rep),
            }
        line["goodput"] = gp_line
        tr_fr = gp_line.get("train", {}).get("fractions", {})
        top = sorted(((v, k) for k, v in tr_fr.items() if v > 0), reverse=True)[:4]
        print("# goodput: train[" + " ".join(f"{k}={v:.0%}" for v, k in top)
              + "] unexpected_compiles=" + " ".join(
                  f"{s}:{n}" for s, n in gp_line["unexpected_compiles"].items()),
              flush=True)
    except Exception as e:  # the headline line never forfeits to telemetry
        print(f"# WARNING: goodput block failed ({type(e).__name__}: {e})", flush=True)
    # roofline block: the cost-vs-measured verdict for every post-warmup
    # compiled bucket (train step, serving put/decode/verify buckets, tuned
    # Pallas entrypoints) + the top gap-to-roof offenders — the buckets the
    # online re-tuner should attack (ROADMAP 5c). On CPU the peaks are null
    # and every verdict reads `unknown` (disclosed, never guessed).
    if os.environ.get("DS_TPU_BENCH_ROOFLINE", "1") != "0":
        try:
            from deepspeed_tpu.monitor.roofline import get_roofline

            rrep = get_roofline().report()
            gaps = sorted(((r["gap_to_roof"], b) for b, r in rrep["buckets"].items()
                           if r["gap_to_roof"] is not None), reverse=True)[:5]
            line["roofline"] = {
                "peak_flops": rrep["peak_flops"], "peak_hbm_bw": rrep["peak_hbm_bw"],
                "buckets": rrep["buckets"],
                "top_gap": [{"bucket": b, "gap_to_roof": g,
                             "verdict": rrep["buckets"][b]["verdict"]} for g, b in gaps],
            }
            counts = {}
            for r in rrep["buckets"].values():
                counts[r["verdict"]] = counts.get(r["verdict"], 0) + 1
            print("# roofline: " + " ".join(f"{v}={n}" for v, n in sorted(counts.items()))
                  + (f" worst={gaps[0][1]}@{gaps[0][0]}x" if gaps else ""), flush=True)
        except Exception as e:
            print(f"# WARNING: roofline block failed ({type(e).__name__}: {e})", flush=True)
    if trace_path:
        from deepspeed_tpu.comm.comm import comms_logger
        from deepspeed_tpu.monitor.trace import get_tracer

        if comms_logger.comms_dict:
            line["comms"] = comms_logger.summary()
        line["trace"] = trace_path
        get_tracer().close()
    print(json.dumps(line))


def _run_child(extra_env, timeout):
    """Run this script in child mode; returns (rc, stdout, stderr)."""
    env = dict(os.environ)
    env.update(extra_env)
    env["DS_TPU_BENCH_CHILD"] = "1"
    try:
        proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                              capture_output=True, text=True, timeout=timeout, env=env)
        return proc.returncode, proc.stdout or "", proc.stderr or ""
    except subprocess.TimeoutExpired as e:
        out = e.stdout.decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
        err = e.stderr.decode() if isinstance(e.stderr, bytes) else (e.stderr or "")
        return -9, out, err + f"\n[supervisor] child timed out after {timeout}s"


def _forward(stdout):
    """Re-emit a child's JSON/comment lines; returns the LAST parseable
    metric line (str) or None."""
    last = None
    for ln in stdout.splitlines():
        ln = ln.rstrip()
        if ln.startswith("{"):
            try:
                if "metric" in json.loads(ln):
                    last = ln
            except ValueError:
                continue
            print(ln, flush=True)
        elif ln.startswith("#"):
            print(ln, flush=True)
    return last


def _tpu_holder_diagnostics():
    """Best-effort census of anything that could explain an unreachable chip:
    processes holding TPU device files / libtpu lockfiles, and the lockfiles
    themselves. Distinguishes "tunnel down" from "chip held by a stale
    process" in the disclosed reason (VERDICT r4: the 3x420s probes recorded
    only 'timed out')."""
    import glob

    notes = []
    for lock in glob.glob("/tmp/libtpu_lockfile*") + glob.glob("/tmp/tpu_logs*"):
        notes.append(f"lockfile present: {lock}")
    me = os.getpid()
    try:
        for pid_dir in glob.glob("/proc/[0-9]*"):
            pid = int(os.path.basename(pid_dir))
            if pid == me:
                continue
            try:
                fds = os.listdir(os.path.join(pid_dir, "fd"))
            except OSError:
                continue
            for fd in fds:
                try:
                    target = os.readlink(os.path.join(pid_dir, "fd", fd))
                except OSError:
                    continue
                if any(k in target for k in ("accel", "libtpu", "vfio")):
                    try:
                        with open(os.path.join(pid_dir, "cmdline")) as f:
                            cmd = f.read().replace("\0", " ").strip()[:120]
                    except OSError:
                        cmd = "?"
                    notes.append(f"pid {pid} holds {target} ({cmd})")
                    break
    except Exception as e:  # /proc scan is best-effort only
        notes.append(f"holder scan failed: {type(e).__name__}")
    return notes


def _relay_port_check():
    """Instant tunnel diagnostic learned in round 5: the axon PJRT plugin
    rides a local stdio relay whose listeners die permanently when the
    tunnel wedges (two concurrent clients, or remote-side failure). A TCP
    connect to the relay ports distinguishes 'tunnel down' (refused — skip
    the 60s jax probe entirely; jax HANGS rather than fails on a half-dead
    tunnel) from 'relay up' in milliseconds. Best-effort: unknown layouts
    return None (no judgement)."""
    import socket

    if "axon" not in os.environ.get("JAX_PLATFORMS", ""):
        # not the relay layout (e.g. a direct-attached TPU VM): refused
        # ports mean nothing here — let the real jax probe decide
        return None, "axon relay not configured"
    ports = (8082, 8083, 8087)
    refused = 0
    for port in ports:
        s = socket.socket()
        s.settimeout(2)
        try:
            s.connect(("127.0.0.1", port))
            return True, f"relay port {port} accepting"
        except ConnectionRefusedError:
            refused += 1
        except OSError:
            pass
        finally:
            s.close()
    if refused == len(ports):
        return False, f"axon relay ports {ports} all refused connection (tunnel listeners dead)"
    return None, "relay port state inconclusive"


def _probe_tpu(probe_timeout):
    """One cheap subprocess probe. Returns (ok, reason) where reason carries
    the actual PJRT stderr excerpt, not just 'timed out'."""
    relay_ok, relay_note = _relay_port_check()
    if relay_ok is False:
        return False, relay_note
    probe_src = ("import jax, json; d = jax.devices(); "
                 "print(json.dumps({'platform': d[0].platform, 'n': len(d)}))")
    try:
        proc = subprocess.run([sys.executable, "-c", probe_src], capture_output=True,
                              text=True, timeout=probe_timeout, env=dict(os.environ))
        if proc.returncode == 0 and '"platform": "tpu"' in proc.stdout:
            return True, ""
        detail = (proc.stderr or proc.stdout).strip()
        return False, f"probe rc={proc.returncode}: ...{detail[-400:]}" if detail else "probe: no output"
    except subprocess.TimeoutExpired as e:
        err = e.stderr.decode() if isinstance(e.stderr, bytes) else (e.stderr or "")
        detail = f"; partial stderr: ...{err.strip()[-300:]}" if err.strip() else ""
        return False, f"probe timed out after {probe_timeout}s{detail}"


def supervise():
    """Never exit nonzero, never leave the driver without a final JSON line.

    Probe strategy (VERDICT r4: r4's 3x420s up-front probes burned 21 min and
    recorded only 'timed out'): ONE cheap diagnostic probe (default 60s) with
    PJRT stderr + stale-holder capture. If the chip is absent, the CPU
    fallback bench runs IMMEDIATELY (a real disclosed line lands early), then
    the supervisor keeps re-probing across the remaining bench window — the
    moment a chip appears, the on-TPU bench runs and its lines supersede
    (last JSON line wins)."""
    # 0) provisional line FIRST: if an external timeout kills this process
    #    mid-probe (the one failure mode the supervisor itself cannot
    #    outlive), the captured stdout still ends in parseable JSON. Every
    #    later real line supersedes it as the last line.
    print(json.dumps({"metric": "train_tokens_per_sec_per_chip", "value": 0.0,
                      "unit": "tokens/s/chip", "vs_baseline": None, "on_tpu": False,
                      "provisional": True,
                      "error": "bench was killed externally before completing; see tail"}),
          flush=True)
    probe_timeout = int(os.environ.get("DS_TPU_BENCH_PROBE_TIMEOUT", "60"))
    reprobe_window = int(os.environ.get("DS_TPU_BENCH_REPROBE_WINDOW", "900"))
    reprobe_interval = int(os.environ.get("DS_TPU_BENCH_REPROBE_INTERVAL", "90"))

    tpu_ok, tpu_error = _probe_tpu(probe_timeout)
    if not tpu_ok:
        diag = _tpu_holder_diagnostics()
        if diag:
            tpu_error += " | " + "; ".join(diag[:4])
        print(f"# bench supervisor: TPU probe failed: {tpu_error}", flush=True)

    def run_tpu_bench():
        """TPU child with one retry; returns the final metric line or None."""
        for timeout in (3000, 3000):
            rc, out, err = _run_child({}, timeout)
            if rc == 0:
                line = _forward(out)
                if line:
                    return line
            last = (err.strip().splitlines() or ["?"])[-1][:300]
            print(f"# bench supervisor: TPU child rc={rc}: {last}", flush=True)
        return None

    if tpu_ok and run_tpu_bench():
        return

    # CPU fallback NOW — a real (disclosed) line lands early no matter what
    cpu_reason = ("TPU bench child failed after successful probe" if tpu_ok
                  else tpu_error or "TPU probe failed")
    rc, out, err = _run_child({"JAX_PLATFORMS": "cpu",
                               "DS_TPU_BENCH_TPU_ERROR": cpu_reason}, 1500)
    final_line = (rc == 0 and _forward(out)) or None
    if not final_line:
        last_err = (err.strip().splitlines() or ["?"])[-1][:300]
        print(f"# bench supervisor: CPU child rc={rc}: {last_err}", flush=True)
        final_line = json.dumps({
            "metric": "train_tokens_per_sec_per_chip", "value": 0.0,
            "unit": "tokens/s/chip", "vs_baseline": None, "on_tpu": False,
            "error": f"all bench children failed; tpu: {tpu_error}; last: {last_err}"})
        print(final_line, flush=True)

    # keep watching for a chip; a late TPU line supersedes the CPU fallback
    # (the driver keeps the LAST line). The window starts NOW — measuring it
    # from supervisor start would let a slow CPU fallback consume it entirely
    # and the loop would never probe (code-review r5 finding).
    t_reprobe = time.time()
    reprobed = False
    while not tpu_ok and time.time() - t_reprobe < reprobe_window:
        time.sleep(min(reprobe_interval,
                       max(1, int(reprobe_window - (time.time() - t_reprobe)))))
        reprobed = True
        tpu_ok, retry_err = _probe_tpu(probe_timeout)
        if tpu_ok:
            print("# bench supervisor: TPU became reachable on re-probe; "
                  "running on-chip bench", flush=True)
            if run_tpu_bench():
                return
            break
        print(f"# bench supervisor: re-probe failed: {retry_err[:200]}", flush=True)
    if reprobed:
        # the loop printed comment lines after the winning JSON — re-emit it
        # so stdout still ENDS in parseable JSON (the supervisor's contract)
        print(final_line, flush=True)


if __name__ == "__main__":
    # --trace OUT.jsonl: Chrome-trace/Perfetto JSONL artifact (README
    # "Observability"). Parsed in both supervisor and child mode; the
    # supervisor forwards it to children through the environment.
    if "--trace" in sys.argv:
        i = sys.argv.index("--trace")
        if i + 1 >= len(sys.argv):
            print("usage: bench.py [--trace OUT.jsonl] [--prefetch]", file=sys.stderr)
            sys.exit(2)
        os.environ["DS_TPU_BENCH_TRACE"] = os.path.abspath(sys.argv[i + 1])
    # --prefetch: add the async-input-pipeline A/B (sync vs prefetched input
    # wait + throughput) to the final JSON; forwarded to children via env
    if "--prefetch" in sys.argv:
        os.environ["DS_TPU_BENCH_PREFETCH"] = "1"
    # --ckpt: add the checkpoint-plane A/B (per-save blocked ms, sync full
    # write vs async host-snapshot + background writer) to the final JSON
    if "--ckpt" in sys.argv:
        os.environ["DS_TPU_BENCH_CKPT"] = "1"
    # --history [DIR] [--out V.json] [--threshold R] [--strict]: don't run a
    # bench — read the BENCH_r*.json round trajectory on disk through
    # tools/perf_sentinel.py and print its regression verdicts
    if "--history" in sys.argv:
        sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools"))
        from perf_sentinel import main as _sentinel_main

        sys.exit(_sentinel_main(sys.argv[sys.argv.index("--history") + 1:]))
    if os.environ.get("DS_TPU_BENCH_CHILD") == "1":
        run_bench()
    else:
        supervise()
