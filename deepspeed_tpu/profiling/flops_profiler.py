"""Flops profiler.

Analog of the reference ``profiling/flops_profiler/profiler.py`` (1,244 LoC)
which monkey-patches torch functional ops to count MACs per module. The
TPU-native mechanism is XLA's own cost analysis: jit-compile the step, ask the
compiled executable for ``cost_analysis()`` (flops, bytes accessed) — exact
for the compiled program, no patching. ``get_model_profile`` mirrors the
reference's public helper of the same name.
"""

import jax

from ..utils.logging import log_dist


def analyze_fn(fn, *example_args, **example_kwargs):
    """Compile ``fn`` and return {'flops': float, 'bytes accessed': float, ...}."""
    lowered = jax.jit(fn).lower(*example_args, **example_kwargs)
    compiled = lowered.compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, list):  # older jax returns [dict]
        cost = cost[0] if cost else {}
    return dict(cost or {})


class FlopsProfiler:
    """Engine-integrated profiler (reference ``FlopsProfiler:28``)."""

    def __init__(self, engine=None):
        self.engine = engine
        self.profile = {}

    def start_profile(self, ignore_list=None):
        pass  # compilation-based: nothing to hook

    def stop_profile(self):
        pass

    def get_total_flops(self, as_string=False):
        f = self.profile.get("flops", 0.0)
        return _num_to_string(f) + "FLOPS" if as_string else f

    def get_total_params(self, as_string=False):
        p = self.profile.get("params", 0.0)
        return _num_to_string(p) if as_string else p

    def profile_step(self, step_fn, *args):
        self.profile.update(analyze_fn(step_fn, *args))
        return self.profile

    def print_model_profile(self, profile_step=1, module_depth=-1, top_modules=1, detailed=True, output_file=None):
        log_dist(f"flops profile: {self.profile}", ranks=[0])


def get_model_profile(model, args=(), kwargs=None, print_profile=True, detailed=True, as_string=True, **_):
    """Reference public helper: profile one forward of ``model``.

    ``model`` follows the framework protocol (init/apply)."""
    import jax.numpy as jnp

    rng = jax.random.PRNGKey(0)
    params = jax.eval_shape(lambda r: model.init(r, None), rng)
    n_params = sum(int(jnp.prod(jnp.asarray(x.shape))) for x in jax.tree_util.tree_leaves(params))
    real_params = jax.jit(lambda r: model.init(r, None))(rng)
    cost = analyze_fn(model.apply, real_params, *args, **(kwargs or {}))
    flops = cost.get("flops", 0.0)
    if print_profile:
        log_dist(f"params={_num_to_string(n_params)} fwd flops={_num_to_string(flops)}", ranks=[0])
    if as_string:
        return _num_to_string(flops), _num_to_string(n_params)
    return flops, n_params


def _num_to_string(num, precision=2):
    for unit, div in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(num) >= div:
            return f"{num / div:.{precision}f} {unit}"
    return str(num)
