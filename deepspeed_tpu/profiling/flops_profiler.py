"""Flops profiler.

Analog of the reference ``profiling/flops_profiler/profiler.py`` (1,244 LoC)
which monkey-patches torch functional ops to count MACs per module. The
TPU-native mechanism is XLA's own cost analysis: jit-compile the step, ask the
compiled executable for ``cost_analysis()`` (flops, bytes accessed) — exact
for the compiled program, no patching. ``get_model_profile`` mirrors the
reference's public helper of the same name.
"""

import jax

from ..utils.logging import log_dist


def training_flops_per_token(n_params, num_layers=None, hidden_size=None, seq_len=None):
    """Model training FLOPs per token, PaLM convention: 6 FLOPs per parameter
    (fwd 2 + bwd 4) plus the attention score/context term when the
    architecture is known. The numerator of every MFU this repo reports
    (``monitor/metrics.py::compute_mfu``, engine step telemetry, bench.py)."""
    flops = 6.0 * float(n_params)
    if num_layers and hidden_size and seq_len:
        flops += 12.0 * num_layers * hidden_size * seq_len
    return flops


def analyze_fn(fn, *example_args, **example_kwargs):
    """Compile ``fn`` and return {'flops': float, 'bytes accessed': float, ...}.
    Extraction is shared with the roofline plane (``monitor/roofline.py``) so
    the point-wise profiler and the per-bucket verdicts can never read
    different keys out of the same executable."""
    from ..monitor.roofline import cost_analysis_dict

    lowered = jax.jit(fn).lower(*example_args, **example_kwargs)
    return cost_analysis_dict(lowered.compile())


def build_module_profile(model, batch_size: int, seq_len: int) -> dict:
    """Per-module MACs/params tree for a ``TransformerLM`` (reference
    ``profiler.py:507-760`` builds the same tree via torch functional hooks;
    here the MAC counts come from the op shapes directly — the identical
    arithmetic — with params counted exactly from the param subtrees, and
    ``total_flops_xla`` as the compiled-program ground truth the analytic
    total is validated against in ``tests/``).

    Returns a nested dict: each node has ``params``, ``macs``, ``flops``
    (2*MACs + elementwise terms) and optional ``children``.
    """
    import numpy as np

    cfg = model.config
    B, S = batch_size, seq_len
    H, F = cfg.hidden_size, cfg.intermediate_size
    nq, nkv, d = cfg.num_heads, cfg.num_kv_heads or cfg.num_heads, cfg.head_dim
    L, V = cfg.num_layers, cfg.vocab_size
    T = B * S

    params = jax.eval_shape(lambda r: model.init(r, None), jax.random.PRNGKey(0))

    def count_params(subtree):
        return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(subtree))

    def node(name, macs, p, elementwise=0.0, children=None):
        n = {"name": name, "macs": float(macs), "params": int(p),
             "flops": 2.0 * macs + elementwise}
        if children:
            n["children"] = children
            n["macs"] = sum(c["macs"] for c in children)
            n["flops"] = sum(c["flops"] for c in children)
        return n

    blocks_p = params.get("blocks", {})
    per_layer_p = count_params(blocks_p) // max(L, 1)

    b = 1 if cfg.use_bias else 0
    qkv = node("qkv_proj", T * H * (nq + 2 * nkv) * d,
               H * (nq + 2 * nkv) * d + b * (nq + 2 * nkv) * d)
    scores = node("attn_scores", T * S * nq * d, 0)
    context = node("attn_context", T * S * nq * d, 0)
    out_proj = node("out_proj", T * nq * d * H, nq * d * H + b * H)
    attn = node("attention", 0, 0, children=[qkv, scores, context, out_proj])
    attn["params"] = qkv["params"] + out_proj["params"]

    gate_macs = T * H * F if cfg.mlp == "swiglu" else 0
    mlp = node("mlp", T * H * F + gate_macs + T * F * H,
               H * F * (2 if cfg.mlp == "swiglu" else 1) + F * H + b * (F + H),
               elementwise=4.0 * T * F)
    # rmsnorm: scale only; layernorm: scale + bias
    norm_p = 2 * H * (2 if cfg.norm == "layernorm" else 1)
    norms = node("layernorms", 0, norm_p, elementwise=2 * 5.0 * T * H)
    layer = node("decoder_layer", 0, 0, children=[attn, mlp, norms])
    layer["params"] = per_layer_p

    blocks = {"name": f"blocks (x{L})", "params": count_params(blocks_p),
              "macs": L * layer["macs"], "flops": L * layer["flops"],
              "children": [layer]}

    embed = node("embed", 0, count_params(params.get("embed", {}))
                 + count_params(params.get("pos_embed", {})), elementwise=float(T * H))
    final_norm = node("final_norm", 0, count_params(params.get("final_norm", {})),
                      elementwise=5.0 * T * H)
    unembed = node("lm_head", T * H * V,
                   0 if cfg.tie_embeddings else count_params(params.get("lm_head", {})))

    children = [embed, blocks, final_norm, unembed]
    root = {"name": type(model).__name__, "params": count_params(params),
            "macs": sum(c["macs"] for c in children),
            "flops": sum(c["flops"] for c in children),
            "children": children,
            "batch_size": B, "seq_len": S}
    return root


def render_module_profile(root: dict, depth: int = -1) -> str:
    """Reference ``print_model_profile`` rendering: one line per module with
    params, MACs, fwd FLOPs and the share of the model total."""
    total = max(root["flops"], 1.0)
    lines = [f"{'module':<28} {'params':>10} {'MACs':>12} {'fwd FLOPs':>12} {'% fwd':>7}"]

    def walk(n, indent, d):
        lines.append(f"{'  ' * indent + n['name']:<28} {_num_to_string(n['params']):>10} "
                     f"{_num_to_string(n['macs']):>12} {_num_to_string(n['flops']):>12} "
                     f"{100.0 * n['flops'] / total:>6.1f}%")
        if d != 0:
            for c in n.get("children", ()):
                walk(c, indent + 1, d - 1)

    walk(root, 0, depth)
    return "\n".join(lines)


class FlopsProfiler:
    """Engine-integrated profiler (reference ``FlopsProfiler:28``).

    ``start_profile`` arms the profiler (and stamps a wall-clock origin);
    ``profile_step`` records the compiled step's XLA cost analysis;
    ``stop_profile`` freezes the captured numbers; ``print_model_profile``
    renders the per-module tree when a model was attached."""

    def __init__(self, engine=None, model=None):
        self.engine = engine
        self.model = model or (engine is not None and getattr(engine, "module", None)) or None
        self.profile = {}
        self.module_profile = None
        self._active = False
        self._t0 = None

    def start_profile(self, ignore_list=None):
        import time

        self._active = True
        self._t0 = time.time()
        self.profile = {}
        self.module_profile = None

    def stop_profile(self):
        import time

        if self._active and self._t0 is not None:
            self.profile.setdefault("wall_seconds", time.time() - self._t0)
        self._active = False

    def end_profile(self):
        self.profile = {}
        self.module_profile = None
        self._active = False

    def get_total_flops(self, as_string=False):
        f = self.profile.get("flops", 0.0)
        return _num_to_string(f) + "FLOPS" if as_string else f

    def get_total_params(self, as_string=False):
        p = self.profile.get("params", 0.0)
        if not p and self.module_profile:
            p = self.module_profile["params"]
        return _num_to_string(p) if as_string else p

    def get_total_duration(self, as_string=False):
        dt = self.profile.get("wall_seconds", 0.0)
        return f"{dt:.2f} s" if as_string else dt

    def profile_step(self, step_fn, *args):
        self.profile.update(analyze_fn(step_fn, *args))
        return self.profile

    def profile_model(self, batch_size: int, seq_len: int):
        """Build the per-module breakdown (requires an attached model)."""
        if self.model is None:
            raise ValueError("FlopsProfiler needs a model (or engine) for the per-module profile")
        self.module_profile = build_module_profile(self.model, batch_size, seq_len)
        self.profile.setdefault("params", self.module_profile["params"])
        return self.module_profile

    def print_model_profile(self, profile_step=1, module_depth=-1, top_modules=1,
                            detailed=True, output_file=None):
        parts = []
        if self.profile:
            parts.append(f"program totals (XLA cost analysis): {self.profile}")
        if self.module_profile is not None:
            parts.append(render_module_profile(self.module_profile,
                                               depth=module_depth if detailed else 1))
        text = "\n".join(parts) or "flops profile: (nothing captured — call "\
            "profile_step and/or profile_model first)"
        if output_file:
            with open(output_file, "w") as f:
                f.write(text + "\n")
        log_dist(text, ranks=[0])
        return text


def get_model_profile(model, args=(), kwargs=None, print_profile=True, detailed=True, as_string=True, **_):
    """Reference public helper: profile one forward of ``model``.

    ``model`` follows the framework protocol (init/apply)."""
    import jax.numpy as jnp

    rng = jax.random.PRNGKey(0)
    params = jax.eval_shape(lambda r: model.init(r, None), rng)
    n_params = sum(int(jnp.prod(jnp.asarray(x.shape))) for x in jax.tree_util.tree_leaves(params))
    real_params = jax.jit(lambda r: model.init(r, None))(rng)
    cost = analyze_fn(model.apply, real_params, *args, **(kwargs or {}))
    flops = cost.get("flops", 0.0)
    if print_profile:
        log_dist(f"params={_num_to_string(n_params)} fwd flops={_num_to_string(flops)}", ranks=[0])
    if as_string:
        return _num_to_string(flops), _num_to_string(n_params)
    return flops, n_params


def _num_to_string(num, precision=2):
    for unit, div in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(num) >= div:
            return f"{num / div:.{precision}f} {unit}"
    return str(num)
