"""Sparse self-attention modules and integration utilities.

Analog of the reference ``sparse_self_attention.py`` (:12
``SparseSelfAttention``), ``bert_sparse_self_attention.py`` (:10) and
``sparse_attention_utils.py`` (:14). Functional JAX style: modules are
plain callables over explicit params, layouts are trace-time constants
(see ``ops/pallas/block_sparse_attention.py`` for the kernel design).
"""

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..pallas.block_sparse_attention import block_sparse_attention
from .sparsity_config import SparsityConfig


class SparseSelfAttention:
    """Efficient sparse self-attention over a blocked sparsity layout
    (reference ``sparse_self_attention.py:12``).

    q/k/v: [B, num_heads, L, head_dim] (the reference's layout). The master
    layout is built once for ``max_seq_length`` and sliced per call-time L.
    No rank-0 broadcast is needed: layouts are deterministic on every host
    (seeded generators — see ``sparsity_config.py`` docstring).

    ``causal='auto'`` (default) applies the token-level causal mask inside
    the kernel iff the sparsity config is unidirectional. The reference
    instead requires the user to pass a dense causal ``attn_mask``
    (``softmax.py:80-86`` just adds it); set ``causal=False`` and pass
    ``attn_mask`` for bit-compatible behavior.
    """

    def __init__(self, sparsity_config=None, key_padding_mask_mode="add", attn_mask_mode="mul",
                 max_seq_length=2048, causal="auto"):
        self.sparsity_config = sparsity_config or SparsityConfig(num_heads=4)
        self.master_layout = np.asarray(self.sparsity_config.make_layout(max_seq_length))
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        if causal == "auto":
            causal = getattr(self.sparsity_config, "attention", "bidirectional") == "unidirectional"
        self.causal = bool(causal)
        self._lut_cache = {}  # L -> (layout, lut, nvalid); layouts are static

    def get_layout(self, L):
        if L % self.sparsity_config.block != 0:
            raise ValueError(
                f"Sequence Length, {L}, needs to be dividable by Block size "
                f"{self.sparsity_config.block}!")
        num_blocks = L // self.sparsity_config.block
        if num_blocks > self.master_layout.shape[1]:
            raise ValueError(f"Sequence length {L} exceeds max_seq_length "
                             f"{self.master_layout.shape[1] * self.sparsity_config.block}")
        return self.master_layout[:, :num_blocks, :num_blocks]

    def __call__(self, query, key, value, rpe=None, key_padding_mask=None, attn_mask=None):
        if query.shape != key.shape or key.shape != value.shape:
            raise NotImplementedError("only self-attention is supported for now")
        B, H, L, d = query.shape
        if L not in self._lut_cache:
            from ..pallas.block_sparse_attention import make_layout_lut

            layout = self.get_layout(L)
            self._lut_cache[L] = (layout,) + make_layout_lut(layout)
        layout, lut, nvalid = self._lut_cache[L]
        return block_sparse_attention(
            query, key, value, layout, self.sparsity_config.block,
            causal=self.causal, scale=1.0 / math.sqrt(d), rpe=rpe,
            key_padding_mask=key_padding_mask, attn_mask=attn_mask,
            key_padding_mask_mode=self.key_padding_mask_mode,
            attn_mask_mode=self.attn_mask_mode, lut=lut, nvalid=nvalid)


class BertSparseSelfAttention:
    """BERT self-attention block with sparse scores (reference
    ``bert_sparse_self_attention.py:10``): q/k/v projections followed by
    :class:`SparseSelfAttention`. ``init(rng, dtype=jnp.float32)`` returns the
    params pytree; ``__call__(params, hidden_states, attention_mask)``
    returns the context layer [B, L, hidden].

    ``key_padding_mask_mode`` picks the mask convention: the default
    ``'mul'`` expects HF-style 0/1 indicator masks (0 = padded, as produced
    by :meth:`SparseAttentionUtils.pad_to_block_size`); pass ``'add'`` when
    feeding pre-scaled additive masks (the ``(1-mask)*-10000`` extended
    form) — under 'mul' those would be interpreted INVERTED."""

    def __init__(self, num_attention_heads, hidden_size, sparsity_config=None,
                 max_seq_length=2048, key_padding_mask_mode="mul"):
        if hidden_size % num_attention_heads != 0:
            raise ValueError(
                f"The hidden size ({hidden_size}) is not a multiple of the number of attention "
                f"heads ({num_attention_heads})")
        self.num_attention_heads = num_attention_heads
        self.hidden_size = hidden_size
        self.attention_head_size = hidden_size // num_attention_heads
        cfg = sparsity_config or SparsityConfig(num_heads=num_attention_heads)
        self.sparse_self_attention = SparseSelfAttention(
            cfg, max_seq_length=max_seq_length, key_padding_mask_mode=key_padding_mask_mode)

    def init(self, rng, dtype=jnp.float32):
        keys = jax.random.split(rng, 3)
        std = 1.0 / math.sqrt(self.hidden_size)
        return {
            name: {"kernel": (jax.random.normal(k, (self.hidden_size, self.hidden_size), dtype) * std),
                   "bias": jnp.zeros((self.hidden_size,), dtype)}
            for name, k in zip(("query", "key", "value"), keys)
        }

    def _split_heads(self, x):
        B, L, _ = x.shape
        return x.reshape(B, L, self.num_attention_heads, self.attention_head_size).transpose(0, 2, 1, 3)

    def __call__(self, params, hidden_states, attention_mask=None):
        proj = {name: hidden_states @ p["kernel"] + p["bias"] for name, p in params.items()}
        q, k, v = (self._split_heads(proj[n]) for n in ("query", "key", "value"))
        ctx = self.sparse_self_attention(q, k, v, key_padding_mask=attention_mask)
        B, H, L, d = ctx.shape
        return ctx.transpose(0, 2, 1, 3).reshape(B, L, H * d)


class SparseAttentionUtils:
    """Helpers for integrating sparse attention into transformer models
    (reference ``sparse_attention_utils.py:14``). The reference mutates HF
    torch modules in place; here the equivalents operate on arrays / param
    pytrees, which is how JAX models are surgically edited."""

    @staticmethod
    def extend_position_embedding(pos_embedding, max_position):
        """Tile an existing [P, hidden] position-embedding table to cover
        ``max_position`` (reference :21 — 'build longer position embeddings
        by duplicating the original')."""
        P = pos_embedding.shape[0]
        if max_position <= P:
            return pos_embedding[:max_position]
        reps = -(-max_position // P)
        return jnp.tile(pos_embedding, (reps, 1))[:max_position]

    @staticmethod
    def update_tokenizer_model_max_length(tokenizer, max_position):
        """Reference :64 — bump the tokenizer's model_max_length."""
        tokenizer.model_max_length = max_position
        if hasattr(tokenizer, "init_kwargs"):
            tokenizer.init_kwargs["model_max_length"] = max_position
        return tokenizer

    @staticmethod
    def pad_to_block_size(block_size, input_ids=None, attention_mask=None, token_type_ids=None,
                          position_ids=None, inputs_embeds=None, pad_token_id=0,
                          model_embeddings=None):
        """Pad sequence-dim inputs up to a multiple of ``block_size``
        (reference :143). Returns ``(pad_len, input_ids, attention_mask,
        token_type_ids, position_ids, inputs_embeds)`` with None passed
        through. Padded attention_mask positions are 0 so the kernel's
        key-padding mask masks them out."""
        seq_len = None
        for t in (input_ids, attention_mask, token_type_ids, position_ids):
            if t is not None:
                seq_len = t.shape[1]
                break
        if seq_len is None and inputs_embeds is not None:
            seq_len = inputs_embeds.shape[1]
        if seq_len is None:
            raise ValueError("at least one sequence input must be provided")
        pad_len = (block_size - seq_len % block_size) % block_size
        if pad_len == 0:
            return 0, input_ids, attention_mask, token_type_ids, position_ids, inputs_embeds

        def pad_ids(t, value):
            return None if t is None else jnp.pad(t, ((0, 0), (0, pad_len)), constant_values=value)

        input_ids = pad_ids(input_ids, pad_token_id)
        attention_mask = pad_ids(attention_mask, 0)
        token_type_ids = pad_ids(token_type_ids, 0)
        position_ids = pad_ids(position_ids, 0)
        if inputs_embeds is not None:
            if model_embeddings is not None:
                pad_embed = model_embeddings[jnp.full((inputs_embeds.shape[0], pad_len),
                                                      pad_token_id)]
            else:
                pad_embed = jnp.zeros((inputs_embeds.shape[0], pad_len, inputs_embeds.shape[2]),
                                      inputs_embeds.dtype)
            inputs_embeds = jnp.concatenate([inputs_embeds, pad_embed.astype(inputs_embeds.dtype)],
                                            axis=1)
        return pad_len, input_ids, attention_mask, token_type_ids, position_ids, inputs_embeds

    @staticmethod
    def unpad_sequence_output(pad_len, sequence_output):
        """Reference :193 — strip the padding added by pad_to_block_size."""
        if pad_len > 0:
            sequence_output = sequence_output[:, :-pad_len]
        return sequence_output
