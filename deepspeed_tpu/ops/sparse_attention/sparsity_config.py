"""Block-sparsity layout generators for sparse self-attention.

Analog of the reference ``deepspeed/ops/sparse_attention/sparsity_config.py``
(727 LoC): the same six pattern classes with the same constructor surface —
``SparsityConfig`` base (:10), Dense (:63), Fixed (:95, Sparse-Transformer
style local+global), Variable (:239, random + per-window local + indexed
global), BigBird (:411, random + sliding + ITC-global), BSLongformer (:546,
sliding + indexed global), LocalSlidingWindow (:674).

TPU-first differences:
- layouts are **numpy** ``int8`` arrays, built vectorized (no per-element
  torch loops). They are host-side trace-time constants: the Pallas kernel
  compiles the layout's LUT into its scalar-prefetch arguments, so the
  layout never touches the device as a tensor.
- random patterns take an explicit ``seed`` (default 0) so every host in a
  pod derives the identical layout — the reference instead samples
  nondeterministically and broadcasts from rank 0
  (``sparse_self_attention.py:53``); with a seeded generator the broadcast
  is unnecessary.
"""

import numpy as np


class SparsityConfig:
    """Base class holding the shared properties of blocked sparsity patterns
    (reference ``sparsity_config.py:10``)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def setup_layout(self, seq_len):
        """Zero layout of shape (num_heads, num_blocks, num_blocks)."""
        if seq_len % self.block != 0:
            raise ValueError(
                f"Sequence Length, {seq_len}, needs to be dividable by Block size {self.block}!")
        num_blocks = seq_len // self.block
        return np.zeros((self.num_heads, num_blocks, num_blocks), dtype=np.int8)

    def check_and_propagate_first_head_layout(self, layout):
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout


class DenseSparsityConfig(SparsityConfig):
    """All blocks active — kept for comparison/comprehension (reference :63)."""

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Sparse-Transformer 'fixed' pattern (arxiv 1904.10509; reference :95):
    local windows of ``num_local_blocks`` plus ``num_global_blocks`` global
    representative blocks per window."""

    def __init__(self,
                 num_heads,
                 block=16,
                 different_layout_per_head=False,
                 num_local_blocks=4,
                 num_global_blocks=1,
                 attention="bidirectional",
                 horizontal_global_attention=False,
                 num_different_global_patterns=1):
        super().__init__(num_heads, block, different_layout_per_head)
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError(
                f"Number of blocks in a local window, {num_local_blocks}, "
                f"must be dividable by number of global blocks, {num_global_blocks}!")
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError('only "uni/bi-directional" attentions are supported for now!')
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError('only "bi-directional" attentions can support horizontal global attention!')
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError(
                "Number of different layouts cannot be more than one when you have set a single layout"
                " for all heads! Set different_layout_per_head to True.")
        if num_different_global_patterns > num_local_blocks // num_global_blocks:
            raise ValueError(
                f"Number of layout versions (num_different_global_patterns), "
                f"{num_different_global_patterns}, cannot be larger than number of local window "
                f"blocks divided by number of global blocks, "
                f"{num_local_blocks} / {num_global_blocks} = {num_local_blocks // num_global_blocks}!")
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def set_local_layout(self, h, layout):
        nb = layout.shape[1]
        r = np.arange(nb)
        same_window = (r[:, None] // self.num_local_blocks) == (r[None, :] // self.num_local_blocks)
        if self.attention == "unidirectional":
            same_window &= r[None, :] <= r[:, None]
        layout[h][same_window] = 1
        return layout

    def set_global_layout(self, h, layout):
        nb = layout.shape[1]
        L, G = self.num_local_blocks, self.num_global_blocks
        first = L - (1 + h % self.num_different_global_patterns) * G
        end = nb - nb % L
        starts = list(range(first, end, L))
        if end < nb:  # short last window: clamp so the global band stays in range
            starts.append(min(end + first, nb - G))
        for i in starts:
            first_row = 0 if self.attention == "bidirectional" else i
            layout[h, first_row:, i:i + G] = 1
            if self.horizontal_global_attention:
                layout[h, i:i + G, :] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_local_layout(h, layout)
            layout = self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """Extension of Fixed (reference :239): optional random blocks, a list of
    local window sizes, and explicit global block indices/ranges."""

    def __init__(self,
                 num_heads,
                 block=16,
                 different_layout_per_head=False,
                 num_random_blocks=0,
                 local_window_blocks=None,
                 global_block_indices=None,
                 global_block_end_indices=None,
                 attention="bidirectional",
                 horizontal_global_attention=False,
                 seed=0):
        super().__init__(num_heads, block, different_layout_per_head)
        local_window_blocks = [4] if local_window_blocks is None else local_window_blocks
        global_block_indices = [0] if global_block_indices is None else global_block_indices
        if global_block_end_indices is not None:
            if len(global_block_indices) != len(global_block_end_indices):
                raise ValueError(
                    f"Global block start indices length, {len(global_block_indices)}, must be same"
                    f" as global block end indices length, {len(global_block_end_indices)}!")
            for start_idx, end_idx in zip(global_block_indices, global_block_end_indices):
                if start_idx >= end_idx:
                    raise ValueError(
                        f"Global block start index, {start_idx}, must be smaller than global block"
                        f" end index, {end_idx}!")
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError('only "uni/bi-directional" attentions are supported for now!')
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError('only "bi-directional" attentions can support horizontal global attention!')
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks
        self.global_block_indices = global_block_indices
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.seed = seed

    def set_random_layout(self, h, layout, rng):
        nb = layout.shape[1]
        if nb < self.num_random_blocks:
            raise ValueError(
                f"Number of random blocks, {self.num_random_blocks}, must be smaller than overall"
                f" number of blocks in a row, {nb}!")
        for row in range(nb):
            # unidirectional layouts must stay block-lower-triangular: sample
            # random blocks only from the row's past (incl. diagonal)
            pool = nb if self.attention == "bidirectional" else row + 1
            n = min(self.num_random_blocks, pool)
            layout[h, row, rng.choice(pool, n, replace=False)] = 1
        return layout

    def set_local_layout(self, h, layout):
        nb = layout.shape[1]
        windows = list(self.local_window_blocks)
        # the last listed window size tiles the remainder of the sequence
        covered = sum(windows)
        while covered < nb:
            windows.append(windows[-1])
            covered += windows[-1]
        start = 0
        for w in windows:
            end = min(start + w, nb)
            for row in range(start, end):
                hi = row + 1 if self.attention == "unidirectional" else end
                layout[h, row, start:hi] = 1
            start += w
        return layout

    def set_global_layout(self, h, layout):
        nb = layout.shape[1]
        if self.global_block_end_indices is None:
            ranges = [(i, i + 1) for i in self.global_block_indices]
        else:
            ranges = list(zip(self.global_block_indices, self.global_block_end_indices))
        for start_idx, end_idx in ranges:
            if start_idx >= nb:
                continue
            end_idx = min(end_idx, nb)
            if self.horizontal_global_attention:
                layout[h, start_idx:end_idx, :] = 1
            first_row = 0 if self.attention == "bidirectional" else start_idx
            layout[h, first_row:, start_idx:end_idx] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        rng = np.random.default_rng(self.seed)
        for h in range(self.num_layout_heads):
            layout = self.set_random_layout(h, layout, rng)
            layout = self.set_local_layout(h, layout)
            layout = self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird pattern (arxiv 2007.14062; reference :411): random + sliding
    window + ITC global (first blocks attend/attended everywhere)."""

    def __init__(self,
                 num_heads,
                 block=16,
                 different_layout_per_head=False,
                 num_random_blocks=1,
                 num_sliding_window_blocks=3,
                 num_global_blocks=1,
                 attention="bidirectional",
                 seed=0):
        super().__init__(num_heads, block, different_layout_per_head)
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError('only "uni/bi-directional" attentions are supported for now!')
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.seed = seed

    def set_random_layout(self, h, layout, rng):
        nb = layout.shape[1]
        if nb < self.num_random_blocks:
            raise ValueError(
                f"Number of random blocks, {self.num_random_blocks}, must be smaller than overall"
                f" number of blocks in a row, {nb}!")
        for row in range(nb):
            pool = nb if self.attention == "bidirectional" else row + 1
            n = min(self.num_random_blocks, pool)
            layout[h, row, rng.choice(pool, n, replace=False)] = 1
        return layout

    def set_sliding_window_layout(self, h, layout):
        nb = layout.shape[1]
        if nb < self.num_sliding_window_blocks:
            raise ValueError(
                f"Number of sliding window blocks, {self.num_sliding_window_blocks}, must be"
                f" smaller than overall number of blocks in a row, {nb}!")
        r = np.arange(nb)
        w = self.num_sliding_window_blocks // 2
        layout[h][np.abs(r[:, None] - r[None, :]) <= w] = 1
        return layout

    def set_global_layout_itc(self, h, layout):
        nb = layout.shape[1]
        if nb < self.num_global_blocks:
            raise ValueError(
                f"Number of global blocks, {self.num_global_blocks}, must be smaller than overall"
                f" number of blocks in a row, {nb}!")
        layout[h, :self.num_global_blocks, :] = 1
        layout[h, :, :self.num_global_blocks] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        rng = np.random.default_rng(self.seed)
        for h in range(self.num_layout_heads):
            layout = self.set_random_layout(h, layout, rng)
            layout = self.set_sliding_window_layout(h, layout)
            layout = self.set_global_layout_itc(h, layout)
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer (arxiv 2004.05150; reference :546): sliding
    window + explicit global block indices/ranges."""

    def __init__(self,
                 num_heads,
                 block=16,
                 different_layout_per_head=False,
                 num_sliding_window_blocks=3,
                 global_block_indices=None,
                 global_block_end_indices=None,
                 attention="bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        global_block_indices = [0] if global_block_indices is None else global_block_indices
        if global_block_end_indices is not None:
            if len(global_block_indices) != len(global_block_end_indices):
                raise ValueError(
                    f"Global block start indices length, {len(global_block_indices)}, must be same"
                    f" as global block end indices length, {len(global_block_end_indices)}!")
            for start_idx, end_idx in zip(global_block_indices, global_block_end_indices):
                if start_idx >= end_idx:
                    raise ValueError(
                        f"Global block start index, {start_idx}, must be smaller than global block"
                        f" end index, {end_idx}!")
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError('only "uni/bi-directional" attentions are supported for now!')
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention

    def set_sliding_window_layout(self, h, layout):
        nb = layout.shape[1]
        if nb < self.num_sliding_window_blocks:
            raise ValueError(
                f"Number of sliding window blocks, {self.num_sliding_window_blocks}, must be"
                f" smaller than overall number of blocks in a row, {nb}!")
        r = np.arange(nb)
        w = self.num_sliding_window_blocks // 2
        layout[h][np.abs(r[:, None] - r[None, :]) <= w] = 1
        return layout

    def set_global_layout(self, h, layout):
        nb = layout.shape[1]
        if self.global_block_end_indices is None:
            ranges = [(i, i + 1) for i in self.global_block_indices]
        else:
            ranges = list(zip(self.global_block_indices, self.global_block_end_indices))
        for start_idx, end_idx in ranges:
            if start_idx >= nb:
                continue
            end_idx = min(end_idx, nb)
            layout[h, start_idx:end_idx, :] = 1
            layout[h, :, start_idx:end_idx] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_sliding_window_layout(h, layout)
            layout = self.set_global_layout(h, layout)
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return self.check_and_propagate_first_head_layout(layout)


class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """Purely-local sliding window pattern (reference :674)."""

    def __init__(self, num_heads, block=16, num_sliding_window_blocks=3, attention="unidirectional"):
        super().__init__(num_heads, block)
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError('only "uni/bi-directional" attentions are supported for now!')
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.attention = attention

    def set_sliding_window_layout(self, h, layout):
        nb = layout.shape[1]
        if nb < self.num_sliding_window_blocks:
            raise ValueError(
                f"Number of sliding window blocks, {self.num_sliding_window_blocks}, must be"
                f" smaller than overall number of blocks in a row, {nb}!")
        r = np.arange(nb)
        w = self.num_sliding_window_blocks // 2
        mask = (r[:, None] - r[None, :] <= w) & (r[None, :] - r[:, None] <= (w if self.attention == "bidirectional" else 0))
        layout[h][mask] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_sliding_window_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


_MODE_CLASSES = {
    "dense": (DenseSparsityConfig,
              ("block", "different_layout_per_head")),
    "fixed": (FixedSparsityConfig,
              ("block", "different_layout_per_head", "num_local_blocks",
               "num_global_blocks", "attention", "horizontal_global_attention",
               "num_different_global_patterns")),
    "variable": (VariableSparsityConfig,
                 ("block", "different_layout_per_head", "num_random_blocks",
                  "local_window_blocks", "global_block_indices",
                  "global_block_end_indices", "attention",
                  "horizontal_global_attention", "seed")),
    "bigbird": (BigBirdSparsityConfig,
                ("block", "different_layout_per_head", "num_random_blocks",
                 "num_sliding_window_blocks", "num_global_blocks", "attention", "seed")),
    "bslongformer": (BSLongformerSparsityConfig,
                     ("block", "different_layout_per_head",
                      "num_sliding_window_blocks", "global_block_indices",
                      "global_block_end_indices", "attention")),
    "local": (LocalSlidingWindowSparsityConfig,
              ("block", "num_sliding_window_blocks", "attention")),
}


def build_sparsity_config(sparsity: dict, num_heads: int):
    """Build a SparsityConfig from a ``sparse_attention`` JSON config block
    (reference ``runtime/config.py:289`` ``get_sparse_attention`` — mode +
    per-mode keys, same names). Unknown modes raise, matching the reference's
    NotImplementedError; unknown/wrong-mode KEYS also raise — a typo'd key
    silently falling back to a class default would train a different
    sparsity pattern than configured."""
    mode = sparsity.get("mode", "fixed")
    if mode not in _MODE_CLASSES:
        raise NotImplementedError(f"Given sparsity mode, {mode}, has not been implemented yet!")
    cls, keys = _MODE_CLASSES[mode]
    allowed = set(keys) | {"mode"}
    unknown = set(sparsity) - allowed
    if unknown:
        raise ValueError(f"sparse_attention mode {mode!r} got unknown keys {sorted(unknown)}; "
                         f"allowed: {sorted(allowed)}")
    return cls(num_heads=num_heads, **{k: sparsity[k] for k in keys if k in sparsity})
