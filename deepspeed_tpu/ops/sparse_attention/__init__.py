"""Sparse attention (reference ``deepspeed/ops/sparse_attention/``) —
blocked sparsity layouts + a Pallas LUT-prefetch kernel."""

from .sparsity_config import (SparsityConfig, DenseSparsityConfig, FixedSparsityConfig,
                              VariableSparsityConfig, BigBirdSparsityConfig,
                              BSLongformerSparsityConfig, LocalSlidingWindowSparsityConfig,
                              build_sparsity_config)
from .attention import SparseSelfAttention, BertSparseSelfAttention, SparseAttentionUtils
from ..pallas.block_sparse_attention import (block_sparse_attention,
                                             block_sparse_attention_gathered, make_layout_lut)
