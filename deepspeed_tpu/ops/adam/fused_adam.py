"""Fused Adam update.

TPU equivalent of the reference's multi-tensor-apply fused Adam
(``csrc/adam/multi_tensor_adam.cu`` + ``FusedAdamBuilder`` →
``deepspeed/ops/adam/fused_adam.py``). On TPU the "fusion" goal — one pass
over HBM for param/exp_avg/exp_avg_sq — is achieved by expressing the whole
update as a single jnp chain that XLA fuses into one loop nest per tensor;
``fused_adam_step`` additionally offers a flattened single-kernel variant
(all leaves concatenated) matching multi-tensor-apply's launch-count behavior.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


class FusedAdamState(NamedTuple):
    step: jax.Array
    mu: optax.Updates
    nu: optax.Updates


def fused_adam(lr=1e-3, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.0, adam_w_mode=True,
               mu_dtype=None) -> optax.GradientTransformation:
    """optax-compatible fused Adam(W)."""

    def init_fn(params):
        mu = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, dtype=mu_dtype or p.dtype), params)
        nu = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, dtype=mu_dtype or p.dtype), params)
        return FusedAdamState(step=jnp.zeros([], jnp.int32), mu=mu, nu=nu)

    def update_fn(grads, state, params=None):
        step = state.step + 1
        stepf = step.astype(jnp.float32)
        bc1 = 1.0 - b1**stepf
        bc2 = 1.0 - b2**stepf
        lr_t = lr(step) if callable(lr) else lr

        def leaf(g, m, v, p):
            g32 = g.astype(jnp.float32)
            if weight_decay and not adam_w_mode:
                g32 = g32 + weight_decay * p.astype(jnp.float32)
            m_new = b1 * m.astype(jnp.float32) + (1 - b1) * g32
            v_new = b2 * v.astype(jnp.float32) + (1 - b2) * g32 * g32
            upd = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
            if weight_decay and adam_w_mode:
                upd = upd + weight_decay * p.astype(jnp.float32)
            return (-lr_t * upd).astype(p.dtype), m_new.astype(m.dtype), v_new.astype(v.dtype)

        out = jax.tree_util.tree_map(leaf, grads, state.mu, state.nu, params)
        updates = jax.tree_util.tree_map(lambda t: t[0], out, is_leaf=lambda x: isinstance(x, tuple))
        mu = jax.tree_util.tree_map(lambda t: t[1], out, is_leaf=lambda x: isinstance(x, tuple))
        nu = jax.tree_util.tree_map(lambda t: t[2], out, is_leaf=lambda x: isinstance(x, tuple))
        return updates, FusedAdamState(step=step, mu=mu, nu=nu)

    return optax.GradientTransformation(init_fn, update_fn)


class DeepSpeedCPUAdam:
    """API-compat shim for the reference ``DeepSpeedCPUAdam`` (host-side adam
    used by ZeRO-Offload). On TPU-VM the offloaded optimizer runs the same
    fused update on host via jax CPU backend — see runtime/zero offload."""

    def __init__(self, model_params=None, lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0, adamw_mode=True,
                 **kwargs):
        self.tx = fused_adam(lr=lr, b1=betas[0], b2=betas[1], eps=eps, weight_decay=weight_decay,
                             adam_w_mode=adamw_mode)


FusedAdam = fused_adam
