"""DeepSpeedCPUAdam — host-side fused Adam over fp32 masters.

Python surface of ``ops/csrc/adam/cpu_adam.cpp`` (reference
``deepspeed/ops/adam/cpu_adam.py`` → CPUAdamBuilder → csrc/adam/cpu_adam.cpp):
the ZeRO-Offload optimizer. State (exp_avg / exp_avg_sq) lives in host numpy;
``step`` runs the fused multithreaded C++ kernel per tensor and can emit bf16
copies for device upload in the same pass (reference
``ds_adam_step_plus_copy``).
"""

import ctypes
import itertools

import numpy as np

from ..native import build_op

_ids = itertools.count()


def _lib():
    lib = build_op("deepspeed_cpu_adam", ["adam/cpu_adam.cpp"])
    if not getattr(lib, "_ds_typed", False):
        lib.ds_adam_create.restype = ctypes.c_int
        lib.ds_adam_create.argtypes = [ctypes.c_int, ctypes.c_float, ctypes.c_float, ctypes.c_float,
                                       ctypes.c_float, ctypes.c_float, ctypes.c_int]
        lib.ds_adam_destroy.restype = ctypes.c_int
        lib.ds_adam_destroy.argtypes = [ctypes.c_int]
        lib.ds_adam_step.restype = ctypes.c_int
        lib.ds_adam_step.argtypes = [ctypes.c_int, ctypes.c_longlong, ctypes.c_longlong,
                                     ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
                                     ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
                                     ctypes.c_float, ctypes.c_float, ctypes.c_void_p, ctypes.c_int]
        lib.ds_fp32_to_bf16.restype = None
        lib.ds_fp32_to_bf16.argtypes = [ctypes.POINTER(ctypes.c_float), ctypes.c_void_p, ctypes.c_longlong]
        lib._ds_typed = True
    return lib


def _f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class DeepSpeedCPUAdam:
    """Fused host Adam/AdamW (reference ``DeepSpeedCPUAdam``).

    Usage: construct once, then per tensor call
    ``step(step_no, params, grads, exp_avg, exp_avg_sq, lr=, bf16_out=)``.
    All arrays must be C-contiguous float32 of equal size; updates happen
    in place (params and moments are mutated).
    """

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0, adamw_mode=True, n_threads=0):
        self._lib = _lib()
        self.opt_id = next(_ids)
        self.defaults = dict(lr=lr, betas=betas, eps=eps, weight_decay=weight_decay, adamw_mode=adamw_mode)
        self.n_threads = n_threads
        rc = self._lib.ds_adam_create(self.opt_id, float(lr), float(betas[0]), float(betas[1]), float(eps),
                                      float(weight_decay), int(bool(adamw_mode)))
        assert rc == 0

    def step(self, step_no, params, grads, exp_avg, exp_avg_sq, lr=None, grad_scale=1.0, bf16_out=None):
        for a in (params, grads, exp_avg, exp_avg_sq):
            assert a.dtype == np.float32 and a.flags["C_CONTIGUOUS"], "fp32 contiguous arrays required"
            assert a.size == params.size
        out_ptr = None
        if bf16_out is not None:
            assert bf16_out.dtype == np.uint16 and bf16_out.size == params.size
            out_ptr = bf16_out.ctypes.data_as(ctypes.c_void_p)
        rc = self._lib.ds_adam_step(self.opt_id, int(step_no), params.size, _f32p(params), _f32p(grads),
                                    _f32p(exp_avg), _f32p(exp_avg_sq),
                                    float(lr) if lr is not None else -1.0, float(grad_scale), out_ptr,
                                    int(self.n_threads))
        if rc != 0:
            raise RuntimeError(f"ds_adam_step failed rc={rc}")

    def fp32_to_bf16(self, src: np.ndarray, dst: np.ndarray):
        assert src.dtype == np.float32 and dst.dtype == np.uint16 and src.size == dst.size
        self._lib.ds_fp32_to_bf16(_f32p(src), dst.ctypes.data_as(ctypes.c_void_p), src.size)

    def destroy(self):
        if getattr(self, "opt_id", None) is not None:
            self._lib.ds_adam_destroy(self.opt_id)
            self.opt_id = None

    def __del__(self):
        try:
            self.destroy()
        except Exception:
            pass
