"""Op registry.

Analog of the reference ``op_builder/all_ops.py`` registry +
``op_builder/builder.py`` JIT machinery. On TPU there is nothing to compile at
import time — Pallas kernels are traced/compiled by XLA on first call — so a
"builder" here is a lazy import handle that reports availability, mirroring
``ds_report``'s compatibility matrix semantics.
"""

import importlib


class OpBuilder:

    NAME = "base"

    def __init__(self, module_path, symbol=None):
        self.module_path = module_path
        self.symbol = symbol

    def is_compatible(self):
        try:
            importlib.import_module(self.module_path)
            return True
        except Exception:
            return False

    def load(self):
        mod = importlib.import_module(self.module_path)
        return getattr(mod, self.symbol) if self.symbol else mod


def _builder(name, module_path, symbol=None):
    b = OpBuilder(module_path, symbol)
    b.NAME = name
    return b


# Registry keyed by the reference builder class names (op_builder/*.py) so
# get_accelerator().create_op_builder("FusedAdamBuilder") resolves here.
op_registry = {
    "FusedAdamBuilder": _builder("fused_adam", "deepspeed_tpu.ops.adam.fused_adam"),
    "FusedLambBuilder": _builder("fused_lamb", "deepspeed_tpu.runtime.optimizers"),
    "CPUAdamBuilder": _builder("cpu_adam", "deepspeed_tpu.ops.adam.cpu_adam", "DeepSpeedCPUAdam"),
    "QuantizerBuilder": _builder("quantizer", "deepspeed_tpu.ops.pallas.quant"),
    "FlashAttnBuilder": _builder("flash_attn", "deepspeed_tpu.ops.pallas.flash_attention"),
    "RaggedOpsBuilder": _builder("ragged_ops", "deepspeed_tpu.ops.pallas.paged_attention"),
    "InferenceCoreBuilder": _builder("inference_core_ops", "deepspeed_tpu.ops.pallas.rmsnorm"),
    "AsyncIOBuilder": _builder("async_io", "deepspeed_tpu.ops.aio"),
    "SparseAttnBuilder": _builder("sparse_attn", "deepspeed_tpu.ops.sparse_attention"),
}
