"""Op registry.

Analog of the reference ``op_builder/all_ops.py`` registry +
``op_builder/builder.py`` JIT machinery. On TPU there is nothing to compile at
import time — Pallas kernels are traced/compiled by XLA on first call — so a
"builder" here is a lazy import handle that reports availability, mirroring
``ds_report``'s compatibility matrix semantics.
"""

import importlib


class OpBuilder:

    NAME = "base"

    def __init__(self, module_path, symbol=None):
        self.module_path = module_path
        self.symbol = symbol

    def is_compatible(self):
        try:
            importlib.import_module(self.module_path)
            return True
        except Exception:
            return False

    def load(self):
        mod = importlib.import_module(self.module_path)
        return getattr(mod, self.symbol) if self.symbol else mod


def _builder(name, module_path, symbol=None):
    b = OpBuilder(module_path, symbol)
    b.NAME = name
    return b


# Registry keyed by the reference builder class names (op_builder/*.py) so
# get_accelerator().create_op_builder("FusedAdamBuilder") resolves here.
op_registry = {
    "FusedAdamBuilder": _builder("fused_adam", "deepspeed_tpu.ops.adam.fused_adam"),
    "FusedLambBuilder": _builder("fused_lamb", "deepspeed_tpu.runtime.optimizers"),
    "FusedLionBuilder": _builder("fused_lion", "deepspeed_tpu.runtime.optimizers"),
    "CPUAdamBuilder": _builder("cpu_adam", "deepspeed_tpu.ops.adam.cpu_adam", "DeepSpeedCPUAdam"),
    "CPULionBuilder": _builder("cpu_lion", "deepspeed_tpu.runtime.optimizers"),
    "CPUAdagradBuilder": _builder("cpu_adagrad", "deepspeed_tpu.runtime.optimizers"),
    "QuantizerBuilder": _builder("quantizer", "deepspeed_tpu.ops.pallas.quant"),
    "FlashAttnBuilder": _builder("flash_attn", "deepspeed_tpu.ops.pallas.flash_attention"),
    # training transformer kernel stack = the Pallas flash path (the
    # reference's TransformerBuilder/StochasticTransformerBuilder kernels)
    "TransformerBuilder": _builder("transformer", "deepspeed_tpu.ops.pallas.flash_attention"),
    "StochasticTransformerBuilder": _builder(
        "stochastic_transformer", "deepspeed_tpu.ops.pallas.flash_attention"),
    # v1 fused inference kernels (reference transformer_inference.py)
    "InferenceBuilder": _builder("transformer_inference", "deepspeed_tpu.ops.pallas.paged_attention"),
    "InferenceCutlassBuilder": _builder("inference_cutlass", "deepspeed_tpu.ops.pallas.paged_attention"),
    "RaggedOpsBuilder": _builder("ragged_ops", "deepspeed_tpu.ops.pallas.paged_attention"),
    "RaggedUtilsBuilder": _builder("ragged_utils", "deepspeed_tpu.inference.v2.ragged"),
    "InferenceCoreBuilder": _builder("inference_core_ops", "deepspeed_tpu.ops.pallas.rmsnorm"),
    "AsyncIOBuilder": _builder("async_io", "deepspeed_tpu.ops.aio"),
    "SparseAttnBuilder": _builder("sparse_attn", "deepspeed_tpu.ops.sparse_attention"),
    "EvoformerAttnBuilder": _builder("evoformer_attn", "deepspeed_tpu.ops.pallas.evoformer_attention"),
    "RandomLTDBuilder": _builder(
        "random_ltd", "deepspeed_tpu.runtime.data_pipeline.data_routing.random_ltd"),
    "SpatialInferenceBuilder": _builder("spatial_inference", "deepspeed_tpu.ops.spatial"),
}
