"""Async file I/O for ZeRO-Infinity NVMe tiering.

Python surface of the native library in ``ops/csrc/aio/deepspeed_aio.cpp``
(reference: ``csrc/aio/py_lib`` AsyncIOBuilder → aio_handle with
``async_pread/async_pwrite/wait``). Buffers are numpy arrays pinned by the
OS page cache; alignment for O_DIRECT is handled by the C++ side's fallback.
"""

import ctypes

import numpy as np

from ..native import build_op

_FUNCS = {
    "ds_aio_create": (ctypes.c_void_p, [ctypes.c_int, ctypes.c_longlong, ctypes.c_int]),
    "ds_aio_submit_read": (ctypes.c_int, [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                                          ctypes.c_longlong, ctypes.c_longlong]),
    "ds_aio_submit_write": (ctypes.c_int, [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                                           ctypes.c_longlong, ctypes.c_longlong]),
    "ds_aio_wait": (ctypes.c_int, [ctypes.c_void_p]),
    "ds_aio_pending": (ctypes.c_int, [ctypes.c_void_p]),
    "ds_aio_destroy": (None, [ctypes.c_void_p]),
    "ds_aio_sync_pread": (ctypes.c_int, [ctypes.c_char_p, ctypes.c_void_p, ctypes.c_longlong, ctypes.c_longlong]),
    "ds_aio_sync_pwrite": (ctypes.c_int, [ctypes.c_char_p, ctypes.c_void_p, ctypes.c_longlong, ctypes.c_longlong]),
}


def _lib():
    lib = build_op("deepspeed_aio", ["aio/deepspeed_aio.cpp"])
    if not getattr(lib, "_ds_typed", False):
        for fname, (restype, argtypes) in _FUNCS.items():
            f = getattr(lib, fname)
            f.restype = restype
            f.argtypes = argtypes
        lib._ds_typed = True
    return lib


def _buf_ptr(arr: np.ndarray):
    assert arr.flags["C_CONTIGUOUS"], "aio buffers must be C-contiguous"
    return arr.ctypes.data_as(ctypes.c_void_p)


class AsyncIOHandle:
    """Reference ``aio_handle``: submit reads/writes, then ``wait()``.

    The caller owns buffer lifetime: every submitted numpy buffer must stay
    alive until the next ``wait()`` returns (same contract as the reference's
    pinned tensors).
    """

    def __init__(self, block_size=1 << 20, queue_depth=8, thread_count=None, single_submit=False,
                 overlap_events=True, use_o_direct=False):
        self._lib = _lib()
        n_threads = thread_count or queue_depth
        self._h = self._lib.ds_aio_create(int(n_threads), int(block_size), int(bool(use_o_direct)))
        if not self._h:
            raise RuntimeError("failed to create aio handle")
        self._inflight_bufs = []

    def async_pread(self, buffer: np.ndarray, filename: str, file_offset: int = 0):
        rc = self._lib.ds_aio_submit_read(self._h, filename.encode(), _buf_ptr(buffer), buffer.nbytes,
                                          int(file_offset))
        if rc != 0:
            raise OSError(-rc, f"aio read submit failed for {filename}")
        self._inflight_bufs.append(buffer)

    def async_pwrite(self, buffer: np.ndarray, filename: str, file_offset: int = 0):
        rc = self._lib.ds_aio_submit_write(self._h, filename.encode(), _buf_ptr(buffer), buffer.nbytes,
                                           int(file_offset))
        if rc != 0:
            raise OSError(-rc, f"aio write submit failed for {filename}")
        self._inflight_bufs.append(buffer)

    def wait(self):
        rc = self._lib.ds_aio_wait(self._h)
        self._inflight_bufs.clear()
        if rc != 0:
            raise OSError(-rc, "aio request failed")
        return 0

    def pending(self):
        return int(self._lib.ds_aio_pending(self._h))

    # synchronous convenience (reference sync_pread/sync_pwrite)
    def sync_pread(self, buffer: np.ndarray, filename: str, file_offset: int = 0):
        rc = self._lib.ds_aio_sync_pread(filename.encode(), _buf_ptr(buffer), buffer.nbytes, int(file_offset))
        if rc != 0:
            raise OSError(-rc, f"pread failed for {filename}")

    def sync_pwrite(self, buffer: np.ndarray, filename: str, file_offset: int = 0):
        rc = self._lib.ds_aio_sync_pwrite(filename.encode(), _buf_ptr(buffer), buffer.nbytes, int(file_offset))
        if rc != 0:
            raise OSError(-rc, f"pwrite failed for {filename}")

    def close(self):
        if self._h:
            self._lib.ds_aio_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
