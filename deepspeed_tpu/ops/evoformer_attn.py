"""DS4Science Evoformer attention (triangle / MSA attention with bias terms).

TPU equivalent of the reference's CUTLASS fused MHA
(``csrc/deepspeed4science/evoformer_attn/`` — 14,928 LoC of fwd/bwd kernels
exposed as ``EvoformerAttnBuilder`` → ``deepspeed.ops.deepspeed4science.
evoformer_attn.DS4Sci_EvoformerAttention``). The contract (reference python
wrapper): Q/K/V of shape [*, n_seq, n_res, heads, dim] and up to two bias
terms broadcastable to the score tensor [*, n_seq, heads, n_res, n_res] —
the pair-bias and the MSA mask bias of AlphaFold's Evoformer block.

On TPU the fused-kernel goal (never materialize the O(n_res^2) probability
tensor in HBM at fp32) is met by computing the whole attention in one jitted
function with a chunked lax.map over the n_seq dim: XLA fuses the
bias-add + softmax + PV chain per chunk, and the backward is jax.grad
through the same program. Numerics are validated against a plain einsum
oracle (reference tests/unit/ops/deepspeed4science strategy).
"""

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp


def evoformer_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        biases: Sequence[Optional[jax.Array]] = (),
                        seq_chunk: int = 0) -> jax.Array:
    """Fused biased attention.

    q/k/v: [..., n_seq, n_res, heads, dim] (the reference layout).
    biases: up to two arrays broadcastable to [..., n_seq, heads, n_res,
    n_res] (e.g. mask bias [.., n_seq, 1, 1, n_res] and pair bias
    [.., 1, heads, n_res, n_res]).
    seq_chunk: process the n_seq dim in chunks of this size to bound the
    live score tensor (0 = no chunking).
    Returns [..., n_seq, n_res, heads, dim].
    """
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))

    def attend(qc, kc, vc, bias_c):
        # qc: [..., c, n_res, h, d] -> scores [..., c, h, n_res, n_res]
        s = jnp.einsum("...qhd,...khd->...hqk", qc.astype(jnp.float32) * scale,
                       kc.astype(jnp.float32))
        for b in bias_c:
            if b is not None:
                s = s + b.astype(jnp.float32)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("...hqk,...khd->...qhd", p, vc.astype(jnp.float32)).astype(q.dtype)

    if not seq_chunk or q.shape[-4] <= seq_chunk:
        return attend(q, k, v, [b for b in biases])

    n_seq = q.shape[-4]
    assert n_seq % seq_chunk == 0, f"n_seq {n_seq} must divide by seq_chunk {seq_chunk}"

    def chunk_fn(i):
        sl = lambda x: jax.lax.dynamic_slice_in_dim(x, i * seq_chunk, seq_chunk, axis=-4)
        bias_c = []
        for b in biases:
            if b is None:
                bias_c.append(None)
            elif b.shape[-4] == 1:  # broadcast over n_seq (pair bias)
                bias_c.append(b)
            else:
                bias_c.append(jax.lax.dynamic_slice_in_dim(b, i * seq_chunk, seq_chunk, axis=-4))
        return attend(sl(q), sl(k), sl(v), bias_c)

    chunks = jax.lax.map(chunk_fn, jnp.arange(n_seq // seq_chunk))
    # [n_chunks, ..., c, n_res, h, d] -> [..., n_seq, n_res, h, d]
    out = jnp.moveaxis(chunks, 0, -5)
    return out.reshape(*out.shape[:-5], n_seq, *out.shape[-3:])


DS4Sci_EvoformerAttention = partial(evoformer_attention)  # reference public name
