"""DS4Science Evoformer attention (triangle / MSA attention with bias terms).

TPU equivalent of the reference's CUTLASS fused MHA
(``csrc/deepspeed4science/evoformer_attn/`` — 14,928 LoC of fwd/bwd kernels
exposed as ``EvoformerAttnBuilder`` → ``deepspeed.ops.deepspeed4science.
evoformer_attn.DS4Sci_EvoformerAttention``). The contract (reference python
wrapper): Q/K/V of shape [*, n_seq, n_res, heads, dim] and up to two bias
terms broadcastable to the score tensor [*, n_seq, heads, n_res, n_res] —
the pair-bias and the MSA mask bias of AlphaFold's Evoformer block.

On TPU the fused-kernel goal (never materialize the O(n_res^2) probability
tensor in HBM at fp32) is met by computing the whole attention in one jitted
function with a chunked lax.map over the n_seq dim: XLA fuses the
bias-add + softmax + PV chain per chunk, and the backward is jax.grad
through the same program. Numerics are validated against a plain einsum
oracle (reference tests/unit/ops/deepspeed4science strategy).
"""

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp


def _pallas_route(q, biases, interpret=False):
    """The Pallas biased-flash kernel handles the AlphaFold bias pattern —
    mask bias [.., n_seq, 1, 1, n_res] + pair bias [.., 1, heads, n_res,
    n_res] (either may be absent) — on TPU, for lane-aligned n_res.
    Returns (bias1 [.., n_seq, 1, 1, R], bias2 [.., 1, h, R, R]) or None.
    ``interpret`` runs the kernel through the Pallas interpreter off-TPU
    (CPU CI coverage of the kernel program)."""
    if not interpret and jax.default_backend() != "tpu":
        return None
    *lead, n_seq, R, h, d = q.shape
    if R % 128 != 0 or d < 32:
        return None
    b1 = b2 = None
    for b in biases:
        if b is None:
            continue
        if b.shape[-4:] == (n_seq, 1, 1, R) and b1 is None:
            b1 = b
        elif b.shape[-4:] == (1, h, R, R) and b2 is None:
            b2 = b
        else:
            return None  # a bias layout the kernel doesn't cover
    return b1, b2


def _evoformer_pallas(q, k, v, b1, b2, interpret=False):
    """Collapse leading dims and run the fused kernel
    (``ops/pallas/evoformer_attention.py``)."""
    from .pallas.evoformer_attention import evo_flash

    *lead, n_seq, R, h, d = q.shape
    G = 1
    for x in lead:
        G *= x
    N = G * n_seq
    qf = q.reshape(N, R, h, d)
    kf = k.reshape(N, R, h, d)
    vf = v.reshape(N, R, h, d)
    # absent biases pass through as None: the kernel substitutes one
    # resident zero tile and skips that bias's backward pass entirely
    b1f = (jnp.broadcast_to(b1, (*lead, n_seq, 1, 1, R)).reshape(N, R).astype(jnp.float32)
           if b1 is not None else None)
    b2f = (jnp.broadcast_to(b2, (*lead, 1, h, R, R)).reshape(G, h, R, R).astype(jnp.float32)
           if b2 is not None else None)
    out = evo_flash(qf, kf, vf, b1f, b2f, interpret=interpret)
    return out.reshape(*lead, n_seq, R, h, d)


def evoformer_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        biases: Sequence[Optional[jax.Array]] = (),
                        seq_chunk: int = 0, interpret: bool = False) -> jax.Array:
    """Fused biased attention.

    q/k/v: [..., n_seq, n_res, heads, dim] (the reference layout).
    biases: up to two arrays broadcastable to [..., n_seq, heads, n_res,
    n_res] (e.g. mask bias [.., n_seq, 1, 1, n_res] and pair bias
    [.., 1, heads, n_res, n_res]).
    seq_chunk: process the n_seq dim in chunks of this size to bound the
    live score tensor (0 = no chunking; ignored on the Pallas route, whose
    residency is already tile-bounded).
    Returns [..., n_seq, n_res, heads, dim].

    On TPU, AlphaFold-pattern biases route to the Pallas biased-flash
    kernel (fwd + bwd incl. bias gradients, never materializing the
    [n_res, n_res] probabilities in HBM); other layouts use the chunked
    jnp path below.
    """
    routed = _pallas_route(q, biases, interpret=interpret)
    if routed is not None:
        return _evoformer_pallas(q, k, v, routed[0], routed[1], interpret=interpret)
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))

    def attend(qc, kc, vc, bias_c):
        # qc: [..., c, n_res, h, d] -> scores [..., c, h, n_res, n_res]
        s = jnp.einsum("...qhd,...khd->...hqk", qc.astype(jnp.float32) * scale,
                       kc.astype(jnp.float32))
        for b in bias_c:
            if b is not None:
                s = s + b.astype(jnp.float32)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("...hqk,...khd->...qhd", p, vc.astype(jnp.float32)).astype(q.dtype)

    if not seq_chunk or q.shape[-4] <= seq_chunk:
        return attend(q, k, v, [b for b in biases])

    n_seq = q.shape[-4]
    assert n_seq % seq_chunk == 0, f"n_seq {n_seq} must divide by seq_chunk {seq_chunk}"

    def chunk_fn(i):
        sl = lambda x: jax.lax.dynamic_slice_in_dim(x, i * seq_chunk, seq_chunk, axis=-4)
        bias_c = []
        for b in biases:
            if b is None:
                bias_c.append(None)
            elif b.shape[-4] == 1:  # broadcast over n_seq (pair bias)
                bias_c.append(b)
            else:
                bias_c.append(jax.lax.dynamic_slice_in_dim(b, i * seq_chunk, seq_chunk, axis=-4))
        return attend(sl(q), sl(k), sl(v), bias_c)

    chunks = jax.lax.map(chunk_fn, jnp.arange(n_seq // seq_chunk))
    # [n_chunks, ..., c, n_res, h, d] -> [..., n_seq, n_res, h, d]
    out = jnp.moveaxis(chunks, 0, -5)
    return out.reshape(*out.shape[:-5], n_seq, *out.shape[-3:])


DS4Sci_EvoformerAttention = partial(evoformer_attention)  # reference public name
