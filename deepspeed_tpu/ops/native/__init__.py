"""Native op JIT builder.

Analog of the reference ``op_builder/builder.py`` which compiles torch
cpp-extensions on first use. Here: g++ compiles each C++ source set to a
shared library loaded via ctypes (no pybind11 in this image). Libraries are
cached under ``<repo>/build/native/`` keyed by a content hash, so a source
edit triggers recompilation — the same staleness contract as the reference's
JIT load path.
"""

import ctypes
import hashlib
import os
import subprocess
import threading

from ...utils.logging import logger

_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "csrc")
_BUILD_ROOT = os.environ.get(
    "DS_TPU_BUILD_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))),
                 "build", "native"))

_lock = threading.Lock()
_loaded = {}


class NativeBuildError(RuntimeError):
    pass


def _source_hash(paths, flags):
    h = hashlib.sha256()
    for p in paths:
        with open(p, "rb") as f:
            h.update(f.read())
    h.update(" ".join(flags).encode())
    return h.hexdigest()[:16]


def build_op(name, sources, extra_flags=()):
    """Compile (if stale) and load the shared library for ``name``.

    ``sources``: paths relative to ``ops/csrc``. Returns a ctypes.CDLL.
    """
    with _lock:
        if name in _loaded:
            return _loaded[name]
        srcs = [os.path.join(_CSRC, s) for s in sources]
        for s in srcs:
            if not os.path.isfile(s):
                raise NativeBuildError(f"missing source {s}")
        flags = ["-O3", "-std=c++17", "-fPIC", "-shared", "-pthread", "-march=native", *extra_flags]
        tag = _source_hash(srcs, flags)
        os.makedirs(_BUILD_ROOT, exist_ok=True)
        lib_path = os.path.join(_BUILD_ROOT, f"lib{name}-{tag}.so")
        if not os.path.isfile(lib_path):
            tmp = lib_path + f".tmp{os.getpid()}"
            cmd = ["g++", *flags, "-o", tmp, *srcs]
            logger.info(f"building native op '{name}': {' '.join(cmd)}")
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                raise NativeBuildError(f"g++ failed for op '{name}':\n{proc.stderr}")
            os.replace(tmp, lib_path)
        lib = ctypes.CDLL(lib_path)
        _loaded[name] = lib
        return lib


def is_available():
    """True when a host toolchain exists (ds_report compat matrix entry)."""
    try:
        return subprocess.run(["g++", "--version"], capture_output=True).returncode == 0
    except OSError:
        return False
