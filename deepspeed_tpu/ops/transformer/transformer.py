"""BERT-style fused transformer layer — the reference's oldest public
kernel API (``deepspeed/ops/transformer/transformer.py``:
``DeepSpeedTransformerConfig:34``, ``DeepSpeedTransformerLayer:296`` backed
by ~12.8k LoC of CUDA in ``csrc/transformer/``).

TPU form: the layer is a pure ``apply(params, hidden, mask)`` whose
attention routes through the Pallas flash kernel (the fused path) and whose
elementwise chain XLA fuses — the functional face of what the CUDA kernel
hand-fused. Pre-LN and Post-LN orderings, gelu MLP, bidirectional
(non-causal) attention with an additive mask, matching the BERT contract.
"""

import math
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp


@dataclass
class DeepSpeedTransformerConfig:
    """Reference config fields (``transformer.py:34``); CUDA-only knobs
    (stochastic_mode, gelu/attn_dropout_checkpoint, huge_batch_optimization)
    are accepted for compatibility and subsumed by XLA/remat."""
    batch_size: int = 1
    hidden_size: int = 768
    intermediate_size: Optional[int] = None
    heads: int = 12
    attn_dropout_ratio: float = 0.0
    hidden_dropout_ratio: float = 0.0
    num_hidden_layers: int = 12
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    local_rank: int = -1
    seed: int = 0
    fp16: bool = False
    pre_layer_norm: bool = True
    normalize_invertible: bool = False
    gelu_checkpoint: bool = False
    adjust_init_range: bool = True
    attn_dropout_checkpoint: bool = False
    stochastic_mode: bool = False
    return_tuple: bool = False
    training: bool = True

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size


class DeepSpeedTransformerLayer:
    """Functional BERT block (reference ``DeepSpeedTransformerLayer:296``).

    ``init(rng)`` → params; ``apply(params, hidden_states, attention_mask)``
    → [B, S, H]. ``attention_mask``: additive mask broadcastable to
    [B, 1, 1, S] (the HF extended-mask convention), or None.
    """
    layer_id = 0

    def __init__(self, config: DeepSpeedTransformerConfig, initial_weights=None,
                 initial_biases=None):
        self.config = config
        self.my_layer_id = DeepSpeedTransformerLayer.layer_id
        DeepSpeedTransformerLayer.layer_id += 1
        self._initial = (initial_weights, initial_biases)

    def init(self, rng):
        cfg = self.config
        H, F = cfg.hidden_size, cfg.intermediate_size
        k = jax.random.split(rng, 6)
        std = cfg.initializer_range
        if cfg.adjust_init_range:
            output_std = std / math.sqrt(2.0 * cfg.num_hidden_layers)
        else:
            output_std = std

        def dense(key, shape, s):
            return jax.random.normal(key, shape, jnp.float32) * s

        params = {
            "qkv": {"kernel": dense(k[0], (H, 3 * H), std), "bias": jnp.zeros((3 * H,))},
            "attn_out": {"kernel": dense(k[1], (H, H), output_std), "bias": jnp.zeros((H,))},
            "attn_norm": {"scale": jnp.ones((H,)), "bias": jnp.zeros((H,))},
            "inter": {"kernel": dense(k[2], (H, F), std), "bias": jnp.zeros((F,))},
            "output": {"kernel": dense(k[3], (F, H), output_std), "bias": jnp.zeros((H,))},
            "norm": {"scale": jnp.ones((H,)), "bias": jnp.zeros((H,))},
        }
        iw, ib = self._initial
        if iw is not None:  # reference unit-test hook: torch-layout [out, in]
            params["qkv"]["kernel"] = jnp.concatenate(
                [jnp.asarray(w, jnp.float32).T for w in iw[0:3]], axis=1)
            params["attn_out"]["kernel"] = jnp.asarray(iw[3], jnp.float32).T
            params["attn_norm"]["scale"] = jnp.asarray(iw[4], jnp.float32)
            params["inter"]["kernel"] = jnp.asarray(iw[5], jnp.float32).T
            params["output"]["kernel"] = jnp.asarray(iw[6], jnp.float32).T
            params["norm"]["scale"] = jnp.asarray(iw[7], jnp.float32)
        if ib is not None:
            params["qkv"]["bias"] = jnp.concatenate([jnp.asarray(b, jnp.float32) for b in ib[0:3]])
            params["attn_out"]["bias"] = jnp.asarray(ib[3], jnp.float32)
            params["attn_norm"]["bias"] = jnp.asarray(ib[4], jnp.float32)
            params["inter"]["bias"] = jnp.asarray(ib[5], jnp.float32)
            params["output"]["bias"] = jnp.asarray(ib[6], jnp.float32)
            params["norm"]["bias"] = jnp.asarray(ib[7], jnp.float32)
        return params

    # -- forward -----------------------------------------------------------
    def _norm(self, x, p):
        eps = self.config.layer_norm_eps
        x32 = x.astype(jnp.float32)
        mu = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.mean((x32 - mu) ** 2, axis=-1, keepdims=True)
        return ((x32 - mu) * jax.lax.rsqrt(var + eps) * p["scale"] + p["bias"]).astype(x.dtype)

    def _attention(self, params, h, mask, attn_rng=None):
        cfg = self.config
        B, S, H = h.shape
        nh = cfg.heads
        d = H // nh
        qkv = jnp.einsum("bsh,hd->bsd", h, params["qkv"]["kernel"].astype(h.dtype)) \
            + params["qkv"]["bias"].astype(h.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        if mask is None and attn_rng is None:
            # flash_attention owns its shape gate and falls back internally
            from ..pallas.flash_attention import flash_attention

            ctx = flash_attention(q.reshape(B, S, nh, d), k.reshape(B, S, nh, d),
                                  v.reshape(B, S, nh, d), causal=False)
            ctx = ctx.reshape(B, S, H)
        else:
            qh = q.reshape(B, S, nh, d).transpose(0, 2, 1, 3).astype(jnp.float32)
            kh = k.reshape(B, S, nh, d).transpose(0, 2, 1, 3).astype(jnp.float32)
            vh = v.reshape(B, S, nh, d).transpose(0, 2, 1, 3).astype(jnp.float32)
            s = jnp.einsum("bnqd,bnkd->bnqk", qh, kh) / math.sqrt(d)
            if mask is not None:
                s = s + jnp.asarray(mask, jnp.float32)
            p = jax.nn.softmax(s, axis=-1)
            if attn_rng is not None:  # attention-prob dropout (reference kernel)
                keep = 1.0 - cfg.attn_dropout_ratio
                p = p * jax.random.bernoulli(attn_rng, keep, p.shape) / keep
            ctx = jnp.einsum("bnqk,bnkd->bnqd", p, vh).transpose(0, 2, 1, 3).reshape(B, S, H)
            ctx = ctx.astype(h.dtype)
        out = jnp.einsum("bsh,hd->bsd", ctx, params["attn_out"]["kernel"].astype(h.dtype)) \
            + params["attn_out"]["bias"].astype(h.dtype)
        return out

    def _dropout_rngs(self, rng, training):
        """Resolve the three dropout streams; LOUD when dropout is configured
        for training but no rng was passed (a silent no-dropout would change
        training dynamics vs the reference without warning)."""
        cfg = self.config
        train = cfg.training if training is None else training
        want_attn = train and cfg.attn_dropout_ratio > 0.0
        want_hidden = train and cfg.hidden_dropout_ratio > 0.0
        if (want_attn or want_hidden) and rng is None:
            raise ValueError("dropout is configured (attn/hidden ratio > 0, training=True) "
                             "but apply() received no rng — pass rng=jax.random.PRNGKey(...) "
                             "or set training=False")
        if not (want_attn or want_hidden):
            return None, None, None
        k = jax.random.split(rng, 3)
        return (k[0] if want_attn else None,
                k[1] if want_hidden else None,
                k[2] if want_hidden else None)

    def _hidden_dropout(self, x, rng):
        if rng is None:
            return x
        keep = 1.0 - self.config.hidden_dropout_ratio
        return x * jax.random.bernoulli(rng, keep, x.shape).astype(x.dtype) / keep

    def _maybe_tuple(self, out):
        return (out, ) if self.config.return_tuple else out

    def apply(self, params, hidden_states, attention_mask=None, rng=None, training=None):
        cfg = self.config
        attn_rng, h1_rng, h2_rng = self._dropout_rngs(rng, training)
        x = hidden_states.astype(jnp.bfloat16 if cfg.fp16 else hidden_states.dtype)
        if cfg.pre_layer_norm:
            attn = self._attention(params, self._norm(x, params["attn_norm"]), attention_mask,
                                   attn_rng)
            x = x + self._hidden_dropout(attn, h1_rng)
            h = self._norm(x, params["norm"])
            inter = jax.nn.gelu(jnp.einsum("bsh,hf->bsf", h, params["inter"]["kernel"].astype(x.dtype))
                                + params["inter"]["bias"].astype(x.dtype), approximate=False)
            out = jnp.einsum("bsf,fh->bsh", inter, params["output"]["kernel"].astype(x.dtype)) \
                + params["output"]["bias"].astype(x.dtype)
            return self._maybe_tuple(x + self._hidden_dropout(out, h2_rng))
        # post-LN (original BERT)
        attn = self._attention(params, x, attention_mask, attn_rng)
        x = self._norm(x + self._hidden_dropout(attn, h1_rng), params["attn_norm"])
        inter = jax.nn.gelu(jnp.einsum("bsh,hf->bsf", x, params["inter"]["kernel"].astype(x.dtype))
                            + params["inter"]["bias"].astype(x.dtype), approximate=False)
        out = jnp.einsum("bsf,fh->bsh", inter, params["output"]["kernel"].astype(x.dtype)) \
            + params["output"]["bias"].astype(x.dtype)
        return self._maybe_tuple(self._norm(x + self._hidden_dropout(out, h2_rng), params["norm"]))

    __call__ = apply
