"""Public transformer-kernel layer API (reference
``deepspeed/ops/transformer/``)."""

from .transformer import DeepSpeedTransformerConfig, DeepSpeedTransformerLayer  # noqa: F401
