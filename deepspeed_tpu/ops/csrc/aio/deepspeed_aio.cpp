// deepspeed_tpu async file I/O library — TPU-native equivalent of the
// reference csrc/aio/ (deepspeed_aio_thread.cpp + py_lib bindings, ~1,693 LoC):
// a host-side thread pool issuing O_DIRECT-capable pread/pwrite for
// ZeRO-Infinity NVMe tiering. Exposed through a C ABI consumed via ctypes
// (no pybind11 in this image).
//
// Semantics match the reference aio handle: submit N requests, wait() blocks
// until all complete, first error wins. O_DIRECT is attempted when requested
// and alignment permits; otherwise falls back to buffered I/O (the reference
// gates this the same way through its aio config block).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <errno.h>
#include <fcntl.h>
#include <stdlib.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

namespace {

constexpr size_t kDirectAlign = 4096;

struct Request {
    bool is_read;
    std::string path;
    void* buf;
    int64_t nbytes;
    int64_t offset;
};

struct AioHandle {
    int n_threads;
    int64_t block_size;
    bool use_o_direct;

    std::mutex mu;
    std::condition_variable cv_work;
    std::condition_variable cv_done;
    std::deque<Request> queue;
    int in_flight = 0;
    int first_error = 0;  // negative errno of first failure
    bool shutting_down = false;
    std::vector<std::thread> workers;
};

bool aligned_ok(const Request& r) {
    return (reinterpret_cast<uintptr_t>(r.buf) % kDirectAlign == 0) && (r.nbytes % kDirectAlign == 0) &&
           (r.offset % kDirectAlign == 0);
}

int do_io(AioHandle* h, const Request& r) {
    int flags = r.is_read ? O_RDONLY : (O_WRONLY | O_CREAT);
    bool o_direct = h->use_o_direct && aligned_ok(r);
#ifdef O_DIRECT
    if (o_direct) flags |= O_DIRECT;
#endif
    int fd = ::open(r.path.c_str(), flags, 0644);
#ifdef O_DIRECT
    if (fd < 0 && o_direct) {  // filesystem may refuse O_DIRECT (e.g. tmpfs)
        flags &= ~O_DIRECT;
        fd = ::open(r.path.c_str(), flags, 0644);
    }
#endif
    if (fd < 0) return -errno;

    char* p = static_cast<char*>(r.buf);
    int64_t remaining = r.nbytes;
    int64_t off = r.offset;
    const int64_t chunk = h->block_size > 0 ? h->block_size : (1 << 20);
    int rc = 0;
    while (remaining > 0) {
        int64_t n = remaining < chunk ? remaining : chunk;
        ssize_t got = r.is_read ? ::pread(fd, p, n, off) : ::pwrite(fd, p, n, off);
        if (got < 0) {
            if (errno == EINTR) continue;
            rc = -errno;
            break;
        }
        if (got == 0) {  // short file on read
            rc = -EIO;
            break;
        }
        p += got;
        off += got;
        remaining -= got;
    }
    ::close(fd);
    return rc;
}

void worker_loop(AioHandle* h) {
    for (;;) {
        Request req;
        {
            std::unique_lock<std::mutex> lk(h->mu);
            h->cv_work.wait(lk, [h] { return h->shutting_down || !h->queue.empty(); });
            if (h->queue.empty()) {
                if (h->shutting_down) return;
                continue;
            }
            req = std::move(h->queue.front());
            h->queue.pop_front();
        }
        int rc = do_io(h, req);
        {
            std::lock_guard<std::mutex> lk(h->mu);
            if (rc != 0 && h->first_error == 0) h->first_error = rc;
            h->in_flight--;
            if (h->in_flight == 0 && h->queue.empty()) h->cv_done.notify_all();
        }
    }
}

int submit(AioHandle* h, bool is_read, const char* path, void* buf, int64_t nbytes, int64_t offset) {
    if (!h || !path || !buf || nbytes < 0) return -EINVAL;
    {
        std::lock_guard<std::mutex> lk(h->mu);
        if (h->shutting_down) return -ESHUTDOWN;
        h->queue.push_back(Request{is_read, path, buf, nbytes, offset});
        h->in_flight++;
    }
    h->cv_work.notify_one();
    return 0;
}

}  // namespace

extern "C" {

void* ds_aio_create(int n_threads, long long block_size, int use_o_direct) {
    auto* h = new AioHandle();
    h->n_threads = n_threads > 0 ? n_threads : 1;
    h->block_size = block_size;
    h->use_o_direct = use_o_direct != 0;
    for (int i = 0; i < h->n_threads; ++i) h->workers.emplace_back(worker_loop, h);
    return h;
}

int ds_aio_submit_read(void* handle, const char* path, void* buf, long long nbytes, long long offset) {
    return submit(static_cast<AioHandle*>(handle), true, path, buf, nbytes, offset);
}

int ds_aio_submit_write(void* handle, const char* path, void* buf, long long nbytes, long long offset) {
    return submit(static_cast<AioHandle*>(handle), false, path, buf, nbytes, offset);
}

// Block until every submitted request completed; returns 0 or the negative
// errno of the first failed request (then resets the error latch).
int ds_aio_wait(void* handle) {
    auto* h = static_cast<AioHandle*>(handle);
    std::unique_lock<std::mutex> lk(h->mu);
    h->cv_done.wait(lk, [h] { return h->in_flight == 0 && h->queue.empty(); });
    int rc = h->first_error;
    h->first_error = 0;
    return rc;
}

int ds_aio_pending(void* handle) {
    auto* h = static_cast<AioHandle*>(handle);
    std::lock_guard<std::mutex> lk(h->mu);
    return h->in_flight;
}

void ds_aio_destroy(void* handle) {
    auto* h = static_cast<AioHandle*>(handle);
    {
        std::lock_guard<std::mutex> lk(h->mu);
        h->shutting_down = true;
    }
    h->cv_work.notify_all();
    for (auto& t : h->workers) t.join();
    delete h;
}

int ds_aio_sync_pread(const char* path, void* buf, long long nbytes, long long offset) {
    AioHandle tmp;
    tmp.block_size = 1 << 20;
    tmp.use_o_direct = false;
    return do_io(&tmp, Request{true, path, buf, nbytes, offset});
}

int ds_aio_sync_pwrite(const char* path, void* buf, long long nbytes, long long offset) {
    AioHandle tmp;
    tmp.block_size = 1 << 20;
    tmp.use_o_direct = false;
    return do_io(&tmp, Request{false, path, buf, nbytes, offset});
}

}  // extern "C"
