// Fused host-side Adam/AdamW — TPU-native equivalent of the reference
// csrc/adam/cpu_adam.cpp + cpu_adam_impl.cpp (+ simd.h AVX kernels):
// the ZeRO-Offload optimizer that updates fp32 master weights and moments in
// host RAM while the device keeps bf16 compute params. Vectorization comes
// from -O3 -march=native on the flat loops (the compiler emits the same
// AVX2/AVX512 FMA sequences the reference hand-writes in simd.h); threading
// splits the flat range across std::threads like the reference's
// parallel-for over tile chunks.
//
// C ABI (ctypes, no pybind11 in this image). An optimizer registry keyed by
// optimizer_id mirrors the reference create_adam/ds_adam_step interface.

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct AdamConfig {
    float lr;
    float beta1;
    float beta2;
    float eps;
    float weight_decay;
    bool adamw_mode;  // true: decoupled decay (AdamW); false: L2 into grad
};

std::mutex g_mu;
std::unordered_map<int, AdamConfig> g_optimizers;

// round-to-nearest-even float32 -> bfloat16 (bit pattern), matching XLA
inline uint16_t float_to_bf16(float f) {
    uint32_t x;
    std::memcpy(&x, &f, 4);
    uint32_t lsb = (x >> 16) & 1;
    uint32_t rounded = x + 0x7fff + lsb;
    return static_cast<uint16_t>(rounded >> 16);
}

void adam_chunk(const AdamConfig& cfg, int64_t begin, int64_t end, int64_t step, float* params, const float* grads,
                float* exp_avg, float* exp_avg_sq, uint16_t* bf16_out, float grad_scale) {
    const float b1 = cfg.beta1, b2 = cfg.beta2;
    const float bias1 = 1.0f - std::pow(b1, static_cast<float>(step));
    const float bias2 = 1.0f - std::pow(b2, static_cast<float>(step));
    const float step_size = cfg.lr / bias1;
    const float inv_sqrt_bias2 = 1.0f / std::sqrt(bias2);
    const float decay = cfg.weight_decay;

#pragma omp simd
    for (int64_t i = begin; i < end; ++i) {
        float g = grads[i] * grad_scale;
        if (!cfg.adamw_mode && decay != 0.0f) g += decay * params[i];
        float m = b1 * exp_avg[i] + (1.0f - b1) * g;
        float v = b2 * exp_avg_sq[i] + (1.0f - b2) * g * g;
        exp_avg[i] = m;
        exp_avg_sq[i] = v;
        float denom = std::sqrt(v) * inv_sqrt_bias2 + cfg.eps;
        float p = params[i];
        if (cfg.adamw_mode && decay != 0.0f) p -= cfg.lr * decay * p;
        p -= step_size * m / denom;
        params[i] = p;
        if (bf16_out) bf16_out[i] = float_to_bf16(p);
    }
}

}  // namespace

extern "C" {

int ds_adam_create(int optimizer_id, float lr, float beta1, float beta2, float eps, float weight_decay,
                   int adamw_mode) {
    std::lock_guard<std::mutex> lk(g_mu);
    g_optimizers[optimizer_id] = AdamConfig{lr, beta1, beta2, eps, weight_decay, adamw_mode != 0};
    return 0;
}

int ds_adam_destroy(int optimizer_id) {
    std::lock_guard<std::mutex> lk(g_mu);
    return g_optimizers.erase(optimizer_id) ? 0 : -1;
}

// One fused Adam step over a flat range. step is 1-based (bias correction).
// lr < 0 keeps the configured lr (so schedules can drive it per step).
// bf16_out != nullptr also emits bf16 copies of the new params for device
// upload (the reference's ds_adam_step_plus_copy).
int ds_adam_step(int optimizer_id, long long step, long long n, float* params, const float* grads, float* exp_avg,
                 float* exp_avg_sq, float lr, float grad_scale, unsigned short* bf16_out, int n_threads) {
    AdamConfig cfg;
    {
        std::lock_guard<std::mutex> lk(g_mu);
        auto it = g_optimizers.find(optimizer_id);
        if (it == g_optimizers.end()) return -1;
        cfg = it->second;
    }
    if (lr >= 0.0f) cfg.lr = lr;
    if (n <= 0) return 0;

    int hw = static_cast<int>(std::thread::hardware_concurrency());
    int nt = n_threads > 0 ? n_threads : (hw > 0 ? hw : 1);
    int64_t min_chunk = 1 << 16;
    nt = static_cast<int>(std::min<int64_t>(nt, (n + min_chunk - 1) / min_chunk));
    if (nt <= 1) {
        adam_chunk(cfg, 0, n, step, params, grads, exp_avg, exp_avg_sq, bf16_out, grad_scale);
        return 0;
    }
    std::vector<std::thread> threads;
    int64_t per = (n + nt - 1) / nt;
    for (int t = 0; t < nt; ++t) {
        int64_t b = t * per, e = std::min<int64_t>(n, b + per);
        if (b >= e) break;
        threads.emplace_back([&, b, e] {
            adam_chunk(cfg, b, e, step, params, grads, exp_avg, exp_avg_sq, bf16_out, grad_scale);
        });
    }
    for (auto& t : threads) t.join();
    return 0;
}

// fp32 -> bf16 conversion helper (device-upload staging)
void ds_fp32_to_bf16(const float* src, unsigned short* dst, long long n) {
#pragma omp simd
    for (int64_t i = 0; i < n; ++i) dst[i] = float_to_bf16(src[i]);
}

}  // extern "C"
