"""Spatial (diffusers) ops.

TPU equivalent of the reference ``csrc/spatial/csrc/opt_bias_add.cu``
(``SpatialInferenceBuilder`` → bias-add variants used by the diffusers
UNet/VAE wrappers, ``deepspeed/ops/transformer/inference/diffusers_*``).
On TPU these are jnp expressions XLA fuses into the surrounding convs; the
functions exist so the diffusers-policy surface has a 1:1 target and the
numerics are pinned by tests.
"""

import jax.numpy as jnp


def bias_add(activation, bias):
    """opt_bias_add: NHWC activation + per-channel bias."""
    return activation + bias.astype(activation.dtype)


def bias_add_add(activation, bias, other):
    """opt_bias_add_add: (activation + bias) + other (residual join)."""
    return activation + bias.astype(activation.dtype) + other.astype(activation.dtype)


def bias_add_bias_add(activation, bias, other, other_bias):
    """opt_bias_add_bias_add: (a + b) + (o + ob) — the UNet dual-residual."""
    return (activation + bias.astype(activation.dtype)
            + other.astype(activation.dtype) + other_bias.astype(activation.dtype))


def nhwc_bias_add_activation(activation, bias, act: str = "silu"):
    """Fused bias + nonlinearity (reference GroupNorm epilogues)."""
    x = activation + bias.astype(activation.dtype)
    if act == "silu":
        return x * jnp.reciprocal(1.0 + jnp.exp(-x.astype(jnp.float32))).astype(x.dtype)
    if act == "gelu":
        import jax

        return jax.nn.gelu(x)
    if act in (None, "none"):
        return x
    raise ValueError(f"unknown activation {act!r}")
