"""Ragged grouped matmul (megablocks-style) for MoE expert FFNs.

Reference counterpart: the CUTLASS grouped expert GEMM
(``inference/v2/kernels/cutlass_ops/`` moe_gemm) — E variable-size GEMMs,
one per expert, over that expert's gathered tokens. SURVEY §2.3 plans the
TPU version as a Pallas ragged matmul; VERDICT r4 missing #5 flagged the
one-hot ``[S, E, C]`` dispatch einsum as the scaling bottleneck at large E.

TPU-first formulation: dynamic per-expert row counts are shape-hostile, so
the DISPATCHER block-aligns every expert's token group (each group padded to
a multiple of the row-block size, zero rows) and hands the kernel a
scalar-prefetched ``block_expert[i]`` table — the expert owning row block
``i``. Every row block then multiplies exactly one expert's weight block, so
the kernel is a plain tiled matmul whose RHS block index is data-dependent
through the prefetch table (the same mechanism the block-sparse attention
kernel uses for its column LUT). Work scales with actual tokens
(+ at most one padding block per expert), not with S*E*C.

Two kernels:
  - :func:`gmm`  — ``[T, K] x [E, K, N] -> [T, N]``: row block i uses
    ``rhs[block_expert[i]]`` (forward, and dx with rhs transposed).
  - :func:`tgmm` — ``[T, K] x [T, N] -> [E, K, N]``: per-expert
    ``x_e^T @ dy_e`` accumulated across that expert's row blocks (dw).
    Requires every expert to own >=1 row block (the dispatcher's padding
    guarantees it) so every output block is written.

:func:`grouped_matmul` wraps gmm with a custom VJP so the training MoE layer
can differentiate through it.
"""

import functools

import jax
import jax.numpy as jnp


def _fit_block(dim: int, preferred: int) -> int:
    """Largest power-of-two block <= preferred that divides dim (1 worst case)."""
    b = preferred
    while b > 1 and dim % b != 0:
        b //= 2
    return max(b, 1)


def _resolve_gmm_tiles(K: int, N: int, block_k=None, block_n=None):
    """K/N tile resolution: explicit caller value > kernel-config registry
    (per chip/topology/shape bucket) > the 512 default. ``block_t`` is NOT
    tunable here — it is a dispatcher contract (block_expert's shape)."""
    from ...autotuning.kernel_config import shape_bucket, tuned_tile

    bucket = shape_bucket(K=K, N=N)
    bk = block_k if block_k is not None else tuned_tile("grouped_matmul", bucket, "block_k", 512)
    bn = block_n if block_n is not None else tuned_tile("grouped_matmul", bucket, "block_n", 512)
    return int(bk), int(bn)


def gmm_reference(lhs, rhs, block_expert, block_t=128):
    """jnp gather oracle for :func:`gmm` — the numerics reference the kernel
    is tested against (and the always-available fallback contract the
    ``tools/check_kernel_configs.py`` gate demands of every tuned kernel)."""
    expert_per_row = jnp.repeat(block_expert, block_t)
    out = jnp.einsum("tk,tkn->tn", lhs.astype(jnp.float32),
                     rhs[expert_per_row].astype(jnp.float32))
    return out.astype(lhs.dtype)


def gmm(lhs, rhs, block_expert, block_t=128, block_k=None, block_n=None, interpret=False):
    """Grouped matmul ``out[i*bt:(i+1)*bt] = lhs[i*bt:(i+1)*bt] @
    rhs[block_expert[i]]``.

    lhs: [T, K] block-aligned expert-sorted rows; rhs: [E, K, N] stacked
    expert weights; block_expert: [T//block_t] int32 (non-decreasing).
    Returns [T, N] in lhs.dtype; fp32 accumulation.

    Registry tiles resolve HERE, outside the jit: resolving inside would key
    the compiled-executable cache on ``block_k=None`` and freeze the
    first-seen tiles — a later kernel-config install would be silently
    ignored for already-traced shapes.
    """
    block_k, block_n = _resolve_gmm_tiles(lhs.shape[1], rhs.shape[2], block_k, block_n)
    return _gmm(lhs, rhs, block_expert, block_t, block_k, block_n, interpret)


@functools.partial(jax.jit, static_argnames=("block_t", "block_k", "block_n", "interpret"))
def _gmm(lhs, rhs, block_expert, block_t, block_k, block_n, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T, K = lhs.shape
    E, K2, N = rhs.shape
    assert K == K2, f"contraction mismatch {K} vs {K2}"
    # block_t is a CONTRACT with the dispatcher (block_expert's shape is tied
    # to it) — never refit it; K/N tiles are free to shrink to fit
    bt = block_t
    assert T % bt == 0, f"T={T} must be a multiple of block_t={bt} (block-aligned dispatch)"
    bk = _fit_block(K, block_k)
    bn = _fit_block(N, block_n)
    nt, nk, nn = T // bt, K // bk, N // bn
    assert block_expert.shape == (nt, ), \
        f"block_expert must be [{nt}] for T={T}, block_t={bt}, got {block_expert.shape}"

    def kernel(be_ref, x_ref, w_ref, o_ref, acc_ref):
        k = pl.program_id(2)

        @pl.when(k == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        acc_ref[:] += jax.lax.dot(x_ref[...].astype(jnp.float32),
                                  w_ref[0].astype(jnp.float32),
                                  preferred_element_type=jnp.float32)

        @pl.when(k == nk - 1)
        def _store():
            o_ref[:] = acc_ref[:].astype(o_ref.dtype)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nt, nn, nk),
        in_specs=[
            pl.BlockSpec((bt, bk), lambda i, j, k, be: (i, k)),
            pl.BlockSpec((1, bk, bn), lambda i, j, k, be: (be[i], k, j)),
        ],
        out_specs=pl.BlockSpec((bt, bn), lambda i, j, k, be: (i, j)),
        scratch_shapes=[pltpu.VMEM((bt, bn), jnp.float32)],
    )
    return pl.pallas_call(kernel, grid_spec=grid_spec,
                          out_shape=jax.ShapeDtypeStruct((T, N), lhs.dtype),
                          interpret=interpret)(block_expert, lhs, rhs)


def tgmm(lhs, dy, block_expert, num_experts, block_t=128, block_k=None, block_n=None,
         interpret=False):
    """Per-expert weight gradient ``out[e] = sum_{i: be[i]=e}
    lhs_block_i^T @ dy_block_i`` → [E, K, N] (fp32).

    ``block_expert`` must be non-decreasing AND cover every expert in
    [0, num_experts) at least once (block-aligned dispatch guarantees both);
    otherwise an absent expert's output block would never be written.
    Registry tiles resolve outside the jit (see :func:`gmm`).
    """
    block_k, block_n = _resolve_gmm_tiles(lhs.shape[1], dy.shape[1], block_k, block_n)
    return _tgmm(lhs, dy, block_expert, num_experts, block_t, block_k, block_n, interpret)


@functools.partial(jax.jit,
                   static_argnames=("num_experts", "block_t", "block_k", "block_n", "interpret"))
def _tgmm(lhs, dy, block_expert, num_experts, block_t, block_k, block_n, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T, K = lhs.shape
    T2, N = dy.shape
    assert T == T2, f"row mismatch {T} vs {T2}"
    bt = block_t  # dispatcher contract, same as gmm
    assert T % bt == 0, f"T={T} must be a multiple of block_t={bt} (block-aligned dispatch)"
    bk = _fit_block(K, block_k)
    bn = _fit_block(N, block_n)
    nt, nk, nn = T // bt, K // bk, N // bn
    assert block_expert.shape == (nt, ), \
        f"block_expert must be [{nt}] for T={T}, block_t={bt}, got {block_expert.shape}"

    def kernel(be_ref, x_ref, dy_ref, o_ref, acc_ref):
        t = pl.program_id(2)
        e = be_ref[t]
        # group boundaries: zero the accumulator on the first block of each
        # expert's run, write back on the last (out block changes there)
        first = jnp.logical_or(t == 0, be_ref[jnp.maximum(t - 1, 0)] != e)
        last = jnp.logical_or(t == nt - 1, be_ref[jnp.minimum(t + 1, nt - 1)] != e)

        @pl.when(first)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        acc_ref[:] += jax.lax.dot_general(
            x_ref[...].astype(jnp.float32), dy_ref[...].astype(jnp.float32),
            dimension_numbers=(((0, ), (0, )), ((), ())),
            preferred_element_type=jnp.float32)

        @pl.when(last)
        def _store():
            o_ref[0] = acc_ref[:]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nk, nn, nt),
        in_specs=[
            pl.BlockSpec((bt, bk), lambda i, j, t, be: (t, i)),
            pl.BlockSpec((bt, bn), lambda i, j, t, be: (t, j)),
        ],
        out_specs=pl.BlockSpec((1, bk, bn), lambda i, j, t, be: (be[t], i, j)),
        scratch_shapes=[pltpu.VMEM((bk, bn), jnp.float32)],
    )
    return pl.pallas_call(kernel, grid_spec=grid_spec,
                          out_shape=jax.ShapeDtypeStruct((num_experts, K, N), jnp.float32),
                          interpret=interpret)(block_expert, lhs, dy)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, ))
def _gm(lhs, rhs, block_expert, opts):
    bt, bk, bn, interpret = opts
    return gmm(lhs, rhs, block_expert, bt, bk, bn, interpret)


def _gm_fwd(lhs, rhs, block_expert, opts):
    return _gm(lhs, rhs, block_expert, opts), (lhs, rhs, block_expert)


def _gm_bwd(opts, res, dy):
    import numpy as np

    lhs, rhs, block_expert = res
    bt, bk, bn, interpret = opts
    dy = dy.astype(lhs.dtype)
    dx = gmm(dy, rhs.transpose(0, 2, 1), block_expert, bt, bk, bn, interpret)
    dw = tgmm(lhs, dy, block_expert, rhs.shape[0], bt, bk, bn, interpret).astype(rhs.dtype)
    # block_expert is integer routing metadata: float0 cotangent
    return dx, dw, np.zeros(block_expert.shape, dtype=jax.dtypes.float0)


_gm.defvjp(_gm_fwd, _gm_bwd)


def grouped_matmul(lhs, rhs, block_expert, block_t=128, block_k=None, block_n=None,
                   interpret=False):
    """Differentiable grouped matmul: gmm forward; backward dx via gmm
    against the transposed expert weights, dw via tgmm. ``block_expert`` is
    an explicit primal (not a closure capture) so the VJP stays valid inside
    scans/jits where the table is itself a traced value."""
    return _gm(lhs, rhs, block_expert, (block_t, block_k, block_n, interpret))
