"""Fused RMSNorm / LayerNorm.

TPU analog of the reference inference norm kernels
(``csrc/transformer/inference/csrc/{layer_norm,rms_norm}.cu`` and v2
``kernels/core_ops/cuda_rms_norm``). jnp-level: XLA fuses the reduction +
scale chain; kept as a named op so models and inference modules share one
numerics-tested implementation.
"""

import jax
import jax.numpy as jnp


def rms_norm(x, scale, eps: float = 1e-5):
    x32 = x.astype(jnp.float32)
    out = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps) * scale
    return out.astype(x.dtype)


def layer_norm(x, scale, bias=None, eps: float = 1e-5):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean((x32 - mu)**2, axis=-1, keepdims=True)
    out = (x32 - mu) * jax.lax.rsqrt(var + eps) * scale
    if bias is not None:
        out = out + bias
    return out.astype(x.dtype)


def rms_norm_residual(x, residual, scale, eps: float = 1e-5):
    """Fused residual-add + rmsnorm (reference ``pre_rms_norm`` pattern)."""
    s = x + residual
    return rms_norm(s, scale, eps), s
