"""Paged (blocked) attention over a flat KV pool — the FastGen data-plane
kernel.

Analog of the reference ``v2/kernels/ragged_ops/blocked_flash`` (CUDA flash
attention adapted to paged KV block tables, SURVEY.md §2.3). TPU design: a
Pallas kernel on a ``(tokens, kv_blocks)`` grid using
``PrefetchScalarGridSpec`` so the K/V BlockSpec index maps read the *block
table* (scalar-prefetched) — the DMA engine then streams exactly the KV
blocks each token's sequence owns, straight from HBM, while the online
softmax accumulates in VMEM scratch across the inner grid dimension.

Token-level formulation: query token ``t`` belongs to ``seq_idx[t]`` at
absolute position ``pos[t]`` and attends all cached positions ``<= pos[t]``.
This covers prefill chunks and decode steps uniformly (Dynamic SplitFuse
mixes both in one batch).

``paged_attention_reference`` is the jnp gather implementation used for CPU
tests and as the numerics oracle (reference test strategy: kernel vs
reference, tests/unit/inference/v2/kernels).
"""

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np


def paged_attention(q, k_pool, v_pool, block_tables, seq_idx, pos, block_size: int, window=None,
                    alibi=None, k_scale=None, v_scale=None):
    """q: [T, nq, d]; k_pool/v_pool: [pool_len, nkv, d] (one layer,
    pool_len = num_blocks*block_size, may include one trailing scratch slot);
    block_tables: [S, max_blocks]; seq_idx/pos: [T].
    ``window``: sliding-window attention (Mistral) — token at position p
    attends cached positions in (p - window, p].
    ``k_scale``/``v_scale``: int8-KV mode (the FastGen quantized-KV analog,
    reference ``csrc/quantization/``) — pools hold int8 values and the
    scales [nkv, pool_len] hold one fp32 absmax/127 factor per (kv-head,
    slot); dequant happens at the kernel's tile read, so only int8 bytes
    stream from HBM.
    Returns [T, nq, d]."""
    T, nq, d = q.shape
    nkv = k_pool.shape[1]
    if window is not None:
        window = int(window)
    if jax.default_backend() != "tpu" or nq < 8 or d % 128 != 0:
        if jax.default_backend() == "tpu":
            # off-TPU the oracle is the design; ON TPU a shape miss silently
            # costing a full context gather per layer per step must be loud
            from ...utils.logging import warning_once

            warning_once(f"pallas paged attention: unsupported shape (nq={nq}, d={d}; needs "
                         "nq>=8, d%128==0) — serving through the DENSE gather fallback")
        return paged_attention_reference(q, k_pool, v_pool, block_tables, seq_idx, pos, block_size,
                                         window=window, alibi=alibi, k_scale=k_scale, v_scale=v_scale)
    try:
        return _pallas_paged(q, k_pool, v_pool, block_tables, seq_idx.astype(jnp.int32), pos.astype(jnp.int32),
                             block_size=block_size, window=window,
                             alibi=tuple(np.asarray(alibi).tolist()) if alibi is not None else None,
                             k_scale=k_scale, v_scale=v_scale)
    except Exception as e:  # pragma: no cover — kernel bring-up safety net
        from ...utils.logging import warning_once

        warning_once(f"pallas paged attention unavailable ({type(e).__name__}: {e}); using gather fallback")
        return paged_attention_reference(q, k_pool, v_pool, block_tables, seq_idx, pos, block_size,
                                         window=window, alibi=alibi, k_scale=k_scale, v_scale=v_scale)


def paged_attention_reference(q, k_pool, v_pool, block_tables, seq_idx, pos, block_size: int,
                              window=None, alibi=None, k_scale=None, v_scale=None):
    """Gather-based oracle: materializes each sequence's context. ``alibi``:
    per-head slopes [nq] (Bloom). ``k_scale``/``v_scale``: int8-KV
    dequantization factors [nkv, pool_len] (see ``paged_attention``)."""
    T, nq, d = q.shape
    nkv = k_pool.shape[1]
    g = nq // nkv
    S, max_blocks = block_tables.shape
    C = max_blocks * block_size
    ctx_slots = (block_tables[:, :, None] * block_size +
                 jnp.arange(block_size, dtype=jnp.int32)[None, None, :]).reshape(S, C)
    ctxk = k_pool[ctx_slots].astype(jnp.float32)  # [S, C, nkv, d]
    ctxv = v_pool[ctx_slots].astype(jnp.float32)
    if k_scale is not None:
        ctxk = ctxk * jnp.transpose(k_scale)[ctx_slots][..., None]  # [S, C, nkv, 1]
        ctxv = ctxv * jnp.transpose(v_scale)[ctx_slots][..., None]
    qr = (q.astype(jnp.float32) / math.sqrt(d)).reshape(T, nkv, g, d)
    s = jnp.einsum("tngd,tcnd->tngc", qr, ctxk[seq_idx])
    if alibi is not None:
        rel = (jnp.arange(C, dtype=jnp.float32)[None, :] - pos[:, None].astype(jnp.float32))
        s = s + jnp.asarray(alibi, jnp.float32).reshape(nkv, g)[None, :, :, None] * rel[:, None, None, :]
    causal = jnp.arange(C, dtype=jnp.int32)[None, :] <= pos[:, None]
    if window is not None:
        causal = causal & (pos[:, None] - jnp.arange(C, dtype=jnp.int32)[None, :] < window)
    s = jnp.where(causal[:, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("tngc,tcnd->tngd", p, ctxv[seq_idx])
    return out.reshape(T, nq, d).astype(q.dtype)


@functools.partial(jax.jit, static_argnames=("block_size", "interpret", "window", "alibi"))
def _pallas_paged(q, k_pool, v_pool, block_tables, seq_idx, pos, block_size: int, interpret: bool = False,
                  window=None, alibi=None, k_scale=None, v_scale=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T, nq, d = q.shape
    nkv = k_pool.shape[1]
    g = nq // nkv
    S, max_blocks = block_tables.shape
    # view the pool as whole blocks; drop any trailing scratch remainder
    n_pool_blocks = k_pool.shape[0] // block_size
    k4 = k_pool[:n_pool_blocks * block_size].reshape(n_pool_blocks, block_size, nkv, d)
    v4 = v_pool[:n_pool_blocks * block_size].reshape(n_pool_blocks, block_size, nkv, d)
    quant = k_scale is not None
    if quant:
        # scales stay [nkv, cols]: sublane = nkv, lane = block_size — the
        # layout the scatter side maintains natively, no per-call transpose
        ks2 = k_scale[:, :n_pool_blocks * block_size]
        vs2 = v_scale[:, :n_pool_blocks * block_size]
    scale = 1.0 / math.sqrt(d)

    grid = (T, max_blocks)

    def q_map(t, j, seq_ref, pos_ref, bt_ref):
        return (t, 0, 0)

    def kv_map(t, j, seq_ref, pos_ref, bt_ref):
        # clamp j into the token's live range: the index map runs (and its
        # DMA issues) even for grid steps the kernel's pl.when skips, so
        # out-of-range columns are remapped to an in-range block — Mosaic
        # sees a repeated index and skips the refetch instead of streaming
        # blocks the online softmax never reads
        hi = pos_ref[t] // block_size
        jj = jnp.minimum(j, hi)
        if window is not None:
            lo = jnp.maximum(pos_ref[t] - (window - 1), 0) // block_size
            jj = jnp.maximum(jj, jnp.minimum(lo, hi))
        return (bt_ref[seq_ref[t], jj], 0, 0, 0)

    def kernel(seq_ref, pos_ref, bt_ref, q_ref, k_ref, v_ref, *rest):
        if quant:
            ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
        else:
            o_ref, acc_ref, m_ref, l_ref = rest
        t = pl.program_id(0)
        j = pl.program_id(1)
        my_pos = pos_ref[t]

        @pl.when(j == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)
            m_ref[:] = jnp.full_like(m_ref, -1e30)
            l_ref[:] = jnp.zeros_like(l_ref)

        in_window = (j * block_size <= my_pos) if window is None else jnp.logical_and(
            j * block_size <= my_pos, (j + 1) * block_size - 1 > my_pos - window)

        @pl.when(in_window)
        def _compute():
            qb = q_ref[0].astype(jnp.float32) * scale  # [nq, d]
            kb = k_ref[0].astype(jnp.float32)  # [bs, nkv, d]
            vb = v_ref[0].astype(jnp.float32)
            if quant:  # dequant at the VMEM tile — HBM only streamed int8
                kb = kb * ks_ref[...].T[:, :, None]  # [bs, nkv, 1]
                vb = vb * vs_ref[...].T[:, :, None]
            # per-kv-head 2-D MXU dots (Mosaic has no mismatched-batch dots);
            # nkv is small and static so the loop unrolls at trace time
            s_heads = []
            for n in range(nkv):
                s_heads.append(jax.lax.dot(qb[n * g:(n + 1) * g], kb[:, n, :].T))  # [g, bs]
            s = jnp.concatenate(s_heads, axis=0)  # [nq, bs]
            kpos = j * block_size + jax.lax.broadcasted_iota(jnp.int32, (nq, block_size), 1)
            if alibi is not None:
                slopes = jnp.asarray(alibi, jnp.float32)[:, None]
                s = s + slopes * (kpos - my_pos).astype(jnp.float32)
            vis = kpos <= my_pos
            if window is not None:
                vis = jnp.logical_and(vis, my_pos - kpos < window)
            s = jnp.where(vis, s, -1e30)
            m_prev = m_ref[:]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)  # [nq, bs]
            alpha = jnp.exp(m_prev - m_new)
            l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
            ctx_heads = []
            for n in range(nkv):
                ctx_heads.append(jax.lax.dot(p[n * g:(n + 1) * g], vb[:, n, :]))  # [g, d]
            ctx = jnp.concatenate(ctx_heads, axis=0)  # [nq, d]
            acc_ref[:] = acc_ref[:] * alpha + ctx
            m_ref[:] = m_new

        @pl.when(j == max_blocks - 1)
        def _finalize():
            o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)

    def scale_map(t, j, seq_ref, pos_ref, bt_ref):
        blk = kv_map(t, j, seq_ref, pos_ref, bt_ref)[0]
        return (0, blk)

    in_specs = [
        pl.BlockSpec((1, nq, d), q_map),
        pl.BlockSpec((1, block_size, nkv, d), kv_map),
        pl.BlockSpec((1, block_size, nkv, d), kv_map),
    ]
    operands = [q, k4, v4]
    if quant:
        in_specs += [pl.BlockSpec((nkv, block_size), scale_map),
                     pl.BlockSpec((nkv, block_size), scale_map)]
        operands += [ks2, vs2]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, nq, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((nq, d), jnp.float32),
            pltpu.VMEM((nq, 1), jnp.float32),
            pltpu.VMEM((nq, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(kernel, grid_spec=grid_spec, out_shape=jax.ShapeDtypeStruct((T, nq, d), q.dtype),
                          interpret=interpret)(seq_idx, pos, block_tables, *operands)
