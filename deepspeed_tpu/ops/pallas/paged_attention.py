"""Paged (blocked) attention over a flat KV pool — the FastGen data-plane
kernel.

Analog of the reference ``v2/kernels/ragged_ops/blocked_flash`` (CUDA flash
attention adapted to paged KV block tables, SURVEY.md §2.3). TPU design: a
Pallas kernel on a ``(tokens, kv_blocks)`` grid using
``PrefetchScalarGridSpec`` so the K/V BlockSpec index maps read the *block
table* (scalar-prefetched) — the DMA engine then streams exactly the KV
blocks each token's sequence owns, straight from HBM, while the online
softmax accumulates in VMEM scratch across the inner grid dimension.

Token-level formulation: query token ``t`` belongs to ``seq_idx[t]`` at
absolute position ``pos[t]`` and attends all cached positions ``<= pos[t]``.
This covers prefill chunks and decode steps uniformly (Dynamic SplitFuse
mixes both in one batch).

``paged_attention_reference`` is the jnp gather implementation used for CPU
tests and as the numerics oracle (reference test strategy: kernel vs
reference, tests/unit/inference/v2/kernels).
"""

import functools
import math
import os

import jax
import jax.numpy as jnp
import numpy as np


def _contiguity_ok(seq_idx, S: int) -> bool:
    """True when the tiled grid's layout contract holds: same-sequence
    tokens contiguous, at most S runs plus the trailing pad run. Traced
    ``seq_idx`` (the jitted ragged step) is covered by the SplitFuse batch
    layout invariant itself (``ragged_wrapper.finalize``)."""
    if seq_idx is None or isinstance(seq_idx, jax.core.Tracer):
        return True
    s = np.asarray(seq_idx)
    runs = 1 + int(np.count_nonzero(s[1:] != s[:-1])) if s.size else 1
    return runs <= S + 1


def _resolve_q_tile(T: int, S: int, seq_idx=None) -> int:
    """Resolve the q-tile through the kernel-config registry
    (``autotuning/kernel_config.py``), falling back to the shape heuristic:
    tile only batches with real multi-token chunks (T well beyond the seq
    count — pure-decode batches have one token per sequence, where tiling
    pays q-DMA for masked rows and buys no KV-stream amortization).

    The tiled grid requires same-sequence tokens to be CONTIGUOUS in the
    batch (the SplitFuse/ragged layout invariant — ``ragged_wrapper.finalize``
    packs per-sequence chunks back to back). When ``seq_idx`` is concrete the
    contract is verified here and tiling is demoted to per-token on
    violation; traced callers (the jitted ragged step) are covered by the
    layout invariant itself.
    """
    from ...autotuning.kernel_config import shape_bucket, tuned_tile

    # DS_TPU_PAGED_Q_TILE: operator kill switch / override. The tiled grid's
    # Mosaic lowering surfaces failures at the OUTER jit compile on the
    # serving path (the in-wrapper ladder can't catch them there) — =1 pins
    # the proven per-token grid without authoring a kernel_config.json.
    env = os.environ.get("DS_TPU_PAGED_Q_TILE")
    if env:
        try:
            qt = max(1, int(env))
        except ValueError:
            qt = 1
        return qt if _contiguity_ok(seq_idx, S) else 1

    prefill_ish = T >= 64 and T >= 2 * max(S, 1)
    default = 8 if prefill_ish else 1
    # lookup order: exact (T, S) bucket, then — for prefill-ish shapes
    # ONLY — the T-only bucket the sweep records (S here is block-table
    # CAPACITY, which varies per deployment, so T generalizes over it). A
    # pure-decode shape (one token per sequence) must never inherit a
    # prefill-tuned tile from the T-only key: every tile would carry qt-1
    # masked slots for zero KV amortization.
    fallback = int(tuned_tile("paged_attention", shape_bucket(T=T), "q_tile",
                              default)) if prefill_ish else default
    qt = int(tuned_tile("paged_attention", shape_bucket(T=T, S=S), "q_tile", fallback))
    if qt > 1 and not _contiguity_ok(seq_idx, S):
        return 1
    return max(qt, 1)


def _resolve_kv_splits(T: int, S: int, max_blocks: int, q_tile: int = 1) -> int:
    """Resolve the flash-decode KV-split factor through the kernel-config
    registry, falling back to the shape heuristic. The split applies ONLY to
    the per-token grid (``q_tile == 1`` — decode-shaped rows): a prefill tile
    already amortizes its KV stream across the tile's tokens, while a decode
    row walks its whole context serially — partitioning the KV blocks across
    a second grid axis lets the online-softmax chains of a long context run
    independently (megacore-parallel on chip) at the cost of one
    log-sum-exp merge over ``kv_splits`` partials.

    ``DS_TPU_PAGED_KV_SPLITS``: operator kill switch / override — ``1`` pins
    the proven single-chain grid (the same escape hatch as
    ``DS_TPU_PAGED_Q_TILE``), any higher value forces that split factor.
    Lookup order mirrors ``q_tile``: exact ``(B, T)`` bucket, then the
    ``B``-only bucket the decode sweep records (B = block-table capacity —
    the KV length is what the split amortizes over; T is just the decode
    batch size of the moment)."""
    from ...autotuning.kernel_config import shape_bucket, tuned_tile

    if q_tile > 1 or max_blocks < 8 or T > 2 * max(S, 1):
        # tiled prefill rows keep the single chain; a short table has no KV
        # axis worth splitting (each split must own >= a few blocks); and a
        # batch with real multi-token chunks (T well past the seq count —
        # e.g. a non-contiguous prefill demoted to the per-token grid) must
        # not inherit the split's T x kv_splits partial buffers
        return 1
    env = os.environ.get("DS_TPU_PAGED_KV_SPLITS")
    if env:
        try:
            ks = max(1, int(env))
        except ValueError:
            ks = 1
        return min(ks, max_blocks)
    default = min(8, max(1, max_blocks // 4))
    fallback = int(tuned_tile("paged_attention", shape_bucket(B=max_blocks), "kv_splits",
                              default))
    ks = int(tuned_tile("paged_attention", shape_bucket(B=max_blocks, T=T), "kv_splits",
                        fallback))
    return max(1, min(ks, max_blocks))


def paged_attention(q, k_pool, v_pool, block_tables, seq_idx, pos, block_size: int, window=None,
                    alibi=None, k_scale=None, v_scale=None, q_tile=None, kv_splits=None):
    """q: [T, nq, d]; k_pool/v_pool: [pool_len, nkv, d] (one layer,
    pool_len = num_blocks*block_size, may include one trailing scratch slot);
    block_tables: [S, max_blocks]; seq_idx/pos: [T].
    ``window``: sliding-window attention (Mistral) — token at position p
    attends cached positions in (p - window, p].
    ``k_scale``/``v_scale``: int8-KV mode (the FastGen quantized-KV analog,
    reference ``csrc/quantization/``) — pools hold int8 values and the
    scales [nkv, pool_len] hold one fp32 absmax/127 factor per (kv-head,
    slot); dequant happens at the kernel's tile read, so only int8 bytes
    stream from HBM.
    ``q_tile``: tokens per q-tile grid row (None = kernel-config registry,
    then shape heuristic). q_tile > 1 packs contiguous same-sequence tokens
    into one grid row so each KV block streams from HBM once per TILE
    instead of once per token — the prefill-chunk amortization win.
    ``kv_splits``: flash-decode KV partitioning for the per-token (decode)
    grid — each split runs a partial online softmax over its share of the
    KV blocks on its own grid row (megacore-parallel on chip) and the
    partials merge with the standard log-sum-exp combine. None = registry,
    then heuristic; ignored whenever the q-tiled grid is taken.
    Returns [T, nq, d]."""
    T, nq, d = q.shape
    nkv = k_pool.shape[1]
    S = block_tables.shape[0]
    if window is not None:
        window = int(window)
    if jax.default_backend() != "tpu" or nq < 8 or d % 128 != 0:
        if jax.default_backend() == "tpu":
            # off-TPU the oracle is the design; ON TPU a shape miss silently
            # costing a full context gather per layer per step must be loud
            from ...utils.logging import warning_once

            warning_once(f"pallas paged attention: unsupported shape (nq={nq}, d={d}; needs "
                         "nq>=8, d%128==0) — serving through the DENSE gather fallback")
        return paged_attention_reference(q, k_pool, v_pool, block_tables, seq_idx, pos, block_size,
                                         window=window, alibi=alibi, k_scale=k_scale, v_scale=v_scale)
    if q_tile is None:
        q_tile = _resolve_q_tile(T, S, seq_idx)
    elif q_tile > 1 and not _contiguity_ok(seq_idx, S):
        # an explicit q_tile must not bypass the layout contract: a
        # non-contiguous batch would overflow the tiled grid's static tile
        # bound and silently scatter tokens into the wrong tiles
        from ...utils.logging import warning_once

        warning_once(f"paged attention: q_tile={q_tile} requested but seq_idx is not "
                     "sequence-contiguous — demoting to the per-token grid")
        q_tile = 1
    alibi_t = tuple(np.asarray(alibi).tolist()) if alibi is not None else None
    max_blocks = block_tables.shape[1]
    if kv_splits is None:
        kv_splits = _resolve_kv_splits(T, S, max_blocks, q_tile=int(q_tile))
    kv_splits = max(1, min(int(kv_splits), max_blocks))
    # failure ladder: q-tiled -> kv-split decode -> per-token -> gather
    # oracle. A tiling/split that fails Mosaic on some generation costs ONE
    # rung, never the fused path. The split rung only exists on the
    # per-token (decode) grid — a tiled prefill row keeps its single chain.
    rungs = [(int(q_tile), 1)] if q_tile > 1 else []
    rungs += [(1, kv_splits), (1, 1)]
    for qt, ks in dict.fromkeys(rungs):
        try:
            return _pallas_paged(q, k_pool, v_pool, block_tables, seq_idx.astype(jnp.int32),
                                 pos.astype(jnp.int32), block_size=block_size, window=window,
                                 alibi=alibi_t, k_scale=k_scale, v_scale=v_scale, q_tile=qt,
                                 kv_splits=ks)
        except Exception as e:  # pragma: no cover — kernel bring-up safety net
            from ...utils.logging import warning_once

            warning_once(f"pallas paged attention (q_tile={qt}, kv_splits={ks}) unavailable "
                         f"({type(e).__name__}: {e}); trying next rung")
    return paged_attention_reference(q, k_pool, v_pool, block_tables, seq_idx, pos, block_size,
                                     window=window, alibi=alibi, k_scale=k_scale, v_scale=v_scale)


def paged_attention_reference(q, k_pool, v_pool, block_tables, seq_idx, pos, block_size: int,
                              window=None, alibi=None, k_scale=None, v_scale=None,
                              pos_ids=None, mask=None, ctx_pos_ids=None):
    """Gather-based oracle: materializes each sequence's context. ``alibi``:
    per-head slopes [nq] (Bloom). ``k_scale``/``v_scale``: int8-KV
    dequantization factors [nkv, pool_len] (see ``paged_attention``).
    ``pos_ids``: logical positions for alibi distances when they differ
    from the KV slot positions (token-tree verification); ``mask``: explicit
    [T, C] visibility replacing the causal/window mask — the tree attention
    mask (the caller owns window semantics inside it); ``ctx_pos_ids``:
    [S, C] logical position of every context slot (tree nodes sit at flat
    slots but depth-based logical positions — alibi distances must use the
    logical ones)."""
    T, nq, d = q.shape
    nkv = k_pool.shape[1]
    g = nq // nkv
    S, max_blocks = block_tables.shape
    C = max_blocks * block_size
    ctx_slots = (block_tables[:, :, None] * block_size +
                 jnp.arange(block_size, dtype=jnp.int32)[None, None, :]).reshape(S, C)
    ctxk = k_pool[ctx_slots].astype(jnp.float32)  # [S, C, nkv, d]
    ctxv = v_pool[ctx_slots].astype(jnp.float32)
    if k_scale is not None:
        ctxk = ctxk * jnp.transpose(k_scale)[ctx_slots][..., None]  # [S, C, nkv, 1]
        ctxv = ctxv * jnp.transpose(v_scale)[ctx_slots][..., None]
    qr = (q.astype(jnp.float32) / math.sqrt(d)).reshape(T, nkv, g, d)
    s = jnp.einsum("tngd,tcnd->tngc", qr, ctxk[seq_idx])
    pid = pos if pos_ids is None else pos_ids
    if alibi is not None:
        ctx_pid = (jnp.arange(C, dtype=jnp.int32)[None, :] if ctx_pos_ids is None
                   else ctx_pos_ids[seq_idx])
        rel = ctx_pid.astype(jnp.float32) - pid[:, None].astype(jnp.float32)
        s = s + jnp.asarray(alibi, jnp.float32).reshape(nkv, g)[None, :, :, None] * rel[:, None, None, :]
    if mask is not None:
        causal = mask
    else:
        causal = jnp.arange(C, dtype=jnp.int32)[None, :] <= pos[:, None]
        if window is not None:
            causal = causal & (pos[:, None] - jnp.arange(C, dtype=jnp.int32)[None, :] < window)
    s = jnp.where(causal[:, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("tngc,tcnd->tngd", p, ctxv[seq_idx])
    return out.reshape(T, nq, d).astype(q.dtype)


def _slopes_rows(alibi, reps):
    """Per-head alibi slopes as kernel rows [len(alibi)*reps, 1], built from
    Python floats: each ``jnp.full`` embeds a SCALAR constant, which Pallas
    accepts — a closure-captured ``jnp.asarray(tuple)`` array is rejected at
    kernel trace ("captures constants ... pass them as inputs"), which
    silently broke the per-token alibi path before this helper."""
    return jnp.concatenate([jnp.full((reps, 1), float(a), jnp.float32) for a in alibi], axis=0)


@functools.partial(jax.jit, static_argnames=("block_size", "interpret", "window", "alibi",
                                             "q_tile", "kv_splits"))
def _pallas_paged(q, k_pool, v_pool, block_tables, seq_idx, pos, block_size: int, interpret: bool = False,
                  window=None, alibi=None, k_scale=None, v_scale=None, q_tile: int = 1,
                  kv_splits: int = 1):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T, nq, d = q.shape
    nkv = k_pool.shape[1]
    g = nq // nkv
    S, max_blocks = block_tables.shape
    # view the pool as whole blocks; drop any trailing scratch remainder
    n_pool_blocks = k_pool.shape[0] // block_size
    k4 = k_pool[:n_pool_blocks * block_size].reshape(n_pool_blocks, block_size, nkv, d)
    v4 = v_pool[:n_pool_blocks * block_size].reshape(n_pool_blocks, block_size, nkv, d)
    quant = k_scale is not None
    if quant:
        # scales stay [nkv, cols]: sublane = nkv, lane = block_size — the
        # layout the scatter side maintains natively, no per-call transpose
        ks2 = k_scale[:, :n_pool_blocks * block_size]
        vs2 = v_scale[:, :n_pool_blocks * block_size]
    scale = 1.0 / math.sqrt(d)

    if q_tile and q_tile > 1:
        return _paged_q_tiled(pl, pltpu, q, k4, v4, block_tables, seq_idx, pos,
                              ks2 if quant else None, vs2 if quant else None,
                              block_size=block_size, q_tile=int(q_tile), window=window,
                              alibi=alibi, interpret=interpret)
    if kv_splits and kv_splits > 1:
        return _paged_kv_split(pl, pltpu, q, k4, v4, block_tables, seq_idx, pos,
                               ks2 if quant else None, vs2 if quant else None,
                               block_size=block_size, kv_splits=int(kv_splits),
                               window=window, alibi=alibi, interpret=interpret)

    grid = (T, max_blocks)

    def q_map(t, j, seq_ref, pos_ref, bt_ref):
        return (t, 0, 0)

    def kv_map(t, j, seq_ref, pos_ref, bt_ref):
        # clamp j into the token's live range: the index map runs (and its
        # DMA issues) even for grid steps the kernel's pl.when skips, so
        # out-of-range columns are remapped to an in-range block — Mosaic
        # sees a repeated index and skips the refetch instead of streaming
        # blocks the online softmax never reads
        hi = pos_ref[t] // block_size
        jj = jnp.minimum(j, hi)
        if window is not None:
            lo = jnp.maximum(pos_ref[t] - (window - 1), 0) // block_size
            jj = jnp.maximum(jj, jnp.minimum(lo, hi))
        return (bt_ref[seq_ref[t], jj], 0, 0, 0)

    def kernel(seq_ref, pos_ref, bt_ref, q_ref, k_ref, v_ref, *rest):
        if quant:
            ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
        else:
            o_ref, acc_ref, m_ref, l_ref = rest
        t = pl.program_id(0)
        j = pl.program_id(1)
        my_pos = pos_ref[t]

        @pl.when(j == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)
            m_ref[:] = jnp.full_like(m_ref, -1e30)
            l_ref[:] = jnp.zeros_like(l_ref)

        in_window = (j * block_size <= my_pos) if window is None else jnp.logical_and(
            j * block_size <= my_pos, (j + 1) * block_size - 1 > my_pos - window)

        @pl.when(in_window)
        def _compute():
            qb = q_ref[0].astype(jnp.float32) * scale  # [nq, d]
            kb = k_ref[0].astype(jnp.float32)  # [bs, nkv, d]
            vb = v_ref[0].astype(jnp.float32)
            if quant:  # dequant at the VMEM tile — HBM only streamed int8
                kb = kb * ks_ref[...].T[:, :, None]  # [bs, nkv, 1]
                vb = vb * vs_ref[...].T[:, :, None]
            # per-kv-head 2-D MXU dots (Mosaic has no mismatched-batch dots);
            # nkv is small and static so the loop unrolls at trace time
            s_heads = []
            for n in range(nkv):
                s_heads.append(jax.lax.dot(qb[n * g:(n + 1) * g], kb[:, n, :].T))  # [g, bs]
            s = jnp.concatenate(s_heads, axis=0)  # [nq, bs]
            kpos = j * block_size + jax.lax.broadcasted_iota(jnp.int32, (nq, block_size), 1)
            if alibi is not None:
                s = s + _slopes_rows(alibi, 1) * (kpos - my_pos).astype(jnp.float32)
            vis = kpos <= my_pos
            if window is not None:
                vis = jnp.logical_and(vis, my_pos - kpos < window)
            s = jnp.where(vis, s, -1e30)
            m_prev = m_ref[:]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)  # [nq, bs]
            alpha = jnp.exp(m_prev - m_new)
            l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
            ctx_heads = []
            for n in range(nkv):
                ctx_heads.append(jax.lax.dot(p[n * g:(n + 1) * g], vb[:, n, :]))  # [g, d]
            ctx = jnp.concatenate(ctx_heads, axis=0)  # [nq, d]
            acc_ref[:] = acc_ref[:] * alpha + ctx
            m_ref[:] = m_new

        @pl.when(j == max_blocks - 1)
        def _finalize():
            o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)

    def scale_map(t, j, seq_ref, pos_ref, bt_ref):
        blk = kv_map(t, j, seq_ref, pos_ref, bt_ref)[0]
        return (0, blk)

    in_specs = [
        pl.BlockSpec((1, nq, d), q_map),
        pl.BlockSpec((1, block_size, nkv, d), kv_map),
        pl.BlockSpec((1, block_size, nkv, d), kv_map),
    ]
    operands = [q, k4, v4]
    if quant:
        in_specs += [pl.BlockSpec((nkv, block_size), scale_map),
                     pl.BlockSpec((nkv, block_size), scale_map)]
        operands += [ks2, vs2]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, nq, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((nq, d), jnp.float32),
            pltpu.VMEM((nq, 1), jnp.float32),
            pltpu.VMEM((nq, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(kernel, grid_spec=grid_spec, out_shape=jax.ShapeDtypeStruct((T, nq, d), q.dtype),
                          interpret=interpret)(seq_idx, pos, block_tables, *operands)


def _paged_q_tiled(pl, pltpu, q, k4, v4, block_tables, seq_idx, pos, ks2, vs2,
                   block_size: int, q_tile: int, window, alibi, interpret: bool):
    """Q-tiled grid: ``(n_tiles, max_blocks)`` where each tile packs up to
    ``q_tile`` CONTIGUOUS same-sequence tokens, so every KV block streams
    from HBM once per *tile* instead of once per token — a 256-token prefill
    chunk at q_tile=8 reads each of its KV blocks 32x instead of 256x.

    Tile assembly happens in jnp-land (traced, static shapes): a segmented
    tiling over the ragged batch — tiles never span a sequence boundary, so
    one block-table row serves the whole grid row. ``n_tiles`` is the static
    upper bound ceil(T/q_tile) + S + 1 (interior splits + one ragged tail
    tile per sequence run + the trailing pad run); unused tiles carry
    ``max_pos = -1`` and every kv step skips them. Ragged tile tails ride the
    existing per-token ``pl.when``/position masking (invalid slots get
    pos = -1, masking every context position). int8-KV dequant, alibi and
    sliding window are preserved bit-for-bit from the per-token grid.
    """
    T, nq, d = q.shape
    nkv = k4.shape[2]
    g = nq // nkv
    S, max_blocks = block_tables.shape
    qt = int(q_tile)
    quant = ks2 is not None
    scale = 1.0 / math.sqrt(d)
    n_tiles = -(-T // qt) + S + 1

    # --- segmented tile descriptors (contiguity contract: see paged_attention) ---
    tok = jnp.arange(T, dtype=jnp.int32)
    newrun = jnp.concatenate([jnp.ones((1, ), bool), seq_idx[1:] != seq_idx[:-1]])
    run_start = jax.lax.associative_scan(jnp.maximum, jnp.where(newrun, tok, 0))
    within = tok - run_start                      # offset inside this token's run
    tile_id = jnp.cumsum((within % qt == 0).astype(jnp.int32)) - 1   # [T]
    slot = within % qt

    tile_tok = jnp.zeros((n_tiles, qt), jnp.int32).at[tile_id, slot].set(tok)
    valid = jnp.zeros((n_tiles, qt), bool).at[tile_id, slot].set(True)
    pos_t = jnp.where(valid, pos[tile_tok], -1)                      # [n_tiles, qt]
    tile_seq = jnp.where(valid[:, 0], seq_idx[tile_tok[:, 0]], 0)    # [n_tiles]
    tile_max = jnp.max(pos_t, axis=1)                                # -1 for empty tiles
    tile_min = jnp.min(jnp.where(valid, pos_t, jnp.int32(2**30)), axis=1)

    # head-major tile layout [n_tiles, nq, qt, d]: the kernel's row view
    # (nq*qt, d) then keeps each kv-head's g*qt query rows contiguous
    q_t = q[tile_tok.reshape(-1)].reshape(n_tiles, qt, nq, d).transpose(0, 2, 1, 3)

    R = nq * qt
    grid = (n_tiles, max_blocks)

    def q_map(i, j, seq_ref, max_ref, min_ref, bt_ref):
        return (i, 0, 0, 0)

    def kv_map(i, j, seq_ref, max_ref, min_ref, bt_ref):
        # clamp j into the tile's live range (same Mosaic idiom as the
        # per-token grid: skipped steps re-use the resident block)
        hi = jnp.maximum(max_ref[i], 0) // block_size
        jj = jnp.minimum(j, hi)
        if window is not None:
            lo = jnp.maximum(jnp.maximum(min_ref[i], 0) - (window - 1), 0) // block_size
            jj = jnp.maximum(jj, jnp.minimum(lo, hi))
        return (bt_ref[seq_ref[i], jj], 0, 0, 0)

    def pos_map(i, j, seq_ref, max_ref, min_ref, bt_ref):
        return (i, 0)

    def scale_map(i, j, seq_ref, max_ref, min_ref, bt_ref):
        return (0, kv_map(i, j, seq_ref, max_ref, min_ref, bt_ref)[0])

    def kernel(seq_ref, max_ref, min_ref, bt_ref, q_ref, k_ref, v_ref, pos_ref, *rest):
        if quant:
            ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
        else:
            o_ref, acc_ref, m_ref, l_ref = rest
        i = pl.program_id(0)
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)
            m_ref[:] = jnp.full_like(m_ref, -1e30)
            l_ref[:] = jnp.zeros_like(l_ref)

        my_max = max_ref[i]
        in_window = j * block_size <= my_max  # empty tile: my_max = -1, always skipped
        if window is not None:
            in_window = jnp.logical_and(
                in_window, (j + 1) * block_size - 1 > min_ref[i] - window)

        @pl.when(in_window)
        def _compute():
            qr = q_ref[0].astype(jnp.float32).reshape(R, d) * scale  # rows r = h*qt + t
            kb = k_ref[0].astype(jnp.float32)  # [bs, nkv, d]
            vb = v_ref[0].astype(jnp.float32)
            if quant:  # dequant at the VMEM tile — HBM only streamed int8
                kb = kb * ks_ref[...].T[:, :, None]
                vb = vb * vs_ref[...].T[:, :, None]
            s_heads = []
            for n in range(nkv):
                s_heads.append(jax.lax.dot(qr[n * g * qt:(n + 1) * g * qt], kb[:, n, :].T))
            s = jnp.concatenate(s_heads, axis=0)  # [R, bs]
            pos_vec = pos_ref[0]                  # [qt]; -1 on invalid slots
            my_pos = jnp.broadcast_to(pos_vec[None, :], (nq, qt)).reshape(R, 1)
            kpos = j * block_size + jax.lax.broadcasted_iota(jnp.int32, (R, block_size), 1)
            if alibi is not None:
                s = s + _slopes_rows(alibi, qt) * (kpos - my_pos).astype(jnp.float32)
            vis = kpos <= my_pos
            if window is not None:
                vis = jnp.logical_and(vis, my_pos - kpos < window)
            s = jnp.where(vis, s, -1e30)
            m_prev = m_ref[:]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m_prev - m_new)
            l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
            ctx_heads = []
            for n in range(nkv):
                ctx_heads.append(jax.lax.dot(p[n * g * qt:(n + 1) * g * qt], vb[:, n, :]))
            acc_ref[:] = acc_ref[:] * alpha + jnp.concatenate(ctx_heads, axis=0)
            m_ref[:] = m_new

        @pl.when(j == max_blocks - 1)
        def _finalize():
            out = acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)
            o_ref[0] = out.reshape(nq, qt, d).astype(o_ref.dtype)

    in_specs = [
        pl.BlockSpec((1, nq, qt, d), q_map),
        pl.BlockSpec((1, block_size, nkv, d), kv_map),
        pl.BlockSpec((1, block_size, nkv, d), kv_map),
        pl.BlockSpec((1, qt), pos_map),
    ]
    operands = [q_t, k4, v4, pos_t]
    if quant:
        in_specs += [pl.BlockSpec((nkv, block_size), scale_map),
                     pl.BlockSpec((nkv, block_size), scale_map)]
        operands += [ks2, vs2]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, nq, qt, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((R, d), jnp.float32),
            pltpu.VMEM((R, 1), jnp.float32),
            pltpu.VMEM((R, 1), jnp.float32),
        ],
    )
    out_t = pl.pallas_call(kernel, grid_spec=grid_spec,
                           out_shape=jax.ShapeDtypeStruct((n_tiles, nq, qt, d), q.dtype),
                           interpret=interpret)(tile_seq, tile_max, tile_min, block_tables,
                                                *operands)
    # scatter tiles back to token order
    flat = out_t.transpose(0, 2, 1, 3).reshape(n_tiles * qt, nq, d)
    return flat[tile_id * qt + slot]


def _paged_kv_split(pl, pltpu, q, k4, v4, block_tables, seq_idx, pos, ks2, vs2,
                    block_size: int, kv_splits: int, window, alibi, interpret: bool):
    """Flash-decode KV-split grid: ``(kv_splits, T, blocks_per_split)``.

    A decode row's online softmax is a serial chain over its whole context
    — on a long context that chain is the decode latency floor. Partition
    the KV blocks: split ``s`` owns block range
    ``[s * blocks_per_split, (s+1) * blocks_per_split)`` and computes an
    independent partial (un-normalized accumulator + its running max ``m``
    and mass ``l``); the partials merge afterwards with the standard
    log-sum-exp combine

        m* = max_s m_s;  out = Σ_s e^{m_s - m*} acc_s / Σ_s e^{m_s - m*} l_s

    which is exactly the two-pass algebra of the online softmax, so the
    result is bit-comparable (within f32 association) to the single chain.
    The split axis leads the grid and is declared ``parallel`` — on chip the
    independent chains distribute across megacores; the per-token grid can
    never parallelize one token's context. Splits wholly beyond a token's
    live range (or wholly below its sliding window) contribute
    ``m = -inf, l = 0`` and vanish in the merge. int8 dequant-at-tile,
    alibi and window masking are inherited unchanged from the per-token
    grid."""
    T, nq, d = q.shape
    nkv = k4.shape[2]
    g = nq // nkv
    S, max_blocks = block_tables.shape
    ks_n = int(kv_splits)
    per = -(-max_blocks // ks_n)
    quant = ks2 is not None
    scale = 1.0 / math.sqrt(d)
    grid = (ks_n, T, per)

    def q_map(s, t, j, seq_ref, pos_ref, bt_ref):
        return (t, 0, 0)

    def o_map(s, t, j, seq_ref, pos_ref, bt_ref):
        return (s, t, 0, 0)

    def kv_map(s, t, j, seq_ref, pos_ref, bt_ref):
        # clamp into the token's live range (the Mosaic skip-refetch idiom
        # of the per-token grid): dead steps re-present a resident block
        hi = pos_ref[t] // block_size
        jj = jnp.minimum(s * per + j, hi)
        if window is not None:
            lo = jnp.maximum(pos_ref[t] - (window - 1), 0) // block_size
            jj = jnp.maximum(jj, jnp.minimum(lo, hi))
        return (bt_ref[seq_ref[t], jj], 0, 0, 0)

    def scale_map(s, t, j, seq_ref, pos_ref, bt_ref):
        return (0, kv_map(s, t, j, seq_ref, pos_ref, bt_ref)[0])

    def kernel(seq_ref, pos_ref, bt_ref, q_ref, k_ref, v_ref, *rest):
        if quant:
            ks_ref, vs_ref, o_ref, m_o_ref, l_o_ref, acc_ref, m_ref, l_ref = rest
        else:
            o_ref, m_o_ref, l_o_ref, acc_ref, m_ref, l_ref = rest
        s_id = pl.program_id(0)
        t = pl.program_id(1)
        j = pl.program_id(2)
        jb = s_id * per + j  # absolute block index this step covers
        my_pos = pos_ref[t]

        @pl.when(j == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)
            m_ref[:] = jnp.full_like(m_ref, -1e30)
            l_ref[:] = jnp.zeros_like(l_ref)

        in_window = jnp.logical_and(jb * block_size <= my_pos, jb < max_blocks)
        if window is not None:
            in_window = jnp.logical_and(
                in_window, (jb + 1) * block_size - 1 > my_pos - window)

        @pl.when(in_window)
        def _compute():
            qb = q_ref[0].astype(jnp.float32) * scale  # [nq, d]
            kb = k_ref[0].astype(jnp.float32)  # [bs, nkv, d]
            vb = v_ref[0].astype(jnp.float32)
            if quant:  # dequant at the VMEM tile — HBM only streamed int8
                kb = kb * ks_ref[...].T[:, :, None]
                vb = vb * vs_ref[...].T[:, :, None]
            s_heads = []
            for n in range(nkv):
                s_heads.append(jax.lax.dot(qb[n * g:(n + 1) * g], kb[:, n, :].T))
            sc = jnp.concatenate(s_heads, axis=0)  # [nq, bs]
            kpos = jb * block_size + jax.lax.broadcasted_iota(jnp.int32, (nq, block_size), 1)
            if alibi is not None:
                sc = sc + _slopes_rows(alibi, 1) * (kpos - my_pos).astype(jnp.float32)
            vis = kpos <= my_pos
            if window is not None:
                vis = jnp.logical_and(vis, my_pos - kpos < window)
            sc = jnp.where(vis, sc, -1e30)
            m_prev = m_ref[:]
            m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1, keepdims=True))
            p = jnp.exp(sc - m_new)
            alpha = jnp.exp(m_prev - m_new)
            l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
            ctx_heads = []
            for n in range(nkv):
                ctx_heads.append(jax.lax.dot(p[n * g:(n + 1) * g], vb[:, n, :]))
            acc_ref[:] = acc_ref[:] * alpha + jnp.concatenate(ctx_heads, axis=0)
            m_ref[:] = m_new

        @pl.when(j == per - 1)
        def _finalize():
            # un-normalized partial + its softmax stats: the merge below
            # owns the division, so the kernel never divides by a dead
            # split's zero mass
            o_ref[0, 0] = acc_ref[:]
            m_o_ref[0, 0] = m_ref[:]
            l_o_ref[0, 0] = l_ref[:]

    in_specs = [
        pl.BlockSpec((1, nq, d), q_map),
        pl.BlockSpec((1, block_size, nkv, d), kv_map),
        pl.BlockSpec((1, block_size, nkv, d), kv_map),
    ]
    operands = [q, k4, v4]
    if quant:
        in_specs += [pl.BlockSpec((nkv, block_size), scale_map),
                     pl.BlockSpec((nkv, block_size), scale_map)]
        operands += [ks2, vs2]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=grid,
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((1, 1, nq, d), o_map),
                   pl.BlockSpec((1, 1, nq, 1), o_map),
                   pl.BlockSpec((1, 1, nq, 1), o_map)],
        scratch_shapes=[
            pltpu.VMEM((nq, d), jnp.float32),
            pltpu.VMEM((nq, 1), jnp.float32),
            pltpu.VMEM((nq, 1), jnp.float32),
        ],
    )
    kwargs = {}
    if not interpret:
        # the split axis is the parallelism the kernel exists for: declare
        # it so Mosaic may distribute independent chains across megacores
        kwargs["compiler_params"] = _parallel_params(pltpu, ("parallel", "arbitrary",
                                                            "arbitrary"))
    acc, m, l = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((ks_n, T, nq, d), jnp.float32),
                   jax.ShapeDtypeStruct((ks_n, T, nq, 1), jnp.float32),
                   jax.ShapeDtypeStruct((ks_n, T, nq, 1), jnp.float32)],
        interpret=interpret, **kwargs)(seq_idx, pos, block_tables, *operands)
    # log-sum-exp merge over splits (the flash-decode combine)
    m_star = jnp.max(m, axis=0, keepdims=True)
    w = jnp.exp(m - m_star)  # dead splits: exp(-1e30 - m*) == 0
    out = jnp.sum(acc * w, axis=0) / jnp.maximum(jnp.sum(l * w, axis=0), 1e-30)
    return out.astype(q.dtype)


def _parallel_params(pltpu, semantics):
    """``dimension_semantics`` across jax versions (CompilerParams vs the
    older TPUCompilerParams spelling); None when neither exists — the call
    then simply compiles without the megacore hint."""
    cls = getattr(pltpu, "CompilerParams", None) or getattr(pltpu, "TPUCompilerParams", None)
    return cls(dimension_semantics=tuple(semantics)) if cls is not None else None
