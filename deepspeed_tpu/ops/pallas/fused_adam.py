"""Fused Adam(W) update as one Pallas pass.

TPU analog of the reference's multi-tensor-apply fused Adam
(``csrc/adam/multi_tensor_adam.cu`` via ``FusedAdamBuilder``): one kernel
reads (grad, param, m, v) and writes (param, m, v) — 28 bytes/param of HBM
traffic, the bandwidth floor of the update — with the overflow gate, loss
un-scaling, and global-norm clipping folded in as scalar inputs so the
engine's step needs NO additional full passes over the state (the eager
optax chain costs extra passes for the finite-check and the overflow
where-selects).

Scalars ride in SMEM: [lr, b1, b2, 1-b1^t, 1-b2^t, eps, weight_decay,
grad_scale, gate]. ``gate`` <= 0 makes the kernel write the inputs back
unchanged — the reference's overflow-skip (``has_overflow``
stage_1_and_2.py:2002) without a second pass.
"""

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

_LANES = 128
_MAX_BLOCK_ROWS = 512


def _adam_kernel(scal_ref, g_ref, p_ref, m_ref, v_ref, p_out, m_out, v_out):
    lr, b1, b2, bc1, bc2, eps, wd, gscale, gate = (scal_ref[i] for i in range(9))
    g = g_ref[...].astype(jnp.float32) * gscale
    p = p_ref[...]
    m = b1 * m_ref[...] + (1.0 - b1) * g
    v = b2 * v_ref[...] + (1.0 - b2) * g * g
    upd = (m / bc1) / (jnp.sqrt(v / bc2) + eps) + wd * p
    ok = gate > 0.0
    p_out[...] = jnp.where(ok, p - lr * upd, p)
    m_out[...] = jnp.where(ok, m, m_ref[...])
    v_out[...] = jnp.where(ok, v, v_ref[...])


def _fusable(x) -> bool:
    return x.size >= _LANES and x.size % _LANES == 0


@functools.partial(jax.jit, static_argnames=("interpret", ))
def _adam_leaf(scalars, g, p, m, v, interpret=False):
    """One-leaf fused update; leaf viewed as (rows, 128) f32."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    shape = p.shape
    rows = p.size // _LANES
    br = min(rows, _MAX_BLOCK_ROWS)
    view = lambda x: x.reshape(rows, _LANES)
    spec = pl.BlockSpec((br, _LANES), lambda i: (i, 0))
    out = pl.pallas_call(
        _adam_kernel,
        grid=(pl.cdiv(rows, br), ),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM), spec, spec, spec, spec],
        out_specs=(spec, spec, spec),
        out_shape=tuple(jax.ShapeDtypeStruct((rows, _LANES), jnp.float32) for _ in range(3)),
        # update in place: outputs alias the p/m/v inputs (the engine donates
        # the state pytree, so no second copy of params/moments ever exists)
        input_output_aliases={2: 0, 3: 1, 4: 2},
        interpret=interpret,
    )(scalars, view(g), view(p), view(m), view(v))
    return tuple(o.reshape(shape) for o in out)


def fused_adam_apply(params, mu, nu, grads, *, lr_t, b1, b2, eps, weight_decay, step,
                     grad_scale, gate, interpret=False):
    """Apply one gated AdamW step across a pytree.

    ``step``: 1-based update index (for bias correction). ``grad_scale``:
    folded loss-unscale x clip coefficient applied to every grad. ``gate``:
    f32 scalar; <= 0 skips the update (overflow). Returns (params, mu, nu).
    Leaves whose size is not lane-aligned take the identical jnp chain (XLA
    fuses those few small tensors fine; the kernel matters for the big ones).
    """
    stepf = jnp.asarray(step, jnp.float32)
    bc1 = 1.0 - b1**stepf
    bc2 = 1.0 - b2**stepf
    scalars = jnp.stack([
        jnp.asarray(lr_t, jnp.float32), jnp.asarray(b1, jnp.float32), jnp.asarray(b2, jnp.float32),
        bc1, bc2, jnp.asarray(eps, jnp.float32), jnp.asarray(weight_decay, jnp.float32),
        jnp.asarray(grad_scale, jnp.float32), jnp.asarray(gate, jnp.float32)
    ])

    def leaf(g, p, m, v):
        if _fusable(p):
            return _adam_leaf(scalars, g, p, m, v, interpret=interpret)
        g32 = g.astype(jnp.float32) * scalars[7]
        m_new = b1 * m + (1.0 - b1) * g32
        v_new = b2 * v + (1.0 - b2) * g32 * g32
        upd = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps) + weight_decay * p
        ok = scalars[8] > 0.0
        return (jnp.where(ok, p - scalars[0] * upd, p), jnp.where(ok, m_new, m),
                jnp.where(ok, v_new, v))

    out = jax.tree_util.tree_map(leaf, grads, params, mu, nu)
    is3 = lambda x: isinstance(x, tuple)
    pick = lambda i: jax.tree_util.tree_map(lambda t: t[i], out, is_leaf=is3)
    return pick(0), pick(1), pick(2)
