"""Evoformer biased flash attention — Pallas fwd + bwd with bias gradients.

TPU replacement for the reference's CUTLASS fused MHA
(``csrc/deepspeed4science/evoformer_attn/`` — ``attention.cu`` fwd,
``attention_back.cu`` bwd with dB1/dB2), the kernel behind
``DS4Sci_EvoformerAttention``. AlphaFold's triangle/MSA attention adds TWO
bias terms to the scores:

  - ``bias1`` (MSA mask): [N, R] — one additive value per key position,
    broadcast over heads and query rows (the reference's
    ``[*, n_seq, 1, 1, n_res]`` layout, batch dims collapsed into N);
  - ``bias2`` (pair bias): [G, h, R, R] — a full per-head score bias shared
    by the ``n_seq = N // G`` sequence rows of each batch group (the
    reference's ``[*, 1, heads, n_res, n_res]``).

The whole point of the fused kernel is never materializing the
[*, h, R, R] probability tensor in HBM at fp32: the forward is the flash
online-softmax with the two bias tiles added to each [bq, bk] score block
(VMEM residency: q/k/v/o tiles + one bias2 tile — independent of R), and
the backward recomputes p blockwise from the saved lse.

Backward structure (the flash two-pass split plus two bias passes — each is
a revisit-accumulate grid whose innermost dimension matches what that
cotangent sums over):

  * dq    — grid (N, h, qi, kj):      dq[n,h,qi]    += ds·k      over kj
  * dk/dv — grid (N, h, kj, qi):      dk/dv[n,h,kj] += ds^T·q    over qi
  * dbias2 — grid (G, h, qi, kj, n):  db2[g,h,qi,kj] += ds       over n_seq
  * dbias1 — grid (N, kj, h, qi):     db1[n,kj]     += Σ_q ds    over (h, qi)

dbias2/dbias1 cannot share a pass with dk/dv (or each other): TPU grids
execute sequentially and an output block only accumulates across
*consecutive* revisits, so each cotangent needs its own innermost-loop
order. The two extra recompute passes cost ~2/3 of the dk/dv pass each —
the price of keeping every bias gradient HBM-resident-free.
"""

import functools
import math

import jax
import jax.numpy as jnp

_NEG_INF = -1e30
_VMEM_BUDGET = 14 * 2**20


def _fit_block(S: int, want: int) -> int:
    b = max(128, min(want, S) // 128 * 128)
    while b > 128 and S % b:
        b -= 128
    return b


def _fit_tiles(R: int, d: int, bq: int, bk: int):
    """Shrink (bq, bk) until the largest pass's VMEM working set fits.
    vs flash: +1 fp32 [bq, bk] bias2 tile and the [1, bk] bias1 row."""
    while True:
        tmp = 3 * bq * bk * 4 + (bq + bk) * d * 16 + bq * 128 * 4 + bk * 4
        if tmp <= _VMEM_BUDGET:
            return bq, bk
        if bq <= 128 and bk <= 128:
            return None
        bq2 = _fit_block(R, max(128, bq // 2)) if bq >= bk else bq
        bk2 = _fit_block(R, max(128, bk // 2)) if bk >= bq else bk
        if (bq2, bk2) == (bq, bk):
            return None
        bq, bk = bq2, bk2


def evo_flash(q, k, v, bias1=None, bias2=None, block_q=512, block_k=512, interpret=False):
    """q/k/v: [N, R, h, d]; bias1: [N, R] fp32 or None; bias2: [G, h, R, R]
    fp32 (N % G == 0) or None. Returns [N, R, h, d]. Differentiable in every
    present operand (bias cotangents accumulate in fp32 inside the kernel);
    an absent bias costs one resident zero tile in the forward and skips its
    backward pass entirely."""
    N, R, h, d = q.shape
    if bias1 is not None:
        assert bias1.shape == (N, R), f"bias1 {bias1.shape} != {(N, R)}"
        bias1 = bias1.astype(jnp.float32)
    if bias2 is not None:
        G = bias2.shape[0]
        assert N % G == 0, f"N={N} must be a multiple of bias2 groups G={G}"
        assert bias2.shape == (G, h, R, R), f"bias2 {bias2.shape} != {(G, h, R, R)}"
        bias2 = bias2.astype(jnp.float32)
    bq = _fit_block(R, min(block_q, R))
    bk = _fit_block(R, min(block_k, R))
    fitted = _fit_tiles(R, d, bq, bk)
    if fitted is None:
        raise ValueError(f"no evoformer tiling fits VMEM for R={R}, d={d}")
    return _evo_core(fitted[0], fitted[1], interpret, q, k, v, bias1, bias2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _evo_core(block_q, block_k, interpret, q, k, v, bias1, bias2):
    out, _ = _evo_fwd_impl(block_q, block_k, interpret, q, k, v, bias1, bias2)
    return out


def _evo_core_fwd(block_q, block_k, interpret, q, k, v, bias1, bias2):
    out, lse = _evo_fwd_impl(block_q, block_k, interpret, q, k, v, bias1, bias2)
    return out, (q, k, v, bias1, bias2, out, lse)


def _evo_core_bwd(block_q, block_k, interpret, res, dout):
    q, k, v, bias1, bias2, out, lse = res
    return _evo_bwd_impl(block_q, block_k, interpret, q, k, v, bias1, bias2, out, lse, dout)


_evo_core.defvjp(_evo_core_fwd, _evo_core_bwd)


def _evo_fwd_impl(block_q, block_k, interpret, q, k, v, bias1, bias2):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N, R, h, d = q.shape
    has_b1, has_b2 = bias1 is not None, bias2 is not None
    G = bias2.shape[0] if has_b2 else 1
    n_seq = N // G
    scale = 1.0 / math.sqrt(d)
    nqb, nkb = R // block_q, R // block_k
    LANES = 128

    qt = q.transpose(0, 2, 1, 3)  # [N, h, R, d]
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    # absent bias: ONE resident zero tile (index map constant -> the DMA
    # refetches nothing, and no [G, h, R, R] zeros ever exist in HBM)
    b1 = bias1[:, None, :] if has_b1 else jnp.zeros((1, 1, block_k), jnp.float32)
    b2 = bias2 if has_b2 else jnp.zeros((1, 1, block_q, block_k), jnp.float32)
    b1_ix = (lambda n, hh, i, j: (n, 0, j)) if has_b1 else (lambda n, hh, i, j: (0, 0, 0))
    b2_ix = ((lambda n, hh, i, j: (n // n_seq, hh, i, j)) if has_b2
             else (lambda n, hh, i, j: (0, 0, 0, 0)))

    def kernel(q_ref, k_ref, v_ref, b1_ref, b2_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref):
        kj = pl.program_id(3)

        @pl.when(kj == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)
            m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
            l_ref[:] = jnp.zeros_like(l_ref)

        qb = q_ref[0, 0].astype(jnp.float32) * scale
        kb = k_ref[0, 0].astype(jnp.float32)
        vb = v_ref[0, 0].astype(jnp.float32)
        s = jnp.dot(qb, kb.T, preferred_element_type=jnp.float32)  # [bq, bk]
        s = s + b2_ref[0, 0] + b1_ref[0, 0][None, :]
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jnp.dot(p, vb, preferred_element_type=jnp.float32)
        m_ref[:] = m_new

        @pl.when(kj == nkb - 1)
        def _flush():
            l_safe = jnp.maximum(l_ref[:], 1e-30)
            o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
            lse_ref[0, 0] = jnp.broadcast_to(m_ref[:] + jnp.log(l_safe), (block_q, LANES))

    out, lse = pl.pallas_call(
        kernel,
        grid=(N, h, nqb, nkb),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda n, hh, i, j: (n, hh, i, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda n, hh, i, j: (n, hh, j, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda n, hh, i, j: (n, hh, j, 0)),
            pl.BlockSpec((1, 1, block_k), b1_ix),
            pl.BlockSpec((1, 1, block_q, block_k), b2_ix),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda n, hh, i, j: (n, hh, i, 0)),
            pl.BlockSpec((1, 1, block_q, LANES), lambda n, hh, i, j: (n, hh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, h, R, d), q.dtype),
            jax.ShapeDtypeStruct((N, h, R, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt, b1, b2)
    return out.transpose(0, 2, 1, 3), lse[..., 0]


def _evo_bwd_impl(block_q, block_k, interpret, q, k, v, bias1, bias2, out, lse, dout):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N, R, h, d = q.shape
    has_b1, has_b2 = bias1 is not None, bias2 is not None
    G = bias2.shape[0] if has_b2 else 1
    n_seq = N // G
    scale = 1.0 / math.sqrt(d)
    nqb, nkb = R // block_q, R // block_k
    LANES = 128

    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    ot = out.transpose(0, 2, 1, 3)
    dot_ = dout.transpose(0, 2, 1, 3)
    lse_b = jnp.broadcast_to(lse[..., None], (N, h, R, LANES))
    # absent bias: one resident zero tile (see _evo_fwd_impl)
    b1 = bias1[:, None, :] if has_b1 else jnp.zeros((1, 1, block_k), jnp.float32)
    b2 = bias2 if has_b2 else jnp.zeros((1, 1, block_q, block_k), jnp.float32)

    def block_math(q_ref, k_ref, v_ref, b1_ref, b2_ref, o_ref, do_ref, lse_ref):
        """Recompute p and ds for the current [bq, bk] tile."""
        qb = q_ref[0, 0].astype(jnp.float32)
        kb = k_ref[0, 0].astype(jnp.float32)
        vb = v_ref[0, 0].astype(jnp.float32)
        ob = o_ref[0, 0].astype(jnp.float32)
        dob = do_ref[0, 0].astype(jnp.float32)
        lseb = lse_ref[0, 0, :, :1]
        s = scale * jnp.dot(qb, kb.T, preferred_element_type=jnp.float32)
        s = s + b2_ref[0, 0] + b1_ref[0, 0][None, :]
        p = jnp.exp(s - lseb)
        delta = jnp.sum(dob * ob, axis=-1, keepdims=True)
        dp = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return qb, kb, vb, dob, p, ds

    # ---- pass 1: dq — grid (N, h, qi, kj), kj innermost ----
    def dq_kernel(q_ref, k_ref, v_ref, b1_ref, b2_ref, o_ref, do_ref, lse_ref, dq_ref, dq_acc):
        kj = pl.program_id(3)

        @pl.when(kj == 0)
        def _init():
            dq_acc[:] = jnp.zeros_like(dq_acc)

        _, kb, _, _, _, ds = block_math(q_ref, k_ref, v_ref, b1_ref, b2_ref, o_ref, do_ref, lse_ref)
        dq_acc[:] += scale * jnp.dot(ds, kb, preferred_element_type=jnp.float32)

        @pl.when(kj == nkb - 1)
        def _flush():
            dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)

    q_spec = pl.BlockSpec((1, 1, block_q, d), lambda n, hh, i, j: (n, hh, i, 0))
    kv_spec = pl.BlockSpec((1, 1, block_k, d), lambda n, hh, i, j: (n, hh, j, 0))
    b1_spec = pl.BlockSpec((1, 1, block_k), (lambda n, hh, i, j: (n, 0, j)) if has_b1
                           else (lambda n, hh, i, j: (0, 0, 0)))
    b2_spec = pl.BlockSpec((1, 1, block_q, block_k),
                           (lambda n, hh, i, j: (n // n_seq, hh, i, j)) if has_b2
                           else (lambda n, hh, i, j: (0, 0, 0, 0)))
    lse_spec = pl.BlockSpec((1, 1, block_q, LANES), lambda n, hh, i, j: (n, hh, i, 0))

    dq = pl.pallas_call(
        dq_kernel,
        grid=(N, h, nqb, nkb),
        in_specs=[q_spec, kv_spec, kv_spec, b1_spec, b2_spec, q_spec, q_spec, lse_spec],
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct((N, h, R, d), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, b1, b2, ot, dot_, lse_b)[0]

    # ---- pass 2: dk/dv — grid (N, h, kj, qi), qi innermost ----
    def dkdv_kernel(q_ref, k_ref, v_ref, b1_ref, b2_ref, o_ref, do_ref, lse_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc):
        qi = pl.program_id(3)

        @pl.when(qi == 0)
        def _init():
            dk_acc[:] = jnp.zeros_like(dk_acc)
            dv_acc[:] = jnp.zeros_like(dv_acc)

        qb, _, _, dob, p, ds = block_math(q_ref, k_ref, v_ref, b1_ref, b2_ref, o_ref, do_ref, lse_ref)
        dv_acc[:] += jnp.dot(p.T, dob, preferred_element_type=jnp.float32)
        dk_acc[:] += scale * jnp.dot(ds.T, qb, preferred_element_type=jnp.float32)

        @pl.when(qi == nqb - 1)
        def _flush():
            dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
            dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)

    q_spec4 = pl.BlockSpec((1, 1, block_q, d), lambda n, hh, j, i: (n, hh, i, 0))
    kv_spec4 = pl.BlockSpec((1, 1, block_k, d), lambda n, hh, j, i: (n, hh, j, 0))
    b1_spec4 = pl.BlockSpec((1, 1, block_k), (lambda n, hh, j, i: (n, 0, j)) if has_b1
                            else (lambda n, hh, j, i: (0, 0, 0)))
    b2_spec4 = pl.BlockSpec((1, 1, block_q, block_k),
                            (lambda n, hh, j, i: (n // n_seq, hh, i, j)) if has_b2
                            else (lambda n, hh, j, i: (0, 0, 0, 0)))
    lse_spec4 = pl.BlockSpec((1, 1, block_q, LANES), lambda n, hh, j, i: (n, hh, i, 0))

    dk, dv = pl.pallas_call(
        dkdv_kernel,
        grid=(N, h, nkb, nqb),
        in_specs=[q_spec4, kv_spec4, kv_spec4, b1_spec4, b2_spec4, q_spec4, q_spec4, lse_spec4],
        out_specs=[kv_spec4, kv_spec4],
        out_shape=[jax.ShapeDtypeStruct((N, h, R, d), k.dtype),
                   jax.ShapeDtypeStruct((N, h, R, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, b1, b2, ot, dot_, lse_b)

    # ---- pass 3: dbias2 — grid (G, h, qi, kj, n), n (within group) innermost.
    # Skipped entirely when the pair bias is absent (no discarded gradient) ----
    def db2_kernel(q_ref, k_ref, v_ref, b1_ref, b2_ref, o_ref, do_ref, lse_ref,
                   db2_ref, db2_acc):
        n_in = pl.program_id(4)

        @pl.when(n_in == 0)
        def _init():
            db2_acc[:] = jnp.zeros_like(db2_acc)

        _, _, _, _, _, ds = block_math(q_ref, k_ref, v_ref, b1_ref, b2_ref, o_ref, do_ref, lse_ref)
        db2_acc[:] += ds

        @pl.when(n_in == n_seq - 1)
        def _flush():
            db2_ref[0, 0] = db2_acc[:]

    def abs_n(g, hh, i, j, n):
        return g * n_seq + n

    db2 = None if not has_b2 else pl.pallas_call(
        db2_kernel,
        grid=(G, h, nqb, nkb, n_seq),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda g, hh, i, j, n: (abs_n(g, hh, i, j, n), hh, i, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda g, hh, i, j, n: (abs_n(g, hh, i, j, n), hh, j, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda g, hh, i, j, n: (abs_n(g, hh, i, j, n), hh, j, 0)),
            pl.BlockSpec((1, 1, block_k), (lambda g, hh, i, j, n: (abs_n(g, hh, i, j, n), 0, j))
                         if has_b1 else (lambda g, hh, i, j, n: (0, 0, 0))),
            pl.BlockSpec((1, 1, block_q, block_k), lambda g, hh, i, j, n: (g, hh, i, j)),
            pl.BlockSpec((1, 1, block_q, d), lambda g, hh, i, j, n: (abs_n(g, hh, i, j, n), hh, i, 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda g, hh, i, j, n: (abs_n(g, hh, i, j, n), hh, i, 0)),
            pl.BlockSpec((1, 1, block_q, LANES), lambda g, hh, i, j, n: (abs_n(g, hh, i, j, n), hh, i, 0)),
        ],
        out_specs=[pl.BlockSpec((1, 1, block_q, block_k), lambda g, hh, i, j, n: (g, hh, i, j))],
        out_shape=[jax.ShapeDtypeStruct((G, h, R, R), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((block_q, block_k), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, b1, b2, ot, dot_, lse_b)[0]

    # ---- pass 4: dbias1 — grid (N, kj, h, qi), (h, qi) innermost.
    # Skipped entirely when the mask bias is absent ----
    def db1_kernel(q_ref, k_ref, v_ref, b1_ref, b2_ref, o_ref, do_ref, lse_ref,
                   db1_ref, db1_acc):
        hh = pl.program_id(2)
        qi = pl.program_id(3)

        @pl.when(jnp.logical_and(hh == 0, qi == 0))
        def _init():
            db1_acc[:] = jnp.zeros_like(db1_acc)

        _, _, _, _, _, ds = block_math(q_ref, k_ref, v_ref, b1_ref, b2_ref, o_ref, do_ref, lse_ref)
        db1_acc[:] += jnp.sum(ds, axis=0, keepdims=True)  # [1, bk]

        @pl.when(jnp.logical_and(hh == h - 1, qi == nqb - 1))
        def _flush():
            db1_ref[0, 0] = db1_acc[0]

    db1 = None if not has_b1 else pl.pallas_call(
        db1_kernel,
        grid=(N, nkb, h, nqb),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda n, j, hh, i: (n, hh, i, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda n, j, hh, i: (n, hh, j, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda n, j, hh, i: (n, hh, j, 0)),
            pl.BlockSpec((1, 1, block_k), lambda n, j, hh, i: (n, 0, j)),
            pl.BlockSpec((1, 1, block_q, block_k),
                         (lambda n, j, hh, i: (n // n_seq, hh, i, j)) if has_b2
                         else (lambda n, j, hh, i: (0, 0, 0, 0))),
            pl.BlockSpec((1, 1, block_q, d), lambda n, j, hh, i: (n, hh, i, 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda n, j, hh, i: (n, hh, i, 0)),
            pl.BlockSpec((1, 1, block_q, LANES), lambda n, j, hh, i: (n, hh, i, 0)),
        ],
        out_specs=[pl.BlockSpec((1, 1, block_k), lambda n, j, hh, i: (n, 0, j))],
        out_shape=[jax.ShapeDtypeStruct((N, 1, R), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((1, block_k), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, b1, b2, ot, dot_, lse_b)[0]

    return (dq.transpose(0, 2, 1, 3), dk.transpose(0, 2, 1, 3), dv.transpose(0, 2, 1, 3),
            None if db1 is None else db1[:, 0, :], db2)
