"""Blockwise quantization kernels.

TPU equivalent of the reference quantization suite
(``csrc/quantization/{quantize,dequantize,quant_reduce,...}.cu``, 2,289 LoC,
exposed via ``QuantizerBuilder``) which powers ZeRO++'s quantized-weight
all-gather (qwZ, ``runtime/zero/partition_parameters.py:1139``) and
quantized-gradient all-to-all reduce (qgZ,
``runtime/comm/coalesced_collectives.py:31``). Here quant/dequant are
jnp-level (XLA fuses the scale/round chain into surrounding ops); the
symmetric int8 blockwise format matches the reference's group-wise scheme.

Two families of entry points:

  * GSPMD (in-jit, sharding-constraint based): ``quantized_reshard`` and its
    straight-through-gradient wrapper ``quantized_gather_ste`` — the weight
    all-gather travels as int8 payload + per-block fp32 scales.
  * shard_map (manual collective axes): ``quantized_all_gather_dim`` /
    ``quantized_psum_scatter_dim`` — the hpZ/qgZ building blocks the engine
    uses inside its ``shard_map`` over the ``data_repl`` axis, plus the
    flat-vector ``quantized_psum_scatter`` / ``quantized_allreduce_mean``.
"""

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def quantize_blockwise(x: jax.Array, block_size: int = 256, dtype=jnp.int8,
                       axis: int = -1) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-block int8 quantization along ``axis``.

    Returns (q, scales): q has x's shape in int8; scales replace the ``axis``
    dim with n_blocks, in fp32.
    """
    axis = axis % max(x.ndim, 1)
    moved = axis != x.ndim - 1
    if moved:
        x = jnp.moveaxis(x, axis, -1)
    n = x.shape[-1]
    pad = (-n) % block_size
    if pad:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    blocks = x.reshape(*x.shape[:-1], -1, block_size).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(blocks), axis=-1, keepdims=True)
    scale = absmax / 127.0
    q = jnp.round(blocks / jnp.maximum(scale, 1e-12)).astype(dtype)
    q = q.reshape(*x.shape[:-1], -1)
    if pad:
        q = q[..., :n]
    s = scale[..., 0]
    if moved:
        q = jnp.moveaxis(q, -1, axis)
        s = jnp.moveaxis(s, -1, axis)
    return q, s


def dequantize_blockwise(q: jax.Array, scales: jax.Array, block_size: int = 256,
                         axis: int = -1) -> jax.Array:
    axis = axis % max(q.ndim, 1)
    moved = axis != q.ndim - 1
    if moved:
        q = jnp.moveaxis(q, axis, -1)
        scales = jnp.moveaxis(scales, axis, -1)
    n = q.shape[-1]
    pad = (-n) % block_size
    if pad:
        q = jnp.pad(q, [(0, 0)] * (q.ndim - 1) + [(0, pad)])
    blocks = q.reshape(*q.shape[:-1], -1, block_size).astype(jnp.float32)
    x = blocks * scales[..., None]
    x = x.reshape(*q.shape[:-1], -1)
    if pad:
        x = x[..., :n]
    if moved:
        x = jnp.moveaxis(x, -1, axis)
    return x


# ---------------------------------------------------------------------------
# shard_map collectives (manual mesh axes)
# ---------------------------------------------------------------------------

def _quant_axis_for(shape, avoid_dim: int) -> Optional[int]:
    """Pick the quantization axis: the largest dim other than ``avoid_dim``
    (blocks must not straddle the concat/split dim of the collective).
    None when the array has no other dim worth blocking (gather plain)."""
    cands = [i for i in range(len(shape)) if i != avoid_dim and shape[i] > 1]
    if not cands:
        return None
    return max(cands, key=lambda i: shape[i])


def quantized_all_gather_dim(x, axis_name, dim: int, block_size: int = 256):
    """ZeRO++ qwZ hop (reference ``partition_parameters.py:1139`` quantized
    all-gather handles): all-gather int8 payload + fp32 block scales along
    ``dim`` over the manual mesh axis ``axis_name``, dequantize locally —
    4x less wire traffic than fp32. For use inside ``shard_map``."""
    qaxis = _quant_axis_for(x.shape, dim % max(x.ndim, 1))
    if qaxis is None:
        return lax.all_gather(x, axis_name, axis=dim, tiled=True)
    q, s = quantize_blockwise(x, block_size, axis=qaxis)
    qf = lax.all_gather(q, axis_name, axis=dim, tiled=True)
    sf = lax.all_gather(s, axis_name, axis=dim, tiled=True)
    return dequantize_blockwise(qf, sf, block_size, axis=qaxis).astype(x.dtype)


def quantized_psum_scatter_dim(x, axis_name, dim: int, block_size: int = 256):
    """ZeRO++ qgZ hop (reference ``all_to_all_quant_reduce``
    coalesced_collectives.py:31): quantize, all-to-all int8 along ``dim``,
    dequantize, local sum — returns the group SUM scattered along ``dim``
    (psum_scatter semantics, tiled). For use inside ``shard_map``."""
    dim = dim % max(x.ndim, 1)
    world = lax.psum(1, axis_name)
    qaxis = _quant_axis_for(x.shape, dim)
    if qaxis is None:
        return lax.psum_scatter(x, axis_name, scatter_dimension=dim, tiled=True)
    q, s = quantize_blockwise(x, block_size, axis=qaxis)
    q2 = lax.all_to_all(q, axis_name, split_axis=dim, concat_axis=dim, tiled=True)
    s2 = lax.all_to_all(s, axis_name, split_axis=dim, concat_axis=dim, tiled=True)
    deq = dequantize_blockwise(q2, s2, block_size, axis=qaxis)
    # the received ``world`` chunks to be summed are tiled along ``dim``
    moved = jnp.moveaxis(deq, dim, 0)
    moved = moved.reshape(world, moved.shape[0] // world, *moved.shape[1:])
    out = jnp.moveaxis(moved.sum(axis=0), 0, dim)
    return out.astype(x.dtype)


def quantized_all_gather(x, axis_name: str, block_size: int = 256):
    """Flat-vector qwZ: all-gather int8 + local dequant along dim 0 — 4x less
    ICI traffic than fp32 all-gather. In-jit (shard_map) only."""
    return quantized_all_gather_dim(x, axis_name, 0, block_size)


def quantized_psum_scatter(x, axis_name: str, block_size: int = 256):
    """qgZ reduced-precision gradient reduce-scatter over dim 0 (reference
    ``all_to_all_quant_reduce`` coalesced_collectives.py:31): quantize, a2a,
    local dequant+reduce. 1-D inputs fall back to the exact psum_scatter
    (blocks along the split dim would straddle the all_to_all chunks).
    In-jit (shard_map) only."""
    return quantized_psum_scatter_dim(x, axis_name, 0, block_size)


def quantized_allreduce_mean(x, axis_name, block_size: int = 256):
    """qgZ-style 2-hop quantized gradient allreduce returning the MEAN over
    ``axis_name`` (reference ``all_to_all_quant_reduce`` followed by the
    allgather its callers perform): int8 reduce-scatter + int8 all-gather —
    ~4x less wire traffic than an fp32 ring allreduce. In-jit (shard_map).

    ``axis_name`` may be a tuple of mesh axes (reduces over their product).
    """
    axes = axis_name if isinstance(axis_name, (tuple, list)) else (axis_name, )
    world = lax.psum(1, axes)
    shape, n = x.shape, x.size
    # pad the flat vector so each device owns an equal, block-aligned chunk
    chunk = -(-n // world)
    chunk = -(-chunk // block_size) * block_size
    flat = jnp.pad(x.reshape(-1).astype(jnp.float32), (0, chunk * world - n))
    rows = flat.reshape(world, chunk)

    q, s = quantize_blockwise(rows, block_size)
    q_sh = lax.all_to_all(q, axes, split_axis=0, concat_axis=0, tiled=True)
    s_sh = lax.all_to_all(s, axes, split_axis=0, concat_axis=0, tiled=True)
    deq = dequantize_blockwise(q_sh, s_sh, block_size)          # (world, chunk)
    local_sum = jnp.sum(deq, axis=0) / world                    # (chunk,) mean
    q2, s2 = quantize_blockwise(local_sum[None], block_size)
    q_full = lax.all_gather(q2[0], axes, axis=0, tiled=False)
    s_full = lax.all_gather(s2[0], axes, axis=0, tiled=False)
    out = dequantize_blockwise(q_full, s_full, block_size)      # (world, chunk)
    return out.reshape(-1)[:n].reshape(shape).astype(x.dtype)


# ---------------------------------------------------------------------------
# GSPMD resharding (in-jit sharding constraints; XLA lowers to int8 gathers)
# ---------------------------------------------------------------------------

def spec_for_scales(spec, ndim: int, axis: int):
    """PartitionSpec for blockwise-quant scales: identical to the payload's
    spec except the quantized ``axis`` (whose size became n_blocks) must be
    unsharded — returns None if that dim was sharded in ``spec`` (blocks
    would straddle shards)."""
    from jax.sharding import PartitionSpec as P

    entries = list(spec) + [None] * (ndim - len(spec))
    entries = entries[:ndim]
    if ndim and entries[axis] is not None:
        return None
    return P(*entries)


def quantized_reshard(x, target_spec, mesh, block_size: int = 256, axis: Optional[int] = None):
    """ZeRO++ qwZ: move ``x`` to a less-sharded layout with int8 on the wire
    (reference quantized all-gather handles, ``partition_parameters.py:1139``):
    quantize shard-locally along a dim the target leaves unsharded, re-shard
    the int8 payload + scales via sharding constraints (XLA lowers to an int8
    all-gather), dequantize locally. Falls back to a plain reshard when every
    dim is sharded in the target (block boundaries would straddle shards).
    In-jit (GSPMD, not shard_map).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    if x.ndim == 0:
        return lax.with_sharding_constraint(x, NamedSharding(mesh, target_spec))
    entries = list(target_spec) + [None] * (x.ndim - len(target_spec))
    entries = entries[:x.ndim]
    if axis is None:
        open_dims = [i for i in range(x.ndim) if entries[i] is None and x.shape[i] > 1]
        axis = max(open_dims, key=lambda i: x.shape[i]) if open_dims else None
    if axis is None:
        return lax.with_sharding_constraint(x, NamedSharding(mesh, P(*entries)))
    s_spec = spec_for_scales(P(*entries), x.ndim, axis)
    if s_spec is None:
        return lax.with_sharding_constraint(x, NamedSharding(mesh, P(*entries)))
    q, s = quantize_blockwise(x, block_size, axis=axis)
    q = lax.with_sharding_constraint(q, NamedSharding(mesh, P(*entries)))
    s = lax.with_sharding_constraint(s, NamedSharding(mesh, s_spec))
    return dequantize_blockwise(q, s, block_size, axis=axis).astype(x.dtype)


def quantized_gather_ste(x, target_spec, mesh, block_size: int = 256):
    """``quantized_reshard`` with a straight-through gradient: the forward
    gathers int8 on the wire; the backward passes the cotangent through
    unchanged (XLA re-shards/reduces it to ``x``'s layout at the join) —
    matching the reference's qwZ semantics where gradients are computed at
    the dequantized weights and applied to the fp32 masters."""

    @jax.custom_vjp
    def f(y):
        return quantized_reshard(y, target_spec, mesh, block_size)

    def fwd(y):
        return quantized_reshard(y, target_spec, mesh, block_size), None

    def bwd(_, g):
        return (g, )

    f.defvjp(fwd, bwd)
    return f(x)
