"""Blockwise quantization kernels.

TPU equivalent of the reference quantization suite
(``csrc/quantization/{quantize,dequantize,quant_reduce,...}.cu``, 2,289 LoC,
exposed via ``QuantizerBuilder``) which powers ZeRO++'s quantized-weight
all-gather (qwZ) and quantized-gradient all-to-all reduce (qgZ,
``runtime/comm/coalesced_collectives.py:31``). Here quant/dequant are
jnp-level (XLA fuses the scale/round chain into surrounding ops); the
symmetric int8 blockwise format matches the reference's group-wise scheme.
"""

import functools
from typing import Tuple

import jax
import jax.numpy as jnp


def quantize_blockwise(x: jax.Array, block_size: int = 256, dtype=jnp.int8) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-block int8 quantization of the last axis.

    Returns (q, scales) with q: same shape as x in int8, scales:
    x.shape[:-1] + [n_blocks] in fp32.
    """
    orig_shape = x.shape
    n = orig_shape[-1]
    pad = (-n) % block_size
    if pad:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    blocks = x.reshape(*x.shape[:-1], -1, block_size).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(blocks), axis=-1, keepdims=True)
    scale = absmax / 127.0
    q = jnp.round(blocks / jnp.maximum(scale, 1e-12)).astype(dtype)
    q = q.reshape(*x.shape[:-1], -1)
    if pad:
        q = q[..., :n]
    return q, scale[..., 0]


def dequantize_blockwise(q: jax.Array, scales: jax.Array, block_size: int = 256) -> jax.Array:
    n = q.shape[-1]
    pad = (-n) % block_size
    if pad:
        q = jnp.pad(q, [(0, 0)] * (q.ndim - 1) + [(0, pad)])
    blocks = q.reshape(*q.shape[:-1], -1, block_size).astype(jnp.float32)
    x = blocks * scales[..., None]
    x = x.reshape(*q.shape[:-1], -1)
    if pad:
        x = x[..., :n]
    return x


def quantized_all_gather(x, axis_name: str, block_size: int = 256):
    """ZeRO++ qwZ: all-gather int8 + local dequant — 4x less ICI traffic than
    fp32 all-gather (reference ``partition_parameters.py:1139`` quantized
    handles). In-jit only."""
    q, s = quantize_blockwise(x, block_size)
    q_full = jax.lax.all_gather(q, axis_name, axis=0, tiled=True)
    s_full = jax.lax.all_gather(s, axis_name, axis=0, tiled=True)
    return dequantize_blockwise(q_full, s_full, block_size)


def quantized_psum_scatter(x, axis_name: str, block_size: int = 256):
    """ZeRO++ qgZ-style reduced-precision gradient reduce-scatter (reference
    ``all_to_all_quant_reduce`` coalesced_collectives.py:31): quantize, a2a,
    local dequant+reduce. In-jit only."""
    n_dev = jax.lax.psum(1, axis_name)
    q, s = quantize_blockwise(x, block_size)
    # all-to-all: each device receives its shard from every peer
    q_sh = jax.lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0, tiled=True)
    s_sh = jax.lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0, tiled=True)
    deq = dequantize_blockwise(q_sh, s_sh, block_size)
    # sum the n_dev received contributions (concatenated along axis 0)
    parts = jnp.split(deq, n_dev, axis=0)
    return functools.reduce(jnp.add, parts)
