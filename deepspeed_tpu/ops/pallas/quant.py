"""Blockwise quantization kernels.

TPU equivalent of the reference quantization suite
(``csrc/quantization/{quantize,dequantize,quant_reduce,...}.cu``, 2,289 LoC,
exposed via ``QuantizerBuilder``) which powers ZeRO++'s quantized-weight
all-gather (qwZ) and quantized-gradient all-to-all reduce (qgZ,
``runtime/comm/coalesced_collectives.py:31``). Here quant/dequant are
jnp-level (XLA fuses the scale/round chain into surrounding ops); the
symmetric int8 blockwise format matches the reference's group-wise scheme.
"""

import functools
from typing import Tuple

import jax
import jax.numpy as jnp


def quantize_blockwise(x: jax.Array, block_size: int = 256, dtype=jnp.int8) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-block int8 quantization of the last axis.

    Returns (q, scales) with q: same shape as x in int8, scales:
    x.shape[:-1] + [n_blocks] in fp32.
    """
    orig_shape = x.shape
    n = orig_shape[-1]
    pad = (-n) % block_size
    if pad:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    blocks = x.reshape(*x.shape[:-1], -1, block_size).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(blocks), axis=-1, keepdims=True)
    scale = absmax / 127.0
    q = jnp.round(blocks / jnp.maximum(scale, 1e-12)).astype(dtype)
    q = q.reshape(*x.shape[:-1], -1)
    if pad:
        q = q[..., :n]
    return q, scale[..., 0]


def dequantize_blockwise(q: jax.Array, scales: jax.Array, block_size: int = 256) -> jax.Array:
    n = q.shape[-1]
    pad = (-n) % block_size
    if pad:
        q = jnp.pad(q, [(0, 0)] * (q.ndim - 1) + [(0, pad)])
    blocks = q.reshape(*q.shape[:-1], -1, block_size).astype(jnp.float32)
    x = blocks * scales[..., None]
    x = x.reshape(*q.shape[:-1], -1)
    if pad:
        x = x[..., :n]
    return x


def quantized_all_gather(x, axis_name: str, block_size: int = 256):
    """ZeRO++ qwZ: all-gather int8 + local dequant — 4x less ICI traffic than
    fp32 all-gather (reference ``partition_parameters.py:1139`` quantized
    handles). In-jit only."""
    q, s = quantize_blockwise(x, block_size)
    q_full = jax.lax.all_gather(q, axis_name, axis=0, tiled=True)
    s_full = jax.lax.all_gather(s, axis_name, axis=0, tiled=True)
    return dequantize_blockwise(q_full, s_full, block_size)


def quantized_allreduce_mean(x, axis_name, block_size: int = 256):
    """qgZ-style 2-hop quantized gradient allreduce returning the MEAN over
    ``axis_name`` (reference ``all_to_all_quant_reduce`` followed by the
    allgather its callers perform): int8 reduce-scatter + int8 all-gather —
    ~4x less wire traffic than an fp32 ring allreduce. In-jit (shard_map).

    ``axis_name`` may be a tuple of mesh axes (reduces over their product).
    """
    import jax.numpy as jnp
    from jax import lax

    axes = axis_name if isinstance(axis_name, (tuple, list)) else (axis_name, )
    world = lax.psum(1, axes)
    shape, n = x.shape, x.size
    # pad the flat vector so each device owns an equal, block-aligned chunk
    chunk = -(-n // world)
    chunk = -(-chunk // block_size) * block_size
    flat = jnp.pad(x.reshape(-1).astype(jnp.float32), (0, chunk * world - n))
    rows = flat.reshape(world, chunk)

    part = rows
    for a in axes:  # hop per axis: a2a quantized partial reduction
        part = quantized_psum_scatter(part.reshape(world, chunk), a, block_size) \
            if False else part  # placeholder — replaced below
    # single fused implementation over the (possibly multi-axis) group:
    q, s = quantize_blockwise(rows, block_size)
    q_sh = lax.all_to_all(q, axes, split_axis=0, concat_axis=0, tiled=True)
    s_sh = lax.all_to_all(s, axes, split_axis=0, concat_axis=0, tiled=True)
    deq = dequantize_blockwise(q_sh, s_sh, block_size)          # (world, chunk)
    local_sum = jnp.sum(deq, axis=0) / world                    # (chunk,) mean
    q2, s2 = quantize_blockwise(local_sum[None], block_size)
    q_full = lax.all_gather(q2[:, 0] if q2.ndim == 3 else q2[0], axes, axis=0, tiled=False)
    s_full = lax.all_gather(s2[0], axes, axis=0, tiled=False)
    out = dequantize_blockwise(q_full, s_full, block_size)      # (world, chunk)
    return out.reshape(-1)[:n].reshape(shape).astype(x.dtype)


def spec_for_scales(spec, ndim: int):
    """PartitionSpec for blockwise-quant scales (last dim replaced by
    n_blocks): keep all entries except the last dim's, which must be None —
    returns None if the last dim was sharded (blocks would straddle shards)."""
    from jax.sharding import PartitionSpec as P

    entries = list(spec) + [None] * (ndim - len(spec))
    entries = entries[:ndim]
    if ndim and entries[-1] is not None:
        return None
    return P(*entries)


def quantized_reshard(x, target_spec, mesh, block_size: int = 256):
    """ZeRO++ qwZ: move ``x`` to a less-sharded layout with int8 on the wire
    (reference quantized all-gather handles, ``partition_parameters.py:1139``):
    quantize shard-locally, re-shard the int8 payload + scales via sharding
    constraints (XLA lowers to an int8 all-gather), dequantize locally.
    Falls back to a plain reshard when the last dim is sharded (block
    boundaries would straddle shards). In-jit (GSPMD, not shard_map).
    """
    import jax
    from jax import lax
    from jax.sharding import NamedSharding

    s_spec = spec_for_scales(target_spec, x.ndim)
    if x.ndim == 0 or s_spec is None:
        return lax.with_sharding_constraint(x, NamedSharding(mesh, target_spec))
    q, s = quantize_blockwise(x, block_size)
    q = lax.with_sharding_constraint(q, NamedSharding(mesh, target_spec))
    s = lax.with_sharding_constraint(s, NamedSharding(mesh, s_spec))
    return dequantize_blockwise(q, s, block_size).astype(x.dtype)


def quantized_psum_scatter(x, axis_name: str, block_size: int = 256):
    """ZeRO++ qgZ-style reduced-precision gradient reduce-scatter (reference
    ``all_to_all_quant_reduce`` coalesced_collectives.py:31): quantize, a2a,
    local dequant+reduce. In-jit only."""
    n_dev = jax.lax.psum(1, axis_name)
    q, s = quantize_blockwise(x, block_size)
    # all-to-all: each device receives its shard from every peer
    q_sh = jax.lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0, tiled=True)
    s_sh = jax.lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0, tiled=True)
    deq = dequantize_blockwise(q_sh, s_sh, block_size)
    # sum the n_dev received contributions (concatenated along axis 0)
    parts = jnp.split(deq, n_dev, axis=0)
    return functools.reduce(jnp.add, parts)
