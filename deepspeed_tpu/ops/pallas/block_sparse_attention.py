"""Block-sparse attention over a static sparsity layout.

Analog of the reference ``deepspeed/ops/sparse_attention/{matmul,softmax}.py``
(Triton block-sparse SDD/DSD matmuls + LUT softmax behind
``SparseSelfAttention.forward``, ``sparse_self_attention.py:99``). TPU
design: the layout is a host-side trace-time constant, so instead of the
reference's device LUT tensors we compile the layout into the kernel itself —
a Pallas grid ``(batch, heads, q_block_rows, max_active_cols)`` whose K/V
BlockSpec index maps read a scalar-prefetched per-row column LUT (same
machinery as ``paged_attention.py``). The DMA engine streams exactly the
active KV blocks; the online softmax accumulates across the inner grid
dimension in VMEM, fusing the reference's three Triton launches
(sdd matmul -> sparse softmax -> dsd matmul) into one kernel.

Two implementations with identical semantics:
- ``block_sparse_attention_gathered`` — jnp LUT-gather, O(L * max_active)
  memory (genuinely block-sparse, never materializes the dense score
  matrix), natively differentiable. CPU / oracle / backward path.
- ``_pallas_block_sparse`` — the fused forward kernel (TPU).

``block_sparse_attention`` dispatches: Pallas forward on TPU with a
``jax.custom_vjp`` whose backward recomputes through the gathered form
(flash-style recompute — no O(L^2) residuals), gathered form elsewhere.

Mask semantics match the reference Triton softmax (``softmax.py:37-86``):
``rpe`` is added to the scaled scores; ``key_padding_mask`` ([B, L]) and
``attn_mask`` ([L, L]) are added in ``'add'`` mode, while ``'mul'`` mode
treats them as 0/1 indicators (0 -> -inf). One deliberate extension: the
reference delegates intra-block causality of diagonal blocks to a
user-supplied ``attn_mask``; here ``causal=True`` applies the token-level
causal mask inside the kernel so unidirectional layouts are correct without
an O(L^2) mask tensor.
"""

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

_NEG_INF = -1e30


def make_layout_lut(layout):
    """Compress a (H, nb, nb) 0/1 layout into per-row column LUTs.

    Returns ``(lut, nvalid)``: ``lut`` int32 [H, nb, A] lists each row's
    active column-block indices (A = densest row in the whole layout),
    padded by repeating the row's last valid column so padded prefetches
    hit an already-resident block; ``nvalid`` int32 [H, nb] is the true
    count. Rows with no active blocks get nvalid 0 (output is zeros).
    Analog of the reference softmax LUT build (``softmax.py:128-149``).
    """
    layout = np.asarray(layout)
    H, nb, _ = layout.shape
    counts = layout.sum(axis=-1).astype(np.int32)  # [H, nb]
    A = max(1, int(counts.max()))
    lut = np.zeros((H, nb, A), dtype=np.int32)
    for h in range(H):
        for r in range(nb):
            cols = np.nonzero(layout[h, r])[0]
            if len(cols):
                lut[h, r, :len(cols)] = cols
                lut[h, r, len(cols):] = cols[-1]
    return lut, counts


def _mask_to_bias(m, mode):
    m = m.astype(jnp.float32)
    if mode == "mul":
        return jnp.where(m == 0, _NEG_INF, 0.0)
    if mode == "add":
        return m
    raise ValueError(f"unknown mask mode {mode!r} (expected 'add' or 'mul')")


def block_sparse_attention_gathered(q, k, v, lut, nvalid, block, *, causal=False, scale=None,
                                    rpe=None, key_padding_mask=None, attn_mask=None,
                                    key_padding_mask_mode="add", attn_mask_mode="mul"):
    """LUT-gather block-sparse attention. q/k/v: [B, H, L, d]; lut/nvalid
    from :func:`make_layout_lut`. Memory O(B*H*L*A*block), not O(L^2)."""
    B, H, L, d = q.shape
    nb = L // block
    A = lut.shape[-1]
    scale = 1.0 / math.sqrt(d) if scale is None else scale
    lut = jnp.asarray(lut)
    nvalid = jnp.asarray(nvalid)

    qb = q.reshape(B, H, nb, block, d).astype(jnp.float32) * scale
    kb = k.reshape(B, H, nb, block, d).astype(jnp.float32)
    vb = v.reshape(B, H, nb, block, d).astype(jnp.float32)
    hidx = jnp.arange(H)[:, None, None]
    kg = kb[:, hidx, lut]  # [B, H, nb, A, block, d]
    vg = vb[:, hidx, lut]

    s = jnp.einsum("bhrqd,bhrjkd->bhrqjk", qb, kg)  # [B, H, nb, block, A, block]

    j_valid = jnp.arange(A)[None, None, :] < nvalid[:, :, None]  # [H, nb, A]
    vis = j_valid[None, :, :, None, :, None]  # broadcast over B, q-token, k-token
    vis = jnp.broadcast_to(vis, s.shape)
    if causal:
        qpos = (jnp.arange(nb)[:, None] * block + jnp.arange(block)[None, :])  # [nb, block]
        kpos = lut[..., None] * block + jnp.arange(block)  # [H, nb, A, block]
        vis = vis & (kpos[None, :, :, None, :, :] <= qpos[None, None, :, :, None, None])
    if rpe is not None:
        s = s + _gather_2d(rpe.astype(jnp.float32), lut, nb, block)[None]
    if key_padding_mask is not None:
        kpb = _mask_to_bias(key_padding_mask, key_padding_mask_mode).reshape(B, nb, block)
        s = s + kpb[:, lut][:, :, :, None, :, :]  # [B,H,nb,1,A,block]
    if attn_mask is not None:
        s = s + _gather_2d(_mask_to_bias(attn_mask, attn_mask_mode), lut, nb, block)[None]

    s = jnp.where(vis, s, _NEG_INF)
    flat = s.reshape(B, H, nb, block, A * block)
    m = jnp.max(flat, axis=-1, keepdims=True)
    # fully-masked rows (empty layout row / all padding) produce zeros, not NaN
    p = jnp.where(flat > _NEG_INF / 2, jnp.exp(flat - m), 0.0)
    denom = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    p = (p / denom).reshape(s.shape)
    out = jnp.einsum("bhrqjk,bhrjkd->bhrqd", p, vg)
    return out.reshape(B, H, L, d).astype(q.dtype)


def _gather_2d(mat, lut, nb, block):
    """[L, L] -> per-(head,row) gathered blocks [H, nb, 1, A, block] ordered
    to broadcast against scores [., H, nb, block, A, block]."""
    blk = mat.reshape(nb, block, nb, block).transpose(0, 2, 1, 3)  # [nb, nb, block, block]
    g = blk[jnp.arange(nb)[None, :, None], lut]  # [H, nb, A, block, block]
    return g.transpose(0, 1, 3, 2, 4)  # [H, nb, block, A, block]


def block_sparse_attention(q, k, v, layout, block, *, causal=False, scale=None, rpe=None,
                           key_padding_mask=None, attn_mask=None,
                           key_padding_mask_mode="add", attn_mask_mode="mul", interpret=False,
                           lut=None, nvalid=None):
    """Public entry. ``layout``: host numpy (H, nb, nb) 0/1 from a
    :class:`~deepspeed_tpu.ops.sparse_attention.SparsityConfig`. Callers that
    reuse a layout (e.g. ``SparseSelfAttention``) pass a precomputed
    ``(lut, nvalid)`` to skip the host-side LUT build on every call."""
    if lut is None or nvalid is None:
        lut, nvalid = make_layout_lut(layout)
    B, H, L, d = q.shape
    kw = dict(causal=causal, scale=scale, rpe=rpe, key_padding_mask=key_padding_mask,
              attn_mask=attn_mask, key_padding_mask_mode=key_padding_mask_mode,
              attn_mask_mode=attn_mask_mode)
    use_kernel = interpret or (jax.default_backend() == "tpu" and d % 128 == 0 and block % 8 == 0
                               and L % block == 0)
    if not use_kernel:
        return block_sparse_attention_gathered(q, k, v, lut, nvalid, block, **kw)

    def gathered(q, k, v, rpe, kp, am):
        return block_sparse_attention_gathered(
            q, k, v, lut, nvalid, block, causal=causal, scale=scale, rpe=rpe,
            key_padding_mask=kp, attn_mask=am, key_padding_mask_mode=key_padding_mask_mode,
            attn_mask_mode=attn_mask_mode)

    # rpe/masks are explicit custom_vjp arguments (not closure captures) so a
    # *trainable* relative-position bias differentiates on the kernel path too
    # — closure-captured tracers would raise CustomVJPException under jax.grad.
    @jax.custom_vjp
    def _fwd(q, k, v, rpe, kp, am):
        try:
            return _pallas_block_sparse(q, k, v, jnp.asarray(lut), jnp.asarray(nvalid),
                                        block=block, causal=causal,
                                        scale=scale if scale is not None else 1.0 / math.sqrt(d),
                                        rpe=rpe, key_padding_mask=kp, attn_mask=am,
                                        key_padding_mask_mode=key_padding_mask_mode,
                                        attn_mask_mode=attn_mask_mode, interpret=interpret)
        except Exception as e:  # pragma: no cover — kernel bring-up safety net.
            # Only reachable for EAGER callers: under an enclosing jit the
            # kernel is staged at trace time and a Mosaic failure surfaces at
            # the caller's compile, outside this try. The real gate for the
            # kernel path is the precondition above (TPU backend + aligned
            # block/head_dim), which is checked before tracing.
            from ...utils.logging import warning_once

            warning_once(f"pallas block-sparse attention unavailable "
                         f"({type(e).__name__}: {e}); using gathered fallback")
            return gathered(q, k, v, rpe, kp, am)

    def _fwd_vjp(q, k, v, rpe, kp, am):
        return _fwd(q, k, v, rpe, kp, am), (q, k, v, rpe, kp, am)

    def _bwd_vjp(res, g):
        q, k, v, rpe, kp, am = res
        _, vjp = jax.vjp(gathered, q, k, v, rpe, kp, am)
        return vjp(g)

    _fwd.defvjp(_fwd_vjp, _bwd_vjp)
    return _fwd(q, k, v, rpe, key_padding_mask, attn_mask)


@functools.partial(jax.jit, static_argnames=("block", "causal", "scale", "key_padding_mask_mode",
                                             "attn_mask_mode", "interpret"))
def _pallas_block_sparse(q, k, v, lut, nvalid, *, block, causal, scale, rpe, key_padding_mask,
                         attn_mask, key_padding_mask_mode, attn_mask_mode, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, L, d = q.shape
    nb = L // block
    A = lut.shape[-1]
    have_rpe = rpe is not None
    have_kp = key_padding_mask is not None
    have_attn = attn_mask is not None

    def q_map(b, h, r, j, lut_ref, nv_ref):
        return (b, h, r, 0)

    def kv_map(b, h, r, j, lut_ref, nv_ref):
        return (b, h, lut_ref[h, r, j], 0)

    def kp_map(b, h, r, j, lut_ref, nv_ref):
        return (b, lut_ref[h, r, j])

    def mat_map(b, h, r, j, lut_ref, nv_ref):
        return (r, lut_ref[h, r, j])

    in_specs = [
        pl.BlockSpec((1, 1, block, d), q_map),
        pl.BlockSpec((1, 1, block, d), kv_map),
        pl.BlockSpec((1, 1, block, d), kv_map),
    ]
    extra = []
    if have_kp:
        in_specs.append(pl.BlockSpec((1, block), kp_map))
        extra.append(key_padding_mask.astype(jnp.float32))
    if have_rpe:
        in_specs.append(pl.BlockSpec((block, block), mat_map))
        extra.append(rpe.astype(jnp.float32))
    if have_attn:
        in_specs.append(pl.BlockSpec((block, block), mat_map))
        extra.append(attn_mask.astype(jnp.float32))

    def kernel(lut_ref, nv_ref, q_ref, k_ref, v_ref, *rest):
        o_ref, acc_ref, m_ref, l_ref = rest[-4:]
        opt = list(rest[:-4])
        kp_ref = opt.pop(0) if have_kp else None
        rpe_ref = opt.pop(0) if have_rpe else None
        attn_ref = opt.pop(0) if have_attn else None
        h = pl.program_id(1)
        r = pl.program_id(2)
        j = pl.program_id(3)

        @pl.when(j == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)
            m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
            l_ref[:] = jnp.zeros_like(l_ref)

        @pl.when(j < nv_ref[h, r])
        def _compute():
            col = lut_ref[h, r, j]
            qb = q_ref[0, 0].astype(jnp.float32) * scale  # [block, d]
            kb = k_ref[0, 0].astype(jnp.float32)
            vb = v_ref[0, 0].astype(jnp.float32)
            s = jax.lax.dot(qb, kb.T)  # [block, block]
            if have_rpe:
                s = s + rpe_ref[:, :]
            if have_kp:
                kpm = kp_ref[0, :][None, :]
                s = s + (jnp.where(kpm == 0, _NEG_INF, 0.0)
                         if key_padding_mask_mode == "mul" else kpm)
            if have_attn:
                am = attn_ref[:, :]
                s = s + (jnp.where(am == 0, _NEG_INF, 0.0) if attn_mask_mode == "mul" else am)
            if causal:
                qpos = r * block + jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
                kpos = col * block + jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
                s = jnp.where(kpos <= qpos, s, _NEG_INF)
            m_prev = m_ref[:]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            # guard fully-masked rows: exp(NEG_INF - NEG_INF) must stay 0
            p = jnp.where(s > _NEG_INF / 2, jnp.exp(s - m_new), 0.0)
            alpha = jnp.exp(m_prev - m_new)
            l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot(p, vb)
            m_ref[:] = m_new

        @pl.when(j == A - 1)
        def _finalize():
            o_ref[0, 0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H, nb, A),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, block, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((block, d), jnp.float32),
            pltpu.VMEM((block, 1), jnp.float32),
            pltpu.VMEM((block, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(kernel, grid_spec=grid_spec,
                          out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
                          interpret=interpret)(lut, nvalid, q, k, v, *extra)
