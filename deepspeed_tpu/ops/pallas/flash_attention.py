"""Flash attention for TPU.

Replaces the reference's fused attention kernels (training:
``csrc/transformer/*.cu`` softmax/transform; inference context:
``csrc/transformer/inference/csrc/softmax.cu``) with a Pallas blocked
flash-attention. The public entry ``flash_attention(q, k, v, causal=...)``
takes [B, S, n_heads, head_dim] (GQA allowed: n_kv may divide n_q) and is
numerically validated against ``models.transformer.reference_attention``
(mirroring the reference's tests/unit/ops kernel-vs-torch strategy).

The Pallas kernel path requires a real TPU; elsewhere (CPU tests) we fall back
to the jnp reference implementation, which XLA fuses reasonably well.
"""

import functools
import math

import jax
import jax.numpy as jnp


def _use_pallas():
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def flash_attention(q, k, v, causal: bool = True, block_q: int = 512, block_k: int = 512):
    """q: [B, S, nq, d]; k/v: [B, S, nkv, d] with nq % nkv == 0."""
    if _use_pallas():
        try:
            return _pallas_flash(q, k, v, causal=causal, block_q=block_q, block_k=block_k)
        except Exception as e:
            from ...utils.logging import warning_once

            warning_once(f"pallas flash attention unavailable ({type(e).__name__}: {e}); "
                         f"falling back to reference attention — expect O(S^2) memory and lower throughput")
    from ...models.transformer import reference_attention

    return reference_attention(q, k, v, causal=causal)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret"))
def _pallas_flash(q, k, v, causal=True, block_q=512, block_k=512, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, S, nq, d = q.shape
    nkv = k.shape[2]
    group = nq // nkv
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    assert S % block_q == 0 and S % block_k == 0
    scale = 1.0 / math.sqrt(d)

    # layout: [B, n, S, d] for contiguous per-head slabs
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    grid = (B, nq, S // block_q)

    def kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref):
        # block refs carry the singleton (batch, head) dims: [1, 1, bq|S, d]
        qi = pl.program_id(2)
        n_kblocks = S // block_k

        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, -1e30)
        l_ref[:] = jnp.zeros_like(l_ref)

        def body(kj, _):
            qb = q_ref[0, 0].astype(jnp.float32) * scale  # [bq, d]
            kb = k_ref[0, 0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)  # [bk, d]
            vb = v_ref[0, 0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
            s = jnp.dot(qb, kb.T, preferred_element_type=jnp.float32)  # [bq, bk]
            if causal:
                q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
                k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
                s = jnp.where(q_pos >= k_pos, s, -1e30)
            m_prev = m_ref[:]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m_prev - m_new)
            l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc_ref[:] = acc_ref[:] * alpha + jnp.dot(p, vb, preferred_element_type=jnp.float32)
            m_ref[:] = m_new
            return 0

        # ceil-div: the k block containing the last visible key must run
        n_iters = ((qi + 1) * block_q + block_k - 1) // block_k if causal else n_kblocks
        jax.lax.fori_loop(0, n_iters, body, 0)
        o_ref[0, 0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)

    def q_index(b, h, i):
        return (b, h, i, 0)

    def kv_index(b, h, i):
        return (b, h // group, 0, 0)

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), q_index),
            pl.BlockSpec((1, 1, S, d), kv_index),
            pl.BlockSpec((1, 1, S, d), kv_index),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d), q_index),
        out_shape=jax.ShapeDtypeStruct((B, nq, S, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3)
