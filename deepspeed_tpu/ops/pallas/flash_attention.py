"""Flash attention for TPU — forward AND backward Pallas kernels.

Replaces the reference's fused attention kernels (training:
``csrc/transformer/softmax_kernels.cu`` / ``general_kernels.cu``; inference
context: ``csrc/transformer/inference/csrc/softmax.cu``) with a Pallas blocked
flash-attention. The public entry ``flash_attention(q, k, v, causal=...)``
takes [B, S, n_heads, head_dim] (GQA allowed: n_kv may divide n_q) and is
numerically validated against ``models.transformer.reference_attention``
(mirroring the reference's tests/unit/ops kernel-vs-torch strategy) — in both
forward and ``jax.grad``.

Backward follows the flash-attention recurrences: the forward saves the
per-row log-sum-exp ``lse = m + log(l)``; the backward recomputes
``p = exp(s - lse)`` blockwise, with the two-pass split:

  * dk/dv pass — grid over k-blocks, inner loop over q-blocks:
      dv += p^T dO;   ds = p * (dO v^T - delta);   dk += ds^T q * scale
  * dq pass — grid over q-blocks, inner loop over k-blocks:
      dq += ds k * scale
  where ``delta = rowsum(dO * O)``.

Fallback policy: on non-TPU backends, or for shapes the kernel does not
support (S not a multiple of 128), we use the jnp reference implementation —
XLA fuses it reasonably. On TPU with supported shapes a kernel failure is
LOUD: it raises unless ``DS_TPU_ALLOW_ATTN_FALLBACK=1`` is set, so training
can never silently drop to O(S^2) unfused attention again (the round-1 perf
failure mode).
"""

import functools
import math
import os

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _use_pallas():
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _fit_block(S: int, want: int) -> int:
    """Largest lane-aligned (multiple-of-128) divisor of S that is <= want.

    Always returns a true divisor: ``_shapes_supported`` guarantees
    S % 128 == 0, so 128 qualifies as the floor — the kernel's
    ``S % block == 0`` precondition can never trip on the auto-fit path."""
    b = max(128, min(want, S) // 128 * 128)
    while b > 128 and S % b:
        b -= 128
    return b


# Generations where the 1024 tiling is validated (bench chip is v5e). Older /
# unknown generations keep the proven 512 default: a VMEM exhaustion inside an
# enclosing jit surfaces at the *caller's* compile, where the retry below
# cannot catch it.
_LARGE_TILE_KINDS = ("v5 lite", "v5e", "v5p", "v6")


def _default_tile():
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return 512
    return 1024 if any(t in kind for t in _LARGE_TILE_KINDS) else 512


def _resolve_tiles(S: int, d: int, block_q=None, block_k=None):
    """Tile resolution order: explicit caller value > kernel-config registry
    (``autotuning/kernel_config.py``, per chip generation/topology/shape
    bucket) > the generation heuristic ``_default_tile``. The VMEM fit and
    divisor snap downstream still apply to tuned values — the registry can
    propose tiles, never break the kernel's preconditions."""
    from ...autotuning.kernel_config import shape_bucket, tuned_tile

    dflt = _default_tile()
    bucket = shape_bucket(S=S, d=d)
    bq = block_q if block_q is not None else tuned_tile("flash_attention", bucket, "block_q", dflt)
    bk = block_k if block_k is not None else tuned_tile("flash_attention", bucket, "block_k", dflt)
    return int(bq), int(bk)


def _shapes_supported(q):
    B, S, nq, d = q.shape
    return S % 128 == 0 and d >= 32


_VMEM_BUDGET = 14 * 2**20  # conservative slice of the ~16MiB/core VMEM


def _fit_tiles_vmem(S: int, d: int, bq: int, bk: int):
    """Shrink (block_q, block_k) until the kernel's VMEM working set fits.

    All four kernels (fwd + the three bwd passes) stream K/V one tile per
    grid step, so residency is independent of S: the working set is the
    [bq, bk] score/prob temporaries, the [bq|bk, d] tiles and accumulators.
    A VMEM overflow inside an enclosing jit (or under jax.grad) is
    uncatchable at runtime, so the fit happens at trace time. Returns
    (bq, bk) — or None if even 128-tiles cannot fit (head_dim would have to
    be pathological for that).
    """
    while True:
        # approximate LARGEST working set across fwd and the bwd passes
        # (bwd holds p/dp/ds temporaries plus more d-sized tiles/accums —
        # the binding term for large head_dim). Calibrated against on-chip
        # evidence: (1024, 1024, d=128) passes (validated by
        # tests_tpu::test_flash_bwd_large_tiles); (1024, 1024, d=256) is
        # rejected to 512 tiles rather than risk an uncatchable grad-compile
        # OOM.
        tmp = 2 * bq * bk * 4 + (bq + bk) * d * 16 + bq * 128 * 4
        if tmp <= _VMEM_BUDGET:
            return bq, bk
        if bq <= 128 and bk <= 128:
            return None
        bq2 = _fit_block(S, max(128, bq // 2)) if bq >= bk else bq
        bk2 = _fit_block(S, max(128, bk // 2)) if bk >= bq else bk
        if (bq2, bk2) == (bq, bk):  # both already at their floor for this S
            return None
        bq, bk = bq2, bk2


def _reference_fallback(q, k, v, causal, window, alibi, reason=None):
    """The single O(S^2) jnp fallback path; ``reason`` warns once."""
    from ...models.transformer import alibi_slopes, reference_attention

    if reason is not None:
        from ...utils.logging import warning_once

        warning_once(f"flash attention: {reason} — using O(S^2) reference attention")
    return reference_attention(q, k, v, causal=causal, window=window,
                               alibi=alibi_slopes(q.shape[2]) if alibi else None)


def flash_attention(q, k, v, causal: bool = True, block_q: int = None, block_k: int = None,
                    window=None, alibi: bool = False):
    """q: [B, S, nq, d]; k/v: [B, S, nkv, d] with nq % nkv == 0.

    Differentiable: both forward and backward run as Pallas kernels on TPU.
    ``window``: sliding-window attention (Mistral reference
    ``inference/v2/model_implementations/mistral/``) — query i attends keys
    in (i - window, i]; requires ``causal=True``. ``alibi``: Bloom-style
    per-head linear bias ``slope_h * (k_pos - q_pos)`` with the standard
    power-of-two slopes (non-power-of-2 head counts use the reference path).
    """
    if window is not None:
        assert causal, "sliding window requires causal attention"
        window = int(window)
    if alibi and (q.shape[2] & (q.shape[2] - 1)) != 0:
        # the in-kernel closed-form slope only matches pow-2 head counts;
        # others use the interleaved table — LOUD jnp path
        return _reference_fallback(q, k, v, causal, window, alibi,
                                   f"alibi with non-power-of-2 head count {q.shape[2]}")
    block_q, block_k = _resolve_tiles(q.shape[1], q.shape[3], block_q, block_k)
    if _use_pallas() and not _shapes_supported(q):
        return _reference_fallback(q, k, v, causal, window, alibi,
                                   f"unsupported shape {q.shape} (S must be a multiple of 128, "
                                   "head_dim >= 32)")
    if _use_pallas():
        # block sizes snap to the largest lane-aligned divisor of S, so
        # non-multiple-of-1024 lengths (1536, 2560, ...) keep the kernel
        S, d = q.shape[1], q.shape[3]
        bq, bk = _fit_block(S, block_q), _fit_block(S, block_k)
        fitted = _fit_tiles_vmem(S, d, bq, bk)
        if fitted is None:
            return _reference_fallback(q, k, v, causal, window, alibi,
                                       f"no tiling fits VMEM for S={S}, d={d}")
        bq, bk = fitted
        try:
            return _pallas_flash(q, k, v, causal=causal, block_q=bq, block_k=bk,
                                 window=window, alibi=alibi)
        except Exception as e:
            if bq > 512 or bk > 512:
                # large tiles can exhaust VMEM on smaller TPU generations:
                # retry once at the proven 512 tiling before going loud.
                # NOTE this guards only the eager FORWARD call — the
                # custom_vjp backward compiles later under jax.grad where no
                # retry can fire; that's why the large-tile default is gated
                # on device generation (_default_tile) and the backward is
                # validated on-chip (tests_tpu::test_flash_bwd_large_tiles)
                try:
                    return _pallas_flash(q, k, v, causal=causal, block_q=_fit_block(S, 512),
                                         block_k=_fit_block(S, 512), window=window, alibi=alibi)
                except Exception:
                    pass
            if os.environ.get("DS_TPU_ALLOW_ATTN_FALLBACK") != "1":
                raise RuntimeError(
                    "Pallas flash attention failed on a supported shape "
                    f"({type(e).__name__}: {e}). Set DS_TPU_ALLOW_ATTN_FALLBACK=1 "
                    "to permit the O(S^2) reference-attention fallback."
                ) from e
            return _reference_fallback(q, k, v, causal, window, alibi,
                                       f"kernel failed ({type(e).__name__}), fallback permitted")
    return _reference_fallback(q, k, v, causal, window, alibi)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret", "window",
                                             "alibi"))
def _pallas_flash(q, k, v, causal=True, block_q=1024, block_k=1024, interpret=False, window=None,
                  alibi=False):
    return _flash_core(causal, min(block_q, q.shape[1]), min(block_k, q.shape[1]),
                       interpret, window, alibi, q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4, 5))
def _flash_core(causal, block_q, block_k, interpret, window, alibi, q, k, v):
    out, _ = _flash_fwd_impl(causal, block_q, block_k, interpret, window, alibi, q, k, v)
    return out


def _flash_core_fwd(causal, block_q, block_k, interpret, window, alibi, q, k, v):
    out, lse = _flash_fwd_impl(causal, block_q, block_k, interpret, window, alibi, q, k, v)
    return out, (q, k, v, out, lse)


def _flash_core_bwd(causal, block_q, block_k, interpret, window, alibi, res, dout):
    q, k, v, out, lse = res
    dq, dk, dv = _flash_bwd_impl(causal, block_q, block_k, interpret, window, alibi, q, k, v, out, lse,
                                 dout)
    return dq, dk, dv


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def _alibi_slope(h, n_heads):
    """Closed-form power-of-2 ALiBi slope for head ``h`` (traced int32):
    2^(-8(h+1)/n) — matches models.transformer.alibi_slopes for pow-2 n."""
    return jnp.exp2(-8.0 * (h.astype(jnp.float32) + 1.0) / n_heads)


def _flash_fwd_impl(causal, block_q, block_k, interpret, window, alibi, q, k, v):
    """Returns (out [B,S,nq,d], lse [B,nq,S] float32).

    Streaming revisit-accumulate grid ``(B, nq, q_blocks, k_blocks)`` — the
    same Mosaic idiom as the backward passes below: K/V arrive one
    ``[block_k, d]`` tile per grid step, the online-softmax state lives in
    VMEM scratch across the innermost dimension, and the output flushes on
    the last k step. VMEM residency is therefore independent of S (the
    previous full-S K/V slabs capped S near 8k on a 16MiB core — the
    long-context path OOM'd inside the training jit where no retry can
    fire). Causal/window skipping: ``pl.when`` guards the compute and the
    K/V index map clamps out-of-range k blocks to the last visible one, so
    the pipeline re-uses the resident tile instead of streaming blocks the
    softmax never reads.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, S, nq, d = q.shape
    nkv = k.shape[2]
    group = nq // nkv
    assert S % block_q == 0 and S % block_k == 0
    scale = 1.0 / math.sqrt(d)
    n_qblocks = S // block_q
    n_kblocks = S // block_k

    # layout: [B, n, S, d] for contiguous per-head slabs
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    # TPU requires the last two block dims to be (8k, 128k)-aligned; stats get
    # a broadcast 128-lane trailing dim (same layout as jax's own TPU flash
    # kernel), sliced back to [B, nq, S] for the saved residual.
    LANES = 128

    def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref):
        qi = pl.program_id(2)
        kj = pl.program_id(3)
        head = pl.program_id(1)

        @pl.when(kj == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)
            m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
            l_ref[:] = jnp.zeros_like(l_ref)

        def compute():
            qb = q_ref[0, 0].astype(jnp.float32) * scale  # [bq, d] (resident across kj)
            kb = k_ref[0, 0].astype(jnp.float32)          # [bk, d]
            vb = v_ref[0, 0].astype(jnp.float32)
            s = jnp.dot(qb, kb.T, preferred_element_type=jnp.float32)  # [bq, bk]
            if causal or alibi:
                q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
                k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
                if alibi:
                    s = s + _alibi_slope(head, nq) * (k_pos - q_pos).astype(jnp.float32)
                if causal:
                    visible = q_pos >= k_pos
                    if window is not None:
                        visible = jnp.logical_and(visible, q_pos - k_pos < window)
                    s = jnp.where(visible, s, _NEG_INF)
            m_prev = m_ref[:]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m_prev - m_new)
            l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc_ref[:] = acc_ref[:] * alpha + jnp.dot(p, vb, preferred_element_type=jnp.float32)
            m_ref[:] = m_new

        if causal:
            in_range = (qi + 1) * block_q > kj * block_k
            if window is not None:
                in_range = jnp.logical_and(
                    in_range, qi * block_q - ((kj + 1) * block_k - 1) < window)
            pl.when(in_range)(compute)
        else:
            compute()

        @pl.when(kj == n_kblocks - 1)
        def _flush():
            l_safe = jnp.maximum(l_ref[:], 1e-30)
            o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
            lse_ref[0, 0] = jnp.broadcast_to(m_ref[:] + jnp.log(l_safe), (block_q, LANES))

    def q_index(b, h, i, j):
        return (b, h, i, 0)

    def kv_index(b, h, i, j):
        if not causal:
            return (b, h // group, j, 0)
        # clamp into the visible range: index maps issue their DMA even for
        # pl.when-skipped steps, so out-of-range columns re-use the resident
        # block (repeated index -> no refetch) instead of streaming dead data
        hi = ((i + 1) * block_q - 1) // block_k
        jj = jnp.minimum(j, hi)
        if window is not None:
            lo = jnp.maximum(i * block_q - (window - 1), 0) // block_k
            jj = jnp.maximum(jj, jnp.minimum(lo, hi))
        return (b, h // group, jj, 0)

    out, lse = pl.pallas_call(
        kernel,
        grid=(B, nq, n_qblocks, n_kblocks),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), q_index),
            pl.BlockSpec((1, 1, block_k, d), kv_index),
            pl.BlockSpec((1, 1, block_k, d), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), q_index),
            pl.BlockSpec((1, 1, block_q, LANES), q_index),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, nq, S, d), q.dtype),
            jax.ShapeDtypeStruct((B, nq, S, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3), lse[..., 0]


def _flash_bwd_impl(causal, block_q, block_k, interpret, window, alibi, q, k, v, out, lse, dout):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, S, nq, d = q.shape
    nkv = k.shape[2]
    group = nq // nkv
    scale = 1.0 / math.sqrt(d)
    n_qblocks = S // block_q
    n_kblocks = S // block_k

    LANES = 128

    qt = q.transpose(0, 2, 1, 3)          # [B, nq, S, d]
    kt = k.transpose(0, 2, 1, 3)          # [B, nkv, S, d]
    vt = v.transpose(0, 2, 1, 3)
    ot = out.transpose(0, 2, 1, 3)        # [B, nq, S, d]
    dot = dout.transpose(0, 2, 1, 3)      # [B, nq, S, d]
    # lane-broadcast the saved [B, nq, S] stats back to the TPU-aligned layout
    lse_b = jnp.broadcast_to(lse[..., None], (B, nq, S, LANES))

    # Both passes use the canonical Mosaic revisit-accumulate idiom: the block
    # loop is the innermost *grid* dimension (TPU grids execute sequentially),
    # the output block spec ignores it, and a VMEM scratch accumulates across
    # revisits — initialized on the first visit, flushed on the last. Causal
    # skipping is done with pl.when on statically-shaped programs (dynamic
    # fori_loop trip counts inside the kernel miscompile on some Mosaic
    # versions — observed as NaNs in the final grid programs in bf16).

    def _shared_block_math(qb, ob, dob, lseb, kb, vb, qi, kj, head):
        """Recompute p and ds for one (q-block, k-block) tile."""
        deltab = jnp.sum(dob * ob, axis=-1, keepdims=True)               # [bq, 1]
        s = scale * jnp.dot(qb, kb.T, preferred_element_type=jnp.float32)  # [bq, bk]
        if causal or alibi:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            if alibi:
                s = s + _alibi_slope(head, nq) * (k_pos - q_pos).astype(jnp.float32)
            if causal:
                vis = q_pos >= k_pos
                if window is not None:
                    vis = jnp.logical_and(vis, q_pos - k_pos < window)
                s = jnp.where(vis, s, _NEG_INF)
        p = jnp.exp(s - lseb)                                            # [bq, bk]
        dp = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)      # [bq, bk]
        ds = p * (dp - deltab)
        return p, ds

    # ---- pass 1: dk/dv (per q-head; grouped-sum outside for GQA) ----
    # grid: q-blocks innermost; dk/dv blocks revisited across qi.
    def dkdv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc):
        kj = pl.program_id(2)
        qi = pl.program_id(3)
        head = pl.program_id(1)

        @pl.when(qi == 0)
        def _init():
            dk_acc[:] = jnp.zeros_like(dk_acc)
            dv_acc[:] = jnp.zeros_like(dv_acc)

        # causal: q blocks strictly before this k block contribute nothing;
        # sliding window: q blocks entirely beyond kj's window contribute
        # nothing either
        visible = (qi + 1) * block_q > kj * block_k if causal else True
        if causal and window is not None:
            visible = jnp.logical_and(
                visible, qi * block_q - ((kj + 1) * block_k - 1) < window)

        @pl.when(visible)
        def _compute():
            kb = k_ref[0, 0].astype(jnp.float32)  # [bk, d]
            vb = v_ref[0, 0].astype(jnp.float32)
            qb = q_ref[0, 0].astype(jnp.float32)  # [bq, d]
            ob = o_ref[0, 0].astype(jnp.float32)
            dob = do_ref[0, 0].astype(jnp.float32)
            lseb = lse_ref[0, 0, :, :1]           # [bq, 1]
            p, ds = _shared_block_math(qb, ob, dob, lseb, kb, vb, qi, kj, head)
            dv_acc[:] += jnp.dot(p.T, dob, preferred_element_type=jnp.float32)
            dk_acc[:] += scale * jnp.dot(ds.T, qb, preferred_element_type=jnp.float32)

        @pl.when(qi == n_qblocks - 1)
        def _flush():
            dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
            dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)

    def kv_index4(b, h, j, i):
        return (b, h // group, j, 0)

    def q_index4(b, h, j, i):
        return (b, h, i, 0)

    dk_g, dv_g = pl.pallas_call(
        dkdv_kernel,
        grid=(B, nq, n_kblocks, n_qblocks),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), q_index4),       # q
            pl.BlockSpec((1, 1, block_k, d), kv_index4),      # k
            pl.BlockSpec((1, 1, block_k, d), kv_index4),      # v
            pl.BlockSpec((1, 1, block_q, d), q_index4),       # out
            pl.BlockSpec((1, 1, block_q, d), q_index4),       # dout
            pl.BlockSpec((1, 1, block_q, LANES), q_index4),   # lse
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d), lambda b, h, j, i: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b, h, j, i: (b, h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, nq, S, d), jnp.float32),
            jax.ShapeDtypeStruct((B, nq, S, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt, ot, dot, lse_b)

    # ---- pass 2: dq — k-blocks innermost; dq block revisited across kj ----
    def dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref, dq_acc):
        qi = pl.program_id(2)
        kj = pl.program_id(3)
        head = pl.program_id(1)

        @pl.when(kj == 0)
        def _init():
            dq_acc[:] = jnp.zeros_like(dq_acc)

        visible = (qi + 1) * block_q > kj * block_k if causal else True
        if causal and window is not None:
            visible = jnp.logical_and(
                visible, qi * block_q - ((kj + 1) * block_k - 1) < window)

        @pl.when(visible)
        def _compute():
            qb = q_ref[0, 0].astype(jnp.float32)     # [bq, d]
            ob = o_ref[0, 0].astype(jnp.float32)
            dob = do_ref[0, 0].astype(jnp.float32)
            lseb = lse_ref[0, 0, :, :1]              # [bq, 1]
            kb = k_ref[0, 0].astype(jnp.float32)     # [bk, d]
            vb = v_ref[0, 0].astype(jnp.float32)
            _, ds = _shared_block_math(qb, ob, dob, lseb, kb, vb, qi, kj, head)
            dq_acc[:] += scale * jnp.dot(ds, kb, preferred_element_type=jnp.float32)

        @pl.when(kj == n_kblocks - 1)
        def _flush():
            dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)

    def q_index_dq(b, h, i, j):
        return (b, h, i, 0)

    def kv_index_dq(b, h, i, j):
        return (b, h // group, j, 0)

    dq_t = pl.pallas_call(
        dq_kernel,
        grid=(B, nq, n_qblocks, n_kblocks),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), q_index_dq),
            pl.BlockSpec((1, 1, block_k, d), kv_index_dq),
            pl.BlockSpec((1, 1, block_k, d), kv_index_dq),
            pl.BlockSpec((1, 1, block_q, d), q_index_dq),
            pl.BlockSpec((1, 1, block_q, d), q_index_dq),
            pl.BlockSpec((1, 1, block_q, LANES), q_index_dq),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d), q_index_dq),
        out_shape=jax.ShapeDtypeStruct((B, nq, S, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, ot, dot, lse_b)

    dq = dq_t.transpose(0, 2, 1, 3).astype(q.dtype)
    if group > 1:
        dk_g = dk_g.reshape(B, nkv, group, S, d).sum(axis=2)
        dv_g = dv_g.reshape(B, nkv, group, S, d).sum(axis=2)
    dk = dk_g.transpose(0, 2, 1, 3).astype(k.dtype)
    dv = dv_g.transpose(0, 2, 1, 3).astype(v.dtype)
    return dq, dk, dv
