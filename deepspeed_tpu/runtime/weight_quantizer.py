"""WeightQuantization (reference ``runtime/weight_quantizer.py`` — the
offline model-quantization helper ``module_inject`` uses for MoQ-style
checkpoint loading: quantize selected weight matrices to int8 with
per-group scales and report the scales for the kernels).

TPU form: delegates the numeric core to ``ops.pallas.quant.quantize_blockwise``
(the single absmax/127 implementation) and returns ``QuantizedWeight``
leaves, which every forward path in this framework reads transparently via
``.astype``.
"""

from typing import Any, Dict, List

import numpy as np

from ..inference.quantization import QuantizedWeight, quantize_weight_int8


class WeightQuantization:

    def __init__(self, mlp_extra_grouping: bool = False, mp_size: int = 1):
        self.mlp_extra_grouping = mlp_extra_grouping  # reference knob; groups below
        self.mp_size = mp_size
        self.scales: List = []

    def quantize_data(self, data, quantize_bits: int = 8, groups: int = 1, key=None):
        """Quantize one matrix; returns (QuantizedWeight[4], scale). ``groups``
        beyond 1 is subsumed by the blockwise kernel's per-output-channel
        scales (finer than the reference's row groups)."""
        if quantize_bits not in (4, 8):
            raise NotImplementedError(
                f"int{quantize_bits} weight quantization not supported (int4/int8 only)")
        if quantize_bits == 4:
            from ..inference.quantization import quantize_weight_int4

            qw = quantize_weight_int4(data)
        else:
            qw = quantize_weight_int8(data)
        self.scales.append(qw.scale)
        return qw, qw.scale

    def model_quantize(self, params: Dict[str, Any], quantize_bits: int = 8,
                       groups: int = 1) -> Dict[str, Any]:
        """Quantize a whole param tree's weight matrices (reference
        ``model_quantize`` walks nn.Module layers)."""
        from ..inference.quantization import quantize_params_for_inference

        return quantize_params_for_inference(params, quantize_bits)

    def is_quantized(self, leaf) -> bool:
        from ..inference.quantization import QuantizedWeight4

        return isinstance(leaf, (QuantizedWeight, QuantizedWeight4))

    def sd_quantize_megatron(self, sd, quantize_bits: int = 8, groups: int = 1):
        """Quantize every >=2-D array in a flat state dict (megatron-style
        checkpoints arrive flat)."""
        out = {}
        for k, v in sd.items():
            arr = np.asarray(v)
            if arr.ndim >= 2 and np.issubdtype(arr.dtype, np.floating):
                out[k], _ = self.quantize_data(arr, quantize_bits, groups)
            else:
                out[k] = v
        return out
