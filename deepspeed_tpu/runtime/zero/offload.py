"""ZeRO-Offload / ZeRO-Infinity host optimizer.

TPU-native analog of the reference's CPU-offload optimizer path
(``runtime/zero/stage_1_and_2.py`` with ``cpu_offload`` → ``DeepSpeedCPUAdam``
csrc/adam/cpu_adam.cpp; NVMe tier via ``runtime/swap_tensor/*`` — SURVEY.md
§2.2 "ZeRO-Offload / Infinity"). Division of labor on a TPU-VM:

  * device (jit): forward + backward → gradients (bf16/fp32, sharded)
  * host: fp32 master params + Adam moments in RAM — or moments on NVMe —
    updated by the fused multithreaded C++ kernel (``ops/csrc/adam``)
  * device upload: new masters placed back into the params' shardings

This removes the optimizer states (8 bytes/param) and the master copies
(4 bytes/param) from HBM, the same memory win as the reference, while the
hot fwd/bwd path stays fully compiled. With NVMe, moments stream through
host buffers with read/write overlap (``OptimizerStateSwapper``), the
pipelined pattern of the reference's ``PipelinedOptimizerSwapper``.
"""

from typing import Callable, Dict, Optional

import numpy as np

import jax

from ..swap_tensor.optimizer_utils import OptimizerStateSwapper
from ...utils.logging import logger


def _leaf_paths(tree):
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    from .partition import path_str

    return [(path_str(kp), leaf) for kp, leaf in flat]


class HostOffloadOptimizer:
    """fp32 masters + Adam moments on host; fused C++ update per leaf."""

    def __init__(self,
                 init_params,
                 lr: float = 1e-3,
                 betas=(0.9, 0.999),
                 eps: float = 1e-8,
                 weight_decay: float = 0.0,
                 adamw_mode: bool = True,
                 nvme_path: Optional[str] = None,
                 pipeline_read: bool = True,
                 pipeline_write: bool = True,
                 grad_clip: float = 0.0):
        from ...ops.adam.cpu_adam import DeepSpeedCPUAdam

        if jax.process_count() > 1:
            # multi-host offload needs per-host shard fetch (each host updating
            # only its addressable gradient shards) — not implemented yet; the
            # single-host path below would crash on non-addressable arrays
            raise NotImplementedError("offload_optimizer is single-host only for now")
        self.opt = DeepSpeedCPUAdam(lr=lr, betas=betas, eps=eps, weight_decay=weight_decay, adamw_mode=adamw_mode)
        self.base_lr = lr
        self.grad_clip = grad_clip
        self.treedef = jax.tree_util.tree_structure(init_params)

        host = jax.device_get(init_params)
        self.keys = []
        self.masters: Dict[str, np.ndarray] = {}
        self.shapes: Dict[str, tuple] = {}
        for key, leaf in _leaf_paths(host):
            # always COPY: masters are mutated in place by the C++ kernel and
            # must never alias caller arrays (on the CPU backend jnp.asarray
            # zero-copies aligned numpy buffers, so an alias here would let
            # the optimizer silently rewrite live jax arrays)
            arr = np.array(leaf, dtype=np.float32, copy=True).reshape(-1)
            self.keys.append(key)
            self.masters[key] = arr
            self.shapes[key] = np.shape(leaf)

        self.swapper = None
        self.moments: Dict[str, Dict[str, np.ndarray]] = {}
        if nvme_path:
            self.swapper = OptimizerStateSwapper(nvme_path, pipeline_read=pipeline_read,
                                                 pipeline_write=pipeline_write)
            for key in self.keys:
                self.swapper.initialize(key, self.masters[key].shape)
            self.swapper.flush_writes()
            logger.info(f"ZeRO-Infinity: {len(self.keys)} optimizer-state leaves on NVMe at {nvme_path}")
        else:
            for key in self.keys:
                self.moments[key] = {
                    "exp_avg": np.zeros_like(self.masters[key]),
                    "exp_avg_sq": np.zeros_like(self.masters[key]),
                }

    # ------------------------------------------------------------------
    def _global_grad_norm(self, grads: Dict[str, np.ndarray], inv_scale: float) -> float:
        sq = 0.0
        for g in grads.values():
            g64 = g.astype(np.float64, copy=False)
            sq += float(np.dot(g64.ravel(), g64.ravel()))
        return float(np.sqrt(sq)) * inv_scale

    def step(self, step_no: int, grads_tree, lr: Optional[float] = None, loss_scale: float = 1.0):
        """Apply one Adam step on the host.

        ``grads_tree``: pytree matching params (device or host arrays).
        Returns (new_params_tree_host, grad_norm, overflow: bool).
        Overflow (non-finite grads) skips the update, reference
        ``has_overflow`` semantics.
        """
        host_grads = jax.device_get(grads_tree)
        grads = {key: np.asarray(leaf, dtype=np.float32).reshape(-1) for key, leaf in _leaf_paths(host_grads)}

        inv_scale = 1.0 / float(loss_scale)
        norm = self._global_grad_norm(grads, inv_scale)
        if not np.isfinite(norm):
            return self.rebuild_params(), norm, True
        scale = inv_scale
        if self.grad_clip and norm > self.grad_clip:
            scale *= self.grad_clip / (norm + 1e-6)

        if self.swapper is not None:
            # pipelined: prefetch leaf i+1 while updating leaf i
            self.swapper.prefetch(self.keys[0])
            for i, key in enumerate(self.keys):
                arrays = self.swapper.fetch(key)
                if i + 1 < len(self.keys):
                    self.swapper.prefetch(self.keys[i + 1])
                self.opt.step(step_no, self.masters[key], grads[key], arrays["exp_avg"], arrays["exp_avg_sq"],
                              lr=lr, grad_scale=scale)
                self.swapper.writeback(key, arrays, async_op=True)
            self.swapper.flush_writes()
        else:
            for key in self.keys:
                m = self.moments[key]
                self.opt.step(step_no, self.masters[key], grads[key], m["exp_avg"], m["exp_avg_sq"],
                              lr=lr, grad_scale=scale)
        return self.rebuild_params(), norm, False

    def rebuild_params(self):
        """Masters → pytree of correctly-shaped fp32 arrays (host). Copies,
        so later in-place master updates can't reach arrays handed out."""
        leaves = [self.masters[key].reshape(self.shapes[key]).copy() for key in self.keys]
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def reset_masters(self, params_tree):
        """Overwrite the fp32 masters from a params pytree (used after a
        checkpoint load that replaced the device params: masters must follow,
        or the next step would resurrect the pre-load weights)."""
        host = jax.device_get(params_tree)
        for key, leaf in _leaf_paths(host):
            np.copyto(self.masters[key], np.asarray(leaf, dtype=np.float32).reshape(-1))

    # ------------------------------------------------------------------
    def state_dict(self):
        # deep-copy: the C++ kernel mutates these buffers in place, and an
        # async checkpoint save must snapshot, not alias, the live state
        moments = self.swapper.state_dict() if self.swapper is not None else self.moments
        return {
            "masters": {k: v.copy() for k, v in self.masters.items()},
            "exp_avg": {k: np.array(moments[k]["exp_avg"], copy=True) for k in self.keys},
            "exp_avg_sq": {k: np.array(moments[k]["exp_avg_sq"], copy=True) for k in self.keys},
        }

    def state_template(self):
        """Shapes/dtypes of ``state_dict()`` without materializing any state
        (no NVMe reads) — for checkpoint-restore templates."""
        spec = {k: jax.ShapeDtypeStruct(v.shape, v.dtype) for k, v in self.masters.items()}
        return {"masters": dict(spec), "exp_avg": dict(spec), "exp_avg_sq": dict(spec)}

    def load_state_dict(self, state):
        for key in self.keys:
            np.copyto(self.masters[key], np.asarray(state["masters"][key], dtype=np.float32))
        moments = {k: {"exp_avg": np.asarray(state["exp_avg"][k], np.float32).reshape(-1),
                       "exp_avg_sq": np.asarray(state["exp_avg_sq"][k], np.float32).reshape(-1)}
                   for k in self.keys}
        if self.swapper is not None:
            self.swapper.load_state_dict(moments)
        else:
            self.moments = moments
