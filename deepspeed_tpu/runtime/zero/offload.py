"""ZeRO-Offload / ZeRO-Infinity host optimizer.

TPU-native analog of the reference's CPU-offload optimizer path
(``runtime/zero/stage_1_and_2.py`` with ``cpu_offload`` → ``DeepSpeedCPUAdam``
csrc/adam/cpu_adam.cpp; NVMe tier via ``runtime/swap_tensor/*`` — SURVEY.md
§2.2 "ZeRO-Offload / Infinity"). Division of labor on a TPU-VM:

  * device (jit): forward + backward → gradients (bf16/fp32, sharded) and
    the global gradient norm (a GSPMD reduction — exact across all hosts)
  * host: fp32 master params + Adam moments in RAM — or moments on NVMe —
    updated by the fused multithreaded C++ kernel (``ops/csrc/adam``)
  * device upload: new masters placed back into the params' shardings

Multi-host (reference per-rank swappers
``runtime/swap_tensor/partitioned_param_swapper.py:36``): each host keeps
masters/moments ONLY for the shard blocks its addressable devices own
(``shard_mode``), updates them from its local gradient shards, and re-assembles
the global param arrays with ``make_array_from_single_device_arrays`` — no
cross-host traffic beyond the device-side norm reduction. The same block
machinery runs single-process over a virtual multi-device mesh, which is how
the path is tested without a pod.
"""

from typing import Dict, Optional

import numpy as np

import jax

from ..swap_tensor.optimizer_utils import OptimizerStateSwapper
from ...utils.logging import logger


def _leaf_paths(tree):
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    from .partition import path_str

    return [(path_str(kp), leaf) for kp, leaf in flat]


# ---------------------------------------------------------------------------
# Twin-flow partial offload (reference ZeRO-Offload++ `offload_optimizer.ratio`,
# blogs/deepspeed-offloadpp: a configurable fraction of the optimizer state
# stays on the accelerator and updates there, overlapping the host update).
# TPU form: a leaf-granularity split of the param pytree — the host set is
# chosen greedily by size until it holds >= ratio of the total bytes; the
# device set keeps a normal optax state in HBM and its update overlaps the
# host C++ Adam via jax async dispatch.
# ---------------------------------------------------------------------------
def partition_leaves_by_ratio(param_shapes, ratio: float):
    """Boolean mask pytree (True = host-offloaded leaf). Greedy subset-sum
    approximation: largest-first but skipping any leaf that would overshoot
    the target byte share, then one minimal top-up if still short — so a
    single huge leaf (e.g. the embedding at ratio=0.1) cannot blow the host
    share far past the configured ratio."""
    flat, treedef = jax.tree_util.tree_flatten(param_shapes)
    sizes = [int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize for l in flat]
    target = ratio * float(sum(sizes))
    order = sorted(range(len(flat)), key=lambda i: -sizes[i])
    host, acc = set(), 0.0
    for i in order:
        if acc + sizes[i] <= target:
            host.add(i)
            acc += sizes[i]
    if acc < target and len(host) < len(flat):
        # every remaining leaf overshoots: add the smallest, but only when
        # that lands CLOSER to the target than stopping short does (a
        # dominant leaf must not flip the whole tree onto the host and
        # silently degenerate twin-flow to full offload) — UNLESS the host
        # set would be empty, which must never happen for ratio > 0 (the
        # NVMe host path requires >= 1 block, and 'offload nothing' would
        # betray a user who sized the ratio to fit HBM)
        j = min((i for i in range(len(flat)) if i not in host), key=lambda i: sizes[i])
        if not host or abs((acc + sizes[j]) - target) < abs(acc - target):
            host.add(j)
    return jax.tree_util.tree_unflatten(treedef, [i in host for i in range(len(flat))])


def prune_tree(tree, mask, keep: bool):
    """Drop leaves where mask != keep (None-elision keeps the remaining
    leaves' key paths identical to the full tree's — checkpoint keys and
    sharding lookups stay stable)."""
    return jax.tree_util.tree_map(lambda x, m: x if m is keep else None, tree, mask)


def merge_by_mask(full_template, mask, host_tree, dev_tree):
    """Reassemble the full pytree from the two pruned halves."""
    from .partition import path_str

    host = {p: l for p, l in _leaf_paths(host_tree)}
    dev = {p: l for p, l in _leaf_paths(dev_tree)}
    flat, treedef = jax.tree_util.tree_flatten_with_path(full_template)
    mask_leaves = jax.tree_util.tree_leaves(mask)
    leaves = [host[path_str(kp)] if m else dev[path_str(kp)]
              for (kp, _), m in zip(flat, mask_leaves)]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _unique_shards(arr):
    """Addressable shards of a jax array, one per distinct index (replicas
    within the process are dropped). Returns [(block_key, index, np_data)],
    deterministically ordered."""
    seen = {}
    for s in arr.addressable_shards:
        key = str(s.index)
        if key not in seen:
            seen[key] = (s.index, np.asarray(s.data))
    return [(k, idx, data) for k, (idx, data) in sorted(seen.items())]


class HostOffloadOptimizer:
    """fp32 masters + Adam moments on host; fused C++ update per leaf (or per
    addressable shard block in ``shard_mode``)."""

    def __init__(self,
                 init_params,
                 lr: float = 1e-3,
                 betas=(0.9, 0.999),
                 eps: float = 1e-8,
                 weight_decay: float = 0.0,
                 adamw_mode: bool = True,
                 nvme_path: Optional[str] = None,
                 pipeline_read: bool = True,
                 pipeline_write: bool = True,
                 grad_clip: float = 0.0,
                 shard_mode: Optional[bool] = None,
                 block_shardings=None):
        from ...ops.adam.cpu_adam import DeepSpeedCPUAdam

        self.opt = DeepSpeedCPUAdam(lr=lr, betas=betas, eps=eps, weight_decay=weight_decay, adamw_mode=adamw_mode)
        self.base_lr = lr
        self.grad_clip = grad_clip
        self.treedef = jax.tree_util.tree_structure(init_params)
        # shard mode: hold only this host's addressable shard blocks
        # (mandatory on a pod, where device_get of a global array would
        # fail); DS_TPU_OFFLOAD_SHARD_MODE=1 forces it single-process so the
        # pod path is exercised on a virtual multi-device mesh
        if shard_mode is None:
            import os

            shard_mode = jax.process_count() > 1 or os.environ.get("DS_TPU_OFFLOAD_SHARD_MODE") == "1"
        self.shard_mode = bool(shard_mode)
        # block layout: masters follow the GRADIENT sharding (each host owns
        # exactly the blocks whose grads it receives — the reference's
        # per-rank optimizer partitions); the upload reshards to the param
        # layout on device (the reference's allgather of updated partitions)
        self._block_shardings = block_shardings
        if self.shard_mode and block_shardings is not None:
            init_params = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(x, s), init_params, block_shardings)

        self.keys = []
        self.masters: Dict[str, np.ndarray] = {}
        self.shapes: Dict[str, tuple] = {}
        self._blocks: Dict[str, Dict[str, str]] = {}  # path -> {str(index): key}
        self._leaf_shapes: Dict[str, tuple] = {}
        if self.shard_mode:
            for path, leaf in _leaf_paths(init_params):
                self._leaf_shapes[path] = tuple(np.shape(leaf))
                self._blocks[path] = {}
                for bk, _idx, data in _unique_shards(leaf):
                    key = f"{path}::{bk}"
                    self.keys.append(key)
                    # COPY: the C++ kernel mutates masters in place
                    self.masters[key] = np.array(data, dtype=np.float32, copy=True).reshape(-1)
                    self.shapes[key] = np.shape(data)
                    self._blocks[path][bk] = key
        else:
            host = jax.device_get(init_params)
            for key, leaf in _leaf_paths(host):
                # always COPY: masters are mutated in place by the C++ kernel
                # and must never alias caller arrays (on the CPU backend
                # jnp.asarray zero-copies aligned numpy buffers, so an alias
                # would let the optimizer silently rewrite live jax arrays)
                arr = np.array(leaf, dtype=np.float32, copy=True).reshape(-1)
                self.keys.append(key)
                self.masters[key] = arr
                self.shapes[key] = np.shape(leaf)

        self.swapper = None
        self.moments: Dict[str, Dict[str, np.ndarray]] = {}
        if nvme_path:
            self.swapper = OptimizerStateSwapper(nvme_path, pipeline_read=pipeline_read,
                                                 pipeline_write=pipeline_write)
            for key in self.keys:
                self.swapper.initialize(key, self.masters[key].shape)
            self.swapper.flush_writes()
            logger.info(f"ZeRO-Infinity: {len(self.keys)} optimizer-state blocks on NVMe at {nvme_path}")
        else:
            for key in self.keys:
                self.moments[key] = {
                    "exp_avg": np.zeros_like(self.masters[key]),
                    "exp_avg_sq": np.zeros_like(self.masters[key]),
                }

    # ------------------------------------------------------------------
    def _grad_blocks(self, grads_tree) -> Dict[str, np.ndarray]:
        """Flat fp32 gradient block per master key."""
        if self.shard_mode:
            out = {}
            for path, leaf in _leaf_paths(grads_tree):
                for bk, _idx, data in _unique_shards(leaf):
                    out[f"{path}::{bk}"] = np.asarray(data, dtype=np.float32).reshape(-1)
            return out
        host = jax.device_get(grads_tree)
        return {key: np.asarray(leaf, dtype=np.float32).reshape(-1) for key, leaf in _leaf_paths(host)}

    def _global_grad_norm(self, grads: Dict[str, np.ndarray], inv_scale: float) -> float:
        sq = 0.0
        for g in grads.values():
            g64 = g.astype(np.float64, copy=False)
            sq += float(np.dot(g64.ravel(), g64.ravel()))
        return float(np.sqrt(sq)) * inv_scale

    def step(self, step_no: int, grads_tree, lr: Optional[float] = None, loss_scale: float = 1.0,
             grad_norm: Optional[float] = None):
        """Apply one Adam step on the host.

        ``grads_tree``: pytree matching params (device or host arrays).
        ``grad_norm``: UNSCALED global gradient norm, ideally computed on
        device inside the compiled grads program (exact across hosts; in
        shard_mode a host-side norm would only see local shards).
        Returns (new_params_tree_host_or_None, grad_norm, overflow: bool) —
        the params tree is None in shard_mode (use ``rebuild_device_params``).
        Overflow (non-finite norm) skips the update, reference
        ``has_overflow`` semantics.
        """
        grads = self._grad_blocks(grads_tree)

        inv_scale = 1.0 / float(loss_scale)
        if grad_norm is None:
            assert not self.shard_mode, "shard_mode needs the device-computed global grad norm"
            norm = self._global_grad_norm(grads, inv_scale)
        else:
            norm = float(grad_norm)
        if not np.isfinite(norm):
            return (None if self.shard_mode else self.rebuild_params()), norm, True
        scale = inv_scale
        if self.grad_clip and norm > self.grad_clip:
            scale *= self.grad_clip / (norm + 1e-6)

        if self.swapper is not None:
            # pipelined: prefetch leaf i+1 while updating leaf i
            self.swapper.prefetch(self.keys[0])
            for i, key in enumerate(self.keys):
                arrays = self.swapper.fetch(key)
                if i + 1 < len(self.keys):
                    self.swapper.prefetch(self.keys[i + 1])
                self.opt.step(step_no, self.masters[key], grads[key], arrays["exp_avg"], arrays["exp_avg_sq"],
                              lr=lr, grad_scale=scale)
                self.swapper.writeback(key, arrays, async_op=True)
            self.swapper.flush_writes()
        else:
            for key in self.keys:
                m = self.moments[key]
                self.opt.step(step_no, self.masters[key], grads[key], m["exp_avg"], m["exp_avg_sq"],
                              lr=lr, grad_scale=scale)
        return (None if self.shard_mode else self.rebuild_params()), norm, False

    def rebuild_params(self):
        """Masters → pytree of correctly-shaped fp32 arrays (host). Copies,
        so later in-place master updates can't reach arrays handed out.
        Whole-leaf mode only."""
        assert not self.shard_mode, "shard_mode: use rebuild_device_params(shardings, dtypes)"
        leaves = [self.masters[key].reshape(self.shapes[key]).copy() for key in self.keys]
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def rebuild_device_params(self, shardings, dtypes):
        """Masters → global device arrays in the given shardings (reference
        per-rank upload: each host contributes only the shard blocks it
        owns). Works in both modes; in whole-leaf mode it is a plain
        device_put per leaf."""
        is_sh = lambda x: hasattr(x, "addressable_devices_indices_map")
        sh_leaves = jax.tree_util.tree_leaves(shardings, is_leaf=is_sh)
        dt_leaves = jax.tree_util.tree_leaves(dtypes)
        bsh_leaves = (jax.tree_util.tree_leaves(self._block_shardings, is_leaf=is_sh)
                      if (self.shard_mode and self._block_shardings is not None) else None)
        paths = [p for p, _ in _leaf_paths(jax.tree_util.tree_unflatten(
            self.treedef, list(range(self.treedef.num_leaves))))]
        out_leaves = []
        for path, sharding, dtype in zip(paths, sh_leaves, dt_leaves):
            if not self.shard_mode:
                arr = self.masters[path].reshape(self.shapes[path]).astype(dtype)
                out_leaves.append(jax.device_put(arr, sharding))
                continue
            shape = self._leaf_shapes[path]
            block_sharding = bsh_leaves[len(out_leaves)] if bsh_leaves is not None else sharding
            index_map = block_sharding.addressable_devices_indices_map(shape)
            bufs = []
            for dev, idx in index_map.items():
                key = self._blocks[path].get(str(idx))
                assert key is not None, f"no master block for {path} index {idx}"
                block = self.masters[key].reshape(self.shapes[key]).astype(dtype)
                bufs.append(jax.device_put(block, dev))
            arr = jax.make_array_from_single_device_arrays(shape, block_sharding, bufs)
            if block_sharding is not sharding:
                # device-side reshard to the param layout (cross-host over
                # ICI/DCN — the reference's updated-partition allgather)
                arr = jax.device_put(arr, sharding)
            out_leaves.append(arr)
        return jax.tree_util.tree_unflatten(self.treedef, out_leaves)

    def reset_masters(self, params_tree):
        """Overwrite the fp32 masters from a params pytree (used after a
        checkpoint load that replaced the device params: masters must follow,
        or the next step would resurrect the pre-load weights)."""
        if self.shard_mode:
            for path, leaf in _leaf_paths(params_tree):
                for bk, _idx, data in _unique_shards(leaf):
                    np.copyto(self.masters[f"{path}::{bk}"],
                              np.asarray(data, dtype=np.float32).reshape(-1))
            return
        host = jax.device_get(params_tree)
        for key, leaf in _leaf_paths(host):
            np.copyto(self.masters[key], np.asarray(leaf, dtype=np.float32).reshape(-1))

    # ------------------------------------------------------------------
    def state_dict(self):
        # deep-copy: the C++ kernel mutates these buffers in place, and an
        # async checkpoint save must snapshot, not alias, the live state
        moments = self.swapper.state_dict() if self.swapper is not None else self.moments
        return {
            "masters": {k: v.copy() for k, v in self.masters.items()},
            "exp_avg": {k: np.array(moments[k]["exp_avg"], copy=True) for k in self.keys},
            "exp_avg_sq": {k: np.array(moments[k]["exp_avg_sq"], copy=True) for k in self.keys},
        }

    def state_template(self):
        """Shapes/dtypes of ``state_dict()`` without materializing any state
        (no NVMe reads) — for checkpoint-restore templates."""
        spec = {k: jax.ShapeDtypeStruct(v.shape, v.dtype) for k, v in self.masters.items()}
        return {"masters": dict(spec), "exp_avg": dict(spec), "exp_avg_sq": dict(spec)}

    def load_state_dict(self, state):
        for key in self.keys:
            np.copyto(self.masters[key], np.asarray(state["masters"][key], dtype=np.float32))
        moments = {k: {"exp_avg": np.asarray(state["exp_avg"][k], np.float32).reshape(-1),
                       "exp_avg_sq": np.asarray(state["exp_avg_sq"][k], np.float32).reshape(-1)}
                   for k in self.keys}
        if self.swapper is not None:
            self.swapper.load_state_dict(moments)
        else:
            self.moments = moments
