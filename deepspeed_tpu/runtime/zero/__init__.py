"""ZeRO subpackage surface (reference ``deepspeed.runtime.zero`` /
``deepspeed.zero``): sharding-spec policies, offload, ZeRO++ config,
TiledLinear analogs."""

from .partition import ZeroShardingPolicy
from .config import DeepSpeedZeroConfig
from .tiling import tiled_linear, memory_efficient_linear
