"""ZeRO configuration.

Mirrors the user-facing fields of the reference ``deepspeed/runtime/zero/config.py``
(``DeepSpeedZeroConfig``, 338 LoC) so that existing ``zero_optimization`` JSON
blocks parse unchanged. On TPU the stages are *sharding policies* rather than
hook-driven partitioning machinery (SURVEY.md §7): stage 1 shards optimizer
state over the data axis, stage 2 additionally shards gradients/accumulators,
stage 3 additionally shards parameters (FSDP-style), with XLA inserting the
all-gather / reduce-scatter collectives.
"""

from enum import Enum
from typing import Optional

from pydantic import Field, model_validator

from ..config_utils import DeepSpeedConfigModel


class OffloadDeviceEnum(str, Enum):
    """Target device for offloading (reference ``zero/offload_config.py``)."""
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class DeepSpeedZeroOffloadParamConfig(DeepSpeedConfigModel):
    """Parameter-offload block (reference ``offload_config.py:DeepSpeedZeroOffloadParamConfig``)."""
    device: OffloadDeviceEnum = "none"
    nvme_path: Optional[str] = None
    buffer_count: int = Field(5, ge=0)
    buffer_size: int = Field(int(1e8), ge=0)
    max_in_cpu: int = Field(int(1e9), ge=0)
    pin_memory: bool = False


class DeepSpeedZeroOffloadOptimizerConfig(DeepSpeedConfigModel):
    """Optimizer-offload block (reference ``offload_config.py``)."""
    device: OffloadDeviceEnum = "none"
    nvme_path: Optional[str] = None
    buffer_count: int = Field(4, ge=0)
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    ratio: float = Field(1.0, ge=0.0, le=1.0)


class DeepSpeedZeroConfig(DeepSpeedConfigModel):
    """``zero_optimization`` block. Field set tracks the reference's."""

    stage: int = Field(0, ge=0, le=3)
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = Field(int(5e8), ge=0)
    use_multi_rank_bucket_allreduce: bool = True
    allgather_partitions: bool = True
    allgather_bucket_size: int = Field(int(5e8), ge=0)
    overlap_comm: Optional[bool] = None  # XLA overlaps automatically; kept for parity
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = False

    offload_param: Optional[DeepSpeedZeroOffloadParamConfig] = None
    offload_optimizer: Optional[DeepSpeedZeroOffloadOptimizerConfig] = None

    sub_group_size: int = Field(int(1e9), ge=0)
    cpu_offload_param: Optional[bool] = Field(None, json_schema_extra={
        "deprecated": True, "new_param": "offload_param",
        "new_param_fn": (lambda v: DeepSpeedZeroOffloadParamConfig(device=OffloadDeviceEnum.cpu) if v else None)})
    cpu_offload_use_pin_memory: Optional[bool] = Field(None, json_schema_extra={"deprecated": True})
    cpu_offload: Optional[bool] = Field(None, json_schema_extra={
        "deprecated": True, "new_param": "offload_optimizer",
        "new_param_fn": (lambda v: DeepSpeedZeroOffloadOptimizerConfig(device=OffloadDeviceEnum.cpu) if v else None)})

    prefetch_bucket_size: int = Field(int(5e7), ge=0, alias="stage3_prefetch_bucket_size")
    param_persistence_threshold: int = Field(int(1e5), ge=0, alias="stage3_param_persistence_threshold")
    model_persistence_threshold: int = Field(int(1e14), ge=0, alias="stage3_model_persistence_threshold")
    max_live_parameters: int = Field(int(1e9), ge=0, alias="stage3_max_live_parameters")
    max_reuse_distance: int = Field(int(1e9), ge=0, alias="stage3_max_reuse_distance")
    gather_16bit_weights_on_model_save: bool = Field(False, alias="stage3_gather_16bit_weights_on_model_save")

    ignore_unused_parameters: bool = True
    legacy_stage1: bool = False
    round_robin_gradients: bool = False

    # ZeRO++ (reference engine.py:858, groups.py:505): quantized weights/grads +
    # secondary intra-node shard. On TPU these map to int8 block-quantized
    # all-gather (Pallas quant kernels) and a sub-mesh secondary axis.
    zero_quantized_weights: bool = False
    zero_hpz_partition_size: int = Field(1, ge=0)
    zero_quantized_gradients: bool = False
    zero_quantized_nontrainable_weights: bool = False

    mics_shard_size: int = Field(-1, alias="mics_shard_size")
    mics_hierarchical_params_gather: bool = False

    memory_efficient_linear: bool = True
    pipeline_loading_checkpoint: bool = False
    override_module_apply: bool = True

    @model_validator(mode="after")
    def overlap_comm_valid(self):
        if self.overlap_comm is None:
            self.overlap_comm = self.stage == 3
        return self

    @property
    def offload_optimizer_device(self):
        return self.offload_optimizer.device if self.offload_optimizer else OffloadDeviceEnum.none

    @property
    def offload_param_device(self):
        return self.offload_param.device if self.offload_param else OffloadDeviceEnum.none
