"""TiledLinear and memory-efficient linear.

TPU equivalents of the reference ZeRO utilities:

  * ``zero/tiling.py`` ``TiledLinear`` (296 LoC) — splits a giant linear
    into (in_splits x out_splits) tiles so no single weight/activation
    buffer exceeds a budget. Here a functional ``tiled_linear`` chunks the
    contraction with ``lax.scan`` over input tiles: at most one
    [in_tile, out] weight slice and one partial-sum accumulator are live —
    the same peak-memory bound, derived from sharding-friendly slices of
    ONE stacked weight instead of a module tree of sub-Linears.
  * ``zero/linear.py`` ``LinearFunctionForZeroStage3`` (178 LoC) — an
    autograd Function that avoids saving the gathered weight for backward.
    The jax analog is ``memory_efficient_linear``: ``jax.checkpoint`` around
    the matmul drops the gathered operand after the forward and regathers
    at backward, exactly the reference's recompute-vs-store trade.
"""

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def tiled_linear(x: jax.Array, w: jax.Array, bias: Optional[jax.Array] = None,
                 in_splits: int = 1) -> jax.Array:
    """y = x @ w (+ bias), contracting in ``in_splits`` chunks.

    x: [..., K]; w: [K, N]. Peak live memory holds one [K/in_splits, N]
    weight tile and the [..., N] accumulator (reference ``TiledLinear``
    forward loop semantics; its out_splits dimension is subsumed by XLA's
    output tiling).
    """
    K, N = w.shape
    if in_splits <= 1:
        y = jnp.einsum("...k,kn->...n", x, w)
        return y + bias if bias is not None else y
    assert K % in_splits == 0, f"in_features {K} must divide by in_splits {in_splits}"
    tk = K // in_splits
    xt = x.reshape(*x.shape[:-1], in_splits, tk)
    wt = w.reshape(in_splits, tk, N)

    def body(acc, i):
        acc = acc + jnp.einsum("...k,kn->...n", xt[..., i, :], wt[i])
        return acc, None

    acc0 = jnp.zeros((*x.shape[:-1], N), jnp.result_type(x.dtype, w.dtype))
    y, _ = lax.scan(body, acc0, jnp.arange(in_splits))
    return y + bias if bias is not None else y


def memory_efficient_linear(x: jax.Array, w: jax.Array, bias: Optional[jax.Array] = None) -> jax.Array:
    """Linear whose backward regathers/recomputes instead of saving the
    (possibly ZeRO-3 gathered) weight operand — reference
    ``LinearFunctionForZeroStage3`` / the ``memory_efficient_linear`` config
    knob (zero/config.py)."""

    @jax.checkpoint
    def f(x, w):
        return jnp.einsum("...k,kn->...n", x, w)

    y = f(x, w)
    return y + bias if bias is not None else y
