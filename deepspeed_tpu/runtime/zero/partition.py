"""ZeRO stages as SPMD sharding policies.

This is the TPU-native replacement for the reference's hook-driven partition
machinery (``runtime/zero/stage_1_and_2.py`` 2,553 LoC and ``stage3.py`` 2,738
LoC). The insight (SURVEY.md §7): on TPU, ZeRO *is* a set of sharding rules —

  stage 0  params R | grads R       | opt R        (DP: psum of grads)
  stage 1  params R | grads R       | opt sharded  (allgather of updates ≡
                                                    XLA resharding opt→param)
  stage 2  params R | grads sharded | opt sharded  (reduce-scatter of grads ≡
                                                    XLA resharding at grad use)
  stage 3  params S | grads sharded | opt sharded  (per-layer allgather ≡ XLA
                                                    resharding at each use site)

"R" = replicated over the data axes, "S" = sharded over them. We annotate the
three state groups with ``NamedSharding``s and XLA inserts exactly the
all-gathers / reduce-scatters the reference hand-schedules with IPG buckets
(``stage_1_and_2.py:1353 reduce_ipg_grads``, ``average_tensor:1033``) and
coalesced collectives (``runtime/comm/coalesced_collectives.py``) — including
overlap, which XLA's latency-hiding scheduler performs automatically where the
reference needs side streams (``overlap_comm``).

Tensor-parallel rules compose: each param first receives its TP spec (over the
``model`` axis), then ZeRO shards the largest remaining divisible dimension
over the data axes, matching how the reference composes mpu TP with ZeRO
(``engine.py:1546``). MiCS (reference ``runtime/zero/mics.py``) maps to
sharding over the inner ``data`` axis of a (data_repl, data) split — states
sharded within a shard group of ``mics_shard_size`` devices, replicated
across groups (see ``ZeroShardingPolicy.__init__`` and ``parallel/mesh.py``).
"""

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ...parallel import groups
from ...utils.logging import logger


def path_str(keypath) -> str:
    """Flatten a jax KeyPath to 'a/b/c' for regex matching."""
    parts = []
    for k in keypath:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


class PartitionRules:
    """Ordered (regex, PartitionSpec) table mapping param paths to TP specs.

    Plays the role of the reference's injection policies
    (``module_inject/replace_module.py`` policy classes) for training-side TP:
    e.g. ``[(r".*attention/(q|k|v)/kernel", P(None, "model")), ...]``.
    First match wins; no match → fully replicated (before ZeRO).
    """

    def __init__(self, rules: Optional[Sequence[Tuple[str, PartitionSpec]]] = None):
        self.rules = [(re.compile(pat), spec) for pat, spec in (rules or [])]

    def spec_for(self, path: str, ndim: int) -> PartitionSpec:
        for pat, spec in self.rules:
            if pat.search(path):
                # pad/truncate spec to ndim
                entries = list(spec) + [None] * (ndim - len(spec))
                return PartitionSpec(*entries[:ndim])
        return PartitionSpec(*([None] * ndim))

    def tree_specs(self, params) -> Any:
        return jax.tree_util.tree_map_with_path(lambda kp, x: self.spec_for(path_str(kp), np.ndim(x)), params)


def _axes_size(mesh: Mesh, axes: Sequence[str]) -> int:
    out = 1
    for a in axes:
        out *= mesh.shape.get(a, 1)
    return out


def sanitize_spec(spec: PartitionSpec, shape: Tuple[int, ...], mesh: Mesh, path: str = "") -> PartitionSpec:
    """Drop spec entries whose mesh-axis product does not divide the dim size
    (e.g. 4 experts over an 8-wide data axis): partial expert parallelism
    degrades gracefully to replication of that dim — loudly, so a
    misconfiguration (hidden size not divisible by the model axis) doesn't
    silently disable TP."""
    entries = list(spec) + [None] * (len(shape) - len(spec))
    out = []
    for i, e in enumerate(entries[:len(shape)]):
        if e is None:
            out.append(None)
            continue
        axes = e if isinstance(e, (tuple, list)) else (e, )
        keep = []
        size = shape[i]
        for a in axes:
            n = mesh.shape.get(a, 1)
            if n <= 1:
                continue
            if size % n == 0:
                keep.append(a)
                size //= n
            else:
                logger.warning(f"partition rule for {path or 'param'} dim {i} (size {shape[i]}) is not divisible "
                               f"by mesh axis '{a}' ({n}); replicating that dim instead")
        out.append(tuple(keep) if len(keep) > 1 else (keep[0] if keep else None))
    return PartitionSpec(*out)


def add_data_axes(spec: PartitionSpec, shape: Tuple[int, ...], mesh: Mesh, data_axes: Sequence[str]) -> PartitionSpec:
    """FSDP-shard: attach the data axes to the largest unsharded divisible dim."""
    dp = _axes_size(mesh, data_axes)
    if dp <= 1 or len(shape) == 0:
        return spec
    # an axis may appear at most once in a PartitionSpec: if the TP/EP rules
    # already consumed any of the data axes (e.g. expert weights sharded over
    # 'data' on the expert dim — that IS the ZeRO sharding), leave it alone
    used = set()
    for e in spec:
        if e is None:
            continue
        used.update(e if isinstance(e, (tuple, list)) else (e, ))
    if used & set(data_axes):
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    entries = entries[:len(shape)]
    # per-dim size remaining after existing sharding
    def remaining(i):
        e = entries[i]
        if e is None:
            denom = 1
        elif isinstance(e, (tuple, list)):
            denom = _axes_size(mesh, e)
        else:
            denom = _axes_size(mesh, (e, ))
        return shape[i] // max(denom, 1), shape[i] % max(denom, 1) == 0
    candidates = []
    for i in range(len(shape)):
        rem, ok = remaining(i)
        if ok and rem % dp == 0 and rem > 0:
            candidates.append((rem, -i))
    if not candidates:
        return PartitionSpec(*entries)  # too small / indivisible: stays replicated
    _, neg_i = max(candidates)
    i = -neg_i
    e = entries[i]
    if e is None:
        entries[i] = tuple(data_axes) if len(data_axes) > 1 else data_axes[0]
    elif isinstance(e, (tuple, list)):
        entries[i] = tuple(e) + tuple(data_axes)
    else:
        entries[i] = (e, ) + tuple(data_axes)
    return PartitionSpec(*entries)


class ZeroShardingPolicy:
    """Computes the three sharding pytrees (param/grad/opt) for a ZeRO stage."""

    def __init__(self,
                 mesh: Mesh,
                 stage: int = 0,
                 tp_rules: Optional[PartitionRules] = None,
                 data_axes: Optional[Sequence[str]] = None,
                 mics_shard_size: int = -1,
                 hpz_partition_size: int = 0):
        self.mesh = mesh
        self.stage = stage
        self.tp_rules = tp_rules or PartitionRules()
        self.data_axes = tuple(data_axes) if data_axes is not None else groups.get_data_parallel_group()
        self.data_axes = tuple(a for a in self.data_axes if mesh.shape.get(a, 1) >= 1)
        self.mics_shard_size = mics_shard_size
        self.hpz_partition_size = hpz_partition_size
        if hpz_partition_size and hpz_partition_size > 1:
            # ZeRO++ hpZ (reference groups.py:505 + partition_parameters.py
            # ds_secondary_tensor): primary states shard over the FULL dp
            # extent (data_repl x data); the forward consumes a secondary
            # copy sharded over only the inner ``data`` axis (== the hpZ
            # group), so per-layer weight gathers stay within the group.
            from ...parallel.mesh import DATA_AXIS, DATA_REPL_AXIS

            got = mesh.shape.get(DATA_AXIS, 1)
            if got != hpz_partition_size:
                raise ValueError(f"hpZ: mesh '{DATA_AXIS}' axis is {got} but zero_hpz_partition_size="
                                 f"{hpz_partition_size}; the engine must split the data axis first")
            self.secondary_axes = self.data_axes
            self.data_axes = (DATA_REPL_AXIS, ) + tuple(self.data_axes)
        if mics_shard_size > 0:
            # MiCS (reference runtime/zero/mics.py): the engine splits the
            # data dimension into (data_repl, data) mesh axes with
            # |data| == mics_shard_size. This policy's data_axes exclude
            # data_repl, so states shard over the small shard group and
            # replicate across replica groups; the batch spans both axes, so
            # XLA's gradient reduction covers the full DP extent
            # (hierarchical: reduce within shard group rides nearest ICI).
            from ...parallel.mesh import DATA_AXIS

            got = mesh.shape.get(DATA_AXIS, 1)
            if got != mics_shard_size:
                raise ValueError(f"MiCS: mesh '{DATA_AXIS}' axis is {got} but mics_shard_size="
                                 f"{mics_shard_size}; the engine must split the data axis first")

    # -- specs --------------------------------------------------------
    def tp_spec_tree(self, params):
        specs = self.tp_rules.tree_specs(params)
        return jax.tree_util.tree_map_with_path(
            lambda kp, x, s: sanitize_spec(s, np.shape(x), self.mesh, path=path_str(kp)), params, specs)

    def _sharded_spec_tree(self, params):
        tp = self.tp_spec_tree(params)
        return jax.tree_util.tree_map(
            lambda x, s: add_data_axes(s, np.shape(x), self.mesh, self.data_axes), params, tp)

    def param_specs(self, params):
        if self.stage >= 3:
            return self._sharded_spec_tree(params)
        return self.tp_spec_tree(params)

    def secondary_param_specs(self, params):
        """hpZ secondary copy: sharded over the intra-group axes only (so the
        forward's per-layer all-gathers stay inside the hpZ group)."""
        assert self.hpz_partition_size and self.hpz_partition_size > 1
        tp = self.tp_spec_tree(params)
        return jax.tree_util.tree_map(
            lambda x, s: add_data_axes(s, np.shape(x), self.mesh, self.secondary_axes), params, tp)

    def grad_specs(self, params):
        if self.stage >= 2:
            return self._sharded_spec_tree(params)
        return self.tp_spec_tree(params)

    def opt_specs_for_params(self, params):
        if self.stage >= 1:
            return self._sharded_spec_tree(params)
        return self.tp_spec_tree(params)

    # -- shardings ----------------------------------------------------
    def _to_sharding(self, spec_tree):
        return jax.tree_util.tree_map(lambda s: NamedSharding(self.mesh, s), spec_tree,
                                      is_leaf=lambda x: isinstance(x, PartitionSpec))

    def param_shardings(self, params):
        return self._to_sharding(self.param_specs(params))

    def grad_shardings(self, params):
        return self._to_sharding(self.grad_specs(params))

    def opt_state_shardings(self, opt_state, params):
        """Map optimizer-state leaves to shardings.

        Optax states embed param-shaped pytrees (mu/nu/...): any subtree whose
        structure matches the param tree is mapped *path-wise* to the param
        opt specs (shape-keyed matching would collide same-shaped params with
        different TP specs, e.g. wk vs wo); scalars and unrecognized leaves
        replicate.
        """
        spec_tree = self.opt_specs_for_params(params)
        target_def = jax.tree_util.tree_structure(params)
        spec_shardings = jax.tree_util.tree_map(lambda s: NamedSharding(self.mesh, s), spec_tree,
                                                is_leaf=lambda x: isinstance(x, PartitionSpec))

        def is_param_tree(x):
            try:
                return jax.tree_util.tree_structure(x) == target_def
            except Exception:
                return False

        def map_node(node):
            if is_param_tree(node):
                return spec_shardings
            # bare leaf (scalar count, etc.)
            return NamedSharding(self.mesh, PartitionSpec())

        return jax.tree_util.tree_map(map_node, opt_state, is_leaf=is_param_tree)


def _lookup(tree, keypath):
    node = tree
    for k in keypath:
        if hasattr(k, "key"):
            node = node[k.key]
        elif hasattr(k, "idx"):
            node = node[k.idx]
        elif hasattr(k, "name"):
            node = getattr(node, k.name)
        else:
            node = node[k]
    return node


def constrain(tree, spec_tree, mesh: Mesh):
    """with_sharding_constraint over a pytree of PartitionSpecs (in-jit)."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.lax.with_sharding_constraint(x, NamedSharding(mesh, s)), tree, spec_tree)
