"""Config key constants.

Mirrors the user-facing JSON key vocabulary of the reference
``deepspeed/runtime/constants.py`` so that existing DeepSpeed JSON configs work
unchanged against the TPU framework. Keys whose semantics are CUDA-only are
accepted and ignored with a warning (see ``runtime/config.py``).
"""

#############################################
# Batch-size triad (reference constants.py)
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_BATCH_SIZE_DEFAULT = None

TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None

GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None

#############################################
# Optimizer / scheduler
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE_DEFAULT = None
OPTIMIZER_PARAMS = "params"
TYPE = "type"
LEGACY_FUSION = "legacy_fusion"
SCHEDULER = "scheduler"
SCHEDULER_TYPE_DEFAULT = None
SCHEDULER_PARAMS = "params"
MAX_GRAD_NORM = "max_grad_norm"

ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
FUSED_ADAM_OPTIMIZER = "fusedadam"
LAMB_OPTIMIZER = "lamb"
SGD_OPTIMIZER = "sgd"
LION_OPTIMIZER = "lion"
ADAGRAD_OPTIMIZER = "adagrad"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
MUADAM_OPTIMIZER = "muadam"
MUADAMW_OPTIMIZER = "muadamw"
MUSGD_OPTIMIZER = "musgd"
DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER, ADAMW_OPTIMIZER, FUSED_ADAM_OPTIMIZER, LAMB_OPTIMIZER, SGD_OPTIMIZER, LION_OPTIMIZER,
    ADAGRAD_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER, ONEBIT_LAMB_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER
]

#############################################
# Precision
#############################################
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_ENABLED_DEFAULT = False
FP16_LOSS_SCALE = "loss_scale"
FP16_LOSS_SCALE_DEFAULT = 0
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_INITIAL_SCALE_POWER_DEFAULT = 16
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000
FP16_HYSTERESIS = "hysteresis"
FP16_HYSTERESIS_DEFAULT = 2
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MIN_LOSS_SCALE_DEFAULT = 1
FP16_MASTER_WEIGHTS_AND_GRADS = "fp16_master_weights_and_grads"
FP16_AUTO_CAST = "auto_cast"

BFLOAT16 = "bf16"
BFLOAT16_OLD = "bfloat16"  # legacy alias accepted by the reference
BFLOAT16_ENABLED = "enabled"
BFLOAT16_ENABLED_DEFAULT = False

PRECISION_DTYPE = "dtype"

#############################################
# Gradient clipping / misc training knobs
#############################################
GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

PRESCALE_GRADIENTS = "prescale_gradients"
PRESCALE_GRADIENTS_DEFAULT = False

GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0

SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_GRADIENTS_DEFAULT = False

STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10

WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False

DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False

MEMORY_BREAKDOWN = "memory_breakdown"
MEMORY_BREAKDOWN_DEFAULT = False

#############################################
# ZeRO
#############################################
ZERO_OPTIMIZATION = "zero_optimization"

#############################################
# Communication
#############################################
COMMUNICATION_DATA_TYPE = "communication_data_type"
COMMUNICATION_DATA_TYPE_DEFAULT = None
SEQ_PARALLEL_COMMUNICATION_DATA_TYPE = "seq_parallel_communication_data_type"
SEQ_PARALLEL_COMMUNICATION_DATA_TYPE_DEFAULT = "fp32"
DISABLE_ALLGATHER = "disable_allgather"
DISABLE_ALLGATHER_DEFAULT = False

#############################################
# Activation checkpointing (remat on TPU)
#############################################
ACTIVATION_CHECKPOINTING = "activation_checkpointing"

#############################################
# Monitoring
#############################################
TENSORBOARD = "tensorboard"
WANDB = "wandb"
CSV_MONITOR = "csv_monitor"

#############################################
# Profiling
#############################################
FLOPS_PROFILER = "flops_profiler"
COMMS_LOGGER = "comms_logger"

#############################################
# Data pipeline / efficiency
#############################################
DATA_EFFICIENCY = "data_efficiency"
CURRICULUM_LEARNING_LEGACY = "curriculum_learning"
DATALOADER_DROP_LAST = "dataloader_drop_last"
DATALOADER_DROP_LAST_DEFAULT = False

#############################################
# Pipeline / TPU-specific sections
#############################################
PIPELINE = "pipeline"
TPU = "tpu"  # TPU-native section: mesh axes, remat policy, donation

#############################################
# Checkpoint
#############################################
CHECKPOINT = "checkpoint"
LOAD_UNIVERSAL_CHECKPOINT = "load_universal"
LOAD_UNIVERSAL_CHECKPOINT_DEFAULT = False
USE_NODE_LOCAL_STORAGE_CHECKPOINT = "use_node_local_storage"
CHECKPOINT_PARALLEL_WRITE = "parallel_write"
CHECKPOINT_PARALLEL_WRITE_PIPELINE_STAGE = "pipeline_stage"

CHECKPOINT_TAG_VALIDATION = "checkpoint_tag_validation"
CHECKPOINT_TAG_VALIDATION_DEFAULT = "Warn"
CHECKPOINT_TAG_VALIDATION_MODES = ["Warn", "Ignore", "Fail"]

#############################################
# Elasticity (reference elasticity/constants.py)
#############################################
ELASTICITY = "elasticity"

#############################################
# Autotuning
#############################################
AUTOTUNING = "autotuning"

#############################################
# Compression
#############################################
COMPRESSION_TRAINING = "compression_training"

#############################################
# Gradient-accumulation-boundary optimization
#############################################
GRAD_ACCUM_DTYPE = "grad_accum_dtype"

ROUTE_TRAIN = "train"
ROUTE_EVAL = "eval"
ROUTE_PREDICT = "predict"
ROUTE_ENCODE = "encode"
