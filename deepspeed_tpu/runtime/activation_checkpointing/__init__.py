from . import checkpointing
from .checkpointing import (checkpoint, configure, is_configured, non_reentrant_checkpoint, reset,
                            get_rng_tracker, model_parallel_rng_tracker_name, partition_activations_wrapper,
                            CheckpointFunction, resolve_policy)
