"""Activation checkpointing — rematerialization policies over ``jax.checkpoint``.

TPU-native analog of the reference Megatron-derived machinery
(``deepspeed/runtime/activation_checkpointing/checkpointing.py``, 1,165 LoC:
``CheckpointFunction:484``, ``non_reentrant_checkpoint:727``,
``partition_activations:373``, ``CudaRNGStatesTracker:122``, ``configure:1073``).

Design: on TPU the compiler owns the trade between recompute and HBM, so the
reference's hand-rolled stash/partition/offload of saved tensors collapses
into a *policy* handed to ``jax.checkpoint``:

  * ``checkpoint(fn, *args)``            — remat ``fn`` under the configured
    policy (reference ``CheckpointFunction.apply`` semantics; in JAX forward
    outputs and recompute-in-backward are derived from one pure function, so
    the reentrant/non-reentrant distinction disappears — both entry points map
    to the same transform).
  * ``partition_activations``            — instead of scattering saved tensors
    across TP ranks (reference ``:373``), residuals carry a sharding
    constraint over the (seq, model) axes so XLA stores each saved activation
    sharded and all-gathers it at recompute time — same memory/comm trade,
    compiler-scheduled.
  * ``cpu_checkpointing``                — maps to ``jax.checkpoint`` +
    host-offload of the named saved residuals where supported
    (``save_and_offload_only_these_names``), else to ``nothing_saveable``
    (recompute everything — strictly less HBM than host offload needs).
  * RNG: dropout inside a remat'd function replays exactly because JAX PRNG
    keys are explicit values — the entire reason the reference needs
    ``CudaRNGStatesTracker`` (:122) to fork/restore device RNG states. A
    tracker with the same API is provided for Megatron-style model code.
"""

import contextlib
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from ...parallel.mesh import MODEL_AXIS, SEQ_AXIS
from ...utils.logging import logger

# ---------------------------------------------------------------------------
# policy registry
# ---------------------------------------------------------------------------

_POLICIES = {
    # recompute everything (max memory savings) — the default, and the analog
    # of the reference checkpointing every transformer block
    "nothing_saveable": lambda: jax.checkpoint_policies.nothing_saveable,
    # save matmul outputs, recompute elementwise — the sweet spot on TPU: the
    # MXU work is saved, the (HBM-bound) elementwise chain is recomputed
    "dots_saveable": lambda: jax.checkpoint_policies.dots_saveable,
    "checkpoint_dots": lambda: jax.checkpoint_policies.dots_saveable,
    "dots_with_no_batch_dims_saveable": lambda: jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    "checkpoint_dots_with_no_batch_dims": lambda: jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    "everything_saveable": lambda: jax.checkpoint_policies.everything_saveable,
}


def resolve_policy(name_or_policy):
    """Resolve a policy name (config string) to a jax.checkpoint policy."""
    if name_or_policy is None:
        return jax.checkpoint_policies.nothing_saveable
    if callable(name_or_policy):
        return name_or_policy
    try:
        return _POLICIES[str(name_or_policy)]()
    except KeyError:
        raise ValueError(f"unknown remat policy '{name_or_policy}'; known: {sorted(_POLICIES)}")


def offload_policy(names=("residual", )):
    """Host-offload policy for ``cpu_checkpointing`` — saved residuals with
    matching ``checkpoint_name`` live in pinned host RAM instead of HBM
    (reference ``checkpoint_in_cpu`` / ``PartitionedTensor`` CPU path)."""
    try:
        return jax.checkpoint_policies.save_and_offload_only_these_names(
            names_which_can_be_saved=[],
            names_which_can_be_offloaded=list(names),
            offload_src="device",
            offload_dst="pinned_host")
    except Exception:  # older jax without offload support
        logger.warning("cpu_checkpointing: host offload unsupported by this jax; recomputing instead")
        return jax.checkpoint_policies.nothing_saveable


# ---------------------------------------------------------------------------
# module state (mirrors the reference's module-level configure() globals)
# ---------------------------------------------------------------------------

class _CkptState:
    configured = False
    policy = None
    partition_activations = False
    cpu_checkpointing = False
    contiguous_memory_optimization = False
    num_checkpoints = None
    synchronize = False
    profile = False


_state = _CkptState()


def configure(mpu_=None,
              deepspeed_config=None,
              partition_activations=None,
              contiguous_checkpointing=None,
              checkpoint_in_cpu=None,
              synchronize=None,
              profile=None,
              num_checkpoints=None,
              remat_policy=None):
    """Configure module-level checkpointing state (reference ``configure:1073``;
    same precedence: explicit kwargs override the deepspeed_config block)."""
    cfg = None
    if deepspeed_config is not None:
        from ..config import DeepSpeedConfig

        ds = deepspeed_config if isinstance(deepspeed_config, DeepSpeedConfig) else DeepSpeedConfig(deepspeed_config)
        cfg = ds.activation_checkpointing_config

    def pick(explicit, from_cfg, default):
        if explicit is not None:
            return explicit
        if cfg is not None:
            return from_cfg(cfg)
        return default

    _state.partition_activations = pick(partition_activations, lambda c: c.partition_activations, False)
    _state.contiguous_memory_optimization = pick(contiguous_checkpointing,
                                                 lambda c: c.contiguous_memory_optimization, False)
    _state.cpu_checkpointing = pick(checkpoint_in_cpu, lambda c: c.cpu_checkpointing, False)
    _state.synchronize = pick(synchronize, lambda c: c.synchronize_checkpoint_boundary, False)
    _state.profile = pick(profile, lambda c: c.profile, False)
    _state.num_checkpoints = pick(num_checkpoints, lambda c: c.number_checkpoints, None)
    policy_name = pick(remat_policy, lambda c: c.remat_policy, "nothing_saveable")
    _state.policy = offload_policy() if _state.cpu_checkpointing else resolve_policy(policy_name)
    _state.configured = True
    logger.info(f"activation checkpointing configured: policy={policy_name} "
                f"partition_activations={_state.partition_activations} cpu={_state.cpu_checkpointing}")


def is_configured():
    return _state.configured


def reset():
    """Reference ``reset()``: drop buffers between iterations. Stateless here
    (XLA owns the buffers); clears config back to defaults."""
    _state.__dict__.clear()
    _state.configured = False
    _state.policy = None
    _state.partition_activations = False
    _state.cpu_checkpointing = False


def _activation_spec(ndim: int) -> PartitionSpec:
    """Sharding for saved activations [batch, seq, ...]: batch over data is
    already carried by the input sharding; partition_activations additionally
    spreads the seq dim over (seq, model) so each TP rank stores 1/mp of every
    residual — the exact memory effect of reference ``partition_activations:373``."""
    if ndim >= 2:
        return PartitionSpec(None, (SEQ_AXIS, MODEL_AXIS))
    return PartitionSpec()


def partition_activations_wrapper(fn: Callable) -> Callable:
    """Wrap ``fn`` so its activation inputs (the tensors that become saved
    residuals of the remat block) carry the partitioned-activation sharding
    constraint. Only rank>=3 [batch, seq, ...] arrays are constrained —
    parameter matrices (rank 2) keep their ZeRO/TP shardings untouched, like
    the reference which partitions only the saved activations (:373)."""

    def wrapped(*args, **kwargs):
        def constrain(x):
            if hasattr(x, "ndim") and x.ndim >= 3:
                try:
                    return jax.lax.with_sharding_constraint(x, _activation_spec(x.ndim))
                except Exception:
                    return x
            return x

        args = jax.tree_util.tree_map(constrain, args)
        return fn(*args, **kwargs)

    return wrapped


def checkpoint(function: Callable, *args, policy=None, prevent_cse: bool = True, static_argnums=()):
    """Checkpoint (remat) ``function`` applied to ``*args`` — drop-in for the
    reference ``checkpoint()`` (``checkpointing.py:484`` CheckpointFunction).

    With no args, returns the remat-wrapped function instead (decorator use).
    """
    if not _state.configured:
        configure()
    pol = resolve_policy(policy) if policy is not None else _state.policy
    fn = function
    if _state.partition_activations:
        fn = partition_activations_wrapper(fn)
    wrapped = jax.checkpoint(fn, policy=pol, prevent_cse=prevent_cse, static_argnums=static_argnums)
    if not args:
        return wrapped
    return wrapped(*args)


def non_reentrant_checkpoint(function: Callable, *args, **kwargs):
    """Reference ``non_reentrant_checkpoint:727`` — identical to ``checkpoint``
    here: jax.checkpoint re-derives the backward from the pure function, which
    is exactly the non-reentrant (no redundant autograd graph) behavior."""
    return checkpoint(function, *args, **kwargs)


# alias matching the reference's exported class name
CheckpointFunction = checkpoint


def checkpoint_name(name: str, x):
    """Tag an intermediate for name-based policies (offload / save lists)."""
    from jax.ad_checkpoint import checkpoint_name as _cn

    return _cn(x, name)


# ---------------------------------------------------------------------------
# RNG tracker (reference CudaRNGStatesTracker:122 — API parity for
# Megatron-style model code; JAX keys are explicit so fork() just derives)
# ---------------------------------------------------------------------------

_MODEL_PARALLEL_RNG_TRACKER_NAME = "model-parallel-rng"


def model_parallel_rng_tracker_name():
    return _MODEL_PARALLEL_RNG_TRACKER_NAME


class RNGStatesTracker:
    """Named PRNG key registry with a fork() context manager.

    The reference must save/restore device RNG *mutable state* around every
    checkpointed region so dropout replays identically in recompute. JAX PRNG
    keys are pure values threaded through the computation, so replay is
    automatic; this tracker exists to give Megatron-style code (which calls
    ``get_cuda_rng_tracker().fork()``) a home for named key streams.
    """

    def __init__(self):
        self.states_ = {}

    def reset(self):
        self.states_.clear()

    def get_states(self):
        return dict(self.states_)

    def set_states(self, states):
        self.states_ = dict(states)

    def add(self, name, seed):
        if name in self.states_:
            raise Exception(f"cuda rng state {name} already exists")
        self.states_[name] = jax.random.PRNGKey(seed)

    def split(self, name=_MODEL_PARALLEL_RNG_TRACKER_NAME):
        """Derive a fresh subkey from the named stream (advances the stream)."""
        if name not in self.states_:
            raise Exception(f"cuda rng state {name} is not added")
        self.states_[name], sub = jax.random.split(self.states_[name])
        return sub

    @contextlib.contextmanager
    def fork(self, name=_MODEL_PARALLEL_RNG_TRACKER_NAME):
        """Context manager yielding a subkey for the region (reference forks
        device RNG state; here the caller uses the yielded key explicitly)."""
        yield self.split(name)


_RNG_TRACKER = RNGStatesTracker()


def get_rng_tracker():
    return _RNG_TRACKER


# reference exports this under the CUDA name; keep an alias for drop-in code
get_cuda_rng_tracker = get_rng_tracker


def model_parallel_reconfigure_tp_seed(seed):
    """Reference ``model_parallel_cuda_manual_seed``: give each TP rank a
    distinct dropout stream. With explicit keys we fold in the model-axis
    index lazily at use; here we just (re)seed the named stream."""
    tracker = get_rng_tracker()
    tracker.states_.pop(_MODEL_PARALLEL_RNG_TRACKER_NAME, None)
    tracker.add(_MODEL_PARALLEL_RNG_TRACKER_NAME, seed)
