"""Hybrid engine — one model flipping between training and inference.

Reference ``deepspeed/runtime/hybrid_engine.py`` (440 LoC,
``DeepSpeedHybridEngine``): the RLHF actor trains under ZeRO-3 and must also
``generate()`` rollouts; the reference gathers the sharded params into
kernel-injected inference containers (``generate:174``), with LoRA
fuse/unfuse around the flip (:138-158).

TPU form: the training params already live in one sharded pytree, so the
"flip" is a resharding (training ZeRO/TP layout → inference TP layout) done
by XLA on device via a jitted identity with inference out-shardings — no
gather to host, no module surgery. The inference engine's compiled
generate reuses the refreshed params between training phases; staleness is
tracked by the train-step counter.
"""

from typing import Optional

import jax
import numpy as np

from .engine import DeepSpeedEngine
from ..utils.logging import log_dist, logger
from ..utils.timer import SynchronizedWallClockTimer


class DeepSpeedHybridEngine(DeepSpeedEngine):
    """Training engine + generate() (construct via
    ``deepspeed_tpu.initialize(..., config={'hybrid_engine': {'enabled': True}})``
    or directly)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._inference_engine = None
        self._inference_params_step = -1
        self._generate_timer = SynchronizedWallClockTimer.Timer("generate")
        self._latency = []

    # ------------------------------------------------------------------
    def _inference_config(self):
        from ..inference.config import DeepSpeedInferenceConfig

        he = getattr(self.config, "hybrid_engine_config", None)
        tp = getattr(he, "inference_tp_size", 1) if he else self.mp_world_size
        return DeepSpeedInferenceConfig(dtype="bfloat16" if self.bfloat16_enabled else "float32",
                                        tensor_parallel={"tp_size": max(tp, self.mp_world_size)})

    def _refresh_inference_engine(self):
        """(Re)build or refresh the inference view of the current params —
        the analog of the reference gathering ZeRO-3 params into the
        inference containers before generation."""
        from ..inference.engine import InferenceEngine

        if self._inference_engine is None:
            self._inference_engine = InferenceEngine(self.module, self._inference_config(),
                                                     params=self.state["params"], mesh=self.mesh)
        elif self._inference_params_step != int(self.state["step"]):
            # params advanced: re-place into the inference shardings (device-
            # to-device resharding, no host round-trip)
            self._inference_engine.params = self._inference_engine._place_params(self.state["params"])
        self._inference_params_step = int(self.state["step"])

    def generate(self, input_ids, max_new_tokens: int = 32, temperature: float = 0.0, top_k: int = 0,
                 eos_token_id: Optional[int] = None, **kwargs):
        """Rollout generation on the CURRENT weights (reference
        ``generate:174``). Safe to interleave with train_batch/step."""
        was_training = self._train_mode
        self.eval()
        self._refresh_inference_engine()
        self._generate_timer.start()
        out = self._inference_engine.generate(input_ids, max_new_tokens=max_new_tokens,
                                              temperature=temperature, top_k=top_k,
                                              eos_token_id=eos_token_id, **kwargs)
        np.asarray(out)  # sync for honest latency accounting
        self._generate_timer.stop()
        # Timer.elapsed() returns SECONDS (unlike the reference's CUDA-event
        # ms) — no conversion
        self._latency.append(self._generate_timer.elapsed())
        if was_training:
            self.train()
        return out

    # ------------------------------------------------------------------
    # LoRA fuse/unfuse (reference :138-158): with functional params LoRA
    # deltas are folded in/out arithmetically
    # ------------------------------------------------------------------
    @staticmethod
    def fuse_lora_weight(base_kernel, lora_a, lora_b, scaling: float = 1.0):
        """W' = W + scaling * A @ B (reference fuses per-layer before gen)."""
        return base_kernel + scaling * lora_a @ lora_b

    @staticmethod
    def unfuse_lora_weight(fused_kernel, lora_a, lora_b, scaling: float = 1.0):
        return fused_kernel - scaling * lora_a @ lora_b

    def generate_latency(self):
        """Seconds per generate call (reference latency bookkeeping used by
        the RLHF trainer's throughput logs)."""
        return list(self._latency)
