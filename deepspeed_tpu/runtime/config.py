"""DeepSpeed-compatible JSON config system.

Analog of the reference ``deepspeed/runtime/config.py`` (1,035 LoC):
``DeepSpeedConfig`` parses a JSON file or dict into ~30 typed sub-configs and
resolves the batch-size triad ``train_batch = micro_batch × gas × dp_world``
with auto-fill (reference ``_configure_train_batch_size``/
``_batch_assertion``). Additions for TPU: a ``tpu`` section describing mesh
axes (data/model/pipe/seq/expert), rematerialization policy and buffer
donation — the knobs that replace CUDA streams/buckets.
"""

import os
import json
import copy
from typing import Literal, Optional, List, Union, Any

from pydantic import Field, model_validator

from .config_utils import DeepSpeedConfigModel, get_scalar_param, dict_raise_error_on_duplicate_keys
from .constants import *  # noqa: F401,F403
from .constants import (TRAIN_BATCH_SIZE, TRAIN_MICRO_BATCH_SIZE_PER_GPU, GRADIENT_ACCUMULATION_STEPS, OPTIMIZER,
                        SCHEDULER, TYPE, OPTIMIZER_PARAMS, SCHEDULER_PARAMS, FP16, BFLOAT16, BFLOAT16_OLD,
                        ZERO_OPTIMIZATION, GRADIENT_CLIPPING, GRADIENT_CLIPPING_DEFAULT, STEPS_PER_PRINT,
                        STEPS_PER_PRINT_DEFAULT, WALL_CLOCK_BREAKDOWN, WALL_CLOCK_BREAKDOWN_DEFAULT, MEMORY_BREAKDOWN,
                        MEMORY_BREAKDOWN_DEFAULT, PRESCALE_GRADIENTS, PRESCALE_GRADIENTS_DEFAULT,
                        GRADIENT_PREDIVIDE_FACTOR, GRADIENT_PREDIVIDE_FACTOR_DEFAULT, SPARSE_GRADIENTS,
                        SPARSE_GRADIENTS_DEFAULT, COMMUNICATION_DATA_TYPE, COMMUNICATION_DATA_TYPE_DEFAULT,
                        SEQ_PARALLEL_COMMUNICATION_DATA_TYPE, SEQ_PARALLEL_COMMUNICATION_DATA_TYPE_DEFAULT,
                        DISABLE_ALLGATHER, DISABLE_ALLGATHER_DEFAULT, DUMP_STATE, DUMP_STATE_DEFAULT,
                        DATALOADER_DROP_LAST, DATALOADER_DROP_LAST_DEFAULT, CHECKPOINT_TAG_VALIDATION,
                        CHECKPOINT_TAG_VALIDATION_DEFAULT, CHECKPOINT_TAG_VALIDATION_MODES, CHECKPOINT,
                        LOAD_UNIVERSAL_CHECKPOINT, LOAD_UNIVERSAL_CHECKPOINT_DEFAULT, GRAD_ACCUM_DTYPE, TPU, PIPELINE,
                        ACTIVATION_CHECKPOINTING, FLOPS_PROFILER, COMMS_LOGGER, ELASTICITY, AUTOTUNING,
                        TRAIN_BATCH_SIZE_DEFAULT, TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT,
                        GRADIENT_ACCUMULATION_STEPS_DEFAULT)
from .zero.config import DeepSpeedZeroConfig
from ..monitor.config import get_monitor_config, DeepSpeedMonitorConfig
from ..parallel.mesh import MeshConfig
from ..utils.logging import logger


class DeepSpeedConfigError(Exception):
    pass


class FP16Config(DeepSpeedConfigModel):
    """``fp16`` block (reference fp16 getters config.py:125-220). On TPU fp16
    matmuls are emulated; bf16 needs no loss scaling and is preferred."""
    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = 0.0
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    consecutive_hysteresis: bool = False
    min_loss_scale: float = 1.0
    fp16_master_weights_and_grads: bool = False


class BF16Config(DeepSpeedConfigModel):
    enabled: bool = False
    immediate_grad_update: bool = False


class OptimizerConfig(DeepSpeedConfigModel):
    type: Optional[str] = None
    params: dict = {}
    legacy_fusion: bool = False


class SchedulerConfig(DeepSpeedConfigModel):
    type: Optional[str] = None
    params: dict = {}


class ActivationCheckpointingConfig(DeepSpeedConfigModel):
    """``activation_checkpointing`` block (reference
    ``runtime/activation_checkpointing/config.py``). On TPU this configures
    ``jax.checkpoint`` (remat) policies instead of manual tensor stashing:
    ``partition_activations`` maps to saving activations sharded over the model
    axis, ``cpu_checkpointing`` to host offload of residuals."""
    partition_activations: bool = False
    contiguous_memory_optimization: bool = False
    cpu_checkpointing: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    # TPU-native: named jax.checkpoint policy, e.g. 'nothing_saveable',
    # 'dots_saveable', 'dots_with_no_batch_dims_saveable', 'checkpoint_dots'
    remat_policy: str = "nothing_saveable"


class FlopsProfilerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    recompute_fwd_factor: float = 0.0
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


class CommsLoggerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = []


class CommsConfig(DeepSpeedConfigModel):
    comms_logger_enabled: bool = False
    comms_logger: CommsLoggerConfig = CommsLoggerConfig()


class CheckpointConfig(DeepSpeedConfigModel):
    tag_validation: str = "Warn"
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write: dict = {}
    # TPU-native: use orbax/tensorstore OCDBT layout under the hood
    async_save: bool = False
    # --- resilience plane (runtime/resilience/) ---
    # auto-save every N engine steps (0 = off); nebula.persistent_time_interval
    # adds the wall-clock cadence when the nebula block is enabled
    save_interval_steps: int = 0
    # retention GC: keep the newest N committed tags (0 = keep everything);
    # mirrored from nebula.num_of_version_in_retention when nebula is on
    num_of_version_in_retention: int = 0
    # archival knob: tags whose step is a multiple of N survive retention
    keep_every_n_steps: int = 0
    # trap SIGTERM -> final checkpoint at the next step boundary -> clean
    # exit (auto-enabled when nebula provides a persistent_storage_path)
    preemption_save: bool = False
    # default directory for auto/preemption saves (nebula's
    # persistent_storage_path wins when set); engine.set_checkpoint_dir()
    # overrides at runtime
    auto_save_dir: Optional[str] = None
    # record per-file sha256 in the commit manifest (deep verification of
    # bit-rot). Costs a full read-back of the payload per save — turn off for
    # huge checkpoints where the size-only manifest check is enough
    manifest_digests: bool = True
    # elastic warm remesh (elasticity/remesh.py): every committed save also
    # publishes a host-RAM universal-layout snapshot, so a topology-change
    # restart under run_resilient(warm_remesh=True) re-shards from memory
    # instead of reading the checkpoint payload back. Costs one fp32 copy of
    # params + both Adam moments in host RAM while armed.
    remesh_snapshot: bool = False


class PipelineConfig(DeepSpeedConfigModel):
    """``pipeline`` block (reference engine pipeline knobs)."""
    stages: Union[int, str] = "auto"
    partition: str = "best"
    seed_layers: bool = False
    activation_checkpoint_interval: int = 0
    pipe_partitioned: bool = True
    grad_partitioned: bool = True
    # '1f1b' (reference TrainSchedule schedule.py:189 — bounded live
    # activations, composes with TP) | 'gpipe' (fill-drain via jax.grad)
    schedule: Literal["1f1b", "gpipe"] = "1f1b"


class ProfilerTraceConfig(DeepSpeedConfigModel):
    """``tpu.profiler_trace`` block — typed so key typos and bad values fail
    at ``initialize()``, not mid-training (same pattern as the fp16 block).
    Enabled by presence: an empty block stays off."""
    trace_dir: str = "/tmp/dstpu_trace"
    start_step: int = Field(0, ge=0)
    num_steps: int = Field(1, ge=1)
    enabled: bool = False

    @model_validator(mode="after")
    def enable_when_configured(self):
        # the base model tolerates unknown keys (reference parity) — but a
        # typo here silently traces the wrong step; warn loudly
        unknown = set(self.model_fields_set) - set(type(self).model_fields)
        if unknown:
            from ..utils.logging import logger

            logger.warning(f"profiler_trace: unknown keys {sorted(unknown)} ignored "
                           f"(valid: trace_dir, start_step, num_steps, enabled)")
        # {"trace_dir": ...} or {"start_step": N} implies the user wants it
        if self.model_fields_set and "enabled" not in self.model_fields_set:
            self.enabled = True
        return self


class TPUConfig(DeepSpeedConfigModel):
    """TPU-native section: the mesh is the single source of truth for every
    parallel dimension (SURVEY.md §7 design stance)."""
    mesh: dict = {}
    # donate param/opt-state buffers into the jitted step (in-place update)
    donate_buffers: bool = True
    # jit the whole train step (fused fwd+bwd+step) vs eager-style 3 calls
    fused_train_step: bool = True
    # matmul precision: 'default' | 'high' | 'highest' (jax.default_matmul_precision)
    matmul_precision: str = "default"
    # Pallas fused Adam(W) step (reference csrc/adam/multi_tensor_adam.cu):
    # one HBM pass over (grad, param, m, v) with overflow gate + clip folded
    # in. Measured on v5e: XLA's fusion of the optax chain already sits near
    # the HBM roofline (~40ms for 748M params), so the kernel is off by
    # default ('auto' == 'never' today); 'always' forces it (interpret mode
    # off-TPU) for experimentation and tests.
    pallas_fused_adam: Literal["auto", "always", "never"] = "auto"
    # compile-only validation mode: state stays abstract (ShapeDtypeStructs
    # with shardings — nothing materializes), so pod-scale configs (7B/70B on
    # a 128-device mesh) can be AOT-lowered/compiled on hosts that could
    # never hold the weights. train_batch() is unusable in this mode; use
    # aot_lower_train_step() (tools/pod_validate.py)
    abstract_init: bool = False
    # device trace capture (the TPU analog of the reference's torch-profiler
    # hooks): captures a perfetto/XPlane trace of global steps
    # [start_step, start_step+num_steps) via jax.profiler — the artifact the
    # "profile, iterate" loop reads in xprof/perfetto. A window ending at the
    # final step is flushed by engine.destroy();
    # engine.start_device_trace()/stop_device_trace() drive it manually.
    profiler_trace: "ProfilerTraceConfig" = {}

    def mesh_config(self) -> MeshConfig:
        known = {k: v for k, v in self.mesh.items() if k in ("data", "model", "pipe", "seq", "expert")}
        return MeshConfig(**known)


class PLDConfig(DeepSpeedConfigModel):
    """``progressive_layer_drop`` block (reference
    ``runtime/progressive_layer_drop.py``; constants PLD_THETA/PLD_GAMMA)."""
    enabled: bool = False
    theta: float = 0.5
    gamma: float = 0.001


class HybridEngineConfig(DeepSpeedConfigModel):
    """``hybrid_engine`` block (reference ``runtime/hybrid_engine.py`` config:
    enable_hybrid_engine, inference_tp_size, release_inference_cache,
    pin_parameters, tp_gather_partition_size)."""
    enabled: bool = False
    max_out_tokens: int = 512
    inference_tp_size: int = 1
    release_inference_cache: bool = False
    pin_parameters: bool = True
    tp_gather_partition_size: int = 8


class ElasticityConfig(DeepSpeedConfigModel):
    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: List[int] = [2, 4, 6]
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    version: float = 0.2
    ignore_non_elastic_batch_info: bool = False
    prefer_larger_batch_size: bool = True


class DeepSpeedConfig:
    """Aggregate typed view over the JSON config (reference class of the same
    name, ``runtime/config.py`` after the getters at :94-:520)."""

    def __init__(self, config: Union[str, dict], mesh=None, mpu=None):
        if isinstance(config, str):
            if not os.path.exists(config):
                raise DeepSpeedConfigError(f"Expected a string path to an existing deepspeed config, got: {config}")
            with open(config, "r") as f:
                self._param_dict = json.load(f, object_pairs_hook=dict_raise_error_on_duplicate_keys)
        elif isinstance(config, dict):
            self._param_dict = copy.deepcopy(config)
        else:
            raise DeepSpeedConfigError(
                f"Expected a string path to a json file or a dict, got: {config} ({type(config)})")

        pd = self._param_dict
        self.mesh = mesh  # resolved later by the engine if None

        # --- precision ---
        self.fp16_config = FP16Config(**pd.get(FP16, {}))
        bf16_dict = pd.get(BFLOAT16, pd.get(BFLOAT16_OLD, {}))
        self.bfloat16_config = BF16Config(**bf16_dict)
        self.fp16_enabled = self.fp16_config.enabled
        self.bfloat16_enabled = self.bfloat16_config.enabled
        if self.fp16_enabled and self.bfloat16_enabled:
            raise DeepSpeedConfigError("fp16 and bf16 modes cannot be simultaneously enabled")
        self.loss_scale = self.fp16_config.loss_scale
        self.initial_dynamic_scale = 2**self.fp16_config.initial_scale_power
        self.dynamic_loss_scale_args = {
            "init_scale": 2**self.fp16_config.initial_scale_power,
            "scale_window": self.fp16_config.loss_scale_window,
            "min_scale": self.fp16_config.min_loss_scale,
            "delayed_shift": self.fp16_config.hysteresis,
        }

        # --- optimizer / scheduler ---
        opt_dict = pd.get(OPTIMIZER, None)
        self.optimizer_name = (opt_dict[TYPE].lower() if opt_dict and TYPE in opt_dict else None)
        self.optimizer_params = opt_dict.get(OPTIMIZER_PARAMS, {}) if opt_dict else None
        self.optimizer_legacy_fusion = opt_dict.get("legacy_fusion", False) if opt_dict else False
        sched_dict = pd.get(SCHEDULER, None)
        self.scheduler_name = sched_dict[TYPE] if sched_dict and TYPE in sched_dict else None
        self.scheduler_params = sched_dict.get(SCHEDULER_PARAMS, {}) if sched_dict else None

        # --- zero ---
        self.zero_config = DeepSpeedZeroConfig(**pd.get(ZERO_OPTIMIZATION, {}))
        self.zero_optimization_stage = self.zero_config.stage
        self.zero_enabled = self.zero_optimization_stage > 0

        # --- training knobs ---
        self.gradient_clipping = get_scalar_param(pd, GRADIENT_CLIPPING, GRADIENT_CLIPPING_DEFAULT)
        self.prescale_gradients = get_scalar_param(pd, PRESCALE_GRADIENTS, PRESCALE_GRADIENTS_DEFAULT)
        self.gradient_predivide_factor = get_scalar_param(pd, GRADIENT_PREDIVIDE_FACTOR,
                                                          GRADIENT_PREDIVIDE_FACTOR_DEFAULT)
        self.sparse_gradients_enabled = get_scalar_param(pd, SPARSE_GRADIENTS, SPARSE_GRADIENTS_DEFAULT)
        # sparse attention block (reference config.py:289 get_sparse_attention):
        # raw dict; ops.sparse_attention.build_sparsity_config turns it into a
        # SparsityConfig at injection time (mode validated there)
        self.sparse_attention = pd.get("sparse_attention")
        self.steps_per_print = get_scalar_param(pd, STEPS_PER_PRINT, STEPS_PER_PRINT_DEFAULT)
        self.wall_clock_breakdown = get_scalar_param(pd, WALL_CLOCK_BREAKDOWN, WALL_CLOCK_BREAKDOWN_DEFAULT)
        self.memory_breakdown = get_scalar_param(pd, MEMORY_BREAKDOWN, MEMORY_BREAKDOWN_DEFAULT)
        self.dump_state = get_scalar_param(pd, DUMP_STATE, DUMP_STATE_DEFAULT)
        self.disable_allgather = get_scalar_param(pd, DISABLE_ALLGATHER, DISABLE_ALLGATHER_DEFAULT)
        self.communication_data_type = get_scalar_param(pd, COMMUNICATION_DATA_TYPE, COMMUNICATION_DATA_TYPE_DEFAULT)
        self.seq_parallel_communication_data_type = get_scalar_param(pd, SEQ_PARALLEL_COMMUNICATION_DATA_TYPE,
                                                                     SEQ_PARALLEL_COMMUNICATION_DATA_TYPE_DEFAULT)
        self.dataloader_drop_last = get_scalar_param(pd, DATALOADER_DROP_LAST, DATALOADER_DROP_LAST_DEFAULT)
        self.grad_accum_dtype = get_scalar_param(pd, GRAD_ACCUM_DTYPE, None)

        # --- sub-configs ---
        self.monitor_config: DeepSpeedMonitorConfig = get_monitor_config(pd)
        self.flops_profiler_config = FlopsProfilerConfig(**pd.get(FLOPS_PROFILER, {}))
        self.activation_checkpointing_config = ActivationCheckpointingConfig(**pd.get(ACTIVATION_CHECKPOINTING, {}))
        comms_dict = pd.get(COMMS_LOGGER, {})
        self.comms_config = CommsConfig(comms_logger_enabled=bool(comms_dict.get("enabled", False)),
                                        comms_logger=CommsLoggerConfig(**comms_dict))
        from .data_pipeline.config import (DataEfficiencyConfig, CurriculumLearningConfig,
                                           get_data_pipeline_config)

        self.data_efficiency_config = DataEfficiencyConfig(**pd.get(DATA_EFFICIENCY, {}))
        # data_pipeline block: input-path perf knobs (async device prefetch)
        self.data_pipeline_config = get_data_pipeline_config(pd)
        self.curriculum_learning_config = CurriculumLearningConfig(**pd.get(CURRICULUM_LEARNING_LEGACY, {}))
        ckpt_dict = pd.get(CHECKPOINT, {})
        self.checkpoint_config = CheckpointConfig(**ckpt_dict)
        from ..nebula.config import DeepSpeedNebulaConfig

        self.nebula_config = DeepSpeedNebulaConfig.from_param_dict(pd)
        if self.nebula_config.enabled:
            # nebula's contract = training never blocks on persistence; the
            # TPU mechanism is orbax async save + the resilience plane
            # (runtime/resilience/): mirror the service knobs onto the
            # checkpoint block so retention/auto-save/preemption are live,
            # not parsed-and-dead (explicit checkpoint-block values win)
            self.checkpoint_config.async_save = True
            if self.checkpoint_config.num_of_version_in_retention == 0:
                self.checkpoint_config.num_of_version_in_retention = \
                    self.nebula_config.num_of_version_in_retention
            if self.checkpoint_config.auto_save_dir is None:
                self.checkpoint_config.auto_save_dir = self.nebula_config.persistent_storage_path
            if self.checkpoint_config.auto_save_dir:
                self.checkpoint_config.preemption_save = True
        self.checkpoint_tag_validation_enabled = self.checkpoint_config.tag_validation != "Ignore"
        self.checkpoint_tag_validation_fail = self.checkpoint_config.tag_validation == "Fail"
        self.load_universal_checkpoint = self.checkpoint_config.load_universal
        self.use_node_local_storage = self.checkpoint_config.use_node_local_storage
        self.elasticity_enabled = bool(pd.get(ELASTICITY, {}).get("enabled", False))
        self.elasticity_config = ElasticityConfig(**pd.get(ELASTICITY, {}))
        self.hybrid_engine_config = HybridEngineConfig(**pd.get("hybrid_engine", {}))
        self.pld_config = PLDConfig(**pd.get("progressive_layer_drop", {}))
        self.pipeline_config = PipelineConfig(**pd.get(PIPELINE, {})) if isinstance(pd.get(PIPELINE, {}),
                                                                                    dict) else PipelineConfig()
        self.tpu_config = TPUConfig(**pd.get(TPU, {}))
        self.autotuning_config = pd.get(AUTOTUNING, {})

        # --- batch triad (resolved against dp size later) ---
        self.train_batch_size = pd.get(TRAIN_BATCH_SIZE, TRAIN_BATCH_SIZE_DEFAULT)
        self.train_micro_batch_size_per_gpu = pd.get(TRAIN_MICRO_BATCH_SIZE_PER_GPU,
                                                     TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT)
        self.gradient_accumulation_steps = pd.get(GRADIENT_ACCUMULATION_STEPS, GRADIENT_ACCUMULATION_STEPS_DEFAULT)
        self._batch_resolved = False

    # ------------------------------------------------------------------
    def resolve_batch_config(self, dp_world_size: int):
        """Reference ``_configure_train_batch_size``: fill in the missing leg
        of train = micro × gas × dp and validate."""
        train = self.train_batch_size
        micro = self.train_micro_batch_size_per_gpu
        gas = self.gradient_accumulation_steps

        if all(v is not None for v in (train, micro, gas)):
            pass
        elif train is not None and micro is not None:
            gas = train // (micro * dp_world_size)
        elif train is not None and gas is not None:
            micro = train // (dp_world_size * gas)
        elif micro is not None:
            gas = gas or 1
            train = micro * gas * dp_world_size
        elif train is not None:
            gas = 1
            micro = train // dp_world_size
        else:
            raise DeepSpeedConfigError(
                "Either train_batch_size or train_micro_batch_size_per_gpu needs to be provided")

        self.train_batch_size = train
        self.train_micro_batch_size_per_gpu = micro
        self.gradient_accumulation_steps = gas
        self._batch_assertion(dp_world_size)
        self._batch_resolved = True

    def _batch_assertion(self, dp_world_size):
        train = self.train_batch_size
        micro = self.train_micro_batch_size_per_gpu
        gas = self.gradient_accumulation_steps
        assert train > 0, f"Train batch size: {train} has to be greater than 0"
        assert micro > 0, f"Micro batch size per gpu: {micro} has to be greater than 0"
        assert gas > 0, f"Gradient accumulation steps: {gas} has to be greater than 0"
        assert train == micro * gas * dp_world_size, (
            f"Check batch related parameters. train_batch_size is not equal "
            f"to micro_batch_per_gpu * gradient_acc_step * world_size "
            f"{train} != {micro} * {gas} * {dp_world_size}")

    # ------------------------------------------------------------------
    def print(self, name="DeepSpeedConfig"):
        logger.info(f"{name}:")
        for k in sorted(vars(self)):
            if not k.startswith("_"):
                logger.info(f"  {k} {getattr(self, k)}")

    @property
    def param_dict(self):
        return self._param_dict
