"""Hessian max-eigenvalue estimation (power iteration).

Analog of the reference ``deepspeed/runtime/eigenvalue.py:12`` (``Eigenvalue``
— per-layer curvature estimates consumed by MoQ's eigenvalue-adaptive
quantization schedule, ``compression``/``quantize_training`` config). The
reference hand-rolls double backward through module hooks; in JAX the
Hessian-vector product is one composition — ``jax.jvp(jax.grad(loss), ...)``
— jitted once and reused across iterations. The per-layer variant passes the
layer index as a TRACED argument so all layers (and repeated calls in one
estimation sweep) share a single compiled HVP.
"""

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from ..utils.logging import log_dist


def _tree_dot(a, b):
    return sum(jnp.vdot(x, y) for x, y in
               zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)))


def _tree_scale(a, s):
    return jax.tree_util.tree_map(lambda x: x * s, a)


class Eigenvalue:
    """Power-iteration estimate of ``lambda_max(H)`` for a loss function.

    Reference-parity constructor surface (verbose/max_iter/tol/stability/
    gas_boundary_resolution/layer_name/layer_num); ``layer_name``/
    ``layer_num`` select the stacked-blocks subtree in this codebase's param
    layout instead of a torch module scope."""

    def __init__(self, verbose: bool = False, max_iter: int = 100, tol: float = 1e-2,
                 stability: float = 1e-6, gas_boundary_resolution: int = 1,
                 layer_name: str = "blocks", layer_num: int = 0):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.layer_name = layer_name
        self.layer_num = layer_num
        # single-entry (loss_fn, jitted hvp) cache: repeated sweeps with the
        # SAME function object reuse the compile; per-call lambdas replace
        # the entry instead of growing an unbounded executable/closure pile —
        # callers that rebind a batch each call should close over a stable
        # function and pass the batch through params-side state instead
        self._hvp_cache = None
        log_dist(f"enabled eigenvalue: max_iter={max_iter} tol={tol} layer_name={layer_name!r}",
                 ranks=[0])

    def nan_to_num(self, tree):
        return jax.tree_util.tree_map(jnp.nan_to_num, tree)

    def normalize(self, v):
        norm = jnp.sqrt(_tree_dot(v, v)) + self.stability
        return self.nan_to_num(_tree_scale(v, 1.0 / norm))

    def _random_like(self, template, rng):
        leaves, treedef = jax.tree_util.tree_flatten(template)
        keys = jax.random.split(rng, len(leaves))
        return jax.tree_util.tree_unflatten(
            treedef, [jax.random.normal(k, x.shape, jnp.float32) for k, x in zip(keys, leaves)])

    def _power_iterate(self, hvp, template, rng) -> float:
        """Shared loop: v <- normalize(H v), stop at max_iter or when the
        Rayleigh quotient moves < tol. Returns max(eig, 0) — reference
        semantics for the MoQ schedule, which consumes curvature magnitudes."""
        v = self.normalize(self._random_like(template, rng))
        eig = 0.0
        for i in range(self.max_iter):
            hv = hvp(v)
            new_eig = float(_tree_dot(v, hv))
            v = self.normalize(hv)
            if abs(new_eig - eig) < self.tol * max(abs(new_eig), 1.0):
                eig = new_eig
                break
            eig = new_eig
            if self.verbose:
                log_dist(f"eigenvalue iter {i}: {eig:.6f}", ranks=[0])
        return max(eig, 0.0)

    def compute_eigenvalue(self, loss_fn: Callable, params, rng: Optional[jax.Array] = None):
        """Dominant Hessian eigenvalue of ``loss_fn(params)``; the HVP
        (forward-over-reverse, no materialized H) is jitted once per
        ``loss_fn`` and reused across repeated estimation sweeps. Estimation
        runs in float32: bf16/fp16 params are upcast so jvp tangent dtypes
        match and the Rayleigh quotient keeps precision."""
        rng = jax.random.PRNGKey(0) if rng is None else rng
        params = jax.tree_util.tree_map(lambda x: jnp.asarray(x, jnp.float32), params)
        if self._hvp_cache is None or self._hvp_cache[0] is not loss_fn:
            grad_fn = jax.grad(loss_fn)
            self._hvp_cache = (loss_fn, jax.jit(lambda p, v: jax.jvp(grad_fn, (p,), (v,))[1]))
        hvp_full = self._hvp_cache[1]
        return self._power_iterate(lambda v: hvp_full(params, v), params, rng)

    def compute_layer_eigenvalues(self, loss_fn: Callable, params,
                                  rng: Optional[jax.Array] = None) -> Dict[int, float]:
        """Per-layer estimates over the stacked ``params[layer_name]``
        subtree (reference per-layer dict for MoQ's schedule). The layer
        index rides as a traced argument, so the whole sweep compiles the
        HVP exactly once."""
        # estimation runs fully in float32 (same as compute_eigenvalue): a
        # bf16 patched tree would round the tangent inside layer_loss and
        # the per-layer Rayleigh quotients lose the precision the tol needs
        params = jax.tree_util.tree_map(lambda x: jnp.asarray(x, jnp.float32), params)
        blocks = params[self.layer_name]
        depth = jax.tree_util.tree_leaves(blocks)[0].shape[0]
        if self.layer_num and not (0 < self.layer_num <= depth):
            # JAX clamps out-of-bounds indices, which would silently report
            # the LAST layer's curvature for phantom layers (and a negative
            # count would silently return {}) — refuse instead
            raise ValueError(f"layer_num={self.layer_num} must be in (0, {depth}] "
                             f"(stacked depth of params[{self.layer_name!r}])")
        L = self.layer_num or depth
        rng = jax.random.PRNGKey(0) if rng is None else rng

        def layer_loss(blk_l, l):
            patched = jax.tree_util.tree_map(
                lambda full, new: jax.lax.dynamic_update_index_in_dim(full, new, l, 0),
                blocks, blk_l)
            return loss_fn({**params, self.layer_name: patched})

        grad_fn = jax.grad(layer_loss, argnums=0)
        hvp = jax.jit(lambda blk, v, l: jax.jvp(lambda b: grad_fn(b, l), (blk,), (v,))[1])

        out = {}
        for l in range(L):
            blk = jax.tree_util.tree_map(lambda a: a[l].astype(jnp.float32), blocks)
            rng, sub_rng = jax.random.split(rng)
            out[l] = self._power_iterate(lambda v: hvp(blk, v, jnp.int32(l)), blk, sub_rng)
        return out
