"""Progressive Layer Dropping (PLD) — compressed-model training.

Analog of the reference ``deepspeed/runtime/progressive_layer_drop.py:10``
(arxiv 2010.13369): a global keep-probability schedule
``theta(t) = (1 - theta_bar) * exp(-gamma * t) + theta_bar`` driven by the
engine each step, with per-layer keep probabilities that shrink with depth.

TPU integration: the engine injects the current ``theta`` into the batch
(``pld_theta``, a traced scalar — no recompilation as it decays) and the
model's layer scan wraps each block in ``lax.cond`` so dropped layers are
genuinely skipped at runtime (TPU conditionals execute one branch), which is
where PLD's training-time saving comes from.
"""

import math

from ..utils.logging import log_dist


class ProgressiveLayerDrop:
    """Reference-parity API: ``get_state`` / ``get_theta`` / ``update_state``."""

    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0
        log_dist(f"Enabled progressive layer dropping (theta = {self.theta})", ranks=[0])

    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def get_theta(self) -> float:
        return self.current_theta

    def theta_at(self, global_step: int) -> float:
        """Side-effect-free theta for ``global_step`` (the schedule is a pure
        function of the step) — used by the prefetch worker thread, which
        must not mutate ``current_theta`` under the main thread."""
        return (1.0 - self.theta) * math.exp(-self.gamma * global_step) + self.theta

    def update_state(self, global_step: int) -> None:
        self.current_theta = self.theta_at(global_step)


def layer_keep_probs(num_layers: int, theta):
    """Per-layer keep probabilities at global keep-rate ``theta`` (traced
    scalar ok): depth-progressive — layer l keeps with
    ``1 - (l+1)/L * (1 - theta)``, so early layers are almost always kept
    and the last layer drops with probability ``1 - theta`` (paper sec 3.2's
    progressive schedule along depth)."""
    import jax.numpy as jnp

    frac = (jnp.arange(num_layers, dtype=jnp.float32) + 1.0) / num_layers
    return 1.0 - frac * (1.0 - jnp.asarray(theta, jnp.float32))
