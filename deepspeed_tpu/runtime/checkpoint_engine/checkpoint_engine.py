"""Pluggable checkpoint engine interface.

Mirrors the reference ``runtime/checkpoint_engine/checkpoint_engine.py:9``
(``CheckpointEngine`` with create/save/load/commit). Implementations:
``OrbaxCheckpointEngine`` (sharded tensorstore layout — the TPU analog of
``TorchCheckpointEngine``; with ``async_save`` it is the
``NebulaCheckpointEngine`` analog: ``save`` returns after the snapshot,
``commit`` joins the background write). Contract refinements the resilience
plane (``runtime/resilience/``) depends on:

* ``commit(tag)`` returns True ONLY when the tag is durably on disk — a
  failed/aborted save must yield False, and callers must not advertise the
  tag (``latest`` pointer, retention protection) on any other evidence;
* ``load`` raises ``resilience.CheckpointCorruptError`` on a missing or
  partial payload instead of silently returning whatever merged.
"""


class CheckpointEngine(object):

    def __init__(self, config_params=None):
        pass

    def create(self, tag):
        """Log the start of a new checkpoint (reference semantics)."""
        pass

    def makedirs(self, path, exist_ok=False):
        import os

        os.makedirs(path, exist_ok=exist_ok)

    def save(self, state_dict, path: str):
        raise NotImplementedError

    def load(self, path: str, map_location=None, template=None):
        raise NotImplementedError

    def commit(self, tag):
        """Flag a checkpoint complete (atomic-visibility point)."""
        raise NotImplementedError
