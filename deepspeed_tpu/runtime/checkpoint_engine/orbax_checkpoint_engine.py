"""Orbax/tensorstore checkpoint engine.

The TPU-native ``TorchCheckpointEngine`` equivalent: sharded arrays are
written by every host in parallel to a tensorstore layout (each host writes
its addressable shards — the same property the reference gets from per-rank
``bf16_zero_pp_rank_X...`` files, ``engine.py:3471``), and restored with
arbitrary resharding — which also subsumes the reference's universal
checkpoint reshape tooling (``deepspeed/checkpoint/ds_to_universal.py``) for
mesh-shape changes.
"""

import os
import pickle

import jax

from .checkpoint_engine import CheckpointEngine
from ...utils.logging import logger


class OrbaxCheckpointEngine(CheckpointEngine):

    def __init__(self, config_params=None, async_save=False):
        super().__init__(config_params)
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self._async = async_save
        self._ckptr = ocp.StandardCheckpointer() if not async_save else ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler())

    def create(self, tag):
        logger.info(f"[OrbaxCheckpointEngine] Checkpoint {tag} is about to be saved!")

    def save(self, state_dict, path: str):
        """Arrays go to tensorstore; non-array client state to a pickle
        sidecar (host 0 only)."""
        arrays, meta = _split_state(state_dict)
        path = os.path.abspath(path)
        if arrays:
            self._ckptr.save(os.path.join(path, "arrays"), arrays, force=True)
            if not self._async and hasattr(self._ckptr, "wait_until_finished"):
                # StandardCheckpointer finalizes in a background thread since
                # orbax 0.11 — a synchronous save contract must block here,
                # else an immediate offline read sees arrays.orbax-checkpoint-tmp
                self._ckptr.wait_until_finished()
        if jax.process_index() == 0:
            os.makedirs(path, exist_ok=True)
            with open(os.path.join(path, "meta.pkl"), "wb") as f:
                pickle.dump(meta, f)
        return None

    def load(self, path: str, map_location=None, template=None):
        """``template`` is a pytree of jax.ShapeDtypeStruct with shardings —
        restore reshards to it (topology-change-tolerant load, the analog of
        the reference's elastic checkpoint load ``stage_1_and_2.py:2275``)."""
        path = os.path.abspath(path)
        meta_path = os.path.join(path, "meta.pkl")
        meta = {}
        if os.path.exists(meta_path):
            with open(meta_path, "rb") as f:
                meta = pickle.load(f)
        arrays = {}
        arrays_path = os.path.join(path, "arrays")
        if os.path.exists(arrays_path):
            if template is not None:
                # partial restore: the template may cover a subset of the
                # on-disk tree (e.g. load_optimizer_states=False skips the
                # host optimizer subtree)
                arr_template, _ = _split_state(template)
                restore_args = self._ocp.checkpoint_utils.construct_restore_args(arr_template)
                with self._ocp.Checkpointer(self._ocp.PyTreeCheckpointHandler()) as ckptr:
                    arrays = ckptr.restore(
                        arrays_path,
                        args=self._ocp.args.PyTreeRestore(item=arr_template, restore_args=restore_args,
                                                          partial_restore=True))
            else:
                arrays = self._ckptr.restore(arrays_path)
        return _merge_state(arrays, meta)

    def commit(self, tag):
        if self._async:
            self._ckptr.wait_until_finished()
        logger.info(f"[OrbaxCheckpointEngine] Checkpoint {tag} is ready now!")
        return True


def _is_array(x):
    return hasattr(x, "shape") and hasattr(x, "dtype")


def _split_state(state):
    """Partition a nested dict into (array leaves, other leaves)."""
    arrays, meta = {}, {}
    for k, v in state.items():
        if isinstance(v, dict):
            a, m = _split_state(v)
            if a:
                arrays[k] = a
            if m:
                meta[k] = m
        elif _is_array(v):
            arrays[k] = v
        else:
            meta[k] = v
    return arrays, meta


def _merge_state(arrays, meta):
    out = dict(meta) if isinstance(meta, dict) else {}
    for k, v in (arrays or {}).items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _merge_state(v, out[k])
        else:
            out[k] = v
    return out
