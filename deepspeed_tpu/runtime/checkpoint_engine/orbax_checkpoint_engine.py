"""Orbax/tensorstore checkpoint engine.

The TPU-native ``TorchCheckpointEngine`` equivalent: sharded arrays are
written by every host in parallel to a tensorstore layout (each host writes
its addressable shards — the same property the reference gets from per-rank
``bf16_zero_pp_rank_X...`` files, ``engine.py:3471``), and restored with
arbitrary resharding — which also subsumes the reference's universal
checkpoint reshape tooling (``deepspeed/checkpoint/ds_to_universal.py``) for
mesh-shape changes.
"""

import os
import pickle

import jax
import numpy as np

from .checkpoint_engine import CheckpointEngine
from ..resilience.errors import CheckpointCorruptError
from ...utils.logging import logger


class OrbaxCheckpointEngine(CheckpointEngine):

    def __init__(self, config_params=None, async_save=False):
        super().__init__(config_params)
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self._async = async_save
        self._save_error = None  # failed save must never commit (nebula contract)
        self._ckptr = ocp.StandardCheckpointer() if not async_save else ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler())

    def create(self, tag):
        logger.info(f"[OrbaxCheckpointEngine] Checkpoint {tag} is about to be saved!")

    def save(self, state_dict, path: str):
        """Arrays go to tensorstore; non-array client state to a pickle
        sidecar (host 0 only). In async mode this returns as soon as orbax
        has snapshotted the arrays — durability is only claimed by a later
        ``commit()`` returning True (the caller must NOT advertise the tag,
        e.g. via a ``latest`` write, on any other evidence)."""
        self._save_error = None
        arrays, meta = _split_state(state_dict)
        path = os.path.abspath(path)
        try:
            if arrays:
                self._ckptr.save(os.path.join(path, "arrays"), arrays, force=True)
                if not self._async and hasattr(self._ckptr, "wait_until_finished"):
                    # StandardCheckpointer finalizes in a background thread since
                    # orbax 0.11 — a synchronous save contract must block here,
                    # else an immediate offline read sees arrays.orbax-checkpoint-tmp
                    self._ckptr.wait_until_finished()
            if jax.process_index() == 0:
                os.makedirs(path, exist_ok=True)
                with open(os.path.join(path, "meta.pkl"), "wb") as f:
                    pickle.dump(meta, f)
        except Exception as e:
            self._save_error = e
            raise
        return None

    def load(self, path: str, map_location=None, template=None):
        """``template`` is a pytree of jax.ShapeDtypeStruct with shardings —
        restore reshards to it (topology-change-tolerant load, the analog of
        the reference's elastic checkpoint load ``stage_1_and_2.py:2275``)."""
        path = os.path.abspath(path)
        meta_path = os.path.join(path, "meta.pkl")
        meta = {}
        if os.path.exists(meta_path):
            with open(meta_path, "rb") as f:
                meta = pickle.load(f)
        arrays = {}
        arrays_path = os.path.join(path, "arrays")
        expects_arrays = template is None or bool(_split_state(template)[0])
        if not os.path.exists(meta_path) and os.path.exists(arrays_path):
            # the inverse torn shape: save() always writes the meta sidecar
            # (even when empty), so arrays without it mean a crash between
            # the tensorstore finalize and the meta write. Loading it
            # silently hands back a tree with step counters/schedulers reset
            # to zero on old weights.
            raise CheckpointCorruptError(
                f"{path}: 'arrays' tree present but meta sidecar missing — partial "
                f"checkpoint (crash mid-write?); refusing to return a half-tree")
        if not os.path.exists(arrays_path):
            if expects_arrays and not meta:
                # neither payload half exists: a torn/never-committed dir (or
                # a bad path) — a silent empty merge here hands the caller a
                # half-tree that trains from garbage
                raise CheckpointCorruptError(f"{path}: no 'arrays' tree and no meta sidecar")
            if expects_arrays:
                raise CheckpointCorruptError(
                    f"{path}: 'arrays' tree missing but meta.pkl present — partial checkpoint "
                    f"(crash mid-write?); refusing to return a half-tree")
        else:
            try:
                if template is not None:
                    # partial restore, emulated against the on-disk metadata
                    # (orbax < 0.11 has no partial_restore kwarg and rejects
                    # any item tree that is not the exact saved structure):
                    # template∩disk restores through the template's
                    # ShapeDtypeStructs (sharded placement), disk-only
                    # subtrees restore as host numpy, template-only subtrees
                    # come back as their ShapeDtypeStruct placeholders (the
                    # ``_fully_restored`` contract — e.g. a non-offload
                    # checkpoint loaded into an offload engine)
                    arr_template, _ = _split_state(template)
                    with self._ocp.Checkpointer(self._ocp.PyTreeCheckpointHandler()) as ckptr:
                        item, restore_args = self._merge_item(ckptr.metadata(arrays_path),
                                                             arr_template)
                        arrays = ckptr.restore(
                            arrays_path,
                            args=self._ocp.args.PyTreeRestore(item=item, restore_args=restore_args))
                    arrays = _graft_missing(arrays, arr_template)
                else:
                    arrays = self._ckptr.restore(arrays_path)
            except CheckpointCorruptError:
                raise
            except Exception as e:
                # tensorstore surfaces torn shard files as a zoo of backend
                # errors; normalize so the fallback path has ONE type to catch
                raise CheckpointCorruptError(f"{arrays_path}: restore failed: {e}") from e
        return _merge_state(arrays, meta)

    def _merge_item(self, metadata, template):
        """Full-structure restore item + args: the saved tree's shape, with
        template leaves (and their shardings) where the template covers it."""
        item, args = {}, {}
        for k, mv in metadata.items():
            tv = template.get(k) if isinstance(template, dict) else None
            if isinstance(mv, dict):
                item[k], args[k] = self._merge_item(mv, tv if isinstance(tv, dict) else {})
            elif tv is not None and not isinstance(tv, dict):
                item[k] = tv
                args[k] = self._ocp.checkpoint_utils.construct_restore_args(tv)
            else:
                item[k] = jax.ShapeDtypeStruct(tuple(mv.shape), mv.dtype)
                args[k] = self._ocp.RestoreArgs(restore_type=np.ndarray, dtype=mv.dtype)
        return item, args

    def commit(self, tag):
        """True only when the tag is durably on disk. Async mode joins the
        background write here (decoupled from ``save``, so the step loop
        that called save already moved on); any recorded save failure makes
        this False — the caller keeps ``latest`` on the previous tag."""
        if self._async:
            try:
                self._ckptr.wait_until_finished()
            except Exception as e:
                self._save_error = self._save_error or e
        if self._save_error is not None:
            logger.error(f"[OrbaxCheckpointEngine] Checkpoint {tag} FAILED: {self._save_error!r}")
            return False
        logger.info(f"[OrbaxCheckpointEngine] Checkpoint {tag} is ready now!")
        return True


def _graft_missing(arrays, template):
    """Graft template-only subtrees (absent on disk) into the restored tree
    as their ShapeDtypeStruct placeholders."""
    if not isinstance(template, dict):
        return arrays
    out = dict(arrays) if isinstance(arrays, dict) else {}
    for k, tv in template.items():
        if k not in out:
            out[k] = tv
        elif isinstance(tv, dict) and isinstance(out[k], dict):
            out[k] = _graft_missing(out[k], tv)
    return out


def _is_array(x):
    return hasattr(x, "shape") and hasattr(x, "dtype")


def _split_state(state):
    """Partition a nested dict into (array leaves, other leaves)."""
    arrays, meta = {}, {}
    for k, v in state.items():
        if isinstance(v, dict):
            a, m = _split_state(v)
            if a:
                arrays[k] = a
            if m:
                meta[k] = m
        elif _is_array(v):
            arrays[k] = v
        else:
            meta[k] = v
    return arrays, meta


def _merge_state(arrays, meta):
    out = dict(meta) if isinstance(meta, dict) else {}
    for k, v in (arrays or {}).items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _merge_state(v, out[k])
        else:
            out[k] = v
    return out
