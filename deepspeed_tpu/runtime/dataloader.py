"""Data loaders.

Analog of the reference ``runtime/dataloader.py`` (162 LoC:
``DeepSpeedDataLoader`` with DistributedSampler defaults, ``RepeatingLoader``).
TPU-native twist: with a single-controller SPMD program each *process* loads
the shard of the global batch covering its addressable devices, so the sampler
partitions by process index rather than device rank.
"""

import math

import numpy as np


class RepeatingLoader:
    """Reference class of the same name: wraps an iterator to restart on
    StopIteration. On each restart the wrapped loader's sampler (when it
    exposes one) is advanced via ``set_epoch`` — without it every epoch
    replays the identical shuffle order, silently degrading training."""

    def __init__(self, loader):
        self.loader = loader
        self.epoch = 0
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.epoch += 1
            sampler = getattr(self.loader, "sampler", None)
            if sampler is not None and hasattr(sampler, "set_epoch"):
                # advance the sampler's OWN epoch when it exposes one, so a
                # resume's set_epoch(N) continues at N+1 instead of being
                # clobbered back to this wrapper's local count
                sampler.set_epoch(getattr(sampler, "epoch", self.epoch - 1) + 1)
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


class DistributedSampler:
    """Process-level round-robin partition of dataset indices."""

    def __init__(self, dataset_len, rank=0, world_size=1, shuffle=True, seed=0, drop_last=False):
        self.dataset_len = dataset_len
        self.rank = rank
        self.world_size = world_size
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.drop_last = drop_last
        if drop_last:
            self.num_samples = dataset_len // world_size
        else:
            self.num_samples = math.ceil(dataset_len / world_size)

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __iter__(self):
        if self.shuffle:
            g = np.random.default_rng(self.seed + self.epoch)
            indices = g.permutation(self.dataset_len)
        else:
            indices = np.arange(self.dataset_len)
        if not self.drop_last:
            pad = self.num_samples * self.world_size - len(indices)
            if pad > 0:
                indices = np.concatenate([indices, indices[:pad]])
        else:
            indices = indices[:self.num_samples * self.world_size]
        return iter(indices[self.rank::self.world_size])

    def __len__(self):
        return self.num_samples


def default_collate(samples):
    """Stack a list of samples (dicts of arrays / arrays) into a batch."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: default_collate([s[k] for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(default_collate([s[i] for s in samples]) for i in range(len(first)))
    return np.stack([np.asarray(s) for s in samples])


class DeepSpeedDataLoader:

    def __init__(self, dataset, batch_size, collate_fn=None, drop_last=False, data_parallel_rank=0,
                 data_parallel_world_size=1, shuffle=True, seed=0):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or default_collate
        self.drop_last = drop_last
        self.sampler = DistributedSampler(len(dataset), rank=data_parallel_rank,
                                          world_size=data_parallel_world_size, shuffle=shuffle, seed=seed,
                                          drop_last=drop_last)
        self.len = len(self.sampler) // batch_size if drop_last else math.ceil(len(self.sampler) / batch_size)

    def __len__(self):
        return self.len

    def __iter__(self):
        buf = []
        for idx in self.sampler:
            buf.append(self.dataset[int(idx)])
            if len(buf) == self.batch_size:
                yield self.collate_fn(buf)
                buf = []
        if buf and not self.drop_last:
            yield self.collate_fn(buf)
