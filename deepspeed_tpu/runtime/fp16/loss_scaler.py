"""Loss scaling (reference ``runtime/fp16/loss_scaler.py``, 270 LoC:
``LossScaler``/``DynamicLossScaler``).

The engine's fused path keeps the scale inside the jitted state pytree
(``engine._apply_update``); these classes are the standalone host-side API for
code that drives scaling manually — identical state machine: on overflow
halve (not below ``min_scale``) and reset the window; after ``scale_window``
consecutive good steps double.
"""

import numpy as np

INITIAL_LOSS_SCALE = "init_scale"
SCALE_WINDOW = "scale_window"
DELAYED_SHIFT = "delayed_shift"
CONSECUTIVE_HYSTERESIS = "consecutive_hysteresis"
MIN_LOSS_SCALE = "min_scale"


class LossScalerBase:

    def __init__(self, cur_scale):
        self.cur_scale = float(cur_scale)
        self.dynamic = False

    @property
    def loss_scale(self):
        return self.cur_scale

    def scale_gradient(self, module, grad_in, grad_out):
        return tuple(self.loss_scale * g for g in grad_in)

    def update_scale(self, overflow):
        pass

    def backward(self, loss, retain_graph=False):
        return loss * self.loss_scale


class LossScaler(LossScalerBase):
    """Static loss scale (reference ``LossScaler``)."""

    def __init__(self, scale=1.0):
        super().__init__(scale)

    def has_overflow(self, params):
        return False


class DynamicLossScaler(LossScalerBase):
    """Dynamic loss scale with hysteresis (reference ``DynamicLossScaler``)."""

    def __init__(self,
                 init_scale=2**32,
                 scale_factor=2.0,
                 scale_window=1000,
                 min_scale=1.0,
                 delayed_shift=1,
                 consecutive_hysteresis=False,
                 raise_error_at_min_scale=True,
                 dtype=np.float16):
        super().__init__(init_scale)
        self.cur_iter = 0
        self.last_overflow_iter = -1
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self.min_scale = min_scale
        self.delayed_shift = delayed_shift
        self.cur_hysteresis = delayed_shift
        self.consecutive_hysteresis = consecutive_hysteresis
        self.raise_error_at_min_scale = raise_error_at_min_scale
        self.dynamic = True
        self.dtype = dtype

    def has_overflow_serial(self, grads):
        for g in grads:
            a = np.asarray(g)
            if not np.isfinite(a).all():
                return True
        return False

    has_overflow = has_overflow_serial

    def update_scale(self, overflow):
        if overflow:
            if self.delayed_shift == 1 or self.cur_hysteresis == 1:
                if self.cur_scale == self.min_scale and self.raise_error_at_min_scale:
                    raise Exception("Current loss scale already at minimum - cannot decrease scale anymore.")
                self.cur_scale = max(self.cur_scale / self.scale_factor, self.min_scale)
            else:
                self.cur_hysteresis -= 1
            self.last_overflow_iter = self.cur_iter
        else:
            if self.consecutive_hysteresis:
                self.cur_hysteresis = self.delayed_shift
            if (self.cur_iter - self.last_overflow_iter) % self.scale_window == 0:
                if not self.consecutive_hysteresis:
                    self.cur_hysteresis = self.delayed_shift
                self.cur_scale *= self.scale_factor
        self.cur_iter += 1


def CreateLossScaler(dtype, static_loss_scale, dynamic_scaling, dynamic_loss_args):
    """Reference factory of the same name."""
    if dtype == np.float16 and dynamic_scaling:
        return DynamicLossScaler(dtype=dtype, **(dynamic_loss_args or {}))
    return LossScaler(scale=static_loss_scale if dtype == np.float16 else 1.0)
