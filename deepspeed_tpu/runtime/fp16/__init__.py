from .loss_scaler import LossScaler, DynamicLossScaler, CreateLossScaler
