"""0/1 Adam (reference ``runtime/fp16/onebit/zoadam.py`` ``ZeroOneAdam``).

The reference's 0/1 Adam adds adaptive variance freezing and local-step
(skipped-synchronization) schedules on top of 1-bit compression. The TPU
build keeps the compression stage (error-feedback 1-bit exchange after
``var_freeze_step``) and treats the local-step schedule as a gradient-
accumulation policy — on an ICI mesh, skipping synchronization entirely is
rarely a win because the collective rides hardware links; the freeze
threshold is honored as the compression switch-over point.
"""

from dataclasses import dataclass

from .adam import OnebitAdam


@dataclass
class ZeroOneAdam(OnebitAdam):
    var_freeze_step: int = 100000
    var_update_scaler: int = 16
    local_step_scaler: int = 32678
    local_step_clipper: int = 16

    @classmethod
    def from_params(cls, params: dict):
        base = OnebitAdam.from_params(params)
        base.freeze_step = params.get("var_freeze_step", params.get("freeze_step", 100))
        return cls(**base.__dict__,
                   var_freeze_step=params.get("var_freeze_step", 100000),
                   var_update_scaler=params.get("var_update_scaler", 16),
                   local_step_scaler=params.get("local_step_scaler", 32678),
                   local_step_clipper=params.get("local_step_clipper", 16))
