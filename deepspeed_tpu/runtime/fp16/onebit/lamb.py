"""1-bit LAMB (reference ``runtime/fp16/onebit/lamb.py``): compressed gradient
exchange + LAMB's per-layer trust-ratio update. The engine composes the 1-bit
collective with ``optax.lamb`` the way the reference composes its compressed
backend with FusedLamb."""

from dataclasses import dataclass

from .adam import OnebitAdam


@dataclass
class OnebitLamb(OnebitAdam):
    max_coeff: float = 10.0
    min_coeff: float = 0.01
    coeff_beta: float = 0.9
    factor_max: float = 4.0
    factor_min: float = 0.5
    factor_threshold: float = 0.1

    base_optimizer = "lamb"

    @classmethod
    def from_params(cls, params: dict):
        base = OnebitAdam.from_params(params)
        return cls(**base.__dict__,
                   max_coeff=params.get("max_coeff", 10.0),
                   min_coeff=params.get("min_coeff", 0.01),
                   coeff_beta=params.get("coeff_beta", 0.9))
