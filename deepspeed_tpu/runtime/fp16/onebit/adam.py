"""1-bit Adam (reference ``runtime/fp16/onebit/adam.py`` ``OnebitAdam``).

Two phases, same as the reference: a warmup of ``freeze_step`` steps with
exact (fp32) gradient averaging, then the compression stage where the
cross-data-axis gradient exchange switches to the error-feedback 1-bit
collective (``runtime/comm/compressed.py``) while Adam's variance term keeps
running on the compressed estimates.

On TPU this class is a *policy object* consumed by the engine: the compressed
exchange happens inside the jitted train step (``engine._build_onebit_train_step``)
and the parameter update itself is the optax adam chain — the reference splits
the same responsibilities between its torch optimizer subclass and the NCCL
compressed backend.
"""

from dataclasses import dataclass


@dataclass
class OnebitAdam:
    freeze_step: int = 100
    lr: float = 1e-3
    betas: tuple = (0.9, 0.999)
    eps: float = 1e-8
    weight_decay: float = 0.0
    cuda_aware: bool = False       # accepted for config parity; no-op on TPU
    comm_backend_name: str = "xla"  # reference default 'nccl'

    #: optax optimizer the engine pairs with the compressed exchange
    base_optimizer = "adam"

    @classmethod
    def from_params(cls, params: dict):
        return cls(freeze_step=params.get("freeze_step", 100),
                   lr=params.get("lr", 1e-3),
                   betas=tuple(params.get("betas", (0.9, 0.999))),
                   eps=params.get("eps", 1e-8),
                   weight_decay=params.get("weight_decay", 0.0),
                   cuda_aware=params.get("cuda_aware", False),
                   comm_backend_name=params.get("comm_backend_name", "xla"))
