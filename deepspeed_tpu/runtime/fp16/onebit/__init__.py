from .adam import OnebitAdam
from .lamb import OnebitLamb
from .zoadam import ZeroOneAdam
