"""Optimizer construction from DeepSpeed config names.

Analog of the reference ``engine.py:1275 _configure_basic_optimizer`` which
instantiates Adam/AdamW/FusedAdam/CPUAdam/Lamb/OneBit*/Lion/Adagrad by config
name. On TPU every optimizer is an optax ``GradientTransformation`` whose
update runs *inside* the compiled step — the "fused optimizer kernel" of the
reference (``csrc/adam/multi_tensor_adam.cu``) is subsumed by XLA fusing the
elementwise update chain; a Pallas fused-Adam kernel is provided in
``deepspeed_tpu.ops.adam`` for explicit control of the HBM traffic.

1-bit optimizers (reference ``runtime/fp16/onebit/*``) use error-feedback sign
compression of the gradient exchange; here the compression is applied to the
cross-data-axis gradient reduction via int8 quantized collectives
(``deepspeed_tpu.ops.pallas.quant``).
"""

from typing import Callable, Optional, Union

import jax.numpy as jnp
import optax

from .constants import (ADAM_OPTIMIZER, ADAMW_OPTIMIZER, FUSED_ADAM_OPTIMIZER, LAMB_OPTIMIZER, SGD_OPTIMIZER,
                        LION_OPTIMIZER, ADAGRAD_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER, ONEBIT_LAMB_OPTIMIZER,
                        ZERO_ONE_ADAM_OPTIMIZER)
from ..utils.logging import logger

ScalarOrSchedule = Union[float, Callable]


def _adam_args(params: dict):
    return dict(
        b1=params.get("betas", (0.9, 0.999))[0],
        b2=params.get("betas", (0.9, 0.999))[1],
        eps=params.get("eps", 1e-8),
    )


def build_optimizer(name: Optional[str],
                    params: Optional[dict] = None,
                    lr: Optional[ScalarOrSchedule] = None,
                    mu_dtype=None) -> optax.GradientTransformation:
    """Map a DeepSpeed optimizer block to an optax transformation chain."""
    params = dict(params or {})
    name = (name or ADAMW_OPTIMIZER).lower()
    learning_rate = lr if lr is not None else params.get("lr", 1e-3)
    wd = params.get("weight_decay", 0.0)

    if name in (ADAM_OPTIMIZER, FUSED_ADAM_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER):
        # DeepSpeed 'adam' honors adam_w_mode (default True) → AdamW semantics
        adam_w_mode = params.get("adam_w_mode", True)
        if name != ADAM_OPTIMIZER:
            logger.info(f"optimizer '{name}' maps to fused adam with compressed gradient reduction on TPU")
        if adam_w_mode:
            return optax.adamw(learning_rate, weight_decay=wd, mu_dtype=mu_dtype, **_adam_args(params))
        tx = optax.adam(learning_rate, mu_dtype=mu_dtype, **_adam_args(params))
        if wd:
            tx = optax.chain(optax.add_decayed_weights(wd), tx)
        return tx
    if name == ADAMW_OPTIMIZER:
        return optax.adamw(learning_rate, weight_decay=wd, mu_dtype=mu_dtype, **_adam_args(params))
    if name in (LAMB_OPTIMIZER, ONEBIT_LAMB_OPTIMIZER):
        # reference FusedLamb (csrc/lamb/fused_lamb_cuda.cu): per-layer trust ratio
        return optax.lamb(learning_rate, weight_decay=wd, **_adam_args(params))
    if name == LION_OPTIMIZER:
        betas = params.get("betas", (0.9, 0.99))
        return optax.lion(learning_rate, b1=betas[0], b2=betas[1], weight_decay=wd)
    if name == SGD_OPTIMIZER:
        tx = optax.sgd(learning_rate, momentum=params.get("momentum", 0.0), nesterov=params.get("nesterov", False))
        if wd:
            tx = optax.chain(optax.add_decayed_weights(wd), tx)
        return tx
    if name == ADAGRAD_OPTIMIZER:
        return optax.adagrad(learning_rate, eps=params.get("eps", 1e-10))
    raise ValueError(f"Unknown optimizer '{name}'")


def master_weight_wrapper(tx: optax.GradientTransformation, compute_dtype=jnp.bfloat16) -> optax.GradientTransformation:
    """fp32 master weights for bf16/fp16 params.

    The reference keeps fp32 masters inside FP16_Optimizer/BF16_Optimizer
    (``runtime/bf16_optimizer.py:30``); on TPU the idiom is: params stored
    fp32, cast to bf16 for compute (mixed-precision policy in the model), so
    the optimizer itself always sees fp32. This wrapper upcasts incoming
    grads to fp32 before the update for the case where grads arrive in bf16.
    """

    def init_fn(params):
        return tx.init(params)

    def update_fn(updates, state, params=None, **extra):
        updates = optax.tree_utils.tree_cast(updates, jnp.float32)
        return tx.update(updates, state, params, **extra)

    return optax.GradientTransformation(init_fn, update_fn)
