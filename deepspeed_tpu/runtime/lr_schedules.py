"""LR schedules.

Analog of the reference ``deepspeed/runtime/lr_schedules.py:23`` which
implements LRRangeTest / OneCycle / WarmupLR / WarmupDecayLR / WarmupCosineLR
as stateful torch schedulers. Here each schedule is a *pure function*
``step -> lr`` (jit-friendly, usable directly inside the compiled train step
via ``optax.inject_hyperparams``) wrapped in a stateful class that preserves
the reference's ``step()/get_lr()/state_dict()`` API for eager use.
"""

import math
from typing import Callable

import jax.numpy as jnp

LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
WARMUP_COSINE_LR = "WarmupCosineLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR, WARMUP_COSINE_LR]

WARMUP_LOG_RATE = "log"
WARMUP_LINEAR_RATE = "linear"


def lr_range_test_fn(lr_range_test_min_lr=1e-3,
                     lr_range_test_step_size=2000,
                     lr_range_test_step_rate=1.0,
                     lr_range_test_staircase=False,
                     **_) -> Callable:
    """Reference ``LRRangeTest`` — linearly/staircase-increasing LR probe."""

    def schedule(step):
        interval = step / lr_range_test_step_size
        if lr_range_test_staircase:
            interval = jnp.floor(interval)
        return lr_range_test_min_lr * (1.0 + interval * lr_range_test_step_rate)

    return schedule


def one_cycle_fn(cycle_min_lr=0.0,
                 cycle_max_lr=1e-3,
                 decay_lr_rate=0.0,
                 cycle_first_step_size=2000,
                 cycle_second_step_size=None,
                 cycle_first_stair_count=0,
                 cycle_second_stair_count=None,
                 decay_step_size=0,
                 **_) -> Callable:
    """Reference ``OneCycle`` (triangular up/down then decay)."""
    second = cycle_second_step_size if cycle_second_step_size is not None else cycle_first_step_size
    total_cycle = cycle_first_step_size + second

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        up_frac = jnp.clip(step / cycle_first_step_size, 0.0, 1.0)
        down_frac = jnp.clip((step - cycle_first_step_size) / second, 0.0, 1.0)
        in_cycle_lr = jnp.where(step <= cycle_first_step_size,
                                cycle_min_lr + (cycle_max_lr - cycle_min_lr) * up_frac,
                                cycle_max_lr - (cycle_max_lr - cycle_min_lr) * down_frac)
        post_steps = jnp.maximum(step - total_cycle, 0.0)
        decay = jnp.where(decay_step_size > 0, post_steps / max(decay_step_size, 1), post_steps)
        post_lr = cycle_min_lr / (1.0 + decay * decay_lr_rate) if decay_lr_rate > 0 else cycle_min_lr
        return jnp.where(step <= total_cycle, in_cycle_lr, post_lr)

    return schedule


def warmup_lr_fn(warmup_min_lr=0.0, warmup_max_lr=1e-3, warmup_num_steps=1000, warmup_type=WARMUP_LOG_RATE,
                 **_) -> Callable:
    """Reference ``WarmupLR`` — warmup then hold."""
    warmup_num_steps = max(2, warmup_num_steps)
    inverse_log_warm_up = 1.0 / math.log(warmup_num_steps)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        if warmup_type == WARMUP_LOG_RATE:
            gamma = inverse_log_warm_up * jnp.log(jnp.maximum(step, 1.0))
        else:
            gamma = step / warmup_num_steps
        gamma = jnp.clip(gamma, 0.0, 1.0)
        return jnp.where(step < warmup_num_steps, warmup_min_lr + (warmup_max_lr - warmup_min_lr) * gamma,
                         warmup_max_lr)

    return schedule


def warmup_decay_lr_fn(total_num_steps,
                       warmup_min_lr=0.0,
                       warmup_max_lr=1e-3,
                       warmup_num_steps=1000,
                       warmup_type=WARMUP_LOG_RATE,
                       **_) -> Callable:
    """Reference ``WarmupDecayLR`` — warmup then linear decay to 0."""
    warm = warmup_lr_fn(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)
    warmup_num_steps_c = max(2, warmup_num_steps)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        decay = jnp.clip((total_num_steps - step) / max(1.0, total_num_steps - warmup_num_steps_c), 0.0, 1.0)
        return jnp.where(step < warmup_num_steps_c, warm(step), warmup_max_lr * decay)

    return schedule


def warmup_cosine_lr_fn(total_num_steps,
                        warmup_min_ratio=0.0,
                        cos_min_ratio=1e-4,
                        warmup_num_steps=1000,
                        warmup_type=WARMUP_LINEAR_RATE,
                        lr=1e-3,
                        **_) -> Callable:
    """Reference ``WarmupCosineLR`` — ratio-based warmup then cosine decay."""
    warmup_num_steps = max(2, warmup_num_steps)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        if warmup_type == WARMUP_LOG_RATE:
            gamma = jnp.log(jnp.maximum(step, 1.0)) / math.log(warmup_num_steps)
        else:
            gamma = step / warmup_num_steps
        warm_ratio = warmup_min_ratio + (1.0 - warmup_min_ratio) * jnp.clip(gamma, 0.0, 1.0)
        progress = jnp.clip((step - warmup_num_steps) / max(1.0, total_num_steps - warmup_num_steps), 0.0, 1.0)
        cos_ratio = cos_min_ratio + (1.0 - cos_min_ratio) * 0.5 * (1.0 + jnp.cos(math.pi * progress))
        return lr * jnp.where(step < warmup_num_steps, warm_ratio, cos_ratio)

    return schedule


SCHEDULE_FNS = {
    LR_RANGE_TEST: lr_range_test_fn,
    ONE_CYCLE: one_cycle_fn,
    WARMUP_LR: warmup_lr_fn,
    WARMUP_DECAY_LR: warmup_decay_lr_fn,
    WARMUP_COSINE_LR: warmup_cosine_lr_fn,
}


def get_lr_schedule_fn(name: str, params: dict, base_lr: float = 1e-3) -> Callable:
    """Build a pure ``step -> lr`` schedule from a DeepSpeed scheduler block."""
    if name not in SCHEDULE_FNS:
        raise ValueError(f"unknown lr schedule {name}; valid: {VALID_LR_SCHEDULES}")
    params = dict(params)
    if name == WARMUP_COSINE_LR:
        params.setdefault("lr", base_lr)
    return SCHEDULE_FNS[name](**params)


class LRScheduler:
    """Stateful wrapper preserving the reference scheduler API
    (``step()``, ``get_lr()``, ``get_last_lr()``, ``state_dict()``)."""

    def __init__(self, schedule_fn: Callable, last_batch_iteration: int = -1):
        self.schedule_fn = schedule_fn
        self.last_batch_iteration = last_batch_iteration

    def step(self, last_batch_iteration=None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration
        return self.get_lr()

    def get_lr(self):
        return [float(self.schedule_fn(max(0, self.last_batch_iteration)))]

    def get_last_lr(self):
        return self.get_lr()

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]
