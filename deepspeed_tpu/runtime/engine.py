"""DeepSpeedEngine — the training orchestrator.

TPU-native analog of the reference ``deepspeed/runtime/engine.py:175``
(``DeepSpeedEngine(torch.nn.Module)``, 3,606 LoC: ``forward:1809``,
``backward:1950``, ``step:2152``, ``save_checkpoint:3069``,
``load_checkpoint:2721``). Design (SURVEY.md §7 "hard parts" #5): the
reference's eager-looking ``forward/backward/step`` contract is preserved as a
thin stateful wrapper over a *functional, fully-jitted* core:

  * ``_train_step_fn``: (state, batch, rng) -> (state, metrics) — fused
    fwd+bwd+clip+update, with gradient accumulation as a ``lax.scan`` over
    microbatches. All ZeRO collectives are XLA-inserted from the sharding
    annotations computed by ``ZeroShardingPolicy`` (see zero/partition.py).
  * ``forward``/``backward``/``step``: the 3-call eager API accumulates
    gradients into a sharded buffer and applies the update at the GAS
    boundary — bitwise the same math, for drop-in DeepSpeed ergonomics.

State lives in one donated pytree (params / opt_state / step / loss-scale),
so each step updates HBM in place — the analog of the reference's fused
multi-tensor optimizer applying updates without extra copies.
"""

import os
import time
from functools import partial
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from .config import DeepSpeedConfig
from .data_pipeline.prefetch import DeviceBatch
from .lr_schedules import get_lr_schedule_fn, LRScheduler
from .optimizers import build_optimizer
from .zero.partition import ZeroShardingPolicy, PartitionRules, constrain
from ..accelerator import get_accelerator
from ..comm import comm as dist
from ..monitor.monitor import MonitorMaster
from ..monitor.trace import configure_tracer, get_tracer
from ..monitor.metrics import get_metrics, compute_mfu
from ..monitor.health import get_health
from ..monitor.goodput import configure_goodput, get_goodput
from ..monitor.roofline import configure_roofline, get_capture_manager, get_roofline
from ..parallel import groups
from ..parallel.mesh import (BATCH_AXES, DATA_AXIS, DATA_REPL_AXIS, SEQ_AXIS, MeshConfig, build_mesh,
                             shard_map_compat)
from ..utils.logging import logger, log_dist
from ..utils.timer import (SynchronizedWallClockTimer, NoopTimer, ThroughputTimer, FORWARD_GLOBAL_TIMER,
                           BACKWARD_GLOBAL_TIMER, STEP_GLOBAL_TIMER)

# reference `latest` tag file semantics; the pointer itself is only ever
# WRITTEN by the resilience saver (tools/check_ckpt_commit.py gate)
from .resilience import chaos  # noqa: E402
from .resilience.saver import LATEST_FILE  # noqa: E402


class EngineTimers:
    """Reference ``engine.py:140`` — micro/global timer split."""

    def __init__(self, enable_micro_timers, enable_global_timers):
        self.timers = SynchronizedWallClockTimer() if (enable_micro_timers or enable_global_timers) else NoopTimer()
        self.enabled = enable_micro_timers or enable_global_timers


class DeepSpeedEngine:

    def __init__(self,
                 model,
                 config: DeepSpeedConfig,
                 optimizer: Optional[optax.GradientTransformation] = None,
                 lr_scheduler=None,
                 mesh=None,
                 example_batch=None,
                 training_data=None,
                 collate_fn=None,
                 dont_change_device=False,
                 seed: int = 42):
        self.module = model
        self.config = config
        self.client_optimizer = optimizer
        self.client_lr_scheduler = lr_scheduler
        self.training_dataloader = None
        self.global_steps = 0
        self.global_samples = 0
        self.micro_steps = 0
        self.skipped_steps = 0
        self._step_metrics = {}
        self._grad_acc_buffer = None
        self._pending_batches = []
        self._compiled = {}
        self._train_mode = True
        self._prefetchers = []  # DevicePrefetchIterators built by this engine
        self._sharding_cache = {}  # (ndim, n_leading) -> NamedSharding (batch placement)

        # --- distributed bring-up (reference __init__.py:133 init_distributed) ---
        if not dist.is_initialized():
            dist.init_distributed(dist_backend=get_accelerator().communication_backend_name())

        # --- mesh: single source of truth for all parallel dims ---
        mics = config.zero_config.mics_shard_size
        # ZeRO++ flags (reference engine.py:858 consumption of
        # zero_quantized_weights / zero_quantized_gradients, groups.py:505 hpZ)
        zcfg = config.zero_config
        hpz = zcfg.zero_hpz_partition_size or 0
        self._qwz = bool(zcfg.zero_quantized_weights)
        self._qgz = bool(zcfg.zero_quantized_gradients)
        self._hpz = hpz if hpz > 1 else 0
        if self._qwz or self._qgz or self._hpz:
            if config.zero_optimization_stage != 3:
                raise ValueError("ZeRO++ (zero_quantized_weights / zero_quantized_gradients / "
                                 "zero_hpz_partition_size) requires zero stage 3, got "
                                 f"stage {config.zero_optimization_stage}")
            if mics and mics > 0:
                raise ValueError("ZeRO++ and MiCS both split the data axis; enable one or the other")
        if self._qgz and not self._hpz:
            raise ValueError(
                "zero_quantized_gradients on TPU rides the hpZ two-level reduction (intra-group "
                "reduce is compiler-scheduled fp32 over nearest ICI, the inter-group hop is int8): "
                "set zero_hpz_partition_size > 1 as well")
        if (self._qwz or self._qgz or self._hpz) and config.zero_config.offload_optimizer is not None \
                and str(config.zero_config.offload_optimizer_device) != "none":
            raise ValueError("ZeRO++ does not compose with offload_optimizer yet")
        # MiCS and hpZ both split the data axis into (data_repl, data); they
        # differ in where the optimizer states live (MiCS: inner axis only;
        # hpZ: full extent, with a per-step secondary gather)
        inner_split = mics if (mics and mics > 0) else self._hpz
        if mesh is not None:
            self.mesh = groups.set_mesh(mesh, ep_size=getattr(config.tpu_config, "expert", 1))
        elif groups.is_initialized():
            self.mesh = groups.get_mesh()
        else:
            mc = config.tpu_config.mesh_config()
            if inner_split:
                # MiCS (reference runtime/zero/mics.py) / ZeRO++ hpZ (reference
                # groups.py:505): split the data axis into (replica, shard)
                import jax as _jax

                sizes = mc.resolve(len(_jax.devices()))
                dp = sizes[DATA_AXIS] * sizes.get(DATA_REPL_AXIS, 1)
                if dp % inner_split != 0:
                    which = "mics_shard_size" if mics and mics > 0 else "zero_hpz_partition_size"
                    raise ValueError(f"{which}={inner_split} must divide the data-parallel size {dp}")
                mc.data, mc.data_repl = inner_split, dp // inner_split
            self.mesh = groups.initialize_mesh(mc)
        if inner_split and self.mesh.shape.get(DATA_AXIS, 1) != inner_split:
            which = "mics_shard_size" if mics and mics > 0 else "zero_hpz_partition_size"
            raise ValueError(f"{which}={inner_split} requires the mesh 'data' axis to equal it "
                             f"(got {dict(self.mesh.shape)}); with an externally-built mesh, size the "
                             f"'data'/'data_repl' axes accordingly")
        self._hpz_degraded = False
        if self._hpz and self.mesh.shape.get(DATA_REPL_AXIS, 1) <= 1:
            logger.warning(f"zero_hpz_partition_size={hpz} covers the whole data-parallel extent: "
                           "hpZ has no secondary hop and degrades to plain ZeRO-3 (choose a "
                           "partition size smaller than the data-parallel size)"
                           + ("; zero_quantized_gradients is a no-op too (there is no inter-group "
                              "hop to quantize)" if self._qgz else ""))
            self._hpz = 0
            self._qgz = False
            self._hpz_degraded = True
        config.mesh = self.mesh

        # ZeRO shards over (data, seq) when SP is on, but the *batch* triad is
        # governed by the pure data axis — SP ranks share samples and split the
        # sequence dim (reference distinguishes dp vs seq_dp groups the same
        # way, engine.py:1143-1156).
        self.dp_world_size = groups.get_data_parallel_world_size()
        self.mp_world_size = groups.get_model_parallel_world_size()
        self.seq_world_size = groups.get_sequence_parallel_world_size()
        self.pipe_world_size = groups.get_pipe_parallel_world_size()
        self.batch_dp_world_size = (self.mesh.shape.get(DATA_AXIS, 1)
                                    * self.mesh.shape.get(DATA_REPL_AXIS, 1))
        config.resolve_batch_config(self.batch_dp_world_size)
        if self.pipe_world_size > 1:
            # same constraint as the reference: PP composes with ZeRO<=1
            # (PipelineEngine asserts zero stage < 2)
            assert config.zero_optimization_stage <= 1, "pipeline parallelism requires ZeRO stage <= 1"
            assert hasattr(model, "pipeline_loss"), "model must provide pipeline_loss for pipeline parallelism"
            assert self.seq_world_size == 1, "pipeline + sequence parallel composition not supported yet"
            self._pipe_schedule = getattr(config.pipeline_config, "schedule", "1f1b")
            import inspect

            try:
                model_takes_schedule = "schedule" in inspect.signature(model.pipeline_loss).parameters
            except (TypeError, ValueError):
                model_takes_schedule = False
            self._model_takes_schedule = model_takes_schedule
            # both pipeline executors' shard_maps are manual over 'pipe' only
            # (since r5 for GPipe), so TP/DP compose by GSPMD propagation
            # (reference PipeModelDataParallelTopology, pipe/topology.py:244).
            # A model whose pipeline_loss does not accept the schedule kwarg
            # runs its own (legacy) pipeline and gets no TP allowance.
            if not model_takes_schedule:
                assert self.mp_world_size == 1, \
                    "pipeline + tensor parallel needs a model whose pipeline_loss accepts " \
                    "the schedule kwarg (both built-in schedules support TP)"

        # --- precision policy ---
        self.compute_dtype = (jnp.bfloat16 if config.bfloat16_enabled else
                              (jnp.float16 if config.fp16_enabled else jnp.float32))
        self.fp16_enabled = config.fp16_enabled
        self.bfloat16_enabled = config.bfloat16_enabled
        self.dynamic_loss_scale = self.fp16_enabled and config.loss_scale == 0

        # --- ZeRO sharding policy ---
        rules = model.partition_rules() if hasattr(model, "partition_rules") else PartitionRules()
        mics = config.zero_config.mics_shard_size
        self.zero_policy = ZeroShardingPolicy(self.mesh, stage=config.zero_optimization_stage, tp_rules=rules,
                                              mics_shard_size=mics, hpz_partition_size=self._hpz)
        self.zero_enabled = config.zero_enabled
        # qwZ without hpZ: the per-layer stage-3 weight gathers themselves
        # go int8 — this needs the model to route its weight views through
        # quantized_gather_ste (reference quantizes inside the all-gather
        # handle, partition_parameters.py:1139; here the model's forward
        # is where the gathers live, so the hook is a model config flag).
        # The flag is SYNCED (set or cleared) so a model object reused across
        # engines does not leak one engine's qwZ mode into the next.
        wants_model_qwz = self._qwz and not self._hpz
        mcfg = getattr(self.module, "config", None)
        if mcfg is not None and hasattr(mcfg, "quantized_weights"):
            mcfg.quantized_weights = wants_model_qwz
        elif wants_model_qwz:
            hint = ("zero_hpz_partition_size was set but covers the whole data-parallel extent "
                    "(degraded to plain ZeRO-3); choose a partition size smaller than the "
                    "data-parallel size" if self._hpz_degraded else
                    "either use such a model or also set zero_hpz_partition_size to quantize "
                    "the inter-group secondary gather instead")
            raise ValueError(
                "zero_quantized_weights without an effective zero_hpz_partition_size needs a "
                "model that supports quantized weight gathers (a config.quantized_weights flag, "
                f"like models.transformer.TransformerLM); {hint}")
        if wants_model_qwz:
            log_dist("ZeRO++ qwZ: per-layer weight gathers quantized to int8 (model-level)", ranks=[0])
        # Explicit ZeRO-3 gather/compute overlap: an EXPLICIT
        # zero_optimization.overlap_comm=true in the user's JSON makes the
        # scan double-buffer next-layer param gathers (transformer.py). The
        # zero-config default (True at stage 3, reference parity) keeps the
        # legacy implicit XLA overlap — flipping every stage-3 run's schedule
        # silently would change memory behavior without consent. Mutually
        # exclusive with qwZ/hpZ, which own their own gather paths. Synced
        # (set or cleared) like quantized_weights above.
        raw_overlap = (config.param_dict.get("zero_optimization") or {}).get("overlap_comm")
        if raw_overlap is True and config.zero_optimization_stage != 3:
            # reference overlap_comm is primarily a stage-1/2 grad-reduction
            # knob; on TPU that overlap is XLA-scheduled — say so instead of
            # silently ignoring a ported config's setting
            logger.warning(f"zero_optimization.overlap_comm=true at stage "
                           f"{config.zero_optimization_stage}: gradient-reduction overlap is "
                           "XLA-scheduled on TPU; the explicit gather schedule applies at "
                           "stage 3 only — knob has no effect here")
        if raw_overlap is True and config.zero_optimization_stage == 3 \
                and (wants_model_qwz or self._hpz):
            logger.warning("zero_optimization.overlap_comm=true: ZeRO++ "
                           f"({'qwZ' if wants_model_qwz else 'hpZ'}) owns its own gather "
                           "schedule — the explicit double-buffered overlap is disabled")
        wants_overlap = (config.zero_optimization_stage == 3 and raw_overlap is True
                         and not wants_model_qwz and not self._hpz)
        if mcfg is not None and hasattr(mcfg, "overlap_gather"):
            mcfg.overlap_gather = wants_overlap
        elif wants_overlap:
            logger.warning("zero_optimization.overlap_comm=true: model has no overlap_gather "
                           "flag; keeping XLA's implicit latency-hiding overlap")
            wants_overlap = False
        if wants_overlap:
            log_dist("ZeRO-3 overlap_comm: explicit double-buffered next-layer param "
                     "all-gather schedule enabled", ranks=[0])
        if self._hpz:
            log_dist(f"ZeRO++ hpZ: secondary weight shard over the {self.mesh.shape[DATA_AXIS]}-wide "
                     f"'data' group, {self.mesh.shape.get(DATA_REPL_AXIS, 1)} groups"
                     + ("; qwZ int8 secondary gather" if self._qwz else "")
                     + ("; qgZ int8 inter-group gradient reduce" if self._qgz else ""), ranks=[0])

        # --- optimizer chain ---
        self.lr_schedule_fn, self.lr_scheduler = self._configure_lr_scheduler(lr_scheduler)
        # ZeRO-Offload / Infinity: optimizer states leave HBM for host RAM /
        # NVMe; the update runs in the fused C++ host kernel (zero/offload.py)
        offload_cfg = config.zero_config.offload_optimizer
        self._offload_enabled = (offload_cfg is not None
                                 and str(config.zero_config.offload_optimizer_device) != "none")
        # Twin-flow partial offload (reference ZeRO-Offload++ `ratio`,
        # blogs/deepspeed-offloadpp): ratio < 1 keeps (1-ratio) of the
        # optimizer-state bytes on device — that slice updates in HBM,
        # overlapping the host C++ Adam on the rest (zero/offload.py)
        self._offload_ratio = float(offload_cfg.ratio) if self._offload_enabled else 1.0
        self._twin_mask = None  # set in _init_state when ratio < 1
        if self._offload_enabled and self._offload_ratio <= 0.0:
            logger.warning("offload_optimizer.ratio=0: nothing to offload — "
                           "running the plain device optimizer")
            self._offload_enabled = False
            self._offload_ratio = 1.0
        if self._offload_enabled and config.tpu_config.abstract_init:
            # the host optimizer materializes masters from real device arrays
            raise ValueError("tpu.abstract_init (compile-only validation) does not compose "
                             "with offload_optimizer: the host optimizer needs materialized "
                             "params. Validate the non-offload shape of the config instead.")
        self.optimizer = self._configure_optimizer(optimizer)
        # twin-flow device-slice optimizer: the bare tx WITHOUT the optax
        # clip link — clipping must use the GLOBAL grad norm (host-computed
        # over all leaves), folded into the scale factor at update time; the
        # chain's clip link would re-clip by the device-subtree norm
        self._twin_tx = None
        if self._offload_enabled and self._offload_ratio < 1.0:
            from .constants import ADAM_OPTIMIZER, ADAMW_OPTIMIZER, FUSED_ADAM_OPTIMIZER

            name = (self.config.optimizer_name or ADAMW_OPTIMIZER).lower()
            if optimizer is not None or name not in (ADAM_OPTIMIZER, ADAMW_OPTIMIZER,
                                                     FUSED_ADAM_OPTIMIZER):
                # the host slice always runs the fused CPU Adam; a different
                # device-slice rule would train halves of the model under
                # different optimizers — reject rather than silently diverge
                raise ValueError(
                    "offload_optimizer.ratio < 1 (twin-flow) requires an Adam/AdamW config "
                    f"optimizer (both slices must share the update rule); got "
                    f"{'a client optimizer object' if optimizer is not None else repr(name)}. "
                    "Use ratio=1.0 (full offload) or switch the optimizer.")
            p = dict(self.config.optimizer_params or {})
            lr = self.lr_schedule_fn if self.lr_schedule_fn is not None else p.get("lr", 1e-3)
            self._twin_tx = build_optimizer(self.config.optimizer_name, p, lr=lr)

        # 1-bit optimizers: compressed gradient exchange after freeze_step
        # (reference runtime/fp16/onebit/* + comm/nccl.py compressed_allreduce)
        self._onebit = self._configure_onebit()

        # Pallas fused Adam(W): single-pass update kernel with overflow gate
        # and clip folded in (reference csrc/adam/multi_tensor_adam.cu)
        self._pallas_adam = self._configure_pallas_adam(optimizer, example_batch)

        # --- state init, sharded at construction (zero.Init equivalent:
        #     params materialize directly into their shards, reference
        #     partition_parameters.py:762) ---
        self._rng = jax.random.PRNGKey(seed)
        self.state = self._init_state(example_batch)
        # HBM attribution ledger (monitor/memory.py): params + optimizer/ZeRO
        # shard bytes enter the process-wide decomposition hbm_report()
        # serves (weakly owned; destroy() unregisters explicitly)
        from ..monitor.memory import get_memory

        self._memory_reg_name = f"train_engine-{id(self)}"
        get_memory().register(self._memory_reg_name,
                              lambda eng: eng._memory_sections(), self)

        # --- host offload optimizer (after state init: needs the params) ---
        self.host_optimizer = None
        if self._offload_enabled:
            self.host_optimizer = self._configure_host_offload_optimizer(offload_cfg)

        # --- data pipeline ---
        if training_data is not None:
            self.training_dataloader = self.deepspeed_io(training_data, collate_fn=collate_fn)

        # --- data efficiency: curriculum learning + random-LTD (reference
        #     engine.py:1848-1854 curriculum/random-LTD updates) ---
        self.curriculum_scheduler = None
        cl_cfg = config.curriculum_learning_config
        de_cl = config.data_efficiency_config.data_sampling.curriculum_learning
        if cl_cfg.enabled or (config.data_efficiency_config.enabled and de_cl.enabled):
            from .data_pipeline.curriculum_scheduler import CurriculumScheduler

            self.curriculum_scheduler = CurriculumScheduler(cl_cfg if cl_cfg.enabled else de_cl)
        self._data_post_process_func = None
        self.random_ltd_scheduler = None
        rl_cfg = config.data_efficiency_config.data_routing
        if config.data_efficiency_config.enabled and rl_cfg.enabled and rl_cfg.random_ltd.enabled:
            from .data_pipeline.data_routing.random_ltd import RandomLTDScheduler

            self.random_ltd_scheduler = RandomLTDScheduler(rl_cfg.random_ltd)
        self.progressive_layer_drop = None
        if config.pld_config.enabled:
            if self.pipe_world_size > 1:
                # silent no-op would be worse: pipeline_loss_fn runs every
                # stage's layers unconditionally and never sees pld_theta
                raise NotImplementedError(
                    "progressive_layer_drop does not compose with pipeline parallelism "
                    "(the compiled stage executors run all layers); disable one of them")
            from .progressive_layer_drop import ProgressiveLayerDrop

            self.progressive_layer_drop = ProgressiveLayerDrop(theta=config.pld_config.theta,
                                                               gamma=config.pld_config.gamma)
        if config.sparse_gradients_enabled:
            # accepted for config compatibility; under XLA embedding grads
            # already lower to fused dense scatter-adds, so there is no
            # torch-style sparse-gradient fast path to switch on
            log_dist("sparse_gradients: no-op on TPU (XLA lowers embedding grads to fused "
                     "scatter-adds); flag accepted for config compatibility", ranks=[0])

        # --- aux subsystems ---
        self.monitor = MonitorMaster(config.monitor_config)
        # unified span/metrics bus (monitor/trace.py + monitor/metrics.py):
        # config-gated; with the block absent the step loop pays one boolean
        # check and makes zero trace-related allocations
        if config.monitor_config.trace.enabled:
            configure_tracer(config=config.monitor_config.trace)
        self._tracer = get_tracer()
        self._metrics = get_metrics()
        if (self.monitor.enabled or config.monitor_config.trace.enabled) and not self._metrics.enabled:
            self._metrics.enable()
        self._tracing = False  # device trace capture state (start/stop_device_trace)
        self.engine_timers = EngineTimers(enable_micro_timers=config.wall_clock_breakdown,
                                          enable_global_timers=config.wall_clock_breakdown)
        self.tput_timer = ThroughputTimer(config=None, batch_size=self.train_batch_size(),
                                          steps_per_output=config.steps_per_print)
        from .checkpoint_engine.orbax_checkpoint_engine import OrbaxCheckpointEngine

        self.checkpoint_engine = OrbaxCheckpointEngine(async_save=config.checkpoint_config.async_save)
        # resilience plane: bounded background writer + manifest-gated
        # `latest`, retention GC, auto-save cadence, preemption trap
        from .resilience import AutoSaveTrigger, PreemptionHandler, ResilientSaver

        ckpt_cfg = config.checkpoint_config
        self._ckpt_saver = ResilientSaver(self.checkpoint_engine,
                                          retention=ckpt_cfg.num_of_version_in_retention,
                                          keep_every_n_steps=ckpt_cfg.keep_every_n_steps,
                                          is_lead=dist.get_rank() == 0,
                                          digests=ckpt_cfg.manifest_digests)
        self._auto_save = AutoSaveTrigger(
            save_interval_steps=ckpt_cfg.save_interval_steps,
            persistent_time_interval=(config.nebula_config.persistent_time_interval
                                      if config.nebula_config.enabled else 0))
        self._ckpt_save_dir = ckpt_cfg.auto_save_dir
        self._preemption = None
        if ckpt_cfg.preemption_save:
            try:
                self._preemption = PreemptionHandler().install()
            except ValueError:
                # signal.signal off the main thread — run preemption-less
                logger.warning("preemption_save: not on the main thread, SIGTERM trap disabled")
        self._resilience_active = (self._preemption is not None
                                   or (self._auto_save.enabled and self._ckpt_save_dir is not None))
        # live-health plane (monitor/health.py): flight recorder + stall
        # watchdog + telemetry exporter, all off by default — when the
        # `health` block is absent the step loop pays one boolean check
        self._health = get_health()
        self._last_step_wall_ms = 0.0
        self._last_input_wait_ms = 0.0
        self._hb_prev_step_t = None
        if config.monitor_config.health.enabled:
            self._health.configure(config=config.monitor_config.health)
            self._health.set_state_provider(
                "engine", lambda: {"step": self.global_steps,
                                   "samples": self.global_samples,
                                   "skipped_steps": self.skipped_steps,
                                   "last_step_wall_ms": round(self._last_step_wall_ms, 3),
                                   "last_input_wait_ms": round(self._last_input_wait_ms, 3)})
            self._health.set_state_provider("saver", self._ckpt_saver.health_state)
            # arm the engine source NOW: a run that wedges inside its very
            # first train_batch (the jit-traced collective class the
            # in-flight registry deliberately can't see) must still trip
            # deadline_train_step_s — a slow first compile past the deadline
            # costs one latched dump, not a kill
            self._health.beat("engine")
        # goodput ledger (monitor/goodput.py): wall-clock attribution +
        # recompile sentinel. The plane is process-global (the training
        # ledger spans resilient restarts); this engine attaches when the
        # config block arms it OR the plane was armed externally (chaos
        # drill, bench). Absent: one `is not None` check per step.
        self._goodput = None
        self._gp_warm_declared = False
        if config.monitor_config.goodput.enabled:
            configure_goodput(config=config.monitor_config.goodput)
        _gp = get_goodput()
        if _gp.enabled:
            self._goodput = _gp.training
        # roofline plane (monitor/roofline.py): executable-cost registry +
        # per-bucket verdicts. Absent block: the singleton stays disabled and
        # the compile site / step boundary pay one `enabled` check each.
        if config.monitor_config.roofline.enabled:
            configure_roofline(config=config.monitor_config.roofline)
        if config.flops_profiler_config.enabled:
            from ..profiling.flops_profiler import FlopsProfiler

            self.flops_profiler = FlopsProfiler(self)
        # async input pipeline: with the config block on, the engine-built
        # dataloader is wrapped LAZILY — the worker starts on first next(),
        # so load_checkpoint / set_data_post_process_func calls between
        # initialize() and the training loop are honored by every batch
        if (self.training_dataloader is not None
                and config.data_pipeline_config.prefetch.enabled):
            from .data_pipeline.prefetch import LazyPrefetchingLoader

            self.training_dataloader = LazyPrefetchingLoader(
                self.prefetching_loader, self.training_dataloader,
                gas=lambda: self.config.gradient_accumulation_steps)
        log_dist(
            f"DeepSpeedEngine ready: zero_stage={config.zero_optimization_stage} "
            f"dtype={self.compute_dtype.__name__} mesh={dict(self.mesh.shape)} "
            f"micro_bsz={config.train_micro_batch_size_per_gpu} gas={config.gradient_accumulation_steps}",
            ranks=[0])

    # ------------------------------------------------------------------
    # configuration
    # ------------------------------------------------------------------
    def _configure_lr_scheduler(self, client_scheduler):
        """Reference ``engine.py:911``: client scheduler wins, else config."""
        if client_scheduler is not None:
            if callable(client_scheduler) and not isinstance(client_scheduler, LRScheduler):
                return client_scheduler, LRScheduler(client_scheduler)
            return client_scheduler.schedule_fn, client_scheduler
        name = self.config.scheduler_name
        if name is not None:
            base_lr = (self.config.optimizer_params or {}).get("lr", 1e-3)
            fn = get_lr_schedule_fn(name, self.config.scheduler_params or {}, base_lr=base_lr)
            return fn, LRScheduler(fn)
        return None, None

    def _configure_optimizer(self, client_optimizer):
        """Reference ``engine.py:1227``: wrap client optimizer or build from
        config; grad clipping composes in front (clip-by-global-norm is the
        reference's ``unscale_and_clip_grads`` stage_1_and_2.py:1955)."""
        if client_optimizer is not None:
            tx = client_optimizer
        else:
            params = dict(self.config.optimizer_params or {})
            lr = self.lr_schedule_fn if self.lr_schedule_fn is not None else params.get("lr", 1e-3)
            tx = build_optimizer(self.config.optimizer_name, params, lr=lr)
        chain = []
        if self.config.gradient_clipping and self.config.gradient_clipping > 0:
            chain.append(optax.clip_by_global_norm(self.config.gradient_clipping))
        chain.append(tx)
        return optax.chain(*chain) if len(chain) > 1 else tx

    def _configure_onebit(self):
        from .constants import ONEBIT_ADAM_OPTIMIZER, ONEBIT_LAMB_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER

        name = (self.config.optimizer_name or "").lower()
        if name not in (ONEBIT_ADAM_OPTIMIZER, ONEBIT_LAMB_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER):
            return None
        assert not self.config.zero_config.mics_shard_size or self.config.zero_config.mics_shard_size <= 0, \
            "1-bit optimizers compose with plain DP, not MiCS (their compressed exchange runs over the data axis only)"
        from .fp16.onebit import OnebitAdam, OnebitLamb, ZeroOneAdam

        cls = {ONEBIT_ADAM_OPTIMIZER: OnebitAdam, ONEBIT_LAMB_OPTIMIZER: OnebitLamb,
               ZERO_ONE_ADAM_OPTIMIZER: ZeroOneAdam}[name]
        policy = cls.from_params(self.config.optimizer_params or {})
        # same envelope as the reference: 1-bit composes with ZeRO<=1, pure DP
        assert self.config.zero_optimization_stage <= 1, "1-bit optimizers require ZeRO stage <= 1"
        assert self.mp_world_size == 1 and self.seq_world_size == 1 and self.pipe_world_size == 1, \
            "1-bit optimizers support pure data parallelism only"
        assert not self._offload_enabled, "1-bit optimizers are incompatible with offload_optimizer"
        log_dist(f"1-bit optimizer '{name}': exact allreduce for {policy.freeze_step} warmup steps, "
                 f"then error-feedback sign compression", ranks=[0])
        return policy

    def _configure_pallas_adam(self, client_optimizer, example_batch):
        """Engage the Pallas fused Adam(W) step when the config maps to plain
        Adam/AdamW on fp32 masters: one HBM pass over (grad, param, m, v)
        with the overflow gate, loss un-scaling, and global-norm clipping
        folded in as scalars — the optax chain costs extra full passes for
        the finite-check and the overflow where-selects. Returns the kernel
        hyperparams dict or None; on engage, swaps ``self.optimizer`` for the
        FusedAdamState-structured transformation (same math, used only for
        state init)."""
        from .constants import ADAM_OPTIMIZER, ADAMW_OPTIMIZER, FUSED_ADAM_OPTIMIZER

        mode = getattr(self.config.tpu_config, "pallas_fused_adam", "auto")
        if (mode == "never" or client_optimizer is not None or self._offload_enabled
                or self._onebit is not None):
            return None
        name = (self.config.optimizer_name or ADAMW_OPTIMIZER).lower()
        if name not in (ADAM_OPTIMIZER, ADAMW_OPTIMIZER, FUSED_ADAM_OPTIMIZER):
            return None
        params = dict(self.config.optimizer_params or {})
        adam_w = name == ADAMW_OPTIMIZER or params.get("adam_w_mode", True)
        wd = params.get("weight_decay", 0.0)
        if not adam_w and wd:
            return None  # plain-Adam weight decay (grad += wd*p) not fused
        if mode == "auto":
            # measured (v5e, 748M params): XLA already fuses the optax update
            # chain to ~1.5x the HBM roofline; the explicit kernel is not
            # faster there, so 'auto' currently resolves to off
            return None
        try:  # fp32 masters only: the kernel reads/writes f32 state
            shapes = jax.eval_shape(lambda r: self.module.init(r, example_batch), jax.random.PRNGKey(0))
            if any(l.dtype != jnp.float32 for l in jax.tree_util.tree_leaves(shapes)):
                return None
        except Exception:
            return None
        from ..ops.adam.fused_adam import fused_adam

        betas = tuple(params.get("betas", (0.9, 0.999)))
        lr = self.lr_schedule_fn if self.lr_schedule_fn is not None else params.get("lr", 1e-3)
        self.optimizer = fused_adam(lr=lr, b1=betas[0], b2=betas[1], eps=params.get("eps", 1e-8),
                                    weight_decay=wd, adam_w_mode=True)
        log_dist("Pallas fused Adam step engaged (single-pass update, gated)", ranks=[0])
        return {"b1": betas[0], "b2": betas[1], "eps": params.get("eps", 1e-8), "wd": wd,
                "lr": params.get("lr", 1e-3)}

    def _configure_host_offload_optimizer(self, offload_cfg):
        """Build the ZeRO-Offload host optimizer (reference: cpu_offload forces
        DeepSpeedCPUAdam, ``engine.py:1275``+``stage_1_and_2.py`` cpu path)."""
        from .zero.offload import HostOffloadOptimizer
        from .constants import ADAM_OPTIMIZER, ADAMW_OPTIMIZER, FUSED_ADAM_OPTIMIZER

        params = dict(self.config.optimizer_params or {})
        name = (self.config.optimizer_name or ADAMW_OPTIMIZER).lower()
        if name not in (ADAM_OPTIMIZER, ADAMW_OPTIMIZER, FUSED_ADAM_OPTIMIZER):
            logger.warning(f"offload_optimizer: '{name}' not supported on host; using fused CPU AdamW")
        adamw = name == ADAMW_OPTIMIZER or params.get("adam_w_mode", True)
        nvme = offload_cfg.nvme_path if str(offload_cfg.device) == "nvme" else None
        if str(offload_cfg.device) == "nvme":
            assert nvme, "offload_optimizer.device=nvme requires nvme_path"
        # twin-flow: the host optimizer owns only its slice of the tree
        host_params = self._host_slice(self.state["params"])
        block_shardings = self._host_slice(self.zero_policy.grad_shardings(self.state["params"]))
        return HostOffloadOptimizer(host_params,
                                    lr=params.get("lr", 1e-3),
                                    betas=tuple(params.get("betas", (0.9, 0.999))),
                                    eps=params.get("eps", 1e-8),
                                    weight_decay=params.get("weight_decay", 0.0),
                                    adamw_mode=adamw,
                                    nvme_path=nvme,
                                    pipeline_read=offload_cfg.pipeline_read,
                                    pipeline_write=offload_cfg.pipeline_write,
                                    grad_clip=self.config.gradient_clipping or 0.0,
                                    block_shardings=block_shardings)

    # ------------------------------------------------------------------
    # state init
    # ------------------------------------------------------------------
    def _init_state(self, example_batch=None):
        init_rng, self._rng = jax.random.split(self._rng)
        param_shapes = jax.eval_shape(lambda r: self.module.init(r, example_batch), init_rng)
        param_shardings = self.zero_policy.param_shardings(param_shapes)
        if self._offload_enabled and self._offload_ratio < 1.0:
            # twin-flow: the device slice keeps a normal optax state in HBM
            from .zero.offload import partition_leaves_by_ratio

            self._twin_mask = partition_leaves_by_ratio(param_shapes, self._offload_ratio)
            n_host = sum(jax.tree_util.tree_leaves(self._twin_mask))
            n_all = len(jax.tree_util.tree_leaves(param_shapes))
            log_dist(f"twin-flow offload: ratio={self._offload_ratio} -> {n_host}/{n_all} "
                     f"param leaves' optimizer state on host, rest on device", ranks=[0])
            dev_shapes = self._dev_slice(param_shapes)
            opt_init = lambda params: self._twin_tx.init(self._dev_slice(params))
            opt_shapes = jax.eval_shape(self._twin_tx.init, dev_shapes)
            opt_shardings = self.zero_policy.opt_state_shardings(opt_shapes, dev_shapes)
        elif self._offload_enabled:
            # ZeRO-Offload: moments live on host/NVMe — nothing in HBM
            opt_init = lambda params: {}
            opt_shardings = {}
        else:
            opt_init = self.optimizer.init
            opt_shapes = jax.eval_shape(self.optimizer.init, param_shapes)
            opt_shardings = self.zero_policy.opt_state_shardings(opt_shapes, param_shapes)
        scalar = NamedSharding(self.mesh, P())

        state_shardings = {
            "params": param_shardings,
            "opt_state": opt_shardings,
            "step": scalar,
            "loss_scale": scalar,
            "good_steps": scalar,
        }
        if self._onebit is not None:
            # per-worker error-feedback buffers, stacked over the data axis:
            # leaf i of err_w is (dp, *param_shape); err_s is (dp, server_chunk)
            from .comm.compressed import onebit_chunk_len

            dp = self.mesh.shape[DATA_AXIS]
            err_sharding = lambda: NamedSharding(self.mesh, P(DATA_AXIS))
            state_shardings["onebit_err_w"] = jax.tree_util.tree_map(lambda _: err_sharding(), param_shapes)
            state_shardings["onebit_err_s"] = jax.tree_util.tree_map(lambda _: err_sharding(), param_shapes)
            self._onebit_dp = dp
        self._state_shardings = state_shardings

        @partial(jax.jit, out_shardings=state_shardings)
        def init_fn(rng):
            params = self.module.init(rng, example_batch)
            state = {
                "params": params,
                "opt_state": opt_init(params),
                "step": jnp.zeros([], jnp.int32),
                "loss_scale": jnp.asarray(
                    float(self.config.loss_scale) if (self.fp16_enabled and self.config.loss_scale) else
                    (float(self.config.initial_dynamic_scale) if self.fp16_enabled else 1.0), jnp.float32),
                "good_steps": jnp.zeros([], jnp.int32),
            }
            if self._onebit is not None:
                from .comm.compressed import onebit_chunk_len

                dp = self._onebit_dp
                state["onebit_err_w"] = jax.tree_util.tree_map(
                    lambda p: jnp.zeros((dp, ) + tuple(p.shape), jnp.float32), params)
                state["onebit_err_s"] = jax.tree_util.tree_map(
                    lambda p: jnp.zeros((dp, onebit_chunk_len(int(np.prod(p.shape) or 1), dp)), jnp.float32),
                    params)
            return state

        if self.config.tpu_config.abstract_init:
            # compile-only validation: the state is the SHAPE of the state
            state = jax.eval_shape(init_fn, init_rng)
            state = jax.tree_util.tree_map(
                lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
                state, state_shardings)
        else:
            with self.mesh:
                state = init_fn(init_rng)
        n_params = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(state["params"]))
        self._n_params = n_params  # MFU derivation (monitor/metrics.py)
        log_dist(f"initialized {n_params/1e6:.2f}M params sharded over mesh"
                 + (" (abstract)" if self.config.tpu_config.abstract_init else ""), ranks=[0])
        return state

    # ------------------------------------------------------------------
    # functional core
    # ------------------------------------------------------------------
    def _loss_fn(self, params, batch, rng):
        if hasattr(self.module, "loss"):
            out = self.module.loss(params, batch, rng)
        else:
            out = self.module(params, batch, rng)
        if isinstance(out, tuple):
            return out[0], out[1] if len(out) > 1 else {}
        return out, {}

    def _microbatch_grads(self, params, batch, rng, loss_scale):
        """One microbatch fwd+bwd. Loss is scaled for fp16 (reference
        ``_scale_loss_by_gas``+loss scaler); grads are unscaled outside."""

        def scaled_loss(p):
            loss, aux = self._loss_fn(p, batch, rng)
            return loss * loss_scale, (loss, aux)

        grads, (loss, _aux) = jax.grad(scaled_loss, has_aux=True)(params)
        grads = constrain(grads, self.zero_policy.grad_specs(params), self.mesh)
        return grads, loss

    def _advance_loss_scale(self, state, finite):
        """Dynamic loss scale state machine (reference DynamicLossScaler)."""
        if self.fp16_enabled and self.dynamic_loss_scale:
            args = self.config.dynamic_loss_scale_args
            window, min_scale = args["scale_window"], args["min_scale"]
            good = jnp.where(finite, state["good_steps"] + 1, 0)
            scale = jnp.where(finite,
                              jnp.where(good >= window, state["loss_scale"] * 2.0, state["loss_scale"]),
                              jnp.maximum(state["loss_scale"] * 0.5, min_scale))
            good = jnp.where(good >= window, 0, good)
            return scale, good
        return state["loss_scale"], state["good_steps"]

    def _apply_update(self, state, grads, grad_norm_ok, unscaled=False):
        """Unscale, update, advance loss scale — skipping on overflow
        (reference ``has_overflow`` stage_1_and_2.py:2002 + DynamicLossScaler).
        ``unscaled=True`` when the caller already divided by the loss scale
        (the 1-bit path compresses in unscaled units)."""
        if self._pallas_adam is not None:
            return self._apply_update_pallas(state, grads, grad_norm_ok, unscaled)
        params, opt_state = state["params"], state["opt_state"]
        inv_scale = 1.0 if unscaled else 1.0 / state["loss_scale"]
        grads = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32) * inv_scale, grads)

        # overflow detection rides the gradient global-norm (any NaN/inf makes
        # it non-finite; an inf norm from huge-but-finite grads is a
        # conservative skip, matching the reference's CheckOverflow) — the
        # norm is computed for metrics/clipping anyway, so this saves a
        # dedicated full read pass over the gradients
        finite = jnp.logical_and(grad_norm_ok, jnp.isfinite(optax.global_norm(grads)))

        updates, new_opt_state = self.optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)

        def sel(a, b):
            return jnp.where(finite, a, b)

        params = jax.tree_util.tree_map(sel, new_params, params)
        opt_state = jax.tree_util.tree_map(sel, new_opt_state, opt_state)

        scale, good = self._advance_loss_scale(state, finite)
        return {
            "params": params,
            "opt_state": opt_state,
            "step": state["step"] + finite.astype(jnp.int32),
            "loss_scale": scale,
            "good_steps": good,
        }, finite

    def _apply_update_pallas(self, state, grads, grad_norm_ok, unscaled=False):
        """Single-pass gated AdamW (ops/pallas/fused_adam.py): overflow
        detection rides the gradient global-norm (NaN/inf anywhere makes the
        norm non-finite — the reference's ``has_overflow`` semantics without
        a dedicated pass), clipping and loss un-scaling fold into one scalar
        gradient factor, and the overflow skip is the kernel's gate rather
        than a post-hoc where-select over params AND optimizer state."""
        from ..ops.adam.fused_adam import FusedAdamState
        from ..ops.pallas.fused_adam import fused_adam_apply

        pa = self._pallas_adam
        inv_scale = jnp.asarray(1.0 if unscaled else 1.0 / state["loss_scale"], jnp.float32)
        gnorm = optax.global_norm(grads).astype(jnp.float32) * inv_scale
        finite = jnp.logical_and(grad_norm_ok, jnp.isfinite(gnorm))
        clip = float(self.config.gradient_clipping or 0.0)
        coef = jnp.minimum(1.0, clip / (gnorm + 1e-6)) if clip > 0 else jnp.asarray(1.0, jnp.float32)
        opt = state["opt_state"]
        count = opt.step
        lr_t = (self.lr_schedule_fn(count) if self.lr_schedule_fn is not None else pa["lr"])
        new_p, new_m, new_v = fused_adam_apply(
            state["params"], opt.mu, opt.nu, grads,
            lr_t=lr_t, b1=pa["b1"], b2=pa["b2"], eps=pa["eps"], weight_decay=pa["wd"],
            step=count + 1, grad_scale=inv_scale * coef, gate=finite.astype(jnp.float32),
            interpret=jax.default_backend() != "tpu")
        scale, good = self._advance_loss_scale(state, finite)
        return {
            "params": new_p,
            "opt_state": FusedAdamState(step=count + finite.astype(count.dtype), mu=new_m, nu=new_v),
            "step": state["step"] + finite.astype(jnp.int32),
            "loss_scale": scale,
            "good_steps": good,
        }, finite

    def _scan_microbatch_grads(self, params, batches, rng, loss_scale, gas: int):
        """Shared accumulation core (traced): scan ``gas`` microbatches,
        return (mean grads fp32 sharded, per-microbatch losses)."""
        grad_specs = self.zero_policy.grad_specs(params)

        def micro(carry, mb):
            acc, rng = carry
            rng, sub = jax.random.split(rng)
            grads, loss = self._microbatch_grads(params, mb, sub, loss_scale)
            acc = jax.tree_util.tree_map(jnp.add, acc, grads)
            acc = constrain(acc, grad_specs, self.mesh)
            return (acc, rng), loss

        zeros = jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        zeros = constrain(zeros, grad_specs, self.mesh)
        if gas == 1:
            one = jax.tree_util.tree_map(lambda x: x[0], batches)
            (acc, _), losses = micro((zeros, rng), one)
            losses = losses[None]
        else:
            (acc, _), losses = jax.lax.scan(micro, (zeros, rng), batches)
        acc = jax.tree_util.tree_map(lambda g: g / gas, acc)
        return acc, losses

    def _accumulate_grads_fn(self, gas: int):
        """Compiled grads-only program for the host-offload path. Also
        returns the (scaled) global gradient norm — a GSPMD reduction, exact
        across hosts, where a host-side norm in multi-host shard mode would
        only see this process's shards."""

        def grads_fn(params, batches, rng, loss_scale):
            acc, losses = self._scan_microbatch_grads(params, batches, rng, loss_scale, gas)
            return acc, jnp.mean(losses), optax.global_norm(acc)

        return jax.jit(grads_fn)

    def _host_slice(self, tree):
        """The host optimizer's slice of a params-shaped tree (identity
        outside twin-flow)."""
        if self._twin_mask is None:
            return tree
        from .zero.offload import prune_tree

        return prune_tree(tree, self._twin_mask, keep=True)

    def _dev_slice(self, tree):
        """The device (HBM) optimizer slice — twin-flow only."""
        assert self._twin_mask is not None, "_dev_slice outside twin-flow"
        from .zero.offload import prune_tree

        return prune_tree(tree, self._twin_mask, keep=False)

    def _build_twin_device_update(self):
        """Compiled update for the twin-flow DEVICE slice: pre-scaled grads
        (unscale + global clip folded into ``scale``) through the bare tx.
        Dispatched async BEFORE the host C++ Adam runs — the two updates
        overlap, the point of the reference's Twin-Flow design."""

        def dev_update(dev_params, opt_state, dev_grads, scale):
            g = jax.tree_util.tree_map(lambda x: x.astype(jnp.float32) * scale, dev_grads)
            updates, new_opt = self._twin_tx.update(g, opt_state, dev_params)
            new_params = optax.apply_updates(dev_params, updates)
            new_params = jax.tree_util.tree_map(lambda n, p: n.astype(p.dtype), new_params, dev_params)
            return new_params, new_opt

        dev_shardings = self._dev_slice(self._state_shardings["params"])
        return jax.jit(dev_update, donate_argnums=(0, 1),
                       out_shardings=(dev_shardings, self._state_shardings["opt_state"]))

    def _host_apply_update(self, grads, scaled_gnorm=None):
        """Shared host-offload tail: fused C++ Adam on the masters, then
        upload of the new params into their shardings. Returns
        (grad_norm, overflow, lr). ``scaled_gnorm``: device-computed global
        norm of the (loss-scaled) grads — required in multi-host shard mode.

        Twin-flow (``offload_optimizer.ratio`` < 1): the device slice's
        compiled update is dispatched (async) before the host loop starts,
        so HBM-side Adam runs concurrently with the host C++ Adam; the two
        halves are merged afterwards. Clip/overflow decisions use the ONE
        global norm for both."""
        from .zero.offload import merge_by_mask

        twin = self._twin_mask is not None
        step_no = int(self.state["step"]) + 1
        lr = (float(self.lr_schedule_fn(step_no - 1)) if self.lr_schedule_fn is not None else
              (self.config.optimizer_params or {}).get("lr", 1e-3))
        scale = float(self.state["loss_scale"])
        gnorm = None if scaled_gnorm is None else float(scaled_gnorm) / scale

        dev_future = None
        if twin:
            assert gnorm is not None, "twin-flow needs the device-computed global norm"
            if np.isfinite(gnorm):
                # dispatch the device slice NOW; it overlaps the host loop
                clip = self.config.gradient_clipping or 0.0
                factor = (1.0 / scale) * (clip / (gnorm + 1e-6) if clip and gnorm > clip else 1.0)
                if "twin_dev_update" not in self._compiled:
                    self._compiled["twin_dev_update"] = self._build_twin_device_update()
                with self.mesh:
                    dev_future = self._compiled["twin_dev_update"](
                        self._dev_slice(self.state["params"]),
                        self.state["opt_state"],
                        self._dev_slice(grads),
                        jnp.asarray(factor, jnp.float32))
            grads = self._host_slice(grads)

        new_params, grad_norm, overflow = self.host_optimizer.step(step_no, grads, lr=lr, loss_scale=scale,
                                                                   grad_norm=gnorm)
        if not overflow:
            param_shardings = self._state_shardings["params"]
            dtypes = jax.tree_util.tree_map(lambda p: p.dtype, self.state["params"])
            if twin:
                param_shardings = self._host_slice(param_shardings)
                dtypes = self._host_slice(dtypes)
            if self.host_optimizer.shard_mode:
                host_params = self.host_optimizer.rebuild_device_params(param_shardings, dtypes)
            else:
                cast = jax.tree_util.tree_map(lambda a, dt: np.asarray(a, dtype=dt), new_params, dtypes)
                host_params = jax.device_put(cast, param_shardings)
            if twin:
                dev_params, self.state["opt_state"] = dev_future
                self.state["params"] = merge_by_mask(self.state["params"], self._twin_mask,
                                                     host_params, dev_params)
            else:
                self.state["params"] = host_params
            self.state["step"] = self.state["step"] + 1
        else:
            self.skipped_steps += 1
        self._advance_loss_scale_host(overflow)
        return grad_norm, overflow, lr

    def _offload_train_batch(self, batch, step_rng):
        """ZeRO-Offload step: compiled fwd+bwd on device, host Adam update.
        ``batch`` arrives ALREADY placed (``train_batch`` shards once for all
        step paths; prefetched batches were placed by the worker)."""
        gas = self.config.gradient_accumulation_steps
        if "offload_grads" not in self._compiled:
            self._compiled["offload_grads"] = self._accumulate_grads_fn(gas)
        with self.mesh:
            grads, loss, gnorm = self._compiled["offload_grads"](self.state["params"], batch, step_rng,
                                                                 self.state["loss_scale"])
        grad_norm, overflow, lr = self._host_apply_update(grads, scaled_gnorm=gnorm)
        return {
            "loss": loss,
            "grad_norm": jnp.asarray(grad_norm),
            "overflow": jnp.asarray(overflow),
            "lr": jnp.asarray(lr),
        }

    def _advance_loss_scale_host(self, overflow: bool):
        """Host mirror of the dynamic loss-scale state machine."""
        if not (self.fp16_enabled and self.dynamic_loss_scale):
            return
        args = self.config.dynamic_loss_scale_args
        window, min_scale = args["scale_window"], args["min_scale"]
        good = int(self.state["good_steps"])
        scale = float(self.state["loss_scale"])
        if overflow:
            scale, good = max(scale * 0.5, min_scale), 0
        else:
            good += 1
            if good >= window:
                scale, good = scale * 2.0, 0
        self.state["loss_scale"] = jnp.asarray(scale, jnp.float32)
        self.state["good_steps"] = jnp.asarray(good, jnp.int32)

    def _build_onebit_train_step(self, gas: int):
        """1-bit train step: per-worker local grads via shard_map over the
        data axis, then the error-feedback compressed allreduce (exact pmean
        during the freeze_step warmup), then the optax update."""
        from .comm.compressed import onebit_allreduce

        dp = self._onebit_dp
        freeze_step = self._onebit.freeze_step
        params_treedef = jax.tree_util.tree_structure(self.state["params"])

        def batch_spec(ndim):
            # rank-1 leaves (e.g. the per-microbatch pld_theta scalar track)
            # are replicated — only [gas, micro, ...] leaves shard over data
            if ndim < 2:
                return P(*([None] * ndim))
            return P(*([None, DATA_AXIS] + [None] * (ndim - 2)))

        def local_fn(params, batches, rng, loss_scale, step, err_w, err_s):
            # everything here is the per-device view: batches (gas, local, ...),
            # err leaves carry a leading length-1 shard of the stacked dim
            def micro(carry, mb):
                acc, rng = carry
                rng, sub = jax.random.split(rng)

                def scaled_loss(p):
                    loss, _aux = self._loss_fn(p, mb, sub)
                    return loss * loss_scale, loss

                grads, loss = jax.grad(scaled_loss, has_aux=True)(params)
                acc = jax.tree_util.tree_map(lambda a, g: a + g.astype(jnp.float32), acc, grads)
                return (acc, rng), loss

            zeros = jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
            (acc, _), losses = jax.lax.scan(micro, (zeros, rng), batches)
            # compress in UNSCALED units: error-feedback residuals persist
            # across steps, so they must not be denominated in a loss scale
            # that the dynamic scaler later changes
            acc = jax.tree_util.tree_map(lambda g: g / (gas * loss_scale), acc)

            # a non-finite gradient anywhere must not poison the persistent
            # error buffers: fall back to the exact path (whose NaN output
            # _apply_update then rejects, leaving params AND errors untouched)
            local_finite = jnp.all(jnp.stack([jnp.all(jnp.isfinite(g))
                                              for g in jax.tree_util.tree_leaves(acc)]))
            finite = jax.lax.pmin(local_finite.astype(jnp.int32), DATA_AXIS) > 0
            use_comp = jnp.logical_and(step >= freeze_step, finite)
            g_leaves = jax.tree_util.tree_leaves(acc)
            ew_leaves = jax.tree_util.tree_leaves(err_w)
            es_leaves = jax.tree_util.tree_leaves(err_s)
            out_g, out_ew, out_es = [], [], []
            for g, ew, es in zip(g_leaves, ew_leaves, es_leaves):
                ew0, es0 = ew[0], es[0]
                comp = lambda g=g, ew0=ew0, es0=es0: onebit_allreduce(g, ew0, es0, DATA_AXIS, dp)
                exact = lambda g=g, ew0=ew0, es0=es0: (jax.lax.pmean(g, DATA_AXIS), ew0, es0)
                o, new_ew, new_es = jax.lax.cond(use_comp, comp, exact)
                out_g.append(o)
                out_ew.append(new_ew[None])
                out_es.append(new_es[None])
            reduced = jax.tree_util.tree_unflatten(params_treedef, out_g)
            new_err_w = jax.tree_util.tree_unflatten(params_treedef, out_ew)
            new_err_s = jax.tree_util.tree_unflatten(params_treedef, out_es)
            mean_loss = jax.lax.pmean(jnp.mean(losses), DATA_AXIS)
            return reduced, new_err_w, new_err_s, mean_loss

        replicated = jax.tree_util.tree_map(lambda _: P(), self.state["params"])
        err_spec = jax.tree_util.tree_map(lambda _: P(DATA_AXIS), self.state["params"])
        batch_specs = jax.tree_util.tree_map(batch_spec, self._last_batch_struct)
        sharded = shard_map_compat(
            local_fn, self.mesh,
            in_specs=(replicated, batch_specs, P(), P(), P(), err_spec, err_spec),
            out_specs=(replicated, err_spec, err_spec, P()))

        def train_step(state, batches, rng):
            reduced, new_ew, new_es, mean_loss = sharded(state["params"], batches, rng, state["loss_scale"],
                                                         state["step"], state["onebit_err_w"],
                                                         state["onebit_err_s"])
            new_state, metrics = self._finalize_step(state, reduced, mean_loss, unscaled=True)
            new_state["onebit_err_w"] = new_ew
            new_state["onebit_err_s"] = new_es
            return new_state, metrics

        return self._jit_step(train_step)

    def _build_train_step(self, gas: int):
        """Fused train step: scan over ``gas`` microbatches then update."""
        if self.pipe_world_size > 1:
            return self._build_pipeline_train_step()
        if self._onebit is not None:
            return self._build_onebit_train_step(gas)
        if self._hpz:
            return self._build_hpz_train_step(gas)

        def train_step(state, batches, rng):
            acc, losses = self._scan_microbatch_grads(state["params"], batches, rng, state["loss_scale"], gas)
            return self._finalize_step(state, acc, jnp.mean(losses))

        return self._jit_step(train_step)

    def _build_hpz_train_step(self, gas: int):
        """ZeRO++ hpZ/qwZ/qgZ train step (reference hpZ groups ``groups.py:505``,
        qwZ ``partition_parameters.py:1139``, qgZ ``coalesced_collectives.py:31``).

        A ``shard_map`` manual over the ``data_repl`` axis (everything else
        stays GSPMD-auto) makes the hierarchy explicit:

          1. gather each primary param shard over ``data_repl`` once per step
             — the hpZ *secondary copy*, int8 on the wire when qwZ — leaving
             it stage-3 sharded over the inner ``data`` axis, so every
             per-layer gather inside the forward/backward stays within the
             hpZ group (nearest ICI);
          2. run the microbatch scan against the secondary copy (intra-group
             collectives compiler-inserted, fp32/bf16);
          3. after EACH microbatch, reduce its grads back to the primary
             layout with a ``psum_scatter`` over ``data_repl`` — the qgZ
             int8 all-to-all when enabled (intra-group reduction already
             happened in fp32 via GSPMD: the reference's 2-level scheme) —
             so the fp32 accumulator stays at primary-shard size.
        """
        from ..ops.pallas.quant import quantized_all_gather_dim, quantized_psum_scatter_dim

        policy = self.zero_policy
        params = self.state["params"]
        primary_specs = policy.param_specs(params)
        n_repl = self.mesh.shape.get(DATA_REPL_AXIS, 1)
        qwz, qgz = self._qwz, self._qgz
        is_spec = lambda x: isinstance(x, P)

        def repl_dim(spec):
            # -1 == replicated over data_repl (None would vanish as a pytree leaf)
            for i, e in enumerate(spec):
                axes = e if isinstance(e, (tuple, list)) else ((e, ) if e is not None else ())
                if DATA_REPL_AXIS in axes:
                    return i
            return -1

        dims = jax.tree_util.tree_map(repl_dim, primary_specs, is_leaf=is_spec)

        def manual_spec(x, d):
            if d < 0:
                return P()
            return P(*[DATA_REPL_AXIS if i == d else None for i in range(np.ndim(x))])

        param_manual = jax.tree_util.tree_map(manual_spec, params, dims)
        batch_manual = jax.tree_util.tree_map(
            lambda nd: P(*([None] * nd)) if nd < 2 else
            P(*([None, DATA_REPL_AXIS] + [None] * (nd - 2))), self._last_batch_struct)

        def local_fn(p_shard, batches, rng, loss_scale):
            def gather(x, d):
                if d < 0:
                    return x
                if qwz:
                    return quantized_all_gather_dim(x, DATA_REPL_AXIS, d)
                return jax.lax.all_gather(x, DATA_REPL_AXIS, axis=d, tiled=True)

            secondary = jax.tree_util.tree_map(gather, p_shard, dims)

            def reduce_(g, d):
                if d < 0:
                    return jax.lax.pmean(g, DATA_REPL_AXIS)
                if qgz:
                    return quantized_psum_scatter_dim(g, DATA_REPL_AXIS, d) / n_repl
                return jax.lax.psum_scatter(g, DATA_REPL_AXIS, scatter_dimension=d, tiled=True) / n_repl

            def micro(carry, mb):
                # the accumulator lives in the PRIMARY (scattered) layout:
                # each microbatch's grads reduce over data_repl immediately,
                # so peak HBM never holds a full fp32 gradient copy per hpZ
                # group (reference reduces per IPG bucket the same way)
                acc, rng = carry
                rng, sub = jax.random.split(rng)

                def scaled(p):
                    loss, _aux = self._loss_fn(p, mb, sub)
                    return loss * loss_scale, loss

                grads, loss = jax.grad(scaled, has_aux=True)(secondary)
                grads = jax.tree_util.tree_map(
                    lambda g, d: reduce_(g.astype(jnp.float32), d), grads, dims)
                acc = jax.tree_util.tree_map(jnp.add, acc, grads)
                return (acc, rng), loss

            zeros = jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), p_shard)
            if gas == 1:
                one = jax.tree_util.tree_map(lambda x: x[0], batches)
                (acc, _), losses = micro((zeros, rng), one)
                losses = losses[None]
            else:
                (acc, _), losses = jax.lax.scan(micro, (zeros, rng), batches)
            grads = jax.tree_util.tree_map(lambda g: g / gas, acc)
            mean_loss = jax.lax.pmean(jnp.mean(losses), DATA_REPL_AXIS)
            return grads, mean_loss

        sharded = shard_map_compat(local_fn, self.mesh,
                                   in_specs=(param_manual, batch_manual, P(), P()),
                                   out_specs=(param_manual, P()),
                                   axis_names=frozenset({DATA_REPL_AXIS}))

        def train_step(state, batches, rng):
            grads, mean_loss = sharded(state["params"], batches, rng, state["loss_scale"])
            return self._finalize_step(state, grads, mean_loss)

        return self._jit_step(train_step)

    def _build_pipeline_train_step(self):
        """PP path: the gas microbatches ARE the pipeline microbatches
        (reference PipelineEngine.train_batch consumes them the same way,
        pipe/engine.py:348); one jitted program runs the whole 1F1B-equivalent
        fill/drain loop forward AND backward."""

        kwargs = {"mesh": self.mesh, "num_stages": self.pipe_world_size}
        if self._model_takes_schedule:
            kwargs["schedule"] = self._pipe_schedule

        def train_step(state, batches, rng):
            def scaled(p):
                loss = self.module.pipeline_loss(p, batches, rng, **kwargs)
                return loss * state["loss_scale"], loss

            grads, loss = jax.grad(scaled, has_aux=True)(state["params"])
            return self._finalize_step(state, grads, loss)

        return self._jit_step(train_step)

    def _finalize_step(self, state, grads, mean_loss, unscaled=False):
        """Shared tail: apply update + build the step metrics dict."""
        new_state, finite = self._apply_update(state, grads, jnp.array(True), unscaled=unscaled)
        metrics = {
            "loss": mean_loss,
            "grad_norm": optax.global_norm(grads),
            "overflow": jnp.logical_not(finite),
            "lr": (self.lr_schedule_fn(state["step"]) if self.lr_schedule_fn is not None else
                   jnp.asarray((self.config.optimizer_params or {}).get("lr", 0.0))),
        }
        return new_state, metrics

    def _jit_step(self, fn):
        donate = (0, ) if self.config.tpu_config.donate_buffers else ()
        return jax.jit(fn, donate_argnums=donate, out_shardings=(self._state_shardings, None))

    # ------------------------------------------------------------------
    # public API — fused path
    # ------------------------------------------------------------------
    def _host_prepare_batch(self, batch=None, mbs=None, step=None):
        """THE single host-side batch-assembly helper — every data-dependent
        training path (inline ``train_batch``, the prefetch worker) routes
        through here, enforced by ``tools/check_data_paths.py`` so a second
        copy of the stack/post-process logic can never drift out of sync.

        ``mbs``: list of ``gas`` microbatches (the ``data_iter`` contract) —
        post-processed per microbatch then gas-major stacked; ``batch``: a
        whole ``gas*micro``-row pytree — post-processed whole then reshaped.
        ``step``: ONLY the prefetch worker passes it — the global step the
        batch will be CONSUMED at, for which curriculum difficulty and PLD
        theta are computed via their side-effect-free accessors (the worker
        thread must not mutate shared scheduler state under the main
        thread); the inline path (``step=None``) uses ``self.global_steps``
        and advances the schedulers as before. Same numbers either way, so
        prefetched and synchronous runs stay bit-identical. Returns the
        host-side ``(gas, micro, ...)`` pytree, not yet placed on device."""
        gas = self.config.gradient_accumulation_steps
        if mbs is not None:
            if self._data_post_process_func is not None:
                mbs = [self._data_post_process_func(mb) for mb in mbs]
            batch = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *mbs)
        else:
            if self._data_post_process_func is not None:
                batch = self._data_post_process_func(batch)
            batch = jax.tree_util.tree_map(lambda x: np.asarray(x).reshape(gas, -1, *np.shape(x)[1:]), batch)
        if self.curriculum_scheduler is not None:
            batch = self._apply_curriculum(batch, step=step)
        if self.progressive_layer_drop is not None:
            # traced scalar per microbatch: theta decays without recompiling
            pld = self.progressive_layer_drop
            if step is None:
                pld.update_state(self.global_steps)
                theta = pld.get_theta()
            else:  # worker thread: pure read, no shared-state mutation
                theta = pld.theta_at(step)
            if not isinstance(batch, dict):
                batch = {"input_ids": batch}
            batch = {**batch, "pld_theta": np.full((gas,), theta, np.float32)}
        return batch

    def prefetching_loader(self, loader, depth=None):
        """Wrap ``loader`` (an iterable of microbatches — the ``data_iter``
        contract) in a :class:`DevicePrefetchIterator`: a background thread
        runs the whole host side (``_host_prepare_batch`` + shard placement)
        up to ``depth`` batches ahead, and ``train_batch(data_iter=...)``
        consumes the already-placed :class:`DeviceBatch` items through its
        fast path. ``depth`` defaults to ``data_pipeline.prefetch.depth``.
        Build it when ``engine.global_steps`` reflects the step the next
        batch feeds (the worker numbers batches from there), and rebuild it
        after ``set_train_batch_size`` (gas is baked in at wrap time)."""
        from .data_pipeline.prefetch import DevicePrefetchIterator

        if isinstance(loader, DevicePrefetchIterator):
            return loader
        if depth is None:
            depth = self.config.data_pipeline_config.prefetch.depth

        def prepare(mbs, step):
            return self._host_prepare_batch(mbs=mbs, step=step)

        def place(batch):
            with self.mesh:
                return self._shard_batch(batch, leading=("mb", ))

        pf = DevicePrefetchIterator(loader, prepare_fn=prepare, place_fn=place,
                                    gas=self.config.gradient_accumulation_steps,
                                    depth=depth, start_step=self.global_steps)
        # the auto-wrap builds one prefetcher per epoch: prune the closed
        # ones so a long run doesn't accumulate dead threads/queues here
        self._prefetchers = [p for p in self._prefetchers if not p._closed]
        self._prefetchers.append(pf)
        return pf

    def train_batch(self, batch=None, data_iter=None):
        """Run one full training step (all microbatches + optimizer update).

        ``batch``: pytree with leading dim ``gas * micro_bsz`` (host local),
        a :class:`DeviceBatch` from a prefetching loader, or ``data_iter``
        yielding microbatches (or ``DeviceBatch`` items — see
        :meth:`prefetching_loader`). Returns the mean loss. This is the
        performant path (one compiled program per step), the analog of
        PipelineEngine.train_batch (reference pipe/engine.py:348)
        generalized to all parallel modes.

        Already-placed ``DeviceBatch`` inputs take the fast path: the inline
        stack/post-process/shard work is skipped entirely (it already ran in
        the prefetch worker), so the step blocks on data only for as long as
        the bounded prefetch queue is empty — measured every step as
        ``train/input_wait_ms`` when metrics are on, plus an ``input_wait``
        span on the ``data`` trace stream.
        """
        gas = self.config.gradient_accumulation_steps
        health_on = self._health.enabled
        gl = self._goodput
        if gl is not None:
            # books the gap since the last boundary as idle (or recovery,
            # when the resilience runner flagged a restart in flight)
            gl.step_entry()
        wait_obs = self._tracer.enabled or self._metrics.enabled or health_on \
            or gl is not None
        t_in = time.perf_counter() if wait_obs else 0.0
        prefetched = isinstance(batch, DeviceBatch)
        if batch is None:
            assert data_iter is not None
            first = next(data_iter)
            if isinstance(first, DeviceBatch):
                batch, prefetched = first, True
            else:
                batch = self._host_prepare_batch(mbs=[first] + [next(data_iter) for _ in range(gas - 1)])
        elif not prefetched:
            batch = self._host_prepare_batch(batch=batch)
        if prefetched:
            placed = batch.data
        else:
            with self.mesh:
                placed = self._shard_batch(batch, leading=("mb", ))
        if wait_obs:
            dt_in = time.perf_counter() - t_in
            if health_on:
                self._last_input_wait_ms = dt_in * 1e3  # straggler-vote sample
            if self._metrics.enabled:
                self._metrics.histogram("train/input_wait_ms").observe(dt_in * 1e3)
            if self._tracer.enabled:
                self._tracer.complete("input_wait", t_in, dt_in, tid="data",
                                      args={"step": self.global_steps, "prefetched": prefetched})

        self._maybe_device_trace()
        if prefetched:
            # scheduler housekeeping stays on the MAIN thread: the worker
            # computed this batch's transforms with the side-effect-free
            # accessors for this very step, so advancing the shared state
            # here keeps checkpoints/introspection fresh without changing
            # any batch content (and without cross-thread mutation)
            if self.curriculum_scheduler is not None:
                self.curriculum_scheduler.update_difficulty(self.global_steps)
            if self.progressive_layer_drop is not None:
                self.progressive_layer_drop.update_state(self.global_steps)
        if self.random_ltd_scheduler is not None:
            self.random_ltd_scheduler.update_seq(self.global_steps)
        step_rng, self._rng = jax.random.split(self._rng)
        self.tput_timer.start()
        # observe every step while tracing (profiling mode: the block that
        # makes spans honest is intended); in sink-only mode sample at the
        # steps_per_print boundary, where _record_metrics pays the host sync
        # anyway — plain telemetry must not serialize the async step pipeline
        observing = self._tracer.enabled or (
            self._metrics.enabled and (self.global_steps + 1) % self.config.steps_per_print == 0)
        t_step = time.perf_counter() if observing else 0.0
        if self.host_optimizer is not None:
            metrics = self._offload_train_batch(placed, step_rng)
        else:
            if "train_step" not in self._compiled:
                self._last_batch_struct = jax.tree_util.tree_map(lambda x: np.ndim(x), placed)
                if gl is not None:
                    # a fused-step (re)build after the warmup boundary is
                    # EXACTLY the silent steady-state recompile the
                    # sentinel exists to flag (shape drift, remesh, a
                    # curriculum bucket never seen in warmup)
                    get_goodput().sentinel.note_compile(
                        "train", bucket="train_step", warmed=self._gp_warm_declared,
                        step=self.global_steps)
                self._compiled["train_step"] = self._build_train_step(gas)
                _rf = get_roofline()
                if _rf.enabled:
                    # cost_analysis of the fused step needs the mesh for
                    # lowering sharded args — captured with the wrapper
                    self._compiled["train_step"] = _rf.capture_executable(
                        "train_step", self._compiled["train_step"], mesh=self.mesh)
            _rf = get_roofline()
            t_rf = time.perf_counter() if _rf.enabled else 0.0
            with self.mesh:
                self.state, metrics = self._compiled["train_step"](self.state, placed, step_rng)
            if _rf.enabled:
                # dispatch-side wall at the step boundary: async steps make a
                # single sample an under-read, but steady-state backpressure
                # converges it to the true step time (same caveat as
                # _last_step_wall_ms)
                _rf.note_wall("train_step", time.perf_counter() - t_rf)
        self.global_steps += 1
        self.micro_steps += gas
        self.global_samples += self.train_batch_size()
        self.tput_timer.stop(global_step=True)
        if observing:
            self._observe_step(t_step, placed, metrics)
        if self.host_optimizer is None and self.fp16_enabled and bool(metrics["overflow"]):
            self.skipped_steps += 1  # offload path counts inside _host_apply_update
        self._record_metrics(metrics)
        self._maybe_flops_profile(placed)
        if health_on:
            # host wall clock from train_batch entry to the step boundary —
            # no device sync forced (dispatch-side time is what skews when a
            # host straggles on input/assembly/python work, and a forced
            # block here would serialize the async step pipeline)
            self._last_step_wall_ms = (time.perf_counter() - t_in) * 1e3
        # chaos injection point: a storm's kill/stall/straggle/preempt land
        # HERE, at the step boundary — the one place the engine's state is
        # consistent enough to restart from (no-op-when-unhooked fire())
        t_fire = time.perf_counter() if gl is not None else 0.0
        chaos.fire("engine/step", {"engine": self, "step": self.global_steps})
        if gl is not None:
            gap = time.perf_counter() - t_fire
            if gap >= get_goodput().stall_gap_s:
                # a fire hook slept/wedged the step thread: the same gap
                # the watchdog trips on, booked as stall (a sub-threshold
                # gap stays in the compute residual)
                gl.book("stall", gap)
        if self._resilience_active:
            self._poll_resilience()
        if health_on:
            self._health.step_boundary(self.global_steps)
        if gl is not None:
            gl.step_boundary(dt_in)
            if not self._gp_warm_declared and self.global_steps >= get_goodput().train_warmup_steps:
                self._gp_warm_declared = True
                get_goodput().sentinel.declare_warmed("train")
        return metrics["loss"]

    def aot_lower_train_step(self, seq_len: int):
        """AOT-lower the FULL fused train step with abstract inputs — no
        state or batch ever materializes. The compile-only validation path
        for pod-scale configs (BASELINE.md Llama-2-7B/70B on v5p-128):
        ``.lower(...)`` proves the program + shardings trace/build;
        ``.compile()`` on the result additionally runs GSPMD partitioning
        and yields XLA's per-device memory analysis. Usable with or without
        ``tpu.abstract_init`` (the state template is shapes either way)."""
        gas = self.config.gradient_accumulation_steps
        rows = self.train_batch_size() // gas
        spec = [None, BATCH_AXES] + [SEQ_AXIS if self.seq_world_size > 1 else None]
        batch_abs = {"input_ids": jax.ShapeDtypeStruct(
            (gas, rows, seq_len), jnp.int32,
            sharding=NamedSharding(self.mesh, P(*spec)))}
        state_abs = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding), self.state)
        rng_abs = jax.eval_shape(lambda: jax.random.PRNGKey(0))
        step = self._build_train_step(gas)
        with self.mesh:
            return step.lower(state_abs, batch_abs,
                              jax.ShapeDtypeStruct(rng_abs.shape, rng_abs.dtype))

    # ------------------------------------------------------------------
    # device trace capture (TPU analog of the reference's torch-profiler
    # hooks; `tpu.profiler_trace` config block or the manual pair below)
    # ------------------------------------------------------------------
    def start_device_trace(self, trace_dir: str):
        """Begin a jax.profiler capture (perfetto/XPlane): device timelines,
        XLA op spans, and every `nvtx`/TraceAnnotation-annotated region.
        Brokered through the process-global capture manager
        (monitor/roofline.py) so a training capture and an on-demand
        ``POST /v1/profile`` capture can never race the one jax profiler."""
        if self._tracing:
            logger.warning("device trace already running; ignoring start_device_trace")
            return
        if not get_capture_manager().start(trace_dir):
            logger.warning("another profiler capture is in flight; "
                           "ignoring start_device_trace")
            return
        self._tracing = True
        log_dist(f"device trace capturing to {trace_dir}", ranks=[0])

    def stop_device_trace(self):
        if not self._tracing:
            return

        def _drain():
            # drain in-flight async work so the trace holds whole steps
            # (skipped post-destroy / under abstract_init — nothing to drain)
            if self.state is not None:
                leaves = jax.tree_util.tree_leaves(self.state["params"])
                if leaves and isinstance(leaves[0], jax.Array):
                    jax.block_until_ready(leaves[0])

        try:
            get_capture_manager().stop(drain=_drain)  # stop_trace writes the artifact
        finally:
            self._tracing = False
        log_dist("device trace stopped", ranks=[0])

    def _maybe_device_trace(self):
        cfg = self.config.tpu_config.profiler_trace
        if not cfg.enabled:
            return
        try:  # profiling must never kill a training step
            if self.global_steps == cfg.start_step and not self._tracing:
                self.start_device_trace(cfg.trace_dir)
            elif self.global_steps >= cfg.start_step + cfg.num_steps and self._tracing:
                self.stop_device_trace()
        except Exception as e:
            logger.warning(f"device trace hook failed ({type(e).__name__}: {e}); "
                           "continuing without trace")
            self._tracing = False

    def _maybe_flops_profile(self, batch):
        """Reference engine flops-profiler hook (``engine.py`` around
        ``flops_profiler_config.profile_step``): at the configured global
        step, capture the compiled step's XLA cost totals plus the
        per-module breakdown and print/persist the model profile."""
        fp = self.config.flops_profiler_config
        if not fp.enabled or self.global_steps != fp.profile_step:
            return
        try:
            prof = self.flops_profiler
            prof.start_profile()
            step_fn = self._compiled.get("train_step")
            if step_fn is not None:
                with self.mesh:
                    step_rng = jax.random.PRNGKey(0)
                    prof.profile_step(step_fn, self.state, self._shard_batch(batch, leading=("mb", )),
                                      step_rng)
            if self.module is not None and hasattr(self.module, "config"):
                leaves = jax.tree_util.tree_leaves(batch)
                seq = int(np.shape(leaves[0])[-1]) if leaves else self.config.train_micro_batch_size_per_gpu
                prof.profile_model(batch_size=self.config.train_micro_batch_size_per_gpu, seq_len=seq)
            prof.stop_profile()
            prof.print_model_profile(profile_step=fp.profile_step, module_depth=fp.module_depth,
                                     top_modules=fp.top_modules, detailed=fp.detailed,
                                     output_file=fp.output_file)
        except Exception as e:  # profiling must never kill a training step
            from ..utils.logging import logger

            logger.warning(f"flops profiler failed at step {self.global_steps}: {e}")

    def _apply_curriculum(self, batch, seq_axis=2, step=None):
        """seqlen curriculum: truncate the sequence dim of (gas, bsz, seq…)
        leaves to the current difficulty (reference passes curriculum_seqlen
        into the model, engine.py:1848; truncation is the model-agnostic TPU
        equivalent — each difficulty bucket compiles once). ``seq_axis``: 2
        on the fused path ((gas, bsz, seq)), 1 on the eager microbatch path.
        ``step``: set ONLY by the prefetch worker (the consuming global step)
        — that path reads the schedule side-effect-free; the inline path
        advances the shared scheduler state on the main thread."""
        sched = self.curriculum_scheduler
        diff = int(sched.difficulty_at(step) if step is not None
                   else sched.update_difficulty(self.global_steps))
        if self.curriculum_scheduler.config.curriculum_type != "seqlen":
            return batch
        # sequence dim must stay divisible by the seq-parallel axis
        if self.seq_world_size > 1:
            diff = max(self.seq_world_size, diff - diff % self.seq_world_size)

        def trunc(x):
            if np.ndim(x) > seq_axis and np.shape(x)[seq_axis] > diff:
                return x[(slice(None), ) * seq_axis + (slice(0, diff), )]
            return x

        return jax.tree_util.tree_map(trunc, batch)

    def _shard_batch(self, batch, leading=()):
        """Place host batch onto the mesh: batch dim over data axes, sequence
        dim over the seq axis when sequence parallelism is enabled.

        Idempotent: leaves that are already ``jax.Array``s sharded on THIS
        mesh (a prefetched batch, or a repeated call) pass through untouched.
        ``NamedSharding`` objects are cached by ``(ndim, n_leading)`` —
        the spec depends on nothing else for a fixed engine — instead of
        being rebuilt per leaf per step."""
        nlead = len(leading)

        def place(x):
            if isinstance(x, jax.Array) and getattr(x.sharding, "mesh", None) is self.mesh:
                return x  # already placed by this engine — placement is idempotent
            x = np.asarray(x)
            s = self._sharding_cache.get((x.ndim, nlead))
            if s is None:
                spec = [None] * x.ndim
                if x.ndim > nlead:
                    spec[nlead] = BATCH_AXES  # (data_repl, data) — full DP extent
                if self.seq_world_size > 1 and x.ndim > nlead + 1:
                    spec[nlead + 1] = SEQ_AXIS
                s = self._sharding_cache[(x.ndim, nlead)] = NamedSharding(self.mesh, P(*spec))
            return jax.make_array_from_process_local_data(s, x)

        return jax.tree_util.tree_map(place, batch)

    # ------------------------------------------------------------------
    # public API — eager 3-call path (drop-in DeepSpeed ergonomics)
    # ------------------------------------------------------------------
    def forward(self, batch, rng=None):
        """Compute loss for one microbatch (reference ``forward:1809``).

        Forward and backward share one compiled value_and_grad program: the
        grads computed here are stashed and consumed by the matching
        ``backward()`` call, so the 3-call API costs the same FLOPs as the
        fused path (no forward recomputation). Thanks to async dispatch the
        returned loss is a future; nothing blocks until the value is read.
        """
        assert self.pipe_world_size <= 1, (
            "forward/backward/step are not supported with pipeline parallelism; use train_batch() "
            "(same contract as the reference PipelineEngine)")
        assert self._onebit is None, (
            "1-bit optimizers require the fused train_batch() path (the compressed exchange lives "
            "inside the compiled step)")
        if self.curriculum_scheduler is not None and self._train_mode:
            batch = self._apply_curriculum(batch, seq_axis=1)
        if self.random_ltd_scheduler is not None and self._train_mode:
            self.random_ltd_scheduler.update_seq(self.global_steps)
        if self.progressive_layer_drop is not None and self._train_mode:
            # same injection as train_batch so the 3-call API gets PLD too
            self.progressive_layer_drop.update_state(self.global_steps)
            if not isinstance(batch, dict):
                batch = {"input_ids": batch}
            batch = {**batch, "pld_theta": np.float32(self.progressive_layer_drop.get_theta())}
        fwd_rng, self._rng = jax.random.split(self._rng)
        t0 = time.perf_counter() if self._tracer.enabled else 0.0
        if not self._train_mode:  # eval: loss only, no grads
            if "loss" not in self._compiled:
                self._compiled["loss"] = jax.jit(lambda p, b, r: self._loss_fn(p, b, r)[0])
            with self.mesh:
                loss = self._compiled["loss"](self.state["params"], self._shard_batch(batch), fwd_rng)
            self._emit_phase("fwd", t0, loss)
            return loss
        if "grads" not in self._compiled:

            def gfn(params, batch, rng, scale):
                return self._microbatch_grads(params, batch, rng, scale)

            self._compiled["grads"] = jax.jit(gfn)
        with self.mesh:
            batch = self._shard_batch(batch)
            grads, loss = self._compiled["grads"](self.state["params"], batch, fwd_rng, self.state["loss_scale"])
        self._emit_phase("fwd", t0, loss)
        self._pending_batches.append(grads)
        return loss

    __call__ = forward

    def backward(self, loss=None, retain_graph=False):
        """Accumulate grads for the last forward microbatch (reference
        ``backward:1950``). The sharded accumulation buffer realizes ZeRO-2:
        with stage>=2 each device holds only its gradient shard."""
        assert self._pending_batches, "backward() called without a prior forward()"
        t0 = time.perf_counter() if self._tracer.enabled else 0.0
        grads = self._pending_batches.pop(0)
        with self.mesh:
            if self._grad_acc_buffer is None:
                self._grad_acc_buffer = grads
            else:
                if "grad_add" not in self._compiled:
                    self._compiled["grad_add"] = jax.jit(
                        lambda a, b: jax.tree_util.tree_map(jnp.add, a, b), donate_argnums=(0, ))
                self._grad_acc_buffer = self._compiled["grad_add"](self._grad_acc_buffer, grads)
        self._emit_phase("bwd", t0, self._grad_acc_buffer)
        self.micro_steps += 1
        return loss

    def _emit_phase(self, name, t0, block_on=None):
        """Emit one engine-phase duration event (fwd/bwd/step). No-op unless
        the trace bus is live; blocking on ``block_on`` then is what makes
        the span cover the device work, not just the async dispatch."""
        if not self._tracer.enabled:
            return
        if block_on is not None:
            try:
                jax.block_until_ready(block_on)
            except Exception:
                pass
        tid = "checkpoint" if name.startswith("checkpoint/") else "engine"
        self._tracer.complete(name, t0, time.perf_counter() - t0, tid=tid,
                              args={"step": self.global_steps})

    def is_gradient_accumulation_boundary(self):
        """Reference ``engine.py`` same name: true when the next step() will
        apply the optimizer."""
        return len(self._pending_batches) == 0 and self._grad_acc_buffer is not None and \
            self.micro_steps % self.config.gradient_accumulation_steps == 0

    def step(self):
        """Apply the optimizer at the GAS boundary (reference ``step:2152``)."""
        gas = self.config.gradient_accumulation_steps
        if self.micro_steps % gas != 0:
            return  # mid-accumulation micro-step, nothing to do
        self._maybe_device_trace()  # eager 3-call path traces too
        assert self._grad_acc_buffer is not None, "step() called with no accumulated gradients"
        t0 = time.perf_counter() if self._tracer.enabled else 0.0
        if self.host_optimizer is not None:
            grads = jax.tree_util.tree_map(lambda g: g / gas, self._grad_acc_buffer)
            if "gnorm" not in self._compiled:
                self._compiled["gnorm"] = jax.jit(optax.global_norm)
            with self.mesh:
                gnorm = self._compiled["gnorm"](grads)  # device-side: exact across hosts
            self._host_apply_update(grads, scaled_gnorm=gnorm)
            self._grad_acc_buffer = None
            self.global_steps += 1
            self.global_samples += self.train_batch_size()
            if self.lr_scheduler is not None:
                self.lr_scheduler.step()
            self._emit_phase("step", t0)
            return
        if "apply" not in self._compiled:

            def apply_fn(state, grads):
                grads = jax.tree_util.tree_map(lambda g: g / gas, grads)
                new_state, finite = self._apply_update(state, grads, jnp.array(True))
                return new_state, finite

            self._compiled["apply"] = jax.jit(apply_fn, donate_argnums=(0, 1),
                                              out_shardings=(self._state_shardings, None))
        with self.mesh:
            self.state, finite = self._compiled["apply"](self.state, self._grad_acc_buffer)
        self._grad_acc_buffer = None
        self.global_steps += 1
        self.global_samples += self.train_batch_size()
        if not bool(finite):
            self.skipped_steps += 1
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        self._emit_phase("step", t0)

    # ------------------------------------------------------------------
    # introspection (reference engine getters)
    # ------------------------------------------------------------------
    def get_global_grad_norm(self):
        return self._step_metrics.get("grad_norm")

    def get_lr(self):
        if self.lr_schedule_fn is not None:
            return [float(self.lr_schedule_fn(int(self.state["step"])))]
        return [float((self.config.optimizer_params or {}).get("lr", 0.0))]

    @property
    def loss_scale(self):
        return float(self.state["loss_scale"])

    def train_batch_size(self):
        return self.config.train_batch_size

    def train_micro_batch_size_per_gpu(self):
        return self.config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self):
        return self.config.gradient_accumulation_steps

    def sparse_attention_config(self):
        """Reference engine accessor: the raw ``sparse_attention`` config
        block (feed to ``ops.sparse_attention.build_sparsity_config``)."""
        return self.config.sparse_attention

    def zero_optimization_stage(self):
        return self.config.zero_optimization_stage

    def get_batch_info(self):
        return (self.train_batch_size(), self.train_micro_batch_size_per_gpu(), self.gradient_accumulation_steps())

    def _observe_step(self, t0, batch, metrics):
        """Trace span + derived throughput/MFU for one fused train step.
        Only runs when the trace bus or metrics registry is live (observing
        implies profiling mode, so blocking on the step result is intended —
        it is what makes the recorded wall time honest)."""
        jax.block_until_ready(metrics["loss"])
        dt = max(time.perf_counter() - t0, 1e-9)
        leaves = jax.tree_util.tree_leaves(batch)
        # (gas, rows, seq, ...) leaves carry a token dim; scalar tracks don't
        seq = int(np.shape(leaves[0])[-1]) if leaves and np.ndim(leaves[0]) >= 3 else None
        tokens = self.train_batch_size() * (seq or 1)
        mfu = None
        if seq is not None:
            from ..profiling.flops_profiler import training_flops_per_token

            mcfg = getattr(self.module, "config", None)
            fpt = training_flops_per_token(self._n_params,
                                           num_layers=getattr(mcfg, "num_layers", None),
                                           hidden_size=getattr(mcfg, "hidden_size", None),
                                           seq_len=seq)
            mfu = compute_mfu(fpt * tokens, dt, n_chips=self.mesh.size)
        reg = self._metrics
        if reg.enabled:
            reg.counter("train/steps").inc()
            reg.counter("train/tokens").inc(tokens)
            reg.histogram("train/step_time_ms").observe(dt * 1e3)
            reg.gauge("train/tokens_per_sec").set(tokens / dt)
            reg.gauge("train/samples_per_sec").set(self.train_batch_size() / dt)
            if mfu is not None:
                reg.gauge("train/mfu").set(mfu)
        if self._tracer.enabled:
            args = {"step": self.global_steps, "tokens": tokens}
            if mfu is not None:
                args["mfu"] = round(mfu, 4)
            self._tracer.complete("train_batch", t0, dt, tid="engine", args=args)

    def _record_metrics(self, metrics):
        self._step_metrics = {k: v for k, v in metrics.items()}
        if self.monitor.enabled and self.global_steps % self.config.steps_per_print == 0:
            events = [("Train/Samples/train_loss", float(metrics["loss"]), self.global_samples),
                      ("Train/Samples/lr", float(metrics["lr"]), self.global_samples)]
            if self.fp16_enabled:
                events.append(("Train/Samples/loss_scale", self.loss_scale, self.global_samples))
            # drain the metrics registry (throughput, MFU, latency histograms,
            # compile counters) into the same sink fan-out, then flush so the
            # persistent-handle CSV sink is crash-safe and tail-able
            events += self._metrics.events(self.global_samples)
            self.monitor.write_events(events)
            self.monitor.flush()
        if self.global_steps % self.config.steps_per_print == 0:
            log_dist(f"step={self.global_steps} loss={float(metrics['loss']):.4f} "
                     f"lr={float(metrics['lr']):.3e} gnorm={float(metrics['grad_norm']):.3f}", ranks=[0])

    # ------------------------------------------------------------------
    # data pipeline (reference ``deepspeed_io`` engine.py:1716)
    # ------------------------------------------------------------------
    def _process_dp_coord(self):
        """(dp_rank, dp_world) of THIS process along the batch data axis.

        With model/seq axes spanning processes, multiple processes belong to
        the same data-parallel replica and must draw the SAME samples; the
        coordinate is derived from which data-axis indices this process's
        addressable devices cover, not from the raw process rank."""
        try:
            mesh_devs = self.mesh.devices  # ndarray indexed by axis order
            axis_names = list(self.mesh.axis_names)
            data_dim = axis_names.index(DATA_AXIS)
            repl_dim = axis_names.index(DATA_REPL_AXIS) if DATA_REPL_AXIS in axis_names else None
            import numpy as _np

            proc = jax.process_index()
            coords = set()
            it = _np.nditer(_np.empty(mesh_devs.shape), flags=["multi_index"])
            data_size = mesh_devs.shape[data_dim]
            for _ in it:
                d = mesh_devs[it.multi_index]
                if d.process_index == proc:
                    # flat coord over (data_repl, data): batch shards span both
                    c = it.multi_index[data_dim]
                    if repl_dim is not None:
                        c += it.multi_index[repl_dim] * data_size
                    coords.add(c)
            dp_size = data_size * (mesh_devs.shape[repl_dim] if repl_dim is not None else 1)
            coords = sorted(coords)
            n_owned = len(coords)
            if n_owned == 0 or dp_size % n_owned != 0:
                return dist.get_rank(), dist.get_world_size()
            return coords[0] // n_owned, dp_size // n_owned
        except Exception:
            return dist.get_rank(), dist.get_world_size()

    def deepspeed_io(self, dataset, batch_size=None, route="train", collate_fn=None, num_local_io_workers=None,
                     data_sampler=None):
        from .dataloader import DeepSpeedDataLoader

        dp_rank, dp_world = self._process_dp_coord()
        if batch_size is None:
            # each PROCESS loads the shard of the global batch covering its
            # addressable devices: micro_bsz per data coordinate, and this
            # process owns batch_dp/dp_world of them (1 on one-device-per-
            # process pods; all of them single-process) — so the loader's
            # microbatches feed train_batch(data_iter=...) directly
            batch_size = (self.config.train_micro_batch_size_per_gpu
                          * max(1, self.batch_dp_world_size // dp_world))
        return DeepSpeedDataLoader(dataset,
                                   batch_size=batch_size,
                                   collate_fn=collate_fn,
                                   drop_last=self.config.dataloader_drop_last,
                                   data_parallel_rank=dp_rank,
                                   data_parallel_world_size=dp_world)

    # ------------------------------------------------------------------
    # checkpointing (reference save_checkpoint:3069 / load_checkpoint:2721)
    # ------------------------------------------------------------------
    def _ckpt_state(self, client_state=None):
        leaves, treedef = jax.tree_util.tree_flatten(self.state["opt_state"])
        onebit = None
        if self._onebit is not None:
            onebit = {
                "err_w": {str(i): l for i, l in enumerate(jax.tree_util.tree_leaves(self.state["onebit_err_w"]))},
                "err_s": {str(i): l for i, l in enumerate(jax.tree_util.tree_leaves(self.state["onebit_err_s"]))},
            }
        return {
            "onebit": onebit,
            "module": self.state["params"],
            "optimizer": {str(i): l for i, l in enumerate(leaves)},
            "scalars": {
                "step": self.state["step"],
                "loss_scale": self.state["loss_scale"],
                "good_steps": self.state["good_steps"],
            },
            "global_steps": self.global_steps,
            "global_samples": self.global_samples,
            "skipped_steps": self.skipped_steps,
            "lr_scheduler": self.lr_scheduler.state_dict() if self.lr_scheduler is not None else None,
            "curriculum_scheduler": (self.curriculum_scheduler.state_dict()
                                     if self.curriculum_scheduler is not None else None),
            "random_ltd_scheduler": (self.random_ltd_scheduler.state_dict()
                                     if self.random_ltd_scheduler is not None else None),
            "host_optimizer": (_escape_keys(self.host_optimizer.state_dict())
                               if self.host_optimizer is not None else None),
            "ds_config": self.config.param_dict,
            "ds_version": "0.1.0-tpu",
            **(client_state or {}),
        }

    def save_checkpoint(self, save_dir, tag=None, client_state=None, save_latest=True,
                        exclude_frozen_parameters=False, blocking=None):
        """Save a durable checkpoint version.

        ``blocking=None`` follows ``checkpoint.async_save`` (nebula flips it
        on). The non-blocking path pays only the host-snapshot cost in the
        step loop (measured as ``train/ckpt_blocked_ms``): the tree is handed
        to the bounded background writer, which persists the payload, commits
        a ``manifest.json`` (the durability point — see
        ``runtime/resilience/saver.py``), and only then flips ``latest``; a
        crash mid-write leaves ``latest`` on the previous durable tag. A
        subsequent save/:meth:`flush_checkpoints`/:meth:`destroy` joins the
        in-flight write. Returns False (and leaves ``latest`` untouched) when
        the engine refuses commit on the blocking path, or when the payload
        write fails on the multi-host async path (where it runs at the step
        boundary and only commit/manifest I/O is backgrounded).

        True on the async path means *submitted*, not durable: the auto-save
        plane retries failed async commits on its own, but any other caller
        must check :meth:`flush_checkpoints` (or ``_ckpt_saver.last_error``)
        before relying on the tag — an async failure is never re-raised into
        the step loop.
        """
        if blocking is None:
            blocking = not self.config.checkpoint_config.async_save
        if tag is None:
            tag = f"global_step{self.global_steps}"
        self._checkpoint_tag_validation(tag)
        path = os.path.join(save_dir, str(tag))
        t0 = time.perf_counter()
        with self._tracer.span("checkpoint/save", tid="checkpoint", tag=str(tag),
                               blocking=bool(blocking)):
            state = self._ckpt_state(client_state)
            # cross-rank success vote (single-host: gathers over one rank and
            # degenerates to the local result). It replaces a trailing
            # dist.barrier(): the vote itself holds every rank at the same
            # point, and unlike a barrier it is reached on EVERY path — a
            # rank whose save raises still votes False before unwinding,
            # where skipping a barrier would hang its peers for good.
            gate = lambda local_ok: all(dist.all_gather_host(bool(local_ok)))
            if blocking:
                # blocking saves vote twice: on the engine commit result
                # (durability) just before the manifest/`latest` flip — one
                # rank's failed payload or refused commit withholds
                # advertisement everywhere — and again after the flip, so no
                # rank returns (and possibly exits, taking the gang with it)
                # while the lead is still writing the manifest
                ok = self._ckpt_saver.save(state, save_dir, str(tag), blocking=True,
                                           save_latest=save_latest, commit_gate=gate)
            elif jax.process_count() == 1:
                # step-boundary host snapshot: after this, training may
                # mutate engine state freely while the writer persists the
                # snapshot
                state = self._host_snapshot(state)
                ok = self._ckpt_saver.save(state, save_dir, str(tag), blocking=False,
                                           save_latest=save_latest)
            else:
                # multi-host arrays are not fully addressable, so the host
                # snapshot above can't be taken here — the orbax save itself
                # performs it. That payload write runs synchronously at the
                # step boundary: handing live jax.Array leaves to the writer
                # thread would race the next train_batch's buffer donation
                # (donate_argnums=(0,)), and orbax's save-side cross-process
                # sync must not interleave with training collectives from a
                # non-main thread. Only host-side I/O (commit join, manifest,
                # `latest`, retention GC) is left to the background writer.
                # The gate here votes on payload *submission* (all the step
                # boundary can observe: with an async engine, save() returns
                # once the snapshot is taken and the write submitted) — a
                # rank whose snapshot fails withholds every rank's commit
                # stage, and the all-gather holds all ranks at the boundary
                # until every snapshot is down. Write-side divergence AFTER
                # submission fails closed in the background commit instead:
                # orbax's AsyncCheckpointer finalize runs its own cross-
                # process sync (via the jax.distributed client — safe off
                # the main thread), so a peer's failed write surfaces as
                # wait_until_finished raising on every rank -> commit()
                # False -> no manifest, no `latest` flip.
                ok = self._ckpt_saver.save(
                    state, save_dir, str(tag), blocking=False,
                    save_latest=save_latest, payload_in_caller=True, commit_gate=gate)
        if self._metrics.enabled:
            self._metrics.histogram("train/ckpt_blocked_ms").observe(
                (time.perf_counter() - t0) * 1e3)
        if self._goodput is not None:
            # the step-loop seconds this save blocked (host snapshot under
            # async, the whole write under sync) — same window the
            # histogram above measures
            self._goodput.book("ckpt_blocked", time.perf_counter() - t0)
        if ok and self.config.checkpoint_config.remesh_snapshot:
            # elastic warm remesh: publish a host universal-layout snapshot
            # alongside the save, so a topology-change restart re-shards
            # from RAM (run_resilient(warm_remesh=True)) instead of reading
            # this checkpoint back. On the async single-host path `state`
            # is already host numpy — the capture reuses it and costs fp32
            # casts, not a second device_get. Single-host only: multi-host
            # arrays are not fully addressable (device_get would raise on
            # every save — the same constraint that routes the multi-host
            # payload through orbax above), so the knob is inert there.
            if jax.process_count() > 1:
                if not getattr(self, "_remesh_multihost_warned", False):
                    self._remesh_multihost_warned = True
                    logger.warning("checkpoint.remesh_snapshot is single-host only "
                                   "(multi-host arrays are not fully addressable); "
                                   "warm resume will use the disk path")
            else:
                try:
                    from ..elasticity import remesh

                    remesh.publish_snapshot(remesh.capture_snapshot(self, state=state),
                                            scope=save_dir)
                except Exception as e:  # noqa: BLE001 — a failed snapshot only
                    # costs the warm path; the durable save above already landed
                    logger.warning(f"remesh snapshot capture failed: {e!r}; "
                                   f"warm resume will fall back to disk")
        if ok:
            # a refused commit must NOT reset the auto-save cadence — the
            # next retry should come promptly, not a full interval away
            self._auto_save.mark_saved(self.global_steps)
            if blocking:
                log_dist(f"saved checkpoint {path}", ranks=[0])
            else:
                # submission, not durability: the writer logs commit/failure
                # when it happens. The auto-save plane retries a failed async
                # commit itself (see _poll_resilience); any other caller must
                # check flush_checkpoints() before relying on the tag.
                log_dist(f"submitted async checkpoint {path} (durable only after the "
                         f"writer commits; flush_checkpoints() reports the outcome)",
                         ranks=[0])
        else:
            logger.error(f"checkpoint {path} NOT committed; 'latest' untouched")
        return ok

    def _host_snapshot(self, state):
        """Copy array leaves to host numpy so the background writer holds no
        device references (the only step-loop-blocking cost of async save)."""
        return jax.tree_util.tree_map(
            lambda x: np.asarray(jax.device_get(x)) if isinstance(x, jax.Array) else x, state)

    def flush_checkpoints(self, raise_on_error=False):
        """Join any in-flight async checkpoint write; returns True when the
        last write committed cleanly."""
        return self._ckpt_saver.flush(raise_on_error=raise_on_error)

    def set_checkpoint_dir(self, save_dir):
        """Arm auto-save/preemption saves to target ``save_dir`` (the
        runtime override of ``checkpoint.auto_save_dir`` /
        ``nebula.persistent_storage_path``). Multi-host: call on every
        process — the triggered save runs collectives."""
        self._ckpt_save_dir = save_dir
        self._resilience_active = (self._preemption is not None
                                   or (self._auto_save.enabled and self._ckpt_save_dir is not None))
        return self

    def _poll_resilience(self):
        """Step-boundary resilience poll (one boolean when inactive).

        Preemption wins over cadence: the final save is BLOCKING (the grace
        window is for durability, not overlap), then the in-flight writer is
        joined and :class:`~.resilience.TrainingPreempted` (a clean
        ``SystemExit(0)``) unwinds the step loop. Cadence saves follow the
        configured async/sync mode."""
        from .resilience import TrainingPreempted

        preempt = self._preemption is not None and self._preemption.requested
        due = (self._auto_save.enabled and self._ckpt_save_dir is not None
               and (self._auto_save.should_save(self.global_steps)
                    # an async commit that failed AFTER the cadence reset must
                    # retry promptly, not a full interval later (last_error is
                    # cleared when the retry save is submitted)
                    or (self._ckpt_saver.last_error is not None
                        and not self._ckpt_saver.in_flight)))
        if jax.process_count() > 1:
            # signal delivery timing, the wall clock, and a failed writer are
            # all process-local: a rank acting on a local decision enters the
            # save path's collectives (tag validation all-gather, barrier)
            # while the others continue training, and the job deadlocks. OR
            # the votes so every process takes the same branch at the same
            # step (one small host all-gather per step, only while the
            # resilience plane is active at all).
            #
            # Straggler piggyback: with the health plane on, each rank rides
            # its (step, step_wall_ms, input_wait_ms) sample on this SAME
            # gather — every host then computes slowest-rank skew for free
            # (no extra collective). Arity is config-derived, so all ranks
            # agree on the tuple shape.
            payload = (bool(preempt), bool(due))
            if self._health.enabled:
                payload += (self.global_steps, round(self._last_step_wall_ms, 3),
                            round(self._last_input_wait_ms, 3))
            votes = dist.all_gather_host(payload)
            preempt = any(v[0] for v in votes)
            due = any(v[1] for v in votes)
            # ranks can be health-armed asymmetrically (programmatic
            # configure() on rank 0 only): skew is only meaningful — and the
            # per-vote tail only present — when EVERY rank sent its sample
            samples = [v[2:] for v in votes if len(v) >= 5]
            if self._health.enabled and samples and len(samples) == len(votes):
                self._health.note_straggler(samples)
        if preempt:
            tag = None
            if self._ckpt_save_dir is not None:
                tag = f"global_step{self.global_steps}"
                # the grace window is for a durable EXIT, not for crashing: a
                # raising final save (disk full, backend gone) must still end
                # in the clean TrainingPreempted exit so the scheduler — and
                # run_resilient — resume from the previous durable tag
                try:
                    if not self.save_checkpoint(self._ckpt_save_dir, tag=tag, blocking=True):
                        tag = None  # never advertise a refused commit as the resume point
                except Exception as e:
                    logger.error(f"preemption: final save raised {e!r}; exiting cleanly "
                                 f"on the previous durable tag")
                    tag = None
            self.flush_checkpoints()
            if self._tracer.enabled:
                self._tracer.instant("preemption_exit", tid="checkpoint")
            if tag is not None:
                log_dist(f"preemption: final checkpoint {tag} committed, exiting cleanly",
                         ranks=[0])
            else:
                logger.error("preemption: final checkpoint did NOT commit; exiting cleanly — "
                             "resume will use the previous durable tag")
            raise TrainingPreempted(tag)
        if due and self._ckpt_save_dir is not None:
            try:
                self.save_checkpoint(self._ckpt_save_dir)
            except Exception as e:
                # a failed cadence save must not kill training — the cadence
                # was not reset (mark_saved only runs on success), so the
                # next step-boundary poll retries promptly
                logger.error(f"auto-save failed: {e!r}; training continues, "
                             f"will retry at the next step boundary")

    def _checkpoint_tag_validation(self, tag):
        """All ranks must agree on the tag (reference ``engine.py:3052``)."""
        if not self.config.checkpoint_tag_validation_enabled:
            return
        import zlib

        tags = dist.all_gather_host(zlib.crc32(str(tag).encode()))
        if any(t != tags[0] for t in tags):
            msg = f"checkpoint tag '{tag}' differs across ranks"
            if self.config.checkpoint_tag_validation_fail:
                raise ValueError(msg)
            logger.warning(msg)

    def load_checkpoint(self, load_dir, tag=None, load_module_strict=True, load_optimizer_states=True,
                        load_lr_scheduler_states=True, load_module_only=False, custom_load_fn=None,
                        fallback_to_valid=True):
        """Restore from ``load_dir``. The resolved tag is validated against
        its commit manifest (when one exists); on corruption — torn payload,
        digest/size mismatch, missing ``arrays`` tree — the load falls back
        to the newest *valid* tag (``fallback_to_valid=False`` raises
        :class:`~.resilience.CheckpointCorruptError` instead)."""
        t0 = time.perf_counter() if self._tracer.enabled else 0.0
        self.flush_checkpoints()  # never race a restore against our own writer
        if tag is None:
            latest_path = os.path.join(load_dir, LATEST_FILE)
            if os.path.isfile(latest_path):
                with open(latest_path, "r") as f:
                    tag = f.read().strip()
            else:
                logger.warning(f"no 'latest' file at {latest_path}, nothing loaded")
                return None, {}
        path = os.path.join(load_dir, str(tag))

        leaves, treedef = jax.tree_util.tree_flatten(self.state["opt_state"])
        template = {
            "module": jax.tree_util.tree_map(_as_shape_struct, self.state["params"],
                                             self._state_shardings["params"]),
            "optimizer": {str(i): _as_shape_struct(l, _shard_of(l)) for i, l in enumerate(leaves)},
            "scalars": {k: _as_shape_struct(self.state[k], _shard_of(self.state[k]))
                        for k in ("step", "loss_scale", "good_steps")},
        }
        if self.host_optimizer is not None and load_optimizer_states:
            # state_template: shapes only — no NVMe reads just for a template
            template["host_optimizer"] = _escape_keys(self.host_optimizer.state_template())
        if self._onebit is not None and load_optimizer_states:
            template["onebit"] = {
                kind: {str(i): _as_shape_struct(l, _shard_of(l))
                       for i, l in enumerate(jax.tree_util.tree_leaves(self.state[state_key]))}
                for kind, state_key in (("err_w", "onebit_err_w"), ("err_s", "onebit_err_s"))
            }
        loaded, path, tag = self._load_verified(load_dir, tag, path, template, fallback_to_valid)
        params = loaded["module"]
        state = dict(self.state)
        state["params"] = params
        if load_optimizer_states and not load_module_only and "optimizer" in loaded:
            opt_leaves = [loaded["optimizer"][str(i)] for i in range(len(leaves))]
            state["opt_state"] = jax.tree_util.tree_unflatten(treedef, opt_leaves)
        for k in ("step", "loss_scale", "good_steps"):
            if "scalars" in loaded and k in loaded["scalars"]:
                state[k] = loaded["scalars"][k]
        if self._onebit is not None and load_optimizer_states and _fully_restored(loaded.get("onebit")):
            for kind, state_key in (("err_w", "onebit_err_w"), ("err_s", "onebit_err_s")):
                tdef = jax.tree_util.tree_structure(state[state_key])
                n = tdef.num_leaves
                state[state_key] = jax.tree_util.tree_unflatten(
                    tdef, [loaded["onebit"][kind][str(i)] for i in range(n)])
        self.state = state
        self.global_steps = int(loaded.get("global_steps", 0))
        self.global_samples = int(loaded.get("global_samples", 0))
        self.skipped_steps = int(loaded.get("skipped_steps", 0))
        if load_lr_scheduler_states and self.lr_scheduler is not None and loaded.get("lr_scheduler"):
            self.lr_scheduler.load_state_dict(loaded["lr_scheduler"])
        if self.curriculum_scheduler is not None and loaded.get("curriculum_scheduler"):
            self.curriculum_scheduler.load_state_dict(loaded["curriculum_scheduler"])
        if self.random_ltd_scheduler is not None and loaded.get("random_ltd_scheduler"):
            self.random_ltd_scheduler.load_state_dict(loaded["random_ltd_scheduler"])
        if self.host_optimizer is not None:
            if load_optimizer_states and _fully_restored(loaded.get("host_optimizer")):
                self.host_optimizer.load_state_dict(_unescape_keys(loaded["host_optimizer"]))
            else:
                # masters must follow the loaded weights, else the next host
                # step would resurrect the pre-load params
                self.host_optimizer.reset_masters(self._host_slice(self.state["params"]))
        client_state = {k: v for k, v in loaded.items()
                        if k not in ("module", "optimizer", "scalars", "global_steps", "global_samples",
                                     "skipped_steps", "lr_scheduler", "curriculum_scheduler",
                                     "random_ltd_scheduler", "host_optimizer", "onebit", "ds_config",
                                     "ds_version")}
        # the restored state IS a fresh save for cadence purposes — without
        # this, a resume at a high step sees (step - 0) >= interval and
        # immediately re-writes a checkpoint nearly identical to the one it
        # just loaded (and, with retention on, evicts a real older version)
        self._auto_save.mark_saved(self.global_steps)
        self._emit_phase("checkpoint/load", t0)
        log_dist(f"loaded checkpoint {path}", ranks=[0])
        return path, client_state

    def _load_verified(self, load_dir, tag, path, template, fallback):
        """Manifest-verify + restore, walking back to the newest valid tag
        on corruption (the self-healing half of the commit protocol)."""
        from .resilience import CheckpointCorruptError
        from .resilience.manifest import is_committed, MANIFEST_FILE, verify_manifest
        from .resilience.saver import list_tags, tag_order_key

        tried = set()
        while True:
            try:
                if os.path.isfile(os.path.join(path, MANIFEST_FILE)):
                    # size/existence pass on every load; legacy dirs without
                    # a manifest skip to the engine's own payload checks
                    verify_manifest(path, deep=False)
                return self.checkpoint_engine.load(path, template=template), path, tag
            except CheckpointCorruptError as e:
                tried.add(os.path.abspath(path))
                logger.error(f"checkpoint {path} failed validation: {e}")
                if not fallback:
                    raise
                nxt = None
                for cand in sorted(list_tags(load_dir), key=lambda t: tag_order_key(load_dir, t),
                                   reverse=True):
                    cand_path = os.path.join(load_dir, cand)
                    if os.path.abspath(cand_path) in tried:
                        continue
                    if is_committed(cand_path):
                        nxt = (cand, cand_path)
                        break
                if nxt is None:
                    raise
                tag, path = nxt
                logger.warning(f"falling back to newest valid checkpoint tag '{tag}'")

    def save_16bit_model(self, save_dir, save_filename="pytorch_model.bin", exclude_frozen_parameters=False):
        """Gather full (unsharded) bf16 weights for export (reference
        ``save_16bit_model`` engine.py:3552 / ``_zero3_consolidated_16bit_state_dict``)."""
        full = self._gather_full_params(dtype=jnp.bfloat16)
        if dist.get_rank() == 0:
            os.makedirs(save_dir, exist_ok=True)
            import pickle

            with open(os.path.join(save_dir, save_filename), "wb") as f:
                pickle.dump(full, f)
        dist.barrier()
        return True

    def save_fp16_model(self, save_dir, save_filename="pytorch_model.bin"):
        """Reference alias (engine.py:3544) of :meth:`save_16bit_model`."""
        return self.save_16bit_model(save_dir, save_filename)

    def _gather_full_params(self, dtype=None):
        """Gather the (possibly sharded) param tree replicated onto host —
        shared by ``save_16bit_model`` and ``module_state_dict``."""
        cast = (lambda x: x.astype(dtype)) if dtype is not None else (lambda x: x)
        full = jax.device_get(
            jax.jit(lambda p: jax.tree_util.tree_map(cast, p),
                    out_shardings=jax.tree_util.tree_map(lambda _: NamedSharding(self.mesh, P()),
                                                         self.state["params"]))(self.state["params"]))
        return jax.tree_util.tree_map(np.asarray, full)

    def module_state_dict(self):
        """Full (unsharded) fp32 param tree on host (reference
        ``module_state_dict`` — consumed by save paths and integrations)."""
        return self._gather_full_params()

    def load_module_state_dict(self, state_dict, strict=True):
        """Install a full param tree into the engine's (sharded) state
        (reference ``load_module_state_dict``). ``strict`` verifies the tree
        structure matches before placement. With ZeRO-Offload the host fp32
        masters are overwritten too — otherwise the next step would
        resurrect the pre-load weights from the stale masters."""
        if strict:
            want = jax.tree_util.tree_structure(self.state["params"])
            got = jax.tree_util.tree_structure(state_dict)
            if want != got:
                raise ValueError(f"state_dict structure mismatch: engine has {want}, got {got}")
        shardings = jax.tree_util.tree_map(lambda x: x.sharding, self.state["params"])
        placed = jax.device_put(
            jax.tree_util.tree_map(lambda new, cur: jnp.asarray(new, cur.dtype),
                                   state_dict, self.state["params"]), shardings)
        self.state = {**self.state, "params": placed}
        if self.host_optimizer is not None:
            self.host_optimizer.reset_masters(self._host_slice(placed))
        return self

    def set_train_batch_size(self, train_batch_size: int):
        """Adjust the global batch by changing gradient accumulation only
        (reference ``set_train_batch_size`` engine.py:446: micro-batch and
        dp world size stay fixed; indivisible values are rejected). Uses the
        BATCH dp extent (data x data_repl axes — the seq axis does not
        multiply the batch)."""
        micro_global = self.config.train_micro_batch_size_per_gpu * self.batch_dp_world_size
        if train_batch_size % micro_global != 0:
            raise ValueError(f"train_batch_size {train_batch_size} must be divisible by "
                             f"micro_batch*dp = {micro_global}")
        self.config.gradient_accumulation_steps = train_batch_size // micro_global
        self.config.train_batch_size = train_batch_size
        # gas is baked into every compiled step (fused, offload, pipeline) —
        # drop them all and recompile on next use
        self._compiled = {}

    def set_train_micro_batch_size(self, micro_batch_size: int):
        """Reference ``set_train_micro_batch_size`` (engine.py:460): change
        the micro batch, keeping gas — the global batch follows."""
        self.config.train_micro_batch_size_per_gpu = micro_batch_size
        self.config.train_batch_size = (micro_batch_size * self.batch_dp_world_size *
                                        self.config.gradient_accumulation_steps)
        self._compiled = {}

    def get_mom(self):
        """Current momentum (reference ``get_mom`` engine.py:1744): betas for
        the Adam family, the scalar momentum for SGD."""
        params = self.config.optimizer_params or {}
        if str(self.config.optimizer_name or "").lower() == "sgd":
            return [params.get("momentum", 0.0)]
        betas = params.get("betas", (params.get("beta1", 0.9), params.get("beta2", 0.999)))
        return [list(betas)]

    def set_data_post_process_func(self, fn):
        """Reference ``set_data_post_process_func`` (data-efficiency hook).
        Contract: ``fn`` receives exactly what the caller feeds
        ``train_batch`` — each dataloader microbatch on the ``data_iter``
        path, or the whole ``gas*micro`` batch on the ``batch=`` path (no
        hidden re-slicing)."""
        self._data_post_process_func = fn

    def _memory_sections(self):
        """HBM attribution provider: live device bytes of the train state,
        split params vs optimizer/ZeRO shards (host-offloaded masters live
        in host RAM and are deliberately NOT HBM rows)."""
        from ..monitor.memory import tree_device_bytes

        state = self.state
        if not isinstance(state, dict):
            return {}
        return {"params": tree_device_bytes(state.get("params")),
                "optimizer": tree_device_bytes(state.get("opt_state"))}

    def destroy(self):
        """Release compiled executables, device state, accumulated grads and
        host optimizer masters (reference ``destroy`` — lets a process build
        a fresh engine without holding two copies in HBM/host RAM)."""
        if self._tracing:
            # a trace window reaching the final step has no later train_batch
            # to close it — flush the artifact before tearing state down
            self.stop_device_trace()
        if self._health.enabled:
            # the step loop is over: disarm its heartbeat BEFORE the writer
            # join below — a slow final checkpoint join past the engine
            # deadline is the saver's problem (it has its own source), not a
            # bogus "engine stalled" forensic dump
            self._health.disarm("engine")
        # join any in-flight async checkpoint write: tearing down state under
        # a live writer would hand tensorstore a half-freed tree. The join is
        # BOUNDED: a writer wedged in storage I/O must not hang destroy()
        # forever (it warns, counts health/saver_join_timeout_total, and the
        # daemon thread dies with the process).
        self._ckpt_saver.shutdown()
        if self._health.enabled:
            # final forensic record: the tail window of everything the run
            # did, so a post-mortem has the same bundle a stall dump carries
            if self._health.dump_on_destroy:
                try:
                    self._health.dump("destroy")
                except Exception as e:
                    logger.warning(f"health: destroy() dump failed: {e!r}")
            self._health.set_state_provider("engine", None)
            self._health.set_state_provider("saver", None)
        if self._preemption is not None:
            self._preemption.uninstall()
            self._preemption = None
        for pf in self._prefetchers:
            pf.close()  # stop workers + drop their queued device batches
        self._prefetchers = []
        from ..monitor.memory import get_memory

        get_memory().unregister(self._memory_reg_name)
        self._compiled = {}
        self.state = None
        self._grad_acc_buffer = None
        self.host_optimizer = None
        import gc

        gc.collect()

    # convenience (torch-style mode flags; eval() makes forward() loss-only)
    def eval(self):
        self._train_mode = False
        return self

    def train(self, mode=True):
        self._train_mode = bool(mode)
        return self


def _fully_restored(tree):
    """True when a restored checkpoint subtree contains real arrays — a
    partial restore leaves ShapeDtypeStruct placeholders for subtrees that
    were absent on disk (e.g. loading a non-offload checkpoint into an
    offload-enabled engine)."""
    if not tree:
        return False
    leaves = jax.tree_util.tree_leaves(tree)
    return bool(leaves) and not any(isinstance(l, jax.ShapeDtypeStruct) for l in leaves)


def _escape_keys(tree):
    """Param-path keys contain '/' which checkpoint layouts reserve."""
    if isinstance(tree, dict):
        return {k.replace("/", "::"): _escape_keys(v) for k, v in tree.items()}
    return tree


def _unescape_keys(tree):
    if isinstance(tree, dict):
        return {k.replace("::", "/"): _unescape_keys(v) for k, v in tree.items()}
    return tree


def _as_shape_struct(x, sharding=None):
    return jax.ShapeDtypeStruct(np.shape(x), x.dtype, sharding=sharding)


def _shard_of(x):
    return getattr(x, "sharding", None)
