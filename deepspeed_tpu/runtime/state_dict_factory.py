"""State-dict loader factory: MP-degree resharding of inference checkpoints.

Analog of the reference ``runtime/state_dict_factory.py`` (434 LoC —
``SDLoaderFactory.get_sd_loader``, ``MegatronSDLoader`` with its
split/merge-qkv handling): a checkpoint saved at one model-parallel degree
is loaded at another by splitting or merging each TP-sharded weight along
its policy axis, with fused-QKV tensors split per-head-interleave so each
rank gets whole heads.

The TPU engine itself never needs per-rank files (a full state dict is
device_put into NamedShardings), so the factory's job here is the NUMERIC
reshape: ``n_ranks x shard dicts at degree A -> m shard dicts at degree B``,
used by conversion tooling and the universal-checkpoint pipeline.
"""

import json
import os
import re
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..module_inject.policies import POLICY_REGISTRY, TransformerPolicy
from ..utils.logging import logger


class SDLoaderFactory:
    """Reference ``SDLoaderFactory``: pick a loader by checkpoint type."""

    @staticmethod
    def get_sd_loader_json(json_file_or_dict, checkpoint_engine=None):
        if isinstance(json_file_or_dict, str):
            with open(json_file_or_dict) as f:
                data = json.load(f)
        else:
            data = dict(json_file_or_dict)
        sd_type = data.get("type", "Megatron")
        ckpt_list = data.get("checkpoints", [])
        version = data.get("version", 0.0)
        return SDLoaderFactory.get_sd_loader(ckpt_list, sd_type=sd_type, version=version)

    @staticmethod
    def get_sd_loader(ckpt_list, sd_type: str = "Megatron", version=0.0):
        return SDLoader(ckpt_list, version=version, sd_type=sd_type)


class SDLoader:
    """Load checkpoint shard lists and reshard to a target MP degree
    (reference ``MegatronSDLoader.load`` split/merge paths)."""

    def __init__(self, ckpt_list: Sequence, version=0.0, sd_type: str = "Megatron",
                 policy: Optional[type] = None):
        self.ckpt_list = list(ckpt_list)
        self.version = version
        self.sd_type = sd_type
        self.policy = policy or TransformerPolicy

    # -- IO ------------------------------------------------------------
    def _load_one(self, item) -> Dict[str, np.ndarray]:
        if isinstance(item, dict):
            return {k: np.asarray(v) for k, v in item.items()}
        if isinstance(item, str) and os.path.isfile(item):
            import pickle

            with open(item, "rb") as f:
                sd = pickle.load(f)
            return {k: np.asarray(v) for k, v in sd.items()}
        raise FileNotFoundError(f"checkpoint shard {item!r}")

    def load(self, mp_world_size: int, mp_rank: int, num_heads: Optional[int] = None):
        """Return this rank's state dict at the requested degree."""
        shards = [self._load_one(it) for it in self.ckpt_list]
        out = reshard_checkpoint(shards, mp_world_size, policy=self.policy, num_heads=num_heads)
        return out[mp_rank]


# ---------------------------------------------------------------------------
# numeric resharding
# ---------------------------------------------------------------------------

_FUSED_QKV_PAT = re.compile(r"(^|[./])(query_key_value|c_attn)([./]|$)")


def _axis_for(policy, key: str, ndim: int) -> Optional[int]:
    """0-based split axis for a weight, from the policy's COL/ROW patterns.

    2-D tensors are torch Linear layout [out, in]: column-parallel splits
    axis 0, row-parallel axis 1. 3-D+ tensors are this framework's stacked
    [L, in, out] layout, where the split axis is wherever the policy put the
    model axis (never the leading layer dim). 1-D biases split iff column.
    """
    spec = policy.spec_for(key.replace(".", "/"), ndim if ndim >= 2 else 2)
    if spec is None:
        return None
    from ..parallel.mesh import MODEL_AXIS

    entries = list(spec)
    col = bool(entries) and entries[-1] == MODEL_AXIS  # last-dim sharded == column
    if ndim == 1:
        return 0 if col else None
    if ndim >= 3:
        # native stacked layout: split exactly where the spec shards
        for i, e in enumerate(entries):
            if e == MODEL_AXIS:
                return i
        return None
    # torch checkpoints store Linear as [out, in] (transposed vs our specs)
    return 0 if col else 1


def split_fused_qkv_per_head(w: np.ndarray, degree: int, num_heads: int) -> List[np.ndarray]:
    """Split a fused per-head-interleaved qkv tensor so each rank receives
    whole heads (reference ``MegatronSDLoader.split_query_key_value``)."""
    out_dim = w.shape[0]
    hd3 = out_dim // num_heads
    wh = w.reshape(num_heads, hd3, *w.shape[1:])
    assert num_heads % degree == 0, f"num_heads {num_heads} must divide by mp degree {degree}"
    per = num_heads // degree
    return [wh[r * per:(r + 1) * per].reshape(per * hd3, *w.shape[1:]) for r in range(degree)]


def merge_fused_qkv_per_head(shards: Sequence[np.ndarray]) -> np.ndarray:
    """Inverse of ``split_fused_qkv_per_head`` (reference merge_query_key_value)."""
    return np.concatenate(list(shards), axis=0)


def reshard_checkpoint(shards: Sequence[Dict[str, np.ndarray]], target_degree: int,
                       policy=TransformerPolicy, num_heads: Optional[int] = None
                       ) -> List[Dict[str, np.ndarray]]:
    """n source shard dicts -> target_degree shard dicts.

    Merge along each weight's policy axis to the full tensor, then split to
    the target degree; fused qkv splits per head so head boundaries are
    respected at any degree (reference ``MegatronSDLoader`` merge/split).
    """
    src_degree = len(shards)
    keys = list(shards[0].keys())
    out: List[Dict[str, np.ndarray]] = [dict() for _ in range(target_degree)]
    for key in keys:
        parts = [np.asarray(sd[key]) for sd in shards]
        ndim = parts[0].ndim
        fused = bool(_FUSED_QKV_PAT.search(key)) and ndim >= 1
        axis = 0 if fused else _axis_for(policy, key, ndim)
        if axis is None or ndim == 0:  # replicated (norms, scalars)
            for r in range(target_degree):
                out[r][key] = parts[0]
            continue
        full = parts[0] if src_degree == 1 else (
            merge_fused_qkv_per_head(parts) if fused and axis == 0
            else np.concatenate(parts, axis=axis))
        if target_degree == 1:
            for r in range(1):
                out[r][key] = full
            continue
        if fused:
            assert num_heads, f"resharding fused qkv {key!r} needs num_heads"
            pieces = split_fused_qkv_per_head(full, target_degree, num_heads)
        else:
            assert full.shape[axis] % target_degree == 0, \
                f"{key}: dim {axis} ({full.shape[axis]}) not divisible by degree {target_degree}"
            pieces = np.split(full, target_degree, axis=axis)
        for r in range(target_degree):
            out[r][key] = pieces[r]
    logger.info(f"resharded {len(keys)} tensors: mp {src_degree} -> {target_degree}")
    return out


def get_policy_for_model_type(model_type: str):
    return POLICY_REGISTRY.get(model_type, TransformerPolicy)
