"""Data-sampling subpackage (reference
``runtime/data_pipeline/data_sampling/``): curriculum sampler + offline
metric analysis + the Megatron mmap indexed-dataset container."""

from ..data_sampler import DeepSpeedDataSampler  # noqa: F401 — reference location alias
from .data_analyzer import (DataAnalyzer, load_metric_to_sample,  # noqa: F401
                            load_sample_to_metric)
from .indexed_dataset import (MMapIndexedDataset, MMapIndexedDatasetBuilder,  # noqa: F401
                              best_fitting_dtype, make_builder)
