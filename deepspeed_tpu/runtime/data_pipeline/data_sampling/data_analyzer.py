"""Offline curriculum metric analysis.

Analog of the reference
``runtime/data_pipeline/data_sampling/data_analyzer.py`` (``DataAnalyzer``:
map-reduce over a dataset computing per-sample difficulty metrics —
seqlen, vocab rarity, … — persisted as indexed datasets that
``DeepSpeedDataSampler`` consumes for curriculum learning at multi-TB
scale). Single-host form: worker sharding is a range split; the merge is a
concatenation in worker order, so the output layout matches the reference's
``<metric>/<metric>_sample_to_metric`` / ``_metric_to_sample`` pair.
"""

import csv
import os
from collections import defaultdict
from typing import Callable, Dict, List, Sequence

import numpy as np

from ....utils.logging import logger
from .indexed_dataset import MMapIndexedDataset, MMapIndexedDatasetBuilder


class DataAnalyzer:

    def __init__(self,
                 dataset: Sequence,
                 metric_names: List[str],
                 metric_functions: List[Callable],
                 save_path: str,
                 metric_types: List[str] = None,
                 num_workers: int = 1,
                 worker_id: int = 0,
                 batch_size: int = 1):
        """``metric_functions[i](sample) -> int`` difficulty value;
        ``metric_types``: 'single_value_per_sample' (curriculum difficulty,
        the default) or 'accumulate_value_over_samples' (corpus statistics,
        e.g. vocab frequency)."""
        assert len(metric_names) == len(metric_functions)
        self.dataset = dataset
        self.metric_names = metric_names
        self.metric_functions = metric_functions
        self.metric_types = metric_types or ["single_value_per_sample"] * len(metric_names)
        self.save_path = save_path
        self.num_workers = num_workers
        self.worker_id = worker_id
        self.batch_size = batch_size

    # -- map phase ---------------------------------------------------------
    def _worker_range(self, worker_id: int):
        n = len(self.dataset)
        per = -(-n // self.num_workers)
        return range(worker_id * per, min(n, (worker_id + 1) * per))

    def run_map(self, worker_id: int = None):
        """Compute this worker's shard of every metric; persist per-worker
        partial indexes."""
        worker_id = self.worker_id if worker_id is None else worker_id
        rng = self._worker_range(worker_id)
        for name, fn, mtype in zip(self.metric_names, self.metric_functions, self.metric_types):
            mdir = os.path.join(self.save_path, name)
            os.makedirs(mdir, exist_ok=True)
            prefix = os.path.join(mdir, f"worker{worker_id}_sample_to_metric")
            builder = MMapIndexedDatasetBuilder(prefix + ".bin", dtype=np.int64)
            acc = None
            for i in rng:
                val = fn(self.dataset[i])
                if mtype == "accumulate_value_over_samples":
                    acc = np.asarray(val, np.int64) if acc is None else acc + np.asarray(val, np.int64)
                else:
                    builder.add_item(np.asarray([int(val)], np.int64))
            if mtype == "accumulate_value_over_samples":
                builder.add_item(acc if acc is not None else np.zeros(1, np.int64))
            builder.finalize(prefix + ".idx")
        logger.info(f"DataAnalyzer map: worker {worker_id} covered {len(rng)} samples")

    # -- reduce phase ------------------------------------------------------
    def run_reduce(self):
        """Merge worker shards into the reference's artifact pair per metric:
        ``<m>_sample_to_metric`` (value per global sample index) and
        ``<m>_metric_to_sample`` (csv: value -> sample ids)."""
        for name, mtype in zip(self.metric_names, self.metric_types):
            mdir = os.path.join(self.save_path, name)
            merged = MMapIndexedDatasetBuilder(
                os.path.join(mdir, f"{name}_sample_to_metric.bin"), dtype=np.int64)
            values: List[int] = []
            accum = None
            for w in range(self.num_workers):
                part = MMapIndexedDataset(os.path.join(mdir, f"worker{w}_sample_to_metric"))
                for i in range(len(part)):
                    arr = np.asarray(part[i])
                    if mtype == "accumulate_value_over_samples":
                        # worker partials SUM into one corpus-wide statistic
                        # (the reference's accumulate reduce), never
                        # concatenate as if they were per-sample rows
                        accum = arr.astype(np.int64) if accum is None else accum + arr
                    else:
                        merged.add_item(arr)
                        values.append(int(arr[0]))
            if mtype == "accumulate_value_over_samples":
                merged.add_item(accum if accum is not None else np.zeros(1, np.int64))
            merged.finalize(os.path.join(mdir, f"{name}_sample_to_metric.idx"))
            if mtype == "single_value_per_sample":
                buckets: Dict[int, List[int]] = defaultdict(list)
                for sample_id, v in enumerate(values):
                    buckets[v].append(sample_id)
                with open(os.path.join(mdir, f"{name}_metric_to_sample.csv"), "w", newline="") as f:
                    w = csv.writer(f)
                    for v in sorted(buckets):
                        w.writerow([v] + buckets[v])
                logger.info(f"DataAnalyzer reduce: metric '{name}' merged ({len(values)} samples)")
            else:
                logger.info(f"DataAnalyzer reduce: metric '{name}' accumulated over "
                            f"{self.num_workers} workers")

    def run_map_reduce(self):
        for w in range(self.num_workers):
            self.run_map(worker_id=w)
        self.run_reduce()


def load_sample_to_metric(save_path: str, metric_name: str) -> np.ndarray:
    """Per-sample difficulty values — plugs directly into
    ``DeepSpeedDataSampler(difficulty_metric=...)``."""
    ds = MMapIndexedDataset(os.path.join(save_path, metric_name, f"{metric_name}_sample_to_metric"))
    return np.asarray([int(np.asarray(ds[i])[0]) for i in range(len(ds))], np.int64)


def load_metric_to_sample(save_path: str, metric_name: str) -> Dict[int, List[int]]:
    out: Dict[int, List[int]] = {}
    with open(os.path.join(save_path, metric_name, f"{metric_name}_metric_to_sample.csv")) as f:
        for row in csv.reader(f):
            if row:
                out[int(row[0])] = [int(x) for x in row[1:]]
    return out
