"""Memory-mapped indexed dataset — the Megatron ``.bin``/``.idx`` format.

Analog of the reference
``runtime/data_pipeline/data_sampling/indexed_dataset.py`` (the format the
curriculum data pipeline stores metric indexes in, and the standard
container for pre-tokenized LM corpora). Implemented against the public
format layout with numpy memmaps — no torch:

``.idx``: magic ``MMIDIDX\\x00\\x00`` · version u64 · dtype-code u8 ·
sequence count u64 · document count u64 · sizes i32[n] · pointers i64[n]
(byte offsets into ``.bin``) · doc_idx i64[docs].
``.bin``: the samples' raw element data, concatenated.
"""

import os
import shutil
import struct
from typing import Optional

import numpy as np

_MAGIC = b"MMIDIDX\x00\x00"
_VERSION = 1


def best_fitting_dtype(vocab_size: Optional[int] = None) -> np.dtype:
    """Smallest token dtype for a vocab (reference ``__best_fitting_dtype``
    indexed_dataset.py:42): uint16 when ids fit, else int32."""
    if vocab_size is not None and vocab_size < 65500:
        return np.dtype(np.uint16)
    return np.dtype(np.int32)


def make_builder(out_file: str, impl: str = "mmap", vocab_size: Optional[int] = None, dtype=None):
    """Builder factory (reference ``make_builder`` indexed_dataset.py:60).
    ``impl`` is accepted for API compatibility; the mmap format is the only
    implementation here (the legacy 'cached'/'lazy' formats are read paths
    for pre-2020 corpora the TPU data layer does not ingest)."""
    if impl not in ("mmap", "infer"):
        raise ValueError(f"unsupported indexed-dataset impl {impl!r}: only 'mmap' is written")
    return MMapIndexedDatasetBuilder(out_file, dtype=dtype if dtype is not None
                                     else best_fitting_dtype(vocab_size))

# dtype codes of the public format
_CODE_TO_DTYPE = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32,
                  5: np.int64, 6: np.float32, 7: np.float64, 8: np.uint16}
_DTYPE_TO_CODE = {np.dtype(v): k for k, v in _CODE_TO_DTYPE.items()}


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


class MMapIndexedDatasetBuilder:
    """Stream samples into ``<prefix>.bin`` and write the index on finalize
    (reference ``MMapIndexedDatasetBuilder``)."""

    def __init__(self, out_file: str, dtype=np.int32):
        self._bin = open(out_file, "wb")
        self._dtype = np.dtype(dtype)
        assert self._dtype in _DTYPE_TO_CODE, f"unsupported dtype {dtype}"
        self._sizes = []
        self._doc_idx = [0]

    def add_item(self, arr) -> None:
        arr = np.asarray(arr, self._dtype)
        self._bin.write(arr.tobytes(order="C"))
        self._sizes.append(arr.size)

    def end_document(self) -> None:
        self._doc_idx.append(len(self._sizes))

    def merge_file_(self, another_file: str) -> None:
        """Append an already-finalized shard ``<another_file>.bin/.idx``
        (reference ``MMapIndexedDatasetBuilder.merge_file_``
        indexed_dataset.py:597) — the multi-shard assembly step of Megatron
        preprocessing pipelines (each worker tokenizes a shard, rank 0 merges).
        Sample data is streamed bin-to-bin; index entries are rebased."""
        shard = MMapIndexedDataset(another_file)
        assert shard._dtype == self._dtype, (
            f"dtype mismatch merging {another_file}: shard {shard._dtype} vs builder {self._dtype}")
        if self._sizes and len(self._doc_idx) == 1:
            # locally-added items without end_document(): make the implicit
            # one-doc-per-item boundaries explicit BEFORE rebasing the
            # shard's doc offsets (finalize's fallback would misfire after)
            self._doc_idx = list(range(len(self._sizes) + 1))
        offset = len(self._sizes)
        self._sizes.extend(int(s) for s in shard.sizes)
        doc_idx = shard.doc_idx if len(shard.doc_idx) else np.asarray([0, len(shard.sizes)])
        self._doc_idx.extend(int(offset + d) for d in doc_idx[1:])
        with open(data_file_path(another_file), "rb") as f:
            shutil.copyfileobj(f, self._bin)

    def finalize(self, index_file: str) -> None:
        self._bin.close()
        if len(self._doc_idx) == 1:  # no explicit documents: one per item
            self._doc_idx = list(range(len(self._sizes) + 1))
        sizes = np.asarray(self._sizes, np.int32)
        itemsize = self._dtype.itemsize
        pointers = np.zeros(len(sizes), np.int64)
        np.cumsum(sizes[:-1] * itemsize, out=pointers[1:])
        with open(index_file, "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<Q", _VERSION))
            f.write(struct.pack("<B", _DTYPE_TO_CODE[self._dtype]))
            f.write(struct.pack("<Q", len(sizes)))
            f.write(struct.pack("<Q", len(self._doc_idx)))
            f.write(sizes.tobytes(order="C"))
            f.write(pointers.tobytes(order="C"))
            f.write(np.asarray(self._doc_idx, np.int64).tobytes(order="C"))


class MMapIndexedDataset:
    """Zero-copy reader (reference ``MMapIndexedDataset``): ``ds[i]`` views
    sample ``i`` straight out of the mapped ``.bin``."""

    def __init__(self, path_prefix: str, skip_warmup: bool = True):
        idx_path = index_file_path(path_prefix)
        with open(idx_path, "rb") as f:
            assert f.read(9) == _MAGIC, f"{idx_path}: bad magic (not an MMIDIDX index)"
            (version, ) = struct.unpack("<Q", f.read(8))
            assert version == _VERSION, f"unsupported index version {version}"
            (code, ) = struct.unpack("<B", f.read(1))
            self._dtype = np.dtype(_CODE_TO_DTYPE[code])
            (n, ) = struct.unpack("<Q", f.read(8))
            (docs, ) = struct.unpack("<Q", f.read(8))
            offset = f.tell()
        idx_map = np.memmap(idx_path, mode="r", offset=offset)
        self.sizes = idx_map[:n * 4].view(np.int32)
        self._pointers = idx_map[n * 4:n * 4 + n * 8].view(np.int64)
        self.doc_idx = idx_map[n * 4 + n * 8:n * 4 + n * 8 + docs * 8].view(np.int64)
        self._data = np.memmap(data_file_path(path_prefix), mode="r")
        self._n = n

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i: int) -> np.ndarray:
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(self._n))]
        ptr, size = int(self._pointers[i]), int(self.sizes[i])
        return self._data[ptr:ptr + size * self._dtype.itemsize].view(self._dtype)

    def get(self, idx: int, offset: int = 0, length: Optional[int] = None) -> np.ndarray:
        full = self[idx]
        return full[offset:offset + length] if length is not None else full[offset:]

    @property
    def supports_prefetch(self) -> bool:
        return False  # mmap: the OS page cache is the prefetcher

    @staticmethod
    def exists(path_prefix: str) -> bool:
        return (os.path.exists(index_file_path(path_prefix))
                and os.path.exists(data_file_path(path_prefix)))
