"""Async device-prefetching input pipeline.

The training input path is the one part of a TPU step the XLA scheduler
cannot overlap for us: pulling microbatches from the loader, collating and
``gas``-major stacking them, running the data-efficiency hooks, and
dispatching ``jax.make_array_from_process_local_data`` all happen on the
host, inline in ``train_batch`` — so the host idles during device compute
and the device idles during host work. The reference's
``DeepSpeedDataLoader`` never needed to solve this because torch's
DataLoader workers + pinned-memory H2D copies did it for CUDA; this module
is the TPU-native equivalent: a background thread that runs the WHOLE
host side of batch ``i+1``..``i+k`` (bounded depth ``k``) while the device
chews on batch ``i``, handing ``train_batch`` batches that are already
sharded device arrays.

Contract:

  * the worker pulls ``gas`` microbatches per item from the wrapped loader
    (the ``train_batch(data_iter=...)`` contract), runs ``prepare_fn(mbs,
    step)`` — the engine's single host-work helper (post-process, stack,
    curriculum, PLD) — then ``place_fn`` (shard + H2D dispatch), and queues
    the result as a :class:`DeviceBatch`;
  * the queue is bounded (``depth`` items) so the worker can run at most
    ``depth`` batches ahead (plus the one in its hands) — backpressure, not
    unbounded HBM growth;
  * a worker exception is re-raised at the consumer's matching ``next()``
    call, AFTER the already-queued good batches drain (ordering preserved);
  * ``close()`` (also via context manager / interpreter exit) stops the
    worker promptly even when it is blocked on a full queue; the thread is
    a daemon and holds no reference to this iterator, so dropping the
    iterator can never wedge interpreter shutdown or leak it forever.

``step`` numbering: item ``i`` is prepared with ``step = start_step + i``,
matching the ``engine.global_steps`` value at which the consumer will feed
it — curriculum difficulty and PLD theta are therefore computed for the
step the batch is USED at, not the step it was produced at, which is what
makes prefetched and synchronous runs bit-identical on a fixed seed
(test-enforced in ``tests/test_prefetch.py``).
"""

import itertools
import queue
import threading
import time

from ...monitor.health import get_health
from ...monitor.metrics import get_metrics
from ...monitor.trace import get_tracer
from ..resilience import chaos

_END = object()  # worker sentinel: wrapped loader exhausted
_WORKER_SEQ = itertools.count()  # unique heartbeat-source suffix per worker


class DeviceBatch:
    """A batch that already went through host assembly AND device placement.

    ``train_batch`` detects this wrapper and skips the inline
    stack/post-process/shard path entirely (the prefetch fast path); ``data``
    is the ``(gas, micro, ...)`` pytree of sharded ``jax.Array`` leaves and
    ``step`` the global step the batch was prepared for.
    """

    __slots__ = ("data", "step")

    def __init__(self, data, step=None):
        self.data = data
        self.step = step


class _WorkerFailure:
    __slots__ = ("exc", )

    def __init__(self, exc):
        self.exc = exc


def _worker(loader, prepare_fn, place_fn, gas, start_step, out_q, stop, name):
    """Worker body — a module function on purpose: it must NOT hold a
    reference to the DevicePrefetchIterator, or the iterator could never be
    garbage-collected while the thread runs (the GC-safety half of the
    shutdown contract)."""

    # per-worker stall-watchdog source (inherits the `prefetch` family
    # deadline via the health plane's prefix fallback): two live workers must
    # not share one heartbeat, or the healthy one masks the wedged one —
    # the seq suffix keeps same-named loaders (epoch restarts) distinct
    hb = get_health()
    hb_src = f"prefetch:{name}-{next(_WORKER_SEQ)}"

    def put(item):
        # bounded-wait put so a consumer that vanished (close()/GC) cannot
        # strand the worker on a full queue forever
        while not stop.is_set():
            try:
                out_q.put(item, timeout=0.1)
                return True
            except queue.Full:
                hb.touch(hb_src)  # parked on backpressure ≠ stalled
                continue
        return False

    step = start_step
    hb.begin(hb_src)
    try:
        it = iter(loader)
        while not stop.is_set():
            # heartbeat per item: a worker wedged inside the loader or the
            # H2D placement stops touching and trips the watchdog; a worker
            # merely parked on a full queue keeps touching via put()'s
            # bounded-wait loop below
            hb.touch(hb_src)
            # chaos injection point: a stall here goes stale against the
            # prefetch deadline; a kill surfaces at the consumer's next()
            chaos.fire("prefetch/item", {"name": name, "step": step})
            t0 = time.perf_counter()
            try:
                mbs = [next(it) for _ in range(gas)]
            except StopIteration:
                put(_END)
                return
            batch = prepare_fn(mbs, step) if prepare_fn is not None else \
                (mbs[0] if gas == 1 else mbs)
            placed = place_fn(batch) if place_fn is not None else batch
            reg = get_metrics()
            if reg.enabled:
                # train/ namespace per tools/check_metric_names.py (the old
                # data/ prefix predated the approved prefix set)
                reg.histogram("train/prefetch_assemble_ms").observe((time.perf_counter() - t0) * 1e3)
            tr = get_tracer()
            if tr.enabled:
                tr.complete(f"{name}/assemble", t0, time.perf_counter() - t0, tid="data",
                            args={"step": step})
            if not put(DeviceBatch(placed, step)):
                return
            step += 1
    except BaseException as e:  # noqa: BLE001 — every failure must reach the consumer
        put(_WorkerFailure(e))
    finally:
        hb.end(hb_src)
        # dynamic source: drop the entry so per-epoch workers don't
        # accumulate dead rows in /healthz forever
        hb.release(hb_src)


class DevicePrefetchIterator:
    """Iterator of :class:`DeviceBatch` items assembled+placed ahead of time
    by a background thread. Build through ``engine.prefetching_loader`` for
    the engine-wired version; direct construction takes any microbatch
    iterable plus optional ``prepare_fn(mbs, step)`` / ``place_fn(batch)``
    callables. Plain-iterator semantics: one pass, then StopIteration
    forever — multi-epoch loader semantics live in
    :class:`LazyPrefetchingLoader`."""

    def __init__(self, loader, prepare_fn=None, place_fn=None, gas=1, depth=2,
                 start_step=0, name="prefetch"):
        if gas < 1:
            raise ValueError(f"gas must be >= 1, got {gas}")
        self.depth = max(1, int(depth))
        self.gas = gas
        self.name = name
        self._loader = loader
        self._queue = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._done = False
        self._closed = False
        self._failure = None
        self._thread = threading.Thread(
            target=_worker, name=f"ds-tpu-{name}", daemon=True,
            args=(loader, prepare_fn, place_fn, gas, start_step, self._queue, self._stop, name))
        self._thread.start()

    # -- iteration ------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self) -> DeviceBatch:
        if self._closed:
            raise RuntimeError(f"{self.name}: iterator is closed")
        if self._failure is not None:
            raise self._failure
        if self._done:
            raise StopIteration
        try:
            # fast path: the whole point of prefetch is that an item is
            # already waiting — skip the timed get's deadline bookkeeping
            item = self._queue.get_nowait()
        except queue.Empty:
            item = None
        while item is None:
            try:
                item = self._queue.get(timeout=0.5)
            except queue.Empty:
                if not self._thread.is_alive():
                    # defensive: the worker always queues _END/_WorkerFailure
                    # before exiting, so this means the thread was killed
                    raise RuntimeError(f"{self.name}: worker thread died without a result")
        if item is _END:
            self._done = True
            raise StopIteration
        if isinstance(item, _WorkerFailure):
            self._failure = item.exc
            raise item.exc
        return item

    # (no __len__: a raising __len__ would also break truthiness checks on
    # the iterator; ask the wrapped loader for its length if you need one)

    # -- lifecycle ------------------------------------------------------
    def close(self, timeout=5.0):
        """Stop the worker and join it. Safe to call more than once, from
        ``__exit__``, ``engine.destroy()``, or ``__del__``; queued batches
        are dropped (their device buffers free with them)."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        # drain so a worker blocked on put() observes the stop event promptly
        self._drain()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=timeout)
        # drain AGAIN after the join: a worker mid-put when stop was set can
        # legally fill the slot the first drain freed — without this a fully
        # placed global batch would stay pinned in HBM behind the closed
        # iterator
        self._drain()

    def _drain(self):
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close(timeout=0.5)
        except Exception:  # interpreter teardown: never raise from __del__
            pass


class LazyPrefetchingLoader:
    """Loader-semantics wrapper around the prefetch pipeline, used by the
    engine's config-driven auto-wrap. Two jobs:

      * LAZY: the DevicePrefetchIterator (and its worker) is only built at
        the first ``next()`` call, so post-``initialize`` configuration —
        ``load_checkpoint`` advancing ``global_steps``,
        ``set_data_post_process_func`` installing the data hook — is
        captured before any batch is prepared (an eager wrap would prepare
        the first ``depth+1`` batches with step 0 and no hook);
      * RESTARTABLE: each ``iter()`` call starts a fresh epoch over the
        wrapped loader, like the loader's own ``__iter__`` — a bare
        DevicePrefetchIterator is one-shot (plain-iterator semantics), which
        would silently end multi-epoch ``for batch in trainloader`` loops
        after epoch 1.

    Unknown attributes (``sampler``, ``dataset``, ...) delegate to the
    wrapped loader; ``len()`` is in consumed items (``len(loader) // gas``).
    ``factory`` is ``engine.prefetching_loader`` (or compatible); ``gas``
    an int or callable returning the current accumulation steps."""

    def __init__(self, factory, loader, gas=1):
        self._factory = factory
        self._loader = loader
        self._gas = gas
        self._pf = None

    def __iter__(self):
        # fresh epoch: drop any previous (possibly exhausted) worker; the
        # next next() re-wraps the loader, whose __iter__ restarts it
        if self._pf is not None:
            self._pf.close()
            self._pf = None
        return self

    def __next__(self) -> DeviceBatch:
        if self._pf is None:
            self._pf = self._factory(self._loader)
        return next(self._pf)

    def __len__(self):
        gas = self._gas() if callable(self._gas) else self._gas
        return len(self._loader) // max(1, int(gas))

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._loader, name)  # sampler, dataset, batch_size, ...

    def close(self, timeout=5.0):
        if self._pf is not None:
            self._pf.close(timeout=timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
