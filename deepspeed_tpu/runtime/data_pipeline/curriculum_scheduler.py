"""Curriculum learning scheduler (reference
``runtime/data_pipeline/curriculum_scheduler.py`` ``CurriculumScheduler``,
158 LoC): maps the global step to a "difficulty" (typically sequence length)
via fixed_linear / fixed_root / fixed_discrete / custom schedules.

TPU note: when the difficulty drives sequence length, every new value means a
new compiled program shape — ``difficulty_step`` should be a multiple large
enough (e.g. 64) that the schedule visits few distinct lengths; the engine
additionally rounds to that step so XLA compiles once per bucket.
"""

import math

from .config import (CURRICULUM_LEARNING_SCHEDULE_CUSTOM, CURRICULUM_LEARNING_SCHEDULE_FIXED_DISCRETE,
                     CURRICULUM_LEARNING_SCHEDULE_FIXED_LINEAR, CURRICULUM_LEARNING_SCHEDULE_FIXED_ROOT,
                     CurriculumLearningConfig)
from ...utils.logging import logger


class CurriculumScheduler:

    def __init__(self, config):
        if isinstance(config, dict):
            config = CurriculumLearningConfig(**config)
        if getattr(config, "curriculum_metrics", None):
            raise NotImplementedError(
                "the multi-metric 'curriculum_metrics' schema (clustered difficulty index) is not "
                "supported; express the curriculum with schedule_type/schedule_config and pass the "
                "per-sample metric to DeepSpeedDataSampler(difficulty_metric=...)")
        self.config = config
        self.state = {
            "current_difficulty": config.min_difficulty,
            "min_difficulty": config.min_difficulty,
            "max_difficulty": config.max_difficulty,
            "schedule_type": config.schedule_type,
            "last_update_step": 0,
        }
        sc = dict(config.schedule_config)
        st = config.schedule_type
        if st in (CURRICULUM_LEARNING_SCHEDULE_FIXED_LINEAR, CURRICULUM_LEARNING_SCHEDULE_FIXED_ROOT):
            assert "total_curriculum_step" in sc, f"{st} schedule requires total_curriculum_step"
            sc.setdefault("difficulty_step", 1)
            if st == CURRICULUM_LEARNING_SCHEDULE_FIXED_ROOT:
                sc.setdefault("root_degree", 2)
        elif st == CURRICULUM_LEARNING_SCHEDULE_FIXED_DISCRETE:
            assert "difficulty" in sc and "max_step" in sc, "fixed_discrete requires difficulty + max_step lists"
            assert len(sc["difficulty"]) == len(sc["max_step"]) + 1, \
                "len(difficulty) must be len(max_step)+1 (last difficulty is open-ended)"
        elif st == CURRICULUM_LEARNING_SCHEDULE_CUSTOM:
            assert callable(sc.get("difficulty_fn")), "custom schedule requires difficulty_fn(global_steps)"
        else:
            raise ValueError(f"unknown curriculum schedule_type '{st}'")
        self.schedule_config = sc

    def get_current_difficulty(self):
        return self.state["current_difficulty"]

    def set_current_difficulty(self, difficulty):
        self.state["current_difficulty"] = difficulty

    def get_state(self):
        return dict(self.state)

    def set_state(self, state):
        self.state = dict(state)

    # -- schedules -----------------------------------------------------
    def __fixed_linear(self, global_steps):
        sc = self.schedule_config
        frac = min(1.0, global_steps / sc["total_curriculum_step"])
        diff = self.state["min_difficulty"] + frac * (self.state["max_difficulty"] - self.state["min_difficulty"])
        step = sc["difficulty_step"]
        diff = int(diff / step) * step
        return max(self.state["min_difficulty"], min(self.state["max_difficulty"], diff))

    def __fixed_root(self, global_steps):
        sc = self.schedule_config
        frac = min(1.0, global_steps / sc["total_curriculum_step"])
        frac = frac**(1.0 / sc["root_degree"])
        diff = self.state["min_difficulty"] + frac * (self.state["max_difficulty"] - self.state["min_difficulty"])
        step = sc["difficulty_step"]
        diff = int(diff / step) * step
        return max(self.state["min_difficulty"], min(self.state["max_difficulty"], diff))

    def __fixed_discrete(self, global_steps):
        sc = self.schedule_config
        for diff, max_step in zip(sc["difficulty"], sc["max_step"]):
            if global_steps <= max_step:
                return diff
        return sc["difficulty"][-1]

    def difficulty_at(self, global_steps):
        """Side-effect-free difficulty for ``global_steps`` — every schedule
        is a pure function of the step. The prefetch worker thread uses this
        (mutating the checkpointed ``state`` from a background thread would
        race the main thread's ``update_difficulty``)."""
        st = self.config.schedule_type
        if st == CURRICULUM_LEARNING_SCHEDULE_FIXED_LINEAR:
            return self.__fixed_linear(global_steps)
        if st == CURRICULUM_LEARNING_SCHEDULE_FIXED_ROOT:
            return self.__fixed_root(global_steps)
        if st == CURRICULUM_LEARNING_SCHEDULE_FIXED_DISCRETE:
            return self.__fixed_discrete(global_steps)
        return self.schedule_config["difficulty_fn"](global_steps)

    def update_difficulty(self, global_steps):
        diff = self.difficulty_at(global_steps)
        if diff != self.state["current_difficulty"]:
            logger.info(f"curriculum difficulty -> {diff} at step {global_steps}")
        self.state["current_difficulty"] = diff
        self.state["last_update_step"] = global_steps
        return diff

    # checkpoint API (reference state_dict/load_state_dict)
    def state_dict(self):
        return self.get_state()

    def load_state_dict(self, state):
        self.set_state(state)
