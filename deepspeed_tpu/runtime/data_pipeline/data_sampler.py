"""Curriculum-aware distributed data sampler (reference
``runtime/data_pipeline/data_sampling/data_sampler.py``
``DeepSpeedDataSampler``): draws each global batch from the subset of samples
whose difficulty metric is within the current curriculum difficulty,
partitioned across data-parallel ranks.

The metric arrives as an in-memory array (or callable evaluated once); for
multi-TB corpora, ``data_sampling.DataAnalyzer`` computes the per-sample
metrics offline into Megatron mmap indexed datasets and
``data_sampling.load_sample_to_metric`` feeds them here.
"""

import math
from typing import Callable, Optional, Sequence, Union

import numpy as np

from .curriculum_scheduler import CurriculumScheduler
from ...utils.logging import logger


class DeepSpeedDataSampler:

    def __init__(self,
                 dataset_len: int,
                 batch_size: int,
                 difficulty_metric: Optional[Union[Sequence, Callable]] = None,
                 curriculum_scheduler: Optional[CurriculumScheduler] = None,
                 data_parallel_rank: int = 0,
                 data_parallel_world_size: int = 1,
                 shuffle: bool = True,
                 seed: int = 1234):
        self.dataset_len = dataset_len
        self.batch_size = batch_size
        self.rank = data_parallel_rank
        self.world = data_parallel_world_size
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.global_steps = 0
        self.curriculum_scheduler = curriculum_scheduler

        if difficulty_metric is None:
            self.metric = None
        elif callable(difficulty_metric):
            self.metric = np.asarray([difficulty_metric(i) for i in range(dataset_len)])
        else:
            self.metric = np.asarray(difficulty_metric)
            assert len(self.metric) == dataset_len

    def set_epoch(self, epoch):
        self.epoch = epoch

    def _eligible(self):
        if self.metric is None or self.curriculum_scheduler is None:
            return np.arange(self.dataset_len)
        diff = self.curriculum_scheduler.get_current_difficulty()
        idx = np.nonzero(self.metric <= diff)[0]
        if len(idx) < self.batch_size * self.world:
            # too few easy samples early in the curriculum: take the easiest
            # batch-worth instead of starving (reference pads the cluster)
            idx = np.argsort(self.metric)[:self.batch_size * self.world]
        return idx

    def __iter__(self):
        g = np.random.default_rng(self.seed + self.epoch)
        while True:
            if self.curriculum_scheduler is not None:
                self.curriculum_scheduler.update_difficulty(self.global_steps)
            pool = self._eligible()
            if self.shuffle:
                chosen = g.choice(pool, size=self.batch_size * self.world, replace=len(pool) < self.batch_size * self.world)
            else:
                start = (self.global_steps * self.batch_size * self.world) % max(1, len(pool))
                rolled = np.roll(pool, -start)
                chosen = rolled[:self.batch_size * self.world]
            self.global_steps += 1
            yield chosen[self.rank::self.world][:self.batch_size]

    def state_dict(self):
        return {"epoch": self.epoch, "global_steps": self.global_steps,
                "curriculum": (self.curriculum_scheduler.state_dict()
                               if self.curriculum_scheduler is not None else None)}

    def load_state_dict(self, state):
        self.epoch = state["epoch"]
        self.global_steps = state["global_steps"]
        if state.get("curriculum") and self.curriculum_scheduler is not None:
            self.curriculum_scheduler.load_state_dict(state["curriculum"])
