"""Random layer-token-drop (random-LTD).

Reference: ``runtime/data_pipeline/data_routing/basic_layer.py``
(``RandomLayerTokenDrop``) + the CUDA kernels in ``csrc/random_ltd/``
(``gather_scatter.cu``, ``token_sort.cu``): during training, middle layers
process a random subset of tokens; the dropped tokens skip the layer and are
scattered back afterwards — compute drops quadratically in kept length for
attention while accuracy is preserved by the schedule that anneals kept
length up to the full sequence.

TPU-native: gather/scatter are ``jnp.take_along_axis`` / ``.at[].set`` (XLA
lowers both to efficient dynamic-slice/dus on sorted indices — the reference's
token_sort kernel exists to keep kept tokens in causal order, which we get by
sorting the sampled indices). All shapes are static per (kept_len) bucket:
the scheduler quantizes kept length so XLA compiles one program per bucket.
"""

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from ..curriculum_scheduler import CurriculumScheduler
from ..config import CurriculumLearningConfig


def token_gather(x: jax.Array, indices: jax.Array) -> jax.Array:
    """Gather kept tokens: x [B, S, H], indices [B, K] (sorted) → [B, K, H].
    (reference csrc/random_ltd/gather_scatter.cu::gather_tokens)"""
    return jnp.take_along_axis(x, indices[..., None], axis=1)


def token_scatter(full: jax.Array, kept: jax.Array, indices: jax.Array) -> jax.Array:
    """Scatter processed tokens back over the (unprocessed) full tensor:
    full [B, S, H], kept [B, K, H], indices [B, K] → [B, S, H].
    (reference scatter_tokens kernel)"""
    B = full.shape[0]
    batch_idx = jnp.arange(B)[:, None]
    return full.at[batch_idx, indices].set(kept)


def random_token_drop(rng: jax.Array, batch: int, seq_len: int, keep_len: int) -> jax.Array:
    """Sample ``keep_len`` token indices per row, sorted ascending so causal
    masks remain valid (the role of the reference token_sort.cu kernel)."""
    noise = jax.random.uniform(rng, (batch, seq_len))
    keep = jnp.argsort(noise, axis=1)[:, :keep_len]
    return jnp.sort(keep, axis=1)


def apply_random_ltd(layer_fn, x: jax.Array, rng: jax.Array, keep_len: int):
    """Run ``layer_fn`` on a random ``keep_len``-token subset and scatter the
    outputs back (identity for dropped tokens) — the RandomLayerTokenDrop
    forward. ``keep_len`` must be static (bucketed by the scheduler)."""
    B, S = x.shape[0], x.shape[1]
    if keep_len >= S:
        return layer_fn(x)
    idx = random_token_drop(rng, B, S, keep_len)
    kept = token_gather(x, idx)
    processed = layer_fn(kept)
    return token_scatter(x, processed, idx)


class RandomLTDScheduler:
    """Schedule of the kept-token count (reference
    ``data_pipeline/data_routing/scheduler.py``): anneals from min_value to
    max_value (the full sequence) with the same schedule machinery as
    curriculum learning. Values are quantized to ``difficulty_step`` so the
    jitted layer compiles once per bucket."""

    def __init__(self, random_ltd_config):
        rl = random_ltd_config
        sched = dict(rl.random_ltd_schedule) if hasattr(rl, "random_ltd_schedule") else dict(rl)
        self.scheduler = CurriculumScheduler(
            CurriculumLearningConfig(enabled=True,
                                     curriculum_type="seqlen",
                                     min_difficulty=sched.get("min_value", 128),
                                     max_difficulty=sched.get("max_value", 2048),
                                     schedule_type=sched.get("schedule_type", "fixed_linear"),
                                     schedule_config=sched.get("schedule_config",
                                                               {"total_curriculum_step": 1000,
                                                                "difficulty_step": 64})))
        self.config = rl

    def get_current_seq(self) -> int:
        return int(self.scheduler.get_current_difficulty())

    def update_seq(self, global_steps: int) -> int:
        return int(self.scheduler.update_difficulty(global_steps))

    def state_dict(self):
        return self.scheduler.state_dict()

    def load_state_dict(self, state):
        self.scheduler.load_state_dict(state)
