from . import random_ltd
from .random_ltd import (RandomLTDScheduler, token_gather, token_scatter, random_token_drop,
                         apply_random_ltd)
