from .config import (DataEfficiencyConfig, CurriculumLearningConfig, RandomLTDConfig,
                     DataPipelineConfig, PrefetchConfig, get_data_efficiency_config,
                     get_data_pipeline_config)
from .curriculum_scheduler import CurriculumScheduler
from .data_sampler import DeepSpeedDataSampler
from .data_routing import random_ltd
from .prefetch import DeviceBatch, DevicePrefetchIterator, LazyPrefetchingLoader
