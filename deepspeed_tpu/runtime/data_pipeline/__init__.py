from .config import (DataEfficiencyConfig, CurriculumLearningConfig, RandomLTDConfig,
                     get_data_efficiency_config)
from .curriculum_scheduler import CurriculumScheduler
from .data_sampler import DeepSpeedDataSampler
from .data_routing import random_ltd
