"""Data-efficiency configuration (reference ``runtime/data_pipeline/config.py``
/ ``constants.py``): the ``data_efficiency`` block with its two arms —
``data_sampling`` (curriculum learning) and ``data_routing`` (random-LTD) —
plus the legacy top-level ``curriculum_learning`` block.
"""

from typing import Any, Callable, Dict, List, Optional, Union

from pydantic import Field

from ..config_utils import DeepSpeedConfigModel

# schedule types (reference data_pipeline/constants.py)
CURRICULUM_LEARNING_SCHEDULE_FIXED_LINEAR = "fixed_linear"
CURRICULUM_LEARNING_SCHEDULE_FIXED_ROOT = "fixed_root"
CURRICULUM_LEARNING_SCHEDULE_FIXED_DISCRETE = "fixed_discrete"
CURRICULUM_LEARNING_SCHEDULE_CUSTOM = "custom"


class CurriculumLearningConfig(DeepSpeedConfigModel):
    """Legacy ``curriculum_learning`` block (reference
    ``curriculum_scheduler.py`` consumes exactly these keys)."""
    enabled: bool = False
    curriculum_type: str = "seqlen"
    min_difficulty: int = 1
    max_difficulty: int = 10**9
    schedule_type: str = CURRICULUM_LEARNING_SCHEDULE_FIXED_LINEAR
    schedule_config: Dict[str, Any] = Field(default_factory=dict)
    # reference data_efficiency schema nests per-metric configs here; that
    # multi-metric clustered-index form is not supported — reject loudly
    # rather than silently dropping it (see CurriculumScheduler.__init__)
    curriculum_metrics: Optional[Dict[str, Any]] = None


class RandomLTDConfig(DeepSpeedConfigModel):
    """``data_routing.random_ltd`` block (reference
    ``data_pipeline/config.py`` random-LTD keys, flattened to the used set)."""
    enabled: bool = False
    total_layer_num: int = 0
    random_ltd_layer_num: int = 0
    random_ltd_layer_id: List[int] = Field(default_factory=list)
    model_mask_name: Optional[str] = None
    model_type: str = "decoder"
    hidden_state_order: str = "batch_seq_dim"
    random_ltd_schedule: Dict[str, Any] = Field(default_factory=dict)  # {min_value, max_value, schedule_type, schedule_config}


class DataSamplingConfig(DeepSpeedConfigModel):
    enabled: bool = False
    # parsed for reference-config compatibility; the jax data path has no
    # worker processes and epochs are driven by the caller's loop
    num_epochs: int = 1000
    num_workers: int = 0
    curriculum_learning: CurriculumLearningConfig = Field(default_factory=CurriculumLearningConfig)


class DataRoutingConfig(DeepSpeedConfigModel):
    enabled: bool = False
    random_ltd: RandomLTDConfig = Field(default_factory=RandomLTDConfig)


class DataEfficiencyConfig(DeepSpeedConfigModel):
    """``data_efficiency`` block (reference DeepSpeedDataEfficiencyConfig)."""
    enabled: bool = False
    seed: int = 1234
    data_sampling: DataSamplingConfig = Field(default_factory=DataSamplingConfig)
    data_routing: DataRoutingConfig = Field(default_factory=DataRoutingConfig)


def get_data_efficiency_config(param_dict: dict) -> DataEfficiencyConfig:
    return DataEfficiencyConfig(**param_dict.get("data_efficiency", {}))


class PrefetchConfig(DeepSpeedConfigModel):
    """``data_pipeline.prefetch`` block: the async device-prefetching input
    pipeline (``data_pipeline/prefetch.py``). ``depth`` bounds how many fully
    assembled+placed batches the background worker may run ahead (each one
    holds a full global batch in HBM)."""
    enabled: bool = False
    depth: int = Field(2, ge=1)


class DataPipelineConfig(DeepSpeedConfigModel):
    """Top-level ``data_pipeline`` block (input-path performance knobs — the
    data-efficiency arms keep their own reference-schema blocks)."""
    prefetch: PrefetchConfig = Field(default_factory=PrefetchConfig)


def get_data_pipeline_config(param_dict: dict) -> DataPipelineConfig:
    return DataPipelineConfig(**param_dict.get("data_pipeline", {}))
