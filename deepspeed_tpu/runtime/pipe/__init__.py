from .schedule import (PipeSchedule, TrainSchedule, InferenceSchedule, DataParallelSchedule, ForwardPass, BackwardPass,
                       SendActivation, RecvActivation, SendGrad, RecvGrad, LoadMicroBatch, OptimizerStep, ReduceGrads,
                       ReduceTiedGrads)
from .module import PipelineModule, LayerSpec, TiedLayerSpec, partition_uniform, partition_balanced
from .spmd import pipeline_apply
