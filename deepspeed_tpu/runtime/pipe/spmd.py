"""Compiled SPMD pipeline runner.

The TPU-native replacement for the reference's host-interpreted pipeline
executor (``runtime/pipe/engine.py:1401 _exec_schedule`` dispatching
instruction handlers, with P2P sends in ``pipe/p2p.py``): the entire
fill/steady/drain loop compiles into ONE XLA program inside ``shard_map`` over
the ``pipe`` mesh axis. Per tick, every stage applies its local layer stack
and rotates boundary activations to its neighbor with ``lax.ppermute`` (the
P2P instruction pair become a single collective-permute that XLA overlaps with
the next tick's compute). ``jax.grad`` through the loop generates the reverse
schedule — backward ppermutes run in the transposed direction — so the
training step needs no hand-written BackwardPass/SendGrad handlers.

``pipeline_apply`` is GPipe-style fill-drain with per-stage rematerialization
(wrap ``stage_fn`` in ``jax.checkpoint``): boundary activations per microbatch
are kept, interior activations recomputed — equivalent to the reference's
activation-checkpointing-between-stages configuration. ``pipeline_1f1b``
interleaves backward ticks into the forward loop (the reference
``TrainSchedule``), bounding live activations to ~num_stages microbatches —
the default schedule; see ``test_1f1b_bounded_live_activations``.
"""

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ...parallel.mesh import PIPE_AXIS, shard_map_compat


def _replicated_specs(tree):
    """P() for every leaf — replicated over the manual (pipe) axis; auto
    axes flow through by GSPMD propagation (shared by both executors)."""
    return jax.tree_util.tree_map(lambda _: P(), tree)


def _psum(v, axis):
    """psum that survives non-native-bf16 backends. On CPU, XLA's float
    normalization rewrites a bf16 all-reduce's reduction computation into
    add+copy, and the all-reduce-promotion pass then CHECK-fails on the
    copy root (``Invalid binary instruction opcode copy``,
    hlo_instruction.cc) — found compile-validating bf16 pipelines on the
    virtual mesh (round 5). TPU has native bf16: no rewrite, no upcast —
    the collective stays half-width there."""
    if v.dtype == jnp.bfloat16 and jax.default_backend() != "tpu":
        return lax.psum(v.astype(jnp.float32), axis).astype(jnp.bfloat16)
    return lax.psum(v, axis)


def pipeline_apply(stage_fn: Callable,
                   stage_params,
                   microbatches,
                   *consts,
                   mesh,
                   num_stages: int,
                   pipe_axis: str = PIPE_AXIS,
                   param_specs=None,
                   remat: bool = True,
                   with_aux: bool = False):
    """Run ``microbatches`` [M, b, ...] through a pipeline of ``num_stages``.

    ``stage_params``: pytree whose leaves have a leading layer dim divisible
    by ``num_stages`` (each stage takes its contiguous slice — the analog of
    ``PipelineModule._partition_layers`` uniform mode).
    ``stage_fn(local_params, x, *consts) -> y``: applies ONE stage's layer
    slice; ``consts`` are replicated side inputs (e.g. rope tables).
    Returns outputs [M, b, ...] (as produced by the last stage, broadcast to
    all stages for the head/loss computation).

    ``with_aux``: the stage fn returns ``(y, aux_scalar)`` (e.g. the MoE
    load-balancing loss summed over the stage's layers — the reference
    accumulates it via ``MoE`` module attributes walked by the engine;
    here it is an explicit dataflow value). Ticks where a stage holds no
    real microbatch (fill/drain bubbles) are masked out. Returns
    ``(outputs, aux_total)`` with ``aux_total`` summed over all stages and
    microbatches; gradients flow through it under ``jax.grad``.
    """
    M = jax.tree_util.tree_leaves(microbatches)[0].shape[0]
    if param_specs is None:
        param_specs = jax.tree_util.tree_map(lambda x: P(pipe_axis), stage_params)

    fn = stage_fn
    if remat:
        fn = jax.checkpoint(stage_fn)

    def pipelined(params_local, xs, *consts):
        stage = lax.axis_index(pipe_axis)
        n_ticks = M + num_stages - 1

        def _pipe_varying(v):
            # mark as pipe-varying so the scan carry type is stable (jax>=0.8
            # tracks varying-manual-axes through shard_map)
            try:
                return lax.pcast(v, (pipe_axis, ), to="varying")
            except (AttributeError, TypeError):
                return v

        x0 = jax.tree_util.tree_map(lambda x: _pipe_varying(jnp.zeros_like(x[0])), xs)
        outputs = jax.tree_util.tree_map(lambda x: _pipe_varying(jnp.zeros_like(x)), xs)

        def tick(carry, t):
            recv, outputs, aux_acc = carry
            # stage 0 ingests microbatch t (clamped; masked-out after M)
            idx = jnp.clip(t, 0, M - 1)
            inject = jax.tree_util.tree_map(lambda x: x[idx], xs)
            x_in = jax.tree_util.tree_map(
                lambda i, r: jnp.where(stage == 0, i, r), inject, recv)
            if with_aux:
                y, aux = fn(params_local, x_in, *consts)
                # this stage is working on microbatch t-stage: mask bubbles
                mf = t - stage
                live = jnp.logical_and(mf >= 0, mf < M).astype(aux.dtype)
                aux_acc = aux_acc + aux * live
            else:
                y = fn(params_local, x_in, *consts)
            # last stage writes its result for microbatch t-(S-1)
            out_idx = jnp.clip(t - (num_stages - 1), 0, M - 1)
            valid = jnp.logical_and(stage == num_stages - 1, t >= num_stages - 1)

            def write(o, yv):
                cur = o[out_idx]
                newv = jnp.where(valid, yv, cur)
                return o.at[out_idx].set(newv)

            outputs = jax.tree_util.tree_map(write, outputs, y)
            # rotate activations downstream (stage i -> i+1; wraparound value
            # is ignored by stage 0's inject select)
            perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]
            recv = jax.tree_util.tree_map(lambda v: lax.ppermute(v, pipe_axis, perm), y)
            return (recv, outputs, aux_acc), None

        aux0 = _pipe_varying(jnp.zeros([], jnp.float32))
        (recv, outputs, aux_acc), _ = lax.scan(
            tick, (x0, outputs, aux0), jnp.arange(n_ticks))
        # broadcast last stage's outputs to every stage (head/loss is
        # computed replicated over pipe)
        outputs = jax.tree_util.tree_map(
            lambda o: _psum(jnp.where(stage == num_stages - 1, o, jnp.zeros_like(o)), pipe_axis), outputs)
        if with_aux:
            # manual over pipe ONLY: data/model/seq are GSPMD-auto inside,
            # so aux is already the global batch mean — psum totals the
            # per-stage layer sums (same aggregation as 1f1b)
            return outputs, lax.psum(aux_acc, pipe_axis)
        return outputs

    # manual over 'pipe' ONLY (same contract as pipeline_1f1b below): the
    # data/model/seq axes stay GSPMD-auto inside the body, so TP shards the
    # per-stage einsums instead of replicating them on every model shard —
    # the manual-over-all-axes form this replaced computed each stage's full
    # matmuls redundantly under tensor parallelism
    x_spec = _replicated_specs(microbatches)
    const_specs = tuple(_replicated_specs(c) for c in consts)
    out_specs = (x_spec, P()) if with_aux else x_spec
    shard_fn = shard_map_compat(pipelined, mesh,
                                in_specs=(param_specs, x_spec) + const_specs,
                                out_specs=out_specs,
                                axis_names=frozenset({pipe_axis}))
    return shard_fn(stage_params, microbatches, *consts)


def pipeline_1f1b(stage_fn: Callable,
                  head_fn: Callable,
                  stage_params,
                  head_params,
                  microbatches,
                  head_aux,
                  *consts,
                  mesh,
                  num_stages: int,
                  pipe_axis: str = PIPE_AXIS,
                  with_aux: bool = False,
                  aux_weight: float = 0.0):
    """Compiled 1F1B pipeline with hand-rolled per-tick VJPs.

    The reference's steady-state 1F1B (``runtime/pipe/schedule.py:189``
    ``TrainSchedule``) alternates one forward with one backward per stage,
    bounding live activations to ~num_stages microbatches instead of M (the
    GPipe fill-drain property of ``pipeline_apply`` + ``jax.grad``). Here the
    interleaving is explicit because autodiff through a scan cannot reorder
    backward work into the forward loop:

      tick t, stage s:  FORWARD  microbatch  mf = t - s            (masked)
                        BACKWARD microbatch  mb = t - (2S-2-s)     (masked)

    — the same tick math as ``TrainSchedule._step_to_micro_batch`` folded
    into the paired-tick form (at the last stage mf == mb: forward, loss
    head, and backward of a microbatch happen in one tick, the "1F1B pivot").
    Each backward recomputes its stage forward from a ring buffer of saved
    stage INPUTS (size min(2S-1, M): the live span of stage 0) — per-stage
    rematerialization, the reference's activation-checkpointing-between-
    stages configuration. Communication is two ``ppermute``s per tick
    (activations down, gradients up) — the SendActivation/RecvGrad pairs of
    the reference schedule as single collective-permutes.

    The shard_map is manual over the ``pipe`` axis ONLY: data/model/seq stay
    GSPMD-auto inside, so PP composes with TP/DP by sharding propagation
    (reference ``pipe/topology.py:244`` PipeModelDataParallelTopology).

    ``stage_fn(stage_params_local, x, *consts) -> y`` applies one stage's
    contiguous layer slice. ``head_fn(head_params, y, aux_mb) -> scalar`` is
    the per-microbatch loss head (executed at the last stage).

    ``with_aux``: the stage fn returns ``(y, aux_scalar)`` (MoE load-balance
    loss summed over the stage's layers). The returned loss then includes
    ``aux_weight * mean_over_microbatches(sum_over_stages(aux))`` and the
    backward VJP seeds the aux cotangent with ``aux_weight / M`` so gate
    gradients flow into the stage grads — the pipelined analog of
    ``loss = ce + coef * moe_aux`` in the non-pipelined loss_fn.

    Returns ``(mean_loss, stage_grads, head_grads, d_microbatches)`` where
    ``stage_grads`` stays sharded over ``pipe`` (each stage owns its slice)
    and ``d_microbatches`` is the cotangent of the injected activations (for
    the caller to chain into the embedding's VJP).
    """
    tree = jax.tree_util.tree_map
    M = jax.tree_util.tree_leaves(microbatches)[0].shape[0]
    S = num_stages
    n_ticks = M + 2 * S - 2
    n_buf = min(2 * S - 1, M)
    param_specs = tree(lambda x: P(pipe_axis), stage_params)

    def pipelined(params_local, head_params, xs, head_aux, *consts):
        stage = lax.axis_index(pipe_axis)
        last = S - 1

        x0 = tree(lambda x: jnp.zeros_like(x[0]), xs)
        buf0 = tree(lambda x: jnp.zeros((n_buf, ) + x.shape[1:], x.dtype), xs)
        gp0 = tree(lambda p: jnp.zeros(p.shape, jnp.float32), params_local)
        gh0 = tree(lambda p: jnp.zeros(p.shape, jnp.float32), head_params)
        dxs0 = tree(jnp.zeros_like, xs)

        def tick(carry, t):
            fwd_recv, bwd_recv, buf, g_params, g_head, d_xs, loss_acc, aux_acc = carry
            mf = t - stage
            mb = t - (2 * last - stage)
            valid_f = jnp.logical_and(mf >= 0, mf < M)
            valid_b = jnp.logical_and(mb >= 0, mb < M)

            # ---- forward: ingest at stage 0, else use received activation
            idx_f = jnp.clip(mf, 0, M - 1)
            inject = tree(lambda x: x[idx_f], xs)
            x_in = tree(lambda i, r: jnp.where(stage == 0, i, r), inject, fwd_recv)
            if with_aux:
                y, aux_f = stage_fn(params_local, x_in, *consts)
                aux_acc = aux_acc + aux_f.astype(jnp.float32) * valid_f.astype(jnp.float32)
            else:
                y = stage_fn(params_local, x_in, *consts)
            slot_f = idx_f % n_buf
            buf = tree(lambda b, v: b.at[slot_f].set(jnp.where(valid_f, v, b[slot_f])), buf, x_in)

            # ---- loss head (last stage only, where mf == mb; a lax.cond
            # keeps the other stages from burning the [b,S,V] head FLOPs —
            # all devices of a pipe stage agree on the predicate, and the
            # head's auto-axis psum groups never span pipe stages)
            aux_mb = tree(lambda a: a[idx_f], head_aux)

            def head_branch(ops):
                hp, yy, am = ops
                loss_mb, head_vjp = jax.vjp(lambda h, y2: head_fn(h, y2, am), hp, yy)
                # total loss is the MEAN over microbatches: seed 1/M so every
                # grad downstream of the head carries the normalization
                dhp, dy = head_vjp(jnp.full_like(loss_mb, 1.0 / M))
                return loss_mb.astype(jnp.float32), dhp, dy

            def skip_branch(ops):
                hp, yy, _ = ops
                return jnp.zeros([], jnp.float32), tree(jnp.zeros_like, hp), tree(jnp.zeros_like, yy)

            loss_mb, dhp, dy = lax.cond(jnp.logical_and(valid_f, stage == last),
                                        head_branch, skip_branch, (head_params, y, aux_mb))
            loss_acc = loss_acc + loss_mb
            g_head = tree(lambda a, g: a + g.astype(jnp.float32), g_head, dhp)

            # ---- backward: recompute this stage's VJP from the saved input
            idx_b = jnp.clip(mb, 0, M - 1)
            x_b = tree(lambda b: b[idx_b % n_buf], buf)
            g_in = tree(lambda d, r: jnp.where(stage == last, d, r), dy, bwd_recv)
            _, stage_vjp = jax.vjp(lambda pl, xx: stage_fn(pl, xx, *consts), params_local, x_b)
            if with_aux:
                # cotangent of (y, aux): the aux term enters the total loss as
                # aux_weight * aux / M; invalid ticks are masked by use_b below
                # (dparams) and by the upstream stage's own mask (dx), exactly
                # as the CE cotangent is
                dparams, dx = stage_vjp((g_in, jnp.asarray(aux_weight / M, jnp.float32)))
            else:
                dparams, dx = stage_vjp(g_in)
            use_b = valid_b.astype(jnp.float32)
            g_params = tree(lambda a, g: a + g.astype(jnp.float32) * use_b, g_params, dparams)
            d_xs = tree(
                lambda D, d: D.at[idx_b].set(
                    jnp.where(jnp.logical_and(valid_b, stage == 0), d.astype(D.dtype), D[idx_b])),
                d_xs, dx)

            # ---- rotate: activations downstream, gradients upstream
            down = [(i, (i + 1) % S) for i in range(S)]
            up = [(i, (i - 1) % S) for i in range(S)]
            fwd_recv = tree(lambda v: lax.ppermute(v, pipe_axis, down), y)
            bwd_recv = tree(lambda v: lax.ppermute(v, pipe_axis, up), dx)
            return (fwd_recv, bwd_recv, buf, g_params, g_head, d_xs, loss_acc, aux_acc), None

        carry0 = (x0, x0, buf0, gp0, gh0, dxs0, jnp.zeros([], jnp.float32),
                  jnp.zeros([], jnp.float32))
        (fwd_recv, bwd_recv, buf, g_params, g_head, d_xs, loss_acc, aux_acc), _ = lax.scan(
            tick, carry0, jnp.arange(n_ticks))

        # loss / head grads accumulated only at the last stage, d_xs only at
        # stage 0 (zeros elsewhere): psum over pipe replicates them
        loss = lax.psum(loss_acc, pipe_axis) / M
        if with_aux:
            # every stage accumulated its own layers' aux: psum = model total
            loss = loss + aux_weight * lax.psum(aux_acc, pipe_axis) / M
        g_head = tree(lambda g: lax.psum(g, pipe_axis), g_head)
        d_xs = tree(lambda d: _psum(jnp.where(stage == 0, d, jnp.zeros_like(d)), pipe_axis), d_xs)
        return loss, g_params, g_head, d_xs

    rep = _replicated_specs
    shard_fn = shard_map_compat(
        pipelined, mesh,
        in_specs=(param_specs, rep(head_params), rep(microbatches), rep(head_aux))
        + tuple(rep(c) for c in consts),
        out_specs=(P(), param_specs, rep(head_params), rep(microbatches)),
        axis_names=frozenset({pipe_axis}))
    return shard_fn(stage_params, head_params, microbatches, head_aux, *consts)
