"""Compiled SPMD pipeline runner.

The TPU-native replacement for the reference's host-interpreted pipeline
executor (``runtime/pipe/engine.py:1401 _exec_schedule`` dispatching
instruction handlers, with P2P sends in ``pipe/p2p.py``): the entire
fill/steady/drain loop compiles into ONE XLA program inside ``shard_map`` over
the ``pipe`` mesh axis. Per tick, every stage applies its local layer stack
and rotates boundary activations to its neighbor with ``lax.ppermute`` (the
P2P instruction pair become a single collective-permute that XLA overlaps with
the next tick's compute). ``jax.grad`` through the loop generates the reverse
schedule — backward ppermutes run in the transposed direction — so the
training step needs no hand-written BackwardPass/SendGrad handlers.

Memory behavior is GPipe-style fill-drain with per-stage rematerialization
(wrap ``stage_fn`` in ``jax.checkpoint``): boundary activations per microbatch
are kept, interior activations recomputed — equivalent to the reference's
activation-checkpointing-between-stages configuration. (A true interleaved
1F1B with hand-scheduled backward ticks is a later optimization; the compute
cost is identical, the difference is peak activation memory M vs stages.)
"""

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ...parallel.mesh import PIPE_AXIS, DATA_AXIS


def pipeline_apply(stage_fn: Callable,
                   stage_params,
                   microbatches,
                   *consts,
                   mesh,
                   num_stages: int,
                   pipe_axis: str = PIPE_AXIS,
                   data_axis: str = DATA_AXIS,
                   param_specs=None,
                   remat: bool = True):
    """Run ``microbatches`` [M, b, ...] through a pipeline of ``num_stages``.

    ``stage_params``: pytree whose leaves have a leading layer dim divisible
    by ``num_stages`` (each stage takes its contiguous slice — the analog of
    ``PipelineModule._partition_layers`` uniform mode).
    ``stage_fn(local_params, x, *consts) -> y``: applies ONE stage's layer
    slice; ``consts`` are replicated side inputs (e.g. rope tables).
    Returns outputs [M, b, ...] (as produced by the last stage, broadcast to
    all stages for the head/loss computation).
    """
    M = jax.tree_util.tree_leaves(microbatches)[0].shape[0]
    if param_specs is None:
        param_specs = jax.tree_util.tree_map(lambda x: P(pipe_axis), stage_params)

    fn = stage_fn
    if remat:
        fn = jax.checkpoint(stage_fn)

    def pipelined(params_local, xs, *consts):
        stage = lax.axis_index(pipe_axis)
        n_ticks = M + num_stages - 1

        def _pipe_varying(v):
            # mark as pipe-varying so the scan carry type is stable (jax>=0.8
            # tracks varying-manual-axes through shard_map)
            try:
                return lax.pcast(v, (pipe_axis, ), to="varying")
            except (AttributeError, TypeError):
                return v

        x0 = jax.tree_util.tree_map(lambda x: _pipe_varying(jnp.zeros_like(x[0])), xs)
        outputs = jax.tree_util.tree_map(lambda x: _pipe_varying(jnp.zeros_like(x)), xs)

        def tick(carry, t):
            recv, outputs = carry
            # stage 0 ingests microbatch t (clamped; masked-out after M)
            idx = jnp.clip(t, 0, M - 1)
            inject = jax.tree_util.tree_map(lambda x: x[idx], xs)
            x_in = jax.tree_util.tree_map(
                lambda i, r: jnp.where(stage == 0, i, r), inject, recv)
            y = fn(params_local, x_in, *consts)
            # last stage writes its result for microbatch t-(S-1)
            out_idx = jnp.clip(t - (num_stages - 1), 0, M - 1)
            valid = jnp.logical_and(stage == num_stages - 1, t >= num_stages - 1)

            def write(o, yv):
                cur = o[out_idx]
                newv = jnp.where(valid, yv, cur)
                return o.at[out_idx].set(newv)

            outputs = jax.tree_util.tree_map(write, outputs, y)
            # rotate activations downstream (stage i -> i+1; wraparound value
            # is ignored by stage 0's inject select)
            perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]
            recv = jax.tree_util.tree_map(lambda v: lax.ppermute(v, pipe_axis, perm), y)
            return (recv, outputs), None

        (recv, outputs), _ = lax.scan(tick, (x0, outputs), jnp.arange(n_ticks))
        # broadcast last stage's outputs to every stage (head/loss is
        # computed replicated over pipe)
        outputs = jax.tree_util.tree_map(
            lambda o: lax.psum(jnp.where(stage == num_stages - 1, o, jnp.zeros_like(o)), pipe_axis), outputs)
        return outputs

    x_spec = jax.tree_util.tree_map(lambda _: P(None, data_axis), microbatches)
    const_specs = tuple(jax.tree_util.tree_map(lambda _: P(), c) for c in consts)
    shard_fn = jax.shard_map(pipelined, mesh=mesh,
                             in_specs=(param_specs, x_spec) + const_specs,
                             out_specs=x_spec)
    return shard_fn(stage_params, microbatches, *consts)
