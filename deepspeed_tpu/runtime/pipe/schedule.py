"""Pipeline schedules.

Analog of the reference ``deepspeed/runtime/pipe/schedule.py`` (494 LoC):
``PipeSchedule:11`` ABC yielding instruction lists per step, ``TrainSchedule:189``
(1F1B), ``InferenceSchedule:135``, and the instruction classes (:327-:475).

On TPU the *executor* is not a host interpreter dispatching instructions — the
whole pipeline compiles into one XLA program (see ``pipe/spmd.py``). These
classes exist for (a) API parity, (b) host-side reasoning/tests about schedule
structure, and (c) deriving tick counts and buffer requirements for the
compiled loop.
"""

from abc import ABC, abstractmethod


class PipeInstruction:
    """Base instruction (reference :327)."""

    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for key, val in kwargs.items():
            setattr(self, key, val)

    def __repr__(self):
        if self.kwargs:
            return self.name + "(" + ", ".join(f"{k}={v}" for k, v in sorted(self.kwargs.items())) + ")"
        return self.name


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class BufferOpInstruction(PipeInstruction):

    def __init__(self, buffer_id, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    pass


class ForwardPass(BufferOpInstruction):
    pass


class BackwardPass(BufferOpInstruction):
    pass


class SendActivation(BufferOpInstruction):
    pass


class RecvActivation(BufferOpInstruction):
    pass


class SendGrad(BufferOpInstruction):
    pass


class RecvGrad(BufferOpInstruction):
    pass


class PipeSchedule(ABC):
    """Reference :11 — yields lists of instructions per step."""

    def __init__(self, micro_batches, stages, stage_id):
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = self.stage_id - 1
        self.next_stage = self.stage_id + 1

    @abstractmethod
    def steps(self):
        ...

    def num_pipe_buffers(self):
        return self.micro_batches

    def _valid_micro_batch(self, micro_batch_id):
        return 0 <= micro_batch_id < self.micro_batches

    def _valid_stage(self, stage_id):
        return 0 <= stage_id < self.stages

    @property
    def stage(self):
        return self.stage_id

    @property
    def num_stages(self):
        return self.stages

    @property
    def num_micro_batches(self):
        return self.micro_batches

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def _buffer_idx(self, micro_batch_id):
        assert self._valid_micro_batch(micro_batch_id)
        return micro_batch_id % self.num_pipe_buffers()

    def __iter__(self):
        self.it = None
        return self

    def __next__(self):
        if self.it is None:
            self.it = self.steps()
        return next(self.it)


class InferenceSchedule(PipeSchedule):
    """Reference :135 — forward-only pipelining."""

    def steps(self):
        total_steps = self.micro_batches + self.stages - 1
        for step_id in range(total_steps):
            cmds = []
            micro_batch_id = step_id - self.stage_id
            if self._valid_micro_batch(micro_batch_id):
                if self.is_first_stage or self.is_last_stage:
                    cmds.append(LoadMicroBatch(self._buffer_idx(micro_batch_id)))
                if not self.is_first_stage:
                    cmds.append(RecvActivation(self._buffer_idx(micro_batch_id)))
            if self._valid_micro_batch(micro_batch_id):
                cmds.append(ForwardPass(self._buffer_idx(micro_batch_id)))
                if not self.is_last_stage:
                    # send happens after compute in the same step
                    cmds.append(SendActivation(self._buffer_idx(micro_batch_id)))
            yield cmds

    def num_pipe_buffers(self):
        return 2


class TrainSchedule(PipeSchedule):
    """Reference :189 — 1F1B: steady state alternates one forward with one
    backward, bounding live activations to ~num_stages microbatches."""

    def steps(self):
        prev_micro_batch_id = -1
        total_steps = 2 * (self.micro_batches + self.stages - 1)
        for step_id in range(total_steps):
            micro_batch_id, is_forward = self._step_to_micro_batch(step_id)
            cmds = []

            # exchange activations/grads
            if self._valid_micro_batch(prev_micro_batch_id):
                if is_forward:
                    if self._valid_stage(self.prev_stage):
                        cmds.append(SendGrad(self._buffer_idx(prev_micro_batch_id)))
                else:
                    if self._valid_stage(self.next_stage):
                        cmds.append(SendActivation(self._buffer_idx(prev_micro_batch_id)))
            if self._valid_micro_batch(micro_batch_id):
                if is_forward:
                    if self._valid_stage(self.prev_stage):
                        cmds.append(RecvActivation(self._buffer_idx(micro_batch_id)))
                else:
                    if self._valid_stage(self.next_stage):
                        cmds.append(RecvGrad(self._buffer_idx(micro_batch_id)))

            # load and compute
            if self._valid_micro_batch(micro_batch_id):
                if is_forward:
                    if self.is_first_stage or self.is_last_stage:
                        cmds.append(LoadMicroBatch(self._buffer_idx(micro_batch_id)))
                    cmds.append(ForwardPass(self._buffer_idx(micro_batch_id)))
                else:
                    cmds.append(BackwardPass(self._buffer_idx(micro_batch_id)))

            # model step at the end
            if step_id == total_steps - 1:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())

            prev_micro_batch_id = micro_batch_id
            yield cmds

    def _step_to_micro_batch(self, step_id):
        """Reference mapping of global step -> (micro_batch, is_forward)."""
        if _is_even(step_id) and _is_even(self.stage_id):
            micro_batch_id = self._even_step_forward_id(step_id)
            is_forward = True
        elif _is_odd(step_id) and _is_odd(self.stage_id):
            micro_batch_id = self._odd_step_forward_id(step_id)
            is_forward = True
        elif _is_even(step_id) and _is_odd(self.stage_id):
            micro_batch_id = self._even_step_backward_id(step_id)
            is_forward = False
        elif _is_odd(step_id) and _is_even(self.stage_id):
            micro_batch_id = self._odd_step_backward_id(step_id)
            is_forward = False
        else:
            raise AssertionError("unreachable")
        return micro_batch_id, is_forward

    def _even_step_forward_id(self, step_id):
        base = step_id // 2
        return int(base - self.stage_id // 2)

    def _odd_step_forward_id(self, step_id):
        base = (step_id - 1) // 2
        return int(base - self.stage_id // 2)

    def _even_step_backward_id(self, step_id):
        base = step_id // 2
        return int(base - self.stages + (self.stage_id + 1) // 2)

    def _odd_step_backward_id(self, step_id):
        base = ((step_id - 1) // 2) - self.stages + 1
        return int(base + self.stage_id // 2)

    def num_pipe_buffers(self):
        """1F1B needs stages - stage_id live buffers (reference :289)."""
        buffers = min(self.stages - self.stage_id, self.micro_batches)
        return max(2, buffers)


class DataParallelSchedule(PipeSchedule):
    """Reference trailing class — degenerate single-stage schedule."""

    def steps(self):
        for step_id in range(self.micro_batches):
            cmds = [LoadMicroBatch(0), ForwardPass(0), BackwardPass(0)]
            if step_id == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            yield cmds

    def num_pipe_buffers(self):
        return 1


def _is_even(x):
    return x % 2 == 0


def _is_odd(x):
    return x % 2 != 0
