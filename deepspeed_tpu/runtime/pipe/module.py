"""PipelineModule — layer-list pipeline container.

Analog of the reference ``runtime/pipe/module.py`` (636 LoC: ``LayerSpec:30``,
``TiedLayerSpec:77``, ``PipelineModule:86``, ``_partition_layers:370`` with
uniform / parameters / type-regex methods). On TPU, stage assignment is a
sharding decision (the stacked layer dim over the 'pipe' axis) rather than
object placement, but the partitioning *math* — balancing layer counts or
parameter counts across stages — is identical and reused to compute each
stage's slice boundaries.
"""

import re
from typing import Callable, List, Optional

import numpy as np

from ...utils.logging import logger


class LayerSpec:
    """Reference ``LayerSpec:30`` — lazy layer constructor."""

    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs

    def build(self, log=False):
        if log:
            logger.info(f"building {repr(self)}")
        return self.typename(*self.module_args, **self.module_kwargs)

    def __repr__(self):
        return f"LayerSpec({getattr(self.typename, '__name__', self.typename)})"


class TiedLayerSpec(LayerSpec):
    """Reference ``TiedLayerSpec:77`` — layers sharing parameters across
    stages (e.g. tied embeddings). The tied group's gradients are summed over
    the owning stages — on TPU this falls out of jax.grad through shared
    params, no ReduceTiedGrads instruction needed."""

    def __init__(self, key, typename, *module_args, forward_fn=None, tied_weight_attr="weight", **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn
        self.tied_weight_attr = tied_weight_attr


def partition_balanced(weights: List[float], num_parts: int) -> List[int]:
    """Reference ``ds_utils.partition_balanced`` — split weights into
    num_parts contiguous groups minimizing the max group weight (binary
    search over capacity)."""
    weights = [float(w) for w in weights]
    n = len(weights)
    if num_parts >= n:
        return list(range(n + 1)) + [n] * (num_parts - n)

    def parts_needed(cap):
        parts, cur = 1, 0.0
        for w in weights:
            if w > cap:
                return num_parts + 1
            if cur + w > cap:
                parts += 1
                cur = w
            else:
                cur += w
        return parts

    lo, hi = max(weights), sum(weights)
    for _ in range(100):
        mid = (lo + hi) / 2
        if parts_needed(mid) <= num_parts:
            hi = mid
        else:
            lo = mid
    # build boundaries with capacity hi
    bounds = [0]
    cur = 0.0
    for i, w in enumerate(weights):
        if cur + w > hi + 1e-9:
            bounds.append(i)
            cur = w
        else:
            cur += w
    while len(bounds) < num_parts:
        bounds.append(n)
    bounds.append(n)
    return bounds[:num_parts + 1]


def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    """Uniform contiguous split boundaries (reference ``partition_uniform``)."""
    parts = [0] * (num_parts + 1)
    chunk = num_items // num_parts
    rem = num_items % num_parts
    for p in range(1, num_parts + 1):
        parts[p] = parts[p - 1] + chunk + (1 if p <= rem else 0)
    return parts


class PipelineModule:
    """Reference ``PipelineModule:86``.

    Accepts a list of layer callables / LayerSpecs, partitions them into
    ``num_stages`` contiguous slices. ``stage_layers(stage_id)`` returns the
    built layers of a stage; ``parts`` holds the slice boundaries used by the
    SPMD pipeline runner.
    """

    def __init__(self, layers, num_stages: Optional[int] = None, topology=None, loss_fn: Optional[Callable] = None,
                 seed_layers: bool = False, partition_method: str = "parameters",
                 activation_checkpoint_interval: int = 0):
        self._layer_specs = list(layers)
        self.loss_fn = loss_fn
        self.partition_method = partition_method
        if num_stages is None and topology is not None:
            num_stages = topology.get_dim("pipe")
        assert num_stages and num_stages > 0, "num_stages or topology required"
        self.num_stages = num_stages
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self.parts = self._partition_layers()

    def _estimate_weights(self):
        method = self.partition_method.lower()
        n = len(self._layer_specs)
        if method == "uniform":
            return [1.0] * n
        if method == "parameters":
            weights = [self._spec_param_count(spec) for spec in self._layer_specs]
            if all(w is None for w in weights):
                logger.warning(
                    "partition_method='parameters' but no layer exposes a parameter count "
                    "(param_count / num_params / params attrs); falling back to uniform partitioning")
                return [1.0] * n
            return [max(w, 1) if w is not None else 1 for w in weights]
        if method.startswith("type:"):
            pat = re.compile(method[5:], re.IGNORECASE)
            return [1.0 if pat.search(getattr(getattr(s, "typename", s), "__name__", str(s))) else 0.0
                    for s in self._layer_specs]
        raise NotImplementedError(f"Partitioning method {self.partition_method} not implemented")

    @staticmethod
    def _probe_param_count(t):
        pc = getattr(t, "param_count", None)
        if pc is not None:
            try:
                v = pc() if callable(pc) else pc
                return int(np.sum(list(v))) if np.iterable(v) else int(v)
            except Exception:  # e.g. unbound instance method probed on the class
                pass
        np_fn = getattr(t, "num_params", None)
        if callable(np_fn):
            try:
                return int(np_fn())
            except Exception:
                pass
        p = getattr(t, "params", None)
        if p is not None:
            try:
                import jax

                return int(sum(np.prod(np.shape(x)) for x in jax.tree_util.tree_leaves(p)))
            except Exception:
                pass
        return None

    @classmethod
    def _spec_param_count(cls, spec):
        """Parameter count of one layer spec, or None if undiscoverable.
        Probes ``param_count`` (int or callable), ``num_params()``, and a
        ``params`` array pytree — on the spec and its class first, and only
        builds the layer (lazily, once) if the cheap probes miss."""
        n = cls._probe_param_count(spec)
        if n is None and isinstance(spec, LayerSpec):
            n = cls._probe_param_count(spec.typename)
            if n is None:
                try:
                    n = cls._probe_param_count(spec.build())
                except Exception:
                    pass
        return n

    def _partition_layers(self):
        method = self.partition_method.lower()
        n = len(self._layer_specs)
        if method == "uniform":
            parts = partition_uniform(n, self.num_stages)
        else:
            parts = partition_balanced(self._estimate_weights(), self.num_stages)
        logger.info("pipeline stage partitions: " + str(
            [f"stage{i}: layers [{parts[i]}, {parts[i+1]})" for i in range(self.num_stages)]))
        return parts

    def stage_layers(self, stage_id: int):
        lo, hi = self.parts[stage_id], self.parts[stage_id + 1]
        out = []
        for spec in self._layer_specs[lo:hi]:
            out.append(spec.build() if isinstance(spec, LayerSpec) else spec)
        return out

    def num_layers_per_stage(self):
        return [self.parts[i + 1] - self.parts[i] for i in range(self.num_stages)]

    def __len__(self):
        return len(self._layer_specs)
