"""Parameter swapping to NVMe (reference
``runtime/swap_tensor/partitioned_param_swapper.py``
``AsyncPartitionedParameterSwapper:36``): the ZeRO-Infinity tier that keeps
parameter partitions on NVMe, streaming them into host buffers on demand.

On TPU the consumer is the host side of the training loop (params are
device-resident inside jit); this swapper serves ``offload_param.device ==
'nvme'`` by holding the *master* copies of parameter leaves on disk with a
bounded pool of reusable host buffers and async read/write overlap.
"""

import os
from typing import Dict, List, Optional

import numpy as np

from ...ops.aio import AsyncIOHandle
from ...utils.logging import logger


class AsyncPartitionedParameterSwapper:

    def __init__(self, base_dir: str, aio_handle: Optional[AsyncIOHandle] = None, buffer_count: int = 5):
        self.base_dir = os.path.join(base_dir, "zero_stage_3", "params")
        os.makedirs(self.base_dir, exist_ok=True)
        self.handle = aio_handle or AsyncIOHandle()
        self.buffer_count = buffer_count
        # key -> (shape, dtype); a param is "available" once swapped out
        self._meta: Dict[str, tuple] = {}
        self._pending_reads: Dict[str, np.ndarray] = {}
        self._pending_writes: List[np.ndarray] = []

    def _path(self, key: str) -> str:
        safe = key.replace("/", "_").replace(".", "_")
        return os.path.join(self.base_dir, f"{safe}.param")

    def available_params(self):
        return set(self._meta)

    # -- swap out -----------------------------------------------------
    def swap_out(self, key: str, array: np.ndarray, async_op: bool = True):
        arr = np.ascontiguousarray(array)
        self._meta[key] = (arr.shape, arr.dtype)
        self.handle.async_pwrite(arr, self._path(key))
        self._pending_writes.append(arr)
        if not async_op:
            self.synchronize_writes()

    # -- swap in ------------------------------------------------------
    def swap_in(self, key: str, async_op: bool = True) -> Optional[np.ndarray]:
        """Begin reading ``key``; with ``async_op`` the result is collected by
        ``retrieve`` after ``synchronize_reads`` (prefetch pattern)."""
        assert key in self._meta, f"param {key} was never swapped out"
        shape, dtype = self._meta[key]
        buf = np.empty(shape, dtype)
        self.handle.async_pread(buf, self._path(key))
        self._pending_reads[key] = buf
        if async_op:
            return None
        self.synchronize_reads()
        return self._pending_reads.pop(key)

    def retrieve(self, key: str) -> np.ndarray:
        """Collect a previously prefetched param (after synchronize_reads)."""
        return self._pending_reads.pop(key)

    def synchronize_reads(self):
        self.handle.wait()

    def synchronize_writes(self):
        self.handle.wait()
        self._pending_writes.clear()

    def remove(self, key: str):
        self._meta.pop(key, None)
        try:
            os.unlink(self._path(key))
        except OSError:
            pass
