"""Optimizer-state swapping for ZeRO-Infinity (reference
``runtime/swap_tensor/optimizer_utils.py`` ``OptimizerSwapper`` +
``partitioned_optimizer_swapper.py`` / ``pipelined_optimizer_swapper.py``).

The moments of each parameter leaf live on NVMe; around the optimizer step
the swapper streams them through host buffers with read/write overlap:
while leaf *i* is being updated by the fused CPU Adam kernel, leaf *i+1*'s
moments are already being read and leaf *i-1*'s are being written back
(reference ``PipelinedOptimizerSwapper`` behavior — separate read and write
aio queues).
"""

import os
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from ...ops.aio import AsyncIOHandle
from ...utils.logging import logger


class OptimizerStateSwapper:
    """NVMe-backed store of per-leaf optimizer state arrays.

    State layout: one file per (leaf, state_name), fp32. The iteration
    protocol used by the host offload optimizer:

        swapper.prefetch(key)           # submit async reads
        arrays = swapper.fetch(key)     # wait + collect
        ... fused adam mutates arrays in place ...
        swapper.writeback(key, arrays)  # submit async writes
        swapper.flush()                 # end of step barrier
    """

    STATE_NAMES = ("exp_avg", "exp_avg_sq")

    def __init__(self, base_dir: str, pipeline_read: bool = True, pipeline_write: bool = True,
                 aio_threads: int = 2):
        self.base_dir = os.path.join(base_dir, "optimizer_state")
        os.makedirs(self.base_dir, exist_ok=True)
        self.read_handle = AsyncIOHandle(thread_count=aio_threads)
        self.write_handle = AsyncIOHandle(thread_count=aio_threads)
        self.pipeline_read = pipeline_read
        self.pipeline_write = pipeline_write
        self._meta: Dict[str, tuple] = {}  # key -> (shape, dtype)
        self._read_bufs: Dict[str, Dict[str, np.ndarray]] = {}
        self._write_keepalive: List[np.ndarray] = []

    def _path(self, key: str, state_name: str) -> str:
        safe = key.replace("/", "_").replace(".", "_")
        return os.path.join(self.base_dir, f"{safe}.{state_name}")

    def initialize(self, key: str, shape, dtype=np.float32):
        """Create zero-initialized moments on NVMe for a leaf."""
        self._meta[key] = (tuple(shape), np.dtype(dtype))
        zeros = np.zeros(shape, dtype)
        for name in self.STATE_NAMES:
            self.write_handle.async_pwrite(zeros, self._path(key, name))
        self._write_keepalive.append(zeros)

    def has(self, key: str) -> bool:
        return key in self._meta

    def prefetch(self, key: str):
        """Submit async reads of the leaf's moments into fresh host buffers.
        With ``pipeline_read`` off this is a no-op and ``fetch`` reads
        synchronously (reference gates prefetch behind PipelinedOptimizerSwapper
        the same way)."""
        if not self.pipeline_read:
            return
        shape, dtype = self._meta[key]
        bufs = {name: np.empty(shape, dtype) for name in self.STATE_NAMES}
        for name, buf in bufs.items():
            self.read_handle.async_pread(buf, self._path(key, name))
        self._read_bufs[key] = bufs

    def fetch(self, key: str) -> Dict[str, np.ndarray]:
        """Wait for the leaf's reads and return {state_name: array}."""
        if key not in self._read_bufs:
            shape, dtype = self._meta[key]
            bufs = {name: np.empty(shape, dtype) for name in self.STATE_NAMES}
            for name, buf in bufs.items():
                self.read_handle.async_pread(buf, self._path(key, name))
            self._read_bufs[key] = bufs
        self.read_handle.wait()
        return self._read_bufs.pop(key)

    def writeback(self, key: str, arrays: Dict[str, np.ndarray], async_op: bool = True):
        for name in self.STATE_NAMES:
            arr = np.ascontiguousarray(arrays[name])
            self.write_handle.async_pwrite(arr, self._path(key, name))
            self._write_keepalive.append(arr)
        if not (async_op and self.pipeline_write):
            self.flush_writes()

    def flush_writes(self):
        if self._write_keepalive:
            self.write_handle.wait()
            self._write_keepalive.clear()

    def flush(self):
        self.flush_writes()

    # -- bulk accessors for checkpointing ------------------------------
    def state_dict(self) -> Dict[str, Dict[str, np.ndarray]]:
        self.flush_writes()
        out = {}
        for key in self._meta:
            self.prefetch(key)
            out[key] = self.fetch(key)
        return out

    def load_state_dict(self, state: Dict[str, Dict[str, np.ndarray]]):
        for key, arrays in state.items():
            some = arrays[self.STATE_NAMES[0]]
            self._meta[key] = (tuple(some.shape), some.dtype)
            self.writeback(key, arrays, async_op=True)
        self.flush_writes()
