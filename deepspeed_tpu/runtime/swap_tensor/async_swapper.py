"""Generic async tensor swapping (reference ``runtime/swap_tensor/async_swapper.py``
``AsyncTensorSwapper``): fire-and-forget swap-out of host tensors to files with
a bounded in-flight window, so compute overlaps the NVMe writes.
"""

import os
from collections import deque

import numpy as np

from ...ops.aio import AsyncIOHandle
from ...utils.logging import logger


class AsyncTensorSwapper:
    """Swap numpy tensors out to files asynchronously.

    ``add_buffers([(array, path), ...])`` submits writes; buffers are kept
    alive until their write completes. ``max_inflight`` bounds host-RAM held
    by pending writes (the reference bounds by buffer count the same way).
    """

    def __init__(self, aio_handle: AsyncIOHandle = None, max_inflight: int = 8, timers=None):
        self.handle = aio_handle or AsyncIOHandle()
        self._own_handle = aio_handle is None
        self.max_inflight = max_inflight
        self._inflight = deque()
        self.swap_bytes = 0

    def swap_out_tensors(self, tensor_path_pairs):
        for arr, path in tensor_path_pairs:
            arr = np.ascontiguousarray(arr)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            if len(self._inflight) >= self.max_inflight:
                self.synchronize()
            self.handle.async_pwrite(arr, path)
            self._inflight.append(arr)  # keep alive until wait()
            self.swap_bytes += arr.nbytes

    def synchronize(self):
        """Wait for all pending writes (reference ``shutdown``/buffer flush)."""
        if self._inflight:
            self.handle.wait()
            self._inflight.clear()

    def shutdown(self):
        self.synchronize()
        if self._own_handle:
            self.handle.close()
