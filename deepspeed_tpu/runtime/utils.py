"""Runtime utility surface (reference ``deepspeed/runtime/utils.py`` — the
grab-bag user code imports from: ``see_memory_usage``, ``clip_grad_norm_``,
``get_global_norm``, ``get_grad_norm``…). Functional JAX forms: clipping
returns the new tree instead of mutating in place.
"""

from typing import Iterable

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.logging import logger


def see_memory_usage(message: str, force: bool = False):
    """Device HBM stats (when the backend exposes them) + host RSS
    (reference ``see_memory_usage`` prints torch.cuda + psutil numbers)."""
    if not force:
        return
    parts = [message]
    try:
        stats = jax.devices()[0].memory_stats() or {}
        if "bytes_in_use" in stats:
            parts.append(f"HBM in use {stats['bytes_in_use'] / 2**30:.2f}GB")
        if "peak_bytes_in_use" in stats:
            parts.append(f"peak {stats['peak_bytes_in_use'] / 2**30:.2f}GB")
        if "bytes_limit" in stats:
            parts.append(f"limit {stats['bytes_limit'] / 2**30:.2f}GB")
    except Exception:
        parts.append("HBM stats unavailable")
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS"):
                    parts.append(f"host RSS {int(line.split()[1]) / 2**20:.2f}GB")
                    break
    except Exception:
        pass
    logger.info(" | ".join(parts))


def get_global_norm(norm_list: Iterable[float]) -> float:
    """l2-combine per-group norms (reference ``get_global_norm``)."""
    return float(np.sqrt(sum(float(n) ** 2 for n in norm_list)))


def get_grad_norm(grads, norm_type: float = 2.0):
    """Global norm of a gradient pytree (reference ``get_grad_norm`` over
    parameter lists). Traced-compatible: returns a jnp scalar inside jit."""
    leaves = jax.tree_util.tree_leaves(grads)
    if not leaves:
        return jnp.asarray(0.0, jnp.float32)
    if norm_type == float("inf"):
        return jnp.max(jnp.stack([jnp.max(jnp.abs(g)).astype(jnp.float32) for g in leaves]))
    norm_type = float(norm_type)
    total = sum(jnp.sum(jnp.abs(g.astype(jnp.float32)) ** norm_type) for g in leaves)
    return total ** (1.0 / norm_type)


def clip_grad_norm_(grads, max_norm: float, norm_type: float = 2.0):
    """Reference ``clip_grad_norm_`` in functional form: returns
    (clipped_grads, total_norm) — JAX trees are immutable, so the clipped
    tree is the result rather than an in-place mutation."""
    total = get_grad_norm(grads, norm_type)
    scale = jnp.minimum(1.0, max_norm / (total + 1e-6))
    return jax.tree_util.tree_map(lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype),
                                  grads), total


def empty_cache():
    """Reference ``empty_cache``: XLA owns the allocator; nothing to drop."""
    return None
