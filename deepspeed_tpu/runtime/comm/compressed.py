"""Compressed gradient collectives.

TPU-native analog of the reference compressed-communication backends
(``runtime/comm/nccl.py:51`` ``NcclBackend.compressed_allreduce`` — the 1-bit
Adam/LAMB error-feedback exchange — and ``runtime/comm/coalesced_collectives.py``
``reduce_scatter_coalesced:73`` / ``all_to_all_quant_reduce:31`` used by
ZeRO-3/ZeRO++). Everything here is traced code running inside
``shard_map`` over a mesh axis; the payloads are bit-packed uint8 sign
tensors + per-chunk fp32 scales, so the wire volume is ~n/4 bytes per
allreduce vs 4n for fp32 — the same ~16-32x compression the reference gets
from its CUDA pack kernels, but riding XLA collectives on ICI.

Algorithm (reference 1-bit Adam, NcclBackend.compressed_allreduce):
  worker:  c = g + err_w;  scale_w = mean|c| per destination chunk;
           err_w' = c - scale_w*sign(c);  a2a(sign(c), scale_w)
  server:  avg = mean_i scale_w_i * sign_i;  c_s = avg + err_s;
           scale_s = mean|c_s|;  err_s' = c_s - scale_s*sign(c_s);
           allgather(sign(c_s), scale_s)
"""

import math
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

_BIT_WEIGHTS = (1, 2, 4, 8, 16, 32, 64, 128)


def pack_signs(bits: jax.Array) -> jax.Array:
    """Pack a {0,1} array whose last dim is a multiple of 8 into uint8
    (8 signs per byte — the reference's CUDA sign-packing kernel)."""
    *lead, n = bits.shape
    assert n % 8 == 0, f"last dim {n} must be a multiple of 8"
    grouped = bits.reshape(*lead, n // 8, 8).astype(jnp.uint8)
    w = jnp.asarray(_BIT_WEIGHTS, jnp.uint8)
    return (grouped * w).sum(axis=-1).astype(jnp.uint8)


def unpack_signs(packed: jax.Array) -> jax.Array:
    """uint8 → ±1 fp32 array with last dim expanded 8x."""
    *lead, nb = packed.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (packed[..., None] >> shifts) & jnp.uint8(1)
    return (bits.reshape(*lead, nb * 8).astype(jnp.float32) * 2.0 - 1.0)


def onebit_chunk_len(n: int, world: int) -> int:
    """Per-device server chunk length: ceil(n/world) rounded up to 8."""
    chunk = -(-n // world)
    return -(-chunk // 8) * 8


def onebit_allreduce(x: jax.Array, err_worker: jax.Array, err_server: jax.Array,
                     axis_name: str, world: int) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Error-feedback 1-bit averaged allreduce of ``x`` over ``axis_name``.

    Must run inside ``shard_map``. Shapes (all local):
      x, err_worker: param shape;  err_server: (onebit_chunk_len(n, world),)
    Returns (avg_approx with x's shape, err_worker', err_server').
    """
    shape = x.shape
    n = math.prod(shape) if shape else 1
    chunk = onebit_chunk_len(n, world)
    total = chunk * world

    flat = x.reshape(-1).astype(jnp.float32) + err_worker.reshape(-1).astype(jnp.float32)
    flat = jnp.pad(flat, (0, total - n))
    rows = flat.reshape(world, chunk)  # row j is destined for device j

    scale_w = jnp.mean(jnp.abs(rows), axis=1)  # (world,)
    bits_w = (rows >= 0).astype(jnp.uint8)
    signs_w = bits_w.astype(jnp.float32) * 2.0 - 1.0
    new_err_w = (rows - scale_w[:, None] * signs_w).reshape(-1)[:n].reshape(shape)

    packed_w = pack_signs(bits_w)  # (world, chunk//8) uint8
    recv_packed = lax.all_to_all(packed_w, axis_name, split_axis=0, concat_axis=0, tiled=True)
    recv_scale = lax.all_to_all(scale_w, axis_name, split_axis=0, concat_axis=0, tiled=True)
    recv_signs = unpack_signs(recv_packed)  # (world, chunk) ±1

    server_avg = jnp.mean(recv_scale[:, None] * recv_signs, axis=0)  # (chunk,)
    comp_s = server_avg + err_server.astype(jnp.float32)
    scale_s = jnp.mean(jnp.abs(comp_s))  # scalar
    bits_s = (comp_s >= 0).astype(jnp.uint8)
    signs_s = bits_s.astype(jnp.float32) * 2.0 - 1.0
    new_err_s = comp_s - scale_s * signs_s

    packed_s = pack_signs(bits_s[None, :])[0]  # (chunk//8,)
    all_packed = lax.all_gather(packed_s, axis_name, axis=0, tiled=False)  # (world, chunk//8)
    all_scale = lax.all_gather(scale_s, axis_name, axis=0, tiled=False)  # (world,)
    out_rows = all_scale[:, None] * unpack_signs(all_packed)  # (world, chunk)
    out = out_rows.reshape(-1)[:n].reshape(shape)
    return out, new_err_w.astype(err_worker.dtype), new_err_s.astype(err_server.dtype)


def reduce_scatter_coalesced(tensors, axis_name: str):
    """Reference ``reduce_scatter_coalesced:73`` — bucketed reduce-scatter of a
    tensor list, returning the MEAN over the axis (the reference pre-divides
    by world size, ``coalesced_collectives.py:116``). In-jit: XLA already
    coalesces adjacent collectives, so this is a per-tensor psum_scatter."""
    world = lax.psum(1, axis_name)
    return [lax.psum_scatter(t / world, axis_name, scatter_dimension=0, tiled=True)
            for t in tensors]


def all_to_all_quant_reduce(tensors, axis_name: str, block_size: int = 256):
    """Reference qgZ ``all_to_all_quant_reduce:31``: int8 block-quantized
    2-hop gradient reduction (quantize → a2a → dequant-reduce), returning the
    MEAN over the axis (the reference divides by num_nodes after its
    quantized_reduction hop)."""
    from ...ops.pallas.quant import quantized_psum_scatter

    world = lax.psum(1, axis_name)
    return [quantized_psum_scatter(t, axis_name, block_size) / world for t in tensors]
