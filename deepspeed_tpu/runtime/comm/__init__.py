from .compressed import (pack_signs, unpack_signs, onebit_allreduce, reduce_scatter_coalesced,
                         all_to_all_quant_reduce, onebit_chunk_len)
