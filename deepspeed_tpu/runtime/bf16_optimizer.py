"""BF16 optimizer (reference ``runtime/bf16_optimizer.py`` —
``BF16_Optimizer``: bf16 params in the model, fp32 masters + fp32 grads in
the optimizer, update in fp32, cast back).

The TPU engine gets these numerics structurally (params rest in fp32; the
model casts to bf16 at compute, see ``optimizers.master_weight_wrapper``) —
this class serves code written against the reference's object API: it OWNS
the fp32 master tree, steps it in fp32, and hands back fresh bf16 compute
params each step.
"""

import jax
import jax.numpy as jnp
import optax


def _is_float(x):
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


class BF16_Optimizer:
    """``init(params)`` → bf16 compute params (masters kept fp32 inside);
    ``step(grads)`` → updated bf16 params."""

    def __init__(self, init_optimizer: optax.GradientTransformation,
                 compute_dtype=jnp.bfloat16, clip_grad: float = 0.0):
        tx = init_optimizer
        if clip_grad and clip_grad > 0:
            tx = optax.chain(optax.clip_by_global_norm(clip_grad), tx)
        self.tx = tx
        self.compute_dtype = compute_dtype
        self.state = None
        self._masters = None
        self._params = None

    def _cast_down(self):
        return jax.tree_util.tree_map(
            lambda m: m.astype(self.compute_dtype) if _is_float(m) else m, self._masters)

    def init(self, params):
        self._masters = jax.tree_util.tree_map(
            lambda p: jnp.asarray(p, jnp.float32) if _is_float(p) else jnp.asarray(p), params)
        self.state = self.tx.init(self._masters)
        self._params = self._cast_down()
        return self._params

    def step(self, grads):
        grads32 = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32) if _is_float(g) else g, grads)
        updates, self.state = self.tx.update(grads32, self.state, self._masters)
        self._masters = optax.apply_updates(self._masters, updates)
        self._params = self._cast_down()
        return self._params

    @property
    def param_groups(self):  # reference surface; one flat group here
        return [{"params": self._params}]

    def fp32_params(self):
        """The fp32 master tree (reference exposes fp32_groups_flat)."""
        return self._masters

    def state_dict(self):
        return {"state": self.state, "masters": self._masters}

    def load_state_dict(self, sd):
        self.state = sd["state"]
        self._masters = sd["masters"]
        self._params = self._cast_down()
