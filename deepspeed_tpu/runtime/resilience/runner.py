"""``run_resilient`` — auto-resume harness around :class:`ElasticAgent`.

The reference's restart story is torch-elastic re-rendezvous + user resume
code; here the agent already re-resolves the elastic batch config per
attempt, and this wrapper adds the missing half: every (re)start receives
the newest *valid* resume point — preferring a live host snapshot (the
elastic warm remesh, ``elasticity/remesh.py``) over the newest
manifest-verified disk tag over a cold start — so an injected worker
failure or a preemption exit resumes exactly where the last durable
version left off, without a disk read when the process still holds the
state in host RAM.
"""

import time
from typing import Callable, Optional

from .errors import TrainingPreempted
from .saver import find_latest_valid, tag_step
from ...monitor.metrics import get_metrics
from ...utils.logging import logger


class ResumePoint(tuple):
    """``(tag, path)`` — unpacks exactly like the historical 2-tuple — plus
    ``snapshot``, the warm-remesh :class:`~...elasticity.remesh.HostSnapshot`
    when one at least as new as the disk tag is available (None otherwise).
    The fallback ladder a ``train_fn`` should implement::

        tag, path = resume
        if resume.snapshot is not None:
            remesh.restore_snapshot(engine, resume.snapshot)   # warm: no disk
        elif tag is not None:
            engine.load_checkpoint(save_dir, tag=tag)          # disk
        # else: cold start
    """

    def __new__(cls, tag=None, path=None, snapshot=None):
        self = super().__new__(cls, (tag, path))
        self.snapshot = snapshot
        return self

    @property
    def tag(self):
        return self[0]

    @property
    def path(self):
        return self[1]


def run_resilient(train_fn: Callable, ds_config: dict, save_dir: Optional[str] = None,
                  max_restarts: int = 3, restart_delay_s: float = 5.0, backoff_factor: float = 2.0,
                  world_size_fn: Optional[Callable[[], int]] = None, deep_verify: bool = False,
                  retryable_exceptions=None, restart_window_s: float = 0.0,
                  warm_remesh: bool = False):
    """Run ``train_fn(batch_config, resume)`` under elastic restarts.

    ``batch_config`` is the re-resolved elastic batch triad for the current
    world size; ``resume`` is a :class:`ResumePoint` — ``(tag, path)`` of
    the newest valid checkpoint under ``save_dir`` (``(None, None)`` on a
    cold start), re-evaluated at every attempt so a restart picks up
    checkpoints the failed attempt committed. With ``warm_remesh`` the
    published host snapshot (``elasticity.remesh``) rides along as
    ``resume.snapshot`` whenever it is at least as new as the disk tag:
    the restart re-shards from host RAM instead of reading the checkpoint
    payload — including onto a DIFFERENT world size, since the snapshot is
    topology-free universal layout. ``retryable_exceptions`` /
    ``restart_window_s`` pass through to the agent (which exception types
    count as worker loss, and the healthy-run budget reset). A
    :class:`TrainingPreempted` escape is a clean shutdown, not a failure:
    it is returned (not re-raised) so supervising code can requeue the job.
    """
    from ...elasticity import ElasticAgent

    agent = ElasticAgent(ds_config, max_restarts=max_restarts, restart_delay_s=restart_delay_s,
                         backoff_factor=backoff_factor,
                         retryable_exceptions=retryable_exceptions,
                         restart_window_s=restart_window_s)

    def attempt(batch_config):
        tag = path = None
        if save_dir is not None:
            tag, path = find_latest_valid(save_dir, deep=deep_verify)
        snapshot = None
        if warm_remesh:
            from ...elasticity import remesh

            # scope-checked: only a snapshot stamped for THIS job's save_dir
            # (or an explicitly hand-published scope-less one) is eligible —
            # a previous job's snapshot in the same process must not
            # warm-resume an unrelated run
            snap = remesh.latest_snapshot(scope=save_dir)
            if save_dir is None and snap is not None and snap.scope is not None:
                # a dir-less run has no identity to match: a JOB-stamped
                # snapshot (auto-published by some engine's save path) must
                # not leak into it — only hand-published scope-less
                # snapshots qualify here
                snap = None
            # the snapshot wins only when at least as new as the durable tag
            # (a crash can postdate the last publish; the disk must win then);
            # a non-step-style tag has no comparable step — the warm copy wins
            disk_step = tag_step(tag) if tag is not None else None
            if snap is not None and (disk_step is None or snap.step >= disk_step):
                snapshot = snap
        resume = ResumePoint(tag, path, snapshot=snapshot)
        if snapshot is not None:
            get_metrics().counter("checkpoint/warm_remesh_resumes_total").inc()
            logger.info(f"run_resilient: warm-remesh resume from host snapshot "
                        f"(step {snapshot.step}; disk tag {tag or 'none'} stays fallback; "
                        f"restart {agent.restart_count}/{max_restarts})")
        elif tag is not None:
            logger.info(f"run_resilient: resuming from valid tag {tag} "
                        f"(restart {agent.restart_count}/{max_restarts})")
        t0 = time.perf_counter()
        try:
            return train_fn(batch_config, resume)
        except BaseException:
            # goodput: recovery badput starts at the failure/preemption
            # boundary and ends at the restarted engine's first step entry
            # (the ledger books the interval there); a disarmed plane makes
            # this one enabled check
            from ...monitor.goodput import get_goodput

            get_goodput().note_training_failure()
            raise
        finally:
            # recovery-time accounting for the chaos drill / bench: how long
            # each restarted attempt ran (the drill derives time-to-recover
            # from the attempt boundaries)
            get_metrics().histogram("checkpoint/attempt_wall_ms").observe(
                (time.perf_counter() - t0) * 1e3)

    try:
        return agent.run(attempt, world_size_fn=world_size_fn)
    except TrainingPreempted as e:
        get_metrics().counter("health/preempted_total").inc()
        logger.warning(f"run_resilient: clean preemption exit (final tag {e.tag})")
        return e
