"""``run_resilient`` — auto-resume harness around :class:`ElasticAgent`.

The reference's restart story is torch-elastic re-rendezvous + user resume
code; here the agent already re-resolves the elastic batch config per
attempt, and this wrapper adds the missing half: every (re)start receives
the newest *valid* checkpoint tag (manifest-verified, torn tags skipped),
so an injected worker failure or a preemption exit resumes exactly where
the last durable version left off.
"""

from typing import Callable, Optional

from .errors import TrainingPreempted
from .saver import find_latest_valid
from ...utils.logging import logger


def run_resilient(train_fn: Callable, ds_config: dict, save_dir: Optional[str] = None,
                  max_restarts: int = 3, restart_delay_s: float = 5.0, backoff_factor: float = 2.0,
                  world_size_fn: Optional[Callable[[], int]] = None, deep_verify: bool = False):
    """Run ``train_fn(batch_config, resume_from)`` under elastic restarts.

    ``batch_config`` is the re-resolved elastic batch triad for the current
    world size; ``resume_from`` is ``(tag, path)`` of the newest valid
    checkpoint under ``save_dir`` (``(None, None)`` on a cold start) —
    re-evaluated at every attempt, so a restart picks up checkpoints the
    failed attempt committed. A :class:`TrainingPreempted` escape is a clean
    shutdown, not a failure: it is returned (not re-raised) so supervising
    code can requeue the job.
    """
    from ...elasticity import ElasticAgent

    agent = ElasticAgent(ds_config, max_restarts=max_restarts, restart_delay_s=restart_delay_s,
                         backoff_factor=backoff_factor)

    def attempt(batch_config):
        resume = (None, None)
        if save_dir is not None:
            resume = find_latest_valid(save_dir, deep=deep_verify)
            if resume[0] is not None:
                logger.info(f"run_resilient: resuming from valid tag {resume[0]} "
                            f"(restart {agent.restart_count}/{max_restarts})")
        return train_fn(batch_config, resume)

    try:
        return agent.run(attempt, world_size_fn=world_size_fn)
    except TrainingPreempted as e:
        logger.warning(f"run_resilient: clean preemption exit (final tag {e.tag})")
        return e
