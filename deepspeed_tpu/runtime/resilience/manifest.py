"""Per-checkpoint commit manifest.

A checkpoint directory is *durable* iff ``manifest.json`` inside it parses
and every file it names is present with the recorded byte count (and, on a
deep verify, the recorded sha256). The manifest is written tmp+fsync+rename
as the LAST step of a save, so its presence is the commit marker: a crash at
any earlier point leaves a directory that ``verify_manifest`` rejects and
the ``latest`` pointer never references (reference semantics: Nebula's
tiered service only advertises fully persisted versions).
"""

import hashlib
import json
import os
import time

MANIFEST_FILE = "manifest.json"
MANIFEST_VERSION = 1

from .errors import CheckpointCorruptError


def _metrics():
    from ...monitor.metrics import get_metrics  # lazy: manifest stays import-light

    return get_metrics()


def _iter_files(ckpt_path):
    """Relative (posix) paths of every payload file under the checkpoint
    dir, manifest excluded."""
    for root, _dirs, files in os.walk(ckpt_path):
        for fname in sorted(files):
            rel = os.path.relpath(os.path.join(root, fname), ckpt_path)
            rel = rel.replace(os.sep, "/")
            if rel == MANIFEST_FILE or rel.endswith(".tmp"):
                continue
            yield rel


def _sha256(path, chunk=1 << 20):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def tree_spec(state):
    """Flattened ``path -> {shape, dtype}`` for the array leaves of a nested
    dict checkpoint state (non-array client state is listed by type only) —
    the restore-side schema half of the crash-consistency contract."""
    spec = {}

    def walk(node, prefix):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, f"{prefix}/{k}" if prefix else str(k))
        elif hasattr(node, "shape") and hasattr(node, "dtype"):
            spec[prefix] = {"shape": [int(d) for d in node.shape], "dtype": str(node.dtype)}
        else:
            spec[prefix] = {"type": type(node).__name__}

    walk(state, "")
    return spec


def build_manifest(ckpt_path, tag, state=None, tree=None, digests=True):
    """Inventory every payload file already on disk under ``ckpt_path``.

    ``tree`` is a precomputed :func:`tree_spec` — the async commit stage
    passes one so the manifest build never touches ``state`` (whose leaves
    may be donated device buffers by the time the writer thread runs).
    ``digests=False`` skips the per-file sha256 (which costs a full
    read-back of the payload); the size-only manifest still gates commit,
    and deep verifies just skip the digest comparison for those entries."""
    files = {}
    total = 0
    for rel in _iter_files(ckpt_path):
        full = os.path.join(ckpt_path, rel)
        n = os.path.getsize(full)
        files[rel] = {"bytes": n, "sha256": _sha256(full)} if digests else {"bytes": n}
        total += n
    return {
        "version": MANIFEST_VERSION,
        "tag": str(tag),
        "created_unix": time.time(),
        "total_bytes": total,
        "files": files,
        "tree": tree if tree is not None else (tree_spec(state) if state is not None else None),
    }


def write_manifest(ckpt_path, manifest):
    """Durable (tmp + fsync + rename) manifest write — the commit point."""
    final = os.path.join(ckpt_path, MANIFEST_FILE)
    tmp = final + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, final)
    return final


def read_manifest(ckpt_path):
    """Parse the manifest or raise :class:`CheckpointCorruptError` (absent
    manifest == uncommitted checkpoint == corrupt for the resilient plane)."""
    path = os.path.join(ckpt_path, MANIFEST_FILE)
    if not os.path.isfile(path):
        raise CheckpointCorruptError(f"no manifest at {path}: checkpoint never committed")
    try:
        with open(path) as f:
            return json.load(f)
    except (ValueError, OSError) as e:
        raise CheckpointCorruptError(f"torn manifest at {path}: {e}")


def verify_manifest(ckpt_path, deep=True):
    """Validate a checkpoint dir against its manifest; returns the manifest.

    ``deep=False`` checks existence + byte counts only (cheap, used when
    scanning many tags for the newest valid one); ``deep=True`` also
    re-digests every file, catching silent bit-rot and partial overwrites.
    """
    man = read_manifest(ckpt_path)
    for rel, meta in (man.get("files") or {}).items():
        full = os.path.join(ckpt_path, rel)
        if not os.path.isfile(full):
            raise CheckpointCorruptError(f"{ckpt_path}: missing payload file {rel}")
        size = os.path.getsize(full)
        if size != meta.get("bytes"):
            raise CheckpointCorruptError(
                f"{ckpt_path}: {rel} is {size}B, manifest says {meta.get('bytes')}B")
        if deep and meta.get("sha256") and _sha256(full) != meta["sha256"]:
            raise CheckpointCorruptError(f"{ckpt_path}: digest mismatch on {rel}")
    return man


def is_committed(ckpt_path, deep=False):
    """True iff the directory verifies against its manifest."""
    try:
        verify_manifest(ckpt_path, deep=deep)
        return True
    except CheckpointCorruptError:
        # the probing face of verify_manifest: False IS the answer, but the
        # rate of torn tags encountered is health signal, not noise
        _metrics().counter("health/ckpt_verify_failed_total").inc()
        return False
