"""Auto-save triggers: step-count and wall-clock cadence.

``nebula.persistent_time_interval`` (seconds between durable versions — the
reference knob our port previously parsed and ignored) and the new
``checkpoint.save_interval_steps`` both feed one trigger; whichever fires
first wins and firing resets both cadences (a save is a save).
"""

import time


class AutoSaveTrigger:

    def __init__(self, save_interval_steps=0, persistent_time_interval=0, clock=time.monotonic):
        self.save_interval_steps = int(save_interval_steps or 0)
        self.persistent_time_interval = float(persistent_time_interval or 0)
        self._clock = clock
        self._last_step = 0
        self._last_time = clock()

    @property
    def enabled(self):
        return self.save_interval_steps > 0 or self.persistent_time_interval > 0

    def should_save(self, step):
        if self.save_interval_steps > 0 and step - self._last_step >= self.save_interval_steps:
            return True
        if (self.persistent_time_interval > 0
                and self._clock() - self._last_time >= self.persistent_time_interval):
            return True
        return False

    def mark_saved(self, step):
        """Reset both cadences — call after ANY save (auto or user)."""
        self._last_step = step
        self._last_time = self._clock()
