"""Crash-consistent checkpoint writer: the ONLY module allowed to move the
``latest`` pointer or delete checkpoint tags (``tools/check_ckpt_commit.py``
enforces this statically, the way ``check_timed_ops.py`` pins collectives to
``@timed_op``).

Commit protocol per save (all stages in the writer thread on the async
path; :mod:`fault_injection` points mark the stage boundaries)::

    payload (engine.save -> arrays/ + meta.pkl)     [crash here: no manifest]
    engine.commit()  -> must return True            [False: save aborted]
    manifest.json    (tmp + fsync + rename)         <- durability point
    latest           (tmp + fsync + rename)         [crash here: next save heals]
    retention GC     (superseded tags only)

A crash at ANY point leaves ``latest`` referencing the previous durable
tag — the step loop never has to trust a torn directory. This is the Nebula
contract (``deepspeed/nebula``: training never blocks on persistence, only
fully-persisted versions are advertised) rebuilt on orbax + manifests.
"""

import os
import re
import shutil
import threading
import time

from . import fault_injection
from .errors import CheckpointCorruptError
from .manifest import build_manifest, is_committed, read_manifest, write_manifest, MANIFEST_FILE
from ...monitor.metrics import get_metrics
from ...monitor.trace import get_tracer
from ...utils.logging import logger

LATEST_FILE = "latest"  # reference `latest` tag file semantics
_STEP_RE = re.compile(r"(\d+)\s*$")


def read_latest(save_dir):
    """Tag named by the ``latest`` pointer, or None."""
    path = os.path.join(save_dir, LATEST_FILE)
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        tag = f.read().strip()
    return tag or None


def list_tags(save_dir):
    """Checkpoint tag directories under ``save_dir``, unordered."""
    if not os.path.isdir(save_dir):
        return []
    return [d for d in os.listdir(save_dir)
            if os.path.isdir(os.path.join(save_dir, d))]


def tag_step(save_dir, tag):
    """Trailing integer of a step-style tag (``global_step12`` -> 12), or
    None for non-numeric tags (``best``) — used only by the
    ``keep_every_n_steps`` archival rule."""
    m = _STEP_RE.search(str(tag))
    return int(m.group(1)) if m else None


# (path -> (manifest mtime, key)): retention sorts, the newest-valid scan,
# and load fallback all call tag_order_key repeatedly per tag, and for a big
# model the manifest (full digest table + tree spec) is hundreds of KB — one
# parse per committed manifest, not one per comparison
_ORDER_KEY_CACHE = {}


def tag_order_key(save_dir, tag):
    """Recency key for a tag: manifest commit time for committed dirs, dir
    mtime for torn/in-flight ones (same unix-seconds unit, so the two order
    consistently — a trailing step number would put a committed ``best``
    tag in a different key space and permanently out-sort every
    ``global_stepN``)."""
    path = os.path.join(save_dir, str(tag))
    try:
        man_mtime = os.path.getmtime(os.path.join(path, MANIFEST_FILE))
    except OSError:
        man_mtime = None
    if man_mtime is not None:
        hit = _ORDER_KEY_CACHE.get(path)
        if hit is not None and hit[0] == man_mtime:
            return hit[1]
    try:
        key = float(read_manifest(path).get("created_unix", -1.0))
    except CheckpointCorruptError:
        try:
            return os.path.getmtime(path)
        except OSError:
            return -1.0
    if man_mtime is not None:
        if len(_ORDER_KEY_CACHE) > 1024:  # GC'd tags leave entries behind
            _ORDER_KEY_CACHE.clear()
        _ORDER_KEY_CACHE[path] = (man_mtime, key)
    return key


def find_latest_valid(save_dir, deep=False):
    """Newest tag whose directory verifies against its manifest, preferring
    the ``latest`` pointer; returns (tag, path) or (None, None).

    This is the load-side half of crash consistency: a torn directory (or a
    corrupted manifest) is skipped, not surfaced, and the scan falls back
    through older tags newest-first.
    """
    candidates = []
    pointed = read_latest(save_dir)
    if pointed is not None:
        candidates.append(pointed)
    for tag in sorted(list_tags(save_dir), key=lambda t: tag_order_key(save_dir, t), reverse=True):
        if tag not in candidates:
            candidates.append(tag)
    for tag in candidates:
        path = os.path.join(save_dir, tag)
        if os.path.isdir(path) and is_committed(path, deep=deep):
            return tag, path
    return None, None


def write_latest(save_dir, tag):
    """Atomically flip the ``latest`` pointer (tmp + fsync + rename)."""
    os.makedirs(save_dir, exist_ok=True)
    final = os.path.join(save_dir, LATEST_FILE)
    tmp = final + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(tag))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, final)


def apply_retention(save_dir, keep, keep_every_n_steps=0, protect=()):
    """Delete superseded tags, honoring ``nebula.num_of_version_in_retention``.

    Keeps: the newest ``keep`` committed step-style tags, every committed
    tag whose step is a multiple of ``keep_every_n_steps`` (the archival
    knob), every committed NON-step tag (a user-named ``best``/``release``
    checkpoint is an explicit decision — cadence GC has no business deleting
    it), and anything in ``protect`` (the just-committed tag + the
    ``latest`` target). Uncommitted directories older than the newest
    committed tag are crash garbage and are removed too. ``keep <= 0``
    disables GC entirely. Returns the list of deleted tags.
    """
    if keep <= 0:
        return []
    protect = {str(t) for t in protect if t is not None}
    pointed = read_latest(save_dir)
    if pointed:
        protect.add(pointed)
    committed, torn = [], []
    for tag in list_tags(save_dir):
        (committed if is_committed(os.path.join(save_dir, tag)) else torn).append(tag)
    committed.sort(key=lambda t: tag_order_key(save_dir, t), reverse=True)
    # only step-style tags compete for the newest-N window; named tags are
    # kept unconditionally (and don't shrink the window for real versions)
    step_tags = [t for t in committed if tag_step(save_dir, t) is not None]
    keep_set = set(step_tags[:keep]) | protect
    keep_set.update(t for t in committed if tag_step(save_dir, t) is None)
    if keep_every_n_steps > 0:
        for tag in step_tags:
            if tag_step(save_dir, tag) % keep_every_n_steps == 0:
                keep_set.add(tag)
    newest_key = tag_order_key(save_dir, committed[0]) if committed else None
    deleted = []
    for tag in committed:
        if tag not in keep_set:
            shutil.rmtree(os.path.join(save_dir, tag), ignore_errors=True)
            deleted.append(tag)
    for tag in torn:
        # only sweep torn dirs strictly older than the newest durable tag's
        # commit time: a *newer* uncommitted dir could be another process's
        # in-flight save (defense in depth — within this process the saver
        # lock serializes writers, so our own in-flight dir can't be here)
        if (tag not in protect and newest_key is not None
                and tag_order_key(save_dir, tag) < newest_key):
            shutil.rmtree(os.path.join(save_dir, tag), ignore_errors=True)
            deleted.append(tag)
    if deleted:
        logger.info(f"checkpoint retention: deleted superseded tags {sorted(deleted)}")
    return deleted


class ResilientSaver:
    """Bounded background checkpoint writer (depth 1: a new submit joins the
    in-flight save first, so at most one write is ever outstanding and HBM
    holds at most one extra host snapshot)."""

    def __init__(self, checkpoint_engine, retention=0, keep_every_n_steps=0, is_lead=True):
        self.checkpoint_engine = checkpoint_engine
        self.retention = int(retention)
        self.keep_every_n_steps = int(keep_every_n_steps)
        self.is_lead = is_lead
        self._thread = None
        self._lock = threading.Lock()
        self.last_error = None
        self.saves_committed = 0
        self.saves_failed = 0

    # ------------------------------------------------------------------
    def save(self, state, save_dir, tag, blocking=True, save_latest=True):
        """Write ``state`` under ``save_dir/tag``. Blocking mode returns the
        commit result; async mode returns True immediately after handing the
        (already host-resident) tree to the writer thread. The lock
        serializes concurrent submitters (depth-1 bound: join the in-flight
        writer first, exactly one thread ever owns a write)."""
        with self._lock:
            self._join_locked()
            self.last_error = None  # status tracks the save being started
            if blocking:
                return self._write_and_commit(state, save_dir, tag, save_latest)
            self._thread = threading.Thread(target=self._background_write,
                                            args=(state, save_dir, tag, save_latest),
                                            name=f"ckpt-writer-{tag}", daemon=True)
            self._thread.start()
            return True

    def flush(self, raise_on_error=False):
        """Join the in-flight save (no-op when idle); True iff the most
        recently submitted save committed cleanly. With ``raise_on_error``
        that save's stored exception is re-raised."""
        with self._lock:
            self._join_locked()
            if raise_on_error and self.last_error is not None:
                raise self.last_error
            return self.last_error is None

    def _join_locked(self):
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None

    @property
    def in_flight(self):
        t = self._thread
        return t is not None and t.is_alive()

    # ------------------------------------------------------------------
    def _background_write(self, state, save_dir, tag, save_latest):
        tracer = get_tracer()
        t0 = time.perf_counter()
        try:
            ok = self._write_and_commit(state, save_dir, tag, save_latest)
            if tracer.enabled:
                tracer.complete("checkpoint/async_write", t0, time.perf_counter() - t0,
                                tid="checkpoint", args={"tag": str(tag), "committed": bool(ok)})
        except BaseException as e:  # noqa: BLE001 — a dead writer must never kill training
            self.last_error = e  # failure counters already bumped in _write_and_commit
            if tracer.enabled:
                tracer.complete("checkpoint/async_write", t0, time.perf_counter() - t0,
                                tid="checkpoint", args={"tag": str(tag), "error": repr(e)})
            logger.error(f"async checkpoint writer died for tag {tag}: {e!r}; "
                         f"'latest' still references the previous durable tag")

    def _write_and_commit(self, state, save_dir, tag, save_latest):
        """The one commit path (see module docstring for the protocol)."""
        path = os.path.join(save_dir, str(tag))
        ctx = {"path": path, "tag": str(tag)}
        metrics = get_metrics()
        t0 = time.perf_counter()
        try:
            fault_injection.fire("before_arrays", ctx)
            self.checkpoint_engine.create(tag)
            self.checkpoint_engine.save(state, path)
            fault_injection.fire("after_arrays", ctx)
            ok = self.checkpoint_engine.commit(tag)
            if not ok:
                self.saves_failed += 1
                self.last_error = RuntimeError(
                    f"checkpoint engine refused commit for tag {tag}")
                metrics.counter("checkpoint/saves_failed").inc()
                logger.error(f"checkpoint engine refused commit for tag {tag}; "
                             f"'latest' left untouched")
                return False
            if self.is_lead:
                fault_injection.fire("before_manifest", ctx)
                man = build_manifest(path, tag, state=state)
                write_manifest(path, man)
                fault_injection.fire("after_manifest", ctx)
                metrics.counter("checkpoint/bytes_written").inc(man["total_bytes"])
                if save_latest:
                    fault_injection.fire("before_latest", ctx)
                    write_latest(save_dir, tag)
                apply_retention(save_dir, self.retention, self.keep_every_n_steps,
                                protect=(str(tag), ))
        except Exception:
            self.saves_failed += 1
            metrics.counter("checkpoint/saves_failed").inc()
            raise
        self.saves_committed += 1
        metrics.counter("checkpoint/saves_committed").inc()
        metrics.histogram("checkpoint/write_ms").observe((time.perf_counter() - t0) * 1e3)
        return True
