"""Crash-consistent checkpoint writer: the ONLY module allowed to move the
``latest`` pointer or delete checkpoint tags (``tools/check_ckpt_commit.py``
enforces this statically, the way ``check_timed_ops.py`` pins collectives to
``@timed_op``).

Commit protocol per save (:mod:`fault_injection` points mark the stage
boundaries; on the async path the commit stages run in the writer thread,
and the payload stage does too unless the caller keeps it — the
``payload_in_caller`` multi-host shape, where device arrays must be
persisted before the step loop donates them)::

    payload (engine.save -> arrays/ + meta.pkl)     [crash here: no manifest]
    engine.commit()  -> must return True            [False: save aborted]
    manifest.json    (tmp + fsync + rename)         <- durability point
    latest           (tmp + fsync + rename)         [crash here: next save heals]
    retention GC     (superseded tags only)

A crash at ANY point leaves ``latest`` referencing the previous durable
tag — the step loop never has to trust a torn directory. This is the Nebula
contract (``deepspeed/nebula``: training never blocks on persistence, only
fully-persisted versions are advertised) rebuilt on orbax + manifests.
"""

import os
import re
import shutil
import threading
import time

from . import fault_injection
from .errors import CheckpointCorruptError
from .manifest import (build_manifest, is_committed, read_manifest, tree_spec,
                       write_manifest, MANIFEST_FILE)
from ...monitor.flight import get_flight_recorder
from ...monitor.health import get_health
from ...monitor.metrics import get_metrics
from ...monitor.trace import get_tracer
from ...utils.logging import logger

LATEST_FILE = "latest"  # reference `latest` tag file semantics
# exactly the auto-save naming scheme (engine.save_checkpoint's default tag)
# — a user-named tag that merely ends in digits (`best2`, `release_v3`,
# `exp_2024`) must NOT compete in the retention window
_STEP_RE = re.compile(r"^global_step(\d+)$")


def read_latest(save_dir):
    """Tag named by the ``latest`` pointer, or None."""
    path = os.path.join(save_dir, LATEST_FILE)
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        tag = f.read().strip()
    return tag or None


def list_tags(save_dir):
    """Checkpoint tag directories under ``save_dir``, unordered."""
    if not os.path.isdir(save_dir):
        return []
    return [d for d in os.listdir(save_dir)
            if os.path.isdir(os.path.join(save_dir, d))]


def tag_step(tag):
    """Step number of an auto-save-style tag (``global_step12`` -> 12), or
    None for anything else. Only tags the auto-save scheme produced compete
    in the newest-N retention window and the ``keep_every_n_steps`` archival
    rule; every other tag — including one that happens to end in digits —
    is a user-named checkpoint and protected from cadence GC."""
    m = _STEP_RE.match(str(tag))
    return int(m.group(1)) if m else None


# (path -> (manifest mtime, key)): retention sorts, the newest-valid scan,
# and load fallback all call tag_order_key repeatedly per tag, and for a big
# model the manifest (full digest table + tree spec) is hundreds of KB — one
# parse per committed manifest, not one per comparison
_ORDER_KEY_CACHE = {}


def tag_order_key(save_dir, tag):
    """Recency key for a tag: manifest commit time for committed dirs, dir
    mtime for torn/in-flight ones (same unix-seconds unit, so the two order
    consistently — a trailing step number would put a committed ``best``
    tag in a different key space and permanently out-sort every
    ``global_stepN``)."""
    path = os.path.join(save_dir, str(tag))
    try:
        man_mtime = os.path.getmtime(os.path.join(path, MANIFEST_FILE))
    except OSError:
        # manifest absent = torn/in-flight tag: the dir-mtime ordering below
        # is the designed fallback; counted so the swallow stays observable
        get_metrics().counter("health/ckpt_order_fallback_total").inc()
        man_mtime = None
    if man_mtime is not None:
        hit = _ORDER_KEY_CACHE.get(path)
        if hit is not None and hit[0] == man_mtime:
            return hit[1]
    try:
        key = float(read_manifest(path).get("created_unix", -1.0))
    except CheckpointCorruptError:
        get_metrics().counter("health/ckpt_order_fallback_total").inc()
        try:
            return os.path.getmtime(path)
        except OSError:
            # the tag vanished under us (concurrent GC): oldest-possible key
            get_metrics().counter("health/ckpt_order_fallback_total").inc()
            return -1.0
    if man_mtime is not None:
        if len(_ORDER_KEY_CACHE) > 1024:  # GC'd tags leave entries behind
            _ORDER_KEY_CACHE.clear()
        _ORDER_KEY_CACHE[path] = (man_mtime, key)
    return key


def find_latest_valid(save_dir, deep=False):
    """Newest tag whose directory verifies against its manifest, preferring
    the ``latest`` pointer; returns (tag, path) or (None, None).

    This is the load-side half of crash consistency: a torn directory (or a
    corrupted manifest) is skipped, not surfaced, and the scan falls back
    through older tags newest-first.
    """
    candidates = []
    pointed = read_latest(save_dir)
    if pointed is not None:
        candidates.append(pointed)
    for tag in sorted(list_tags(save_dir), key=lambda t: tag_order_key(save_dir, t), reverse=True):
        if tag not in candidates:
            candidates.append(tag)
    for tag in candidates:
        path = os.path.join(save_dir, tag)
        if os.path.isdir(path) and is_committed(path, deep=deep):
            return tag, path
    return None, None


def write_latest(save_dir, tag):
    """Atomically flip the ``latest`` pointer (tmp + fsync + rename)."""
    os.makedirs(save_dir, exist_ok=True)
    final = os.path.join(save_dir, LATEST_FILE)
    tmp = final + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(tag))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, final)


def apply_retention(save_dir, keep, keep_every_n_steps=0, protect=()):
    """Delete superseded tags, honoring ``nebula.num_of_version_in_retention``.

    Keeps: the newest ``keep`` committed step-style tags, every committed
    tag whose step is a multiple of ``keep_every_n_steps`` (the archival
    knob), every committed NON-step tag (a user-named ``best``/``release``
    checkpoint is an explicit decision — cadence GC has no business deleting
    it), and anything in ``protect`` (the just-committed tag + the
    ``latest`` target). Uncommitted directories older than the newest
    committed tag are crash garbage and are removed too. ``keep <= 0``
    disables GC entirely. Returns the list of deleted tags.
    """
    if keep <= 0:
        return []
    protect = {str(t) for t in protect if t is not None}
    pointed = read_latest(save_dir)
    if pointed:
        protect.add(pointed)
    committed, torn = [], []
    for tag in list_tags(save_dir):
        (committed if is_committed(os.path.join(save_dir, tag)) else torn).append(tag)
    committed.sort(key=lambda t: tag_order_key(save_dir, t), reverse=True)
    # only auto-save-style tags compete for the newest-N window; named tags
    # are kept unconditionally (and don't shrink the window for real versions)
    step_tags = [t for t in committed if tag_step(t) is not None]
    keep_set = set(step_tags[:keep]) | protect
    keep_set.update(t for t in committed if tag_step(t) is None)
    if keep_every_n_steps > 0:
        for tag in step_tags:
            if tag_step(tag) % keep_every_n_steps == 0:
                keep_set.add(tag)
    newest_key = tag_order_key(save_dir, committed[0]) if committed else None
    deleted = []
    for tag in committed:
        if tag not in keep_set:
            shutil.rmtree(os.path.join(save_dir, tag), ignore_errors=True)
            deleted.append(tag)
    for tag in torn:
        # only sweep torn dirs strictly older than the newest durable tag's
        # commit time: a *newer* uncommitted dir could be another process's
        # in-flight save (defense in depth — within this process the saver
        # lock serializes writers, so our own in-flight dir can't be here)
        if (tag not in protect and newest_key is not None
                and tag_order_key(save_dir, tag) < newest_key):
            shutil.rmtree(os.path.join(save_dir, tag), ignore_errors=True)
            deleted.append(tag)
    if deleted:
        logger.info(f"checkpoint retention: deleted superseded tags {sorted(deleted)}")
    return deleted


class ResilientSaver:
    """Bounded background checkpoint writer (depth 1: a new submit joins the
    in-flight save first, so at most one write is ever outstanding and HBM
    holds at most one extra host snapshot)."""

    def __init__(self, checkpoint_engine, retention=0, keep_every_n_steps=0, is_lead=True,
                 digests=True):
        self.checkpoint_engine = checkpoint_engine
        self.retention = int(retention)
        self.keep_every_n_steps = int(keep_every_n_steps)
        self.is_lead = is_lead
        self.digests = bool(digests)
        self._thread = None
        self._lock = threading.Lock()
        self.last_error = None
        self.saves_committed = 0
        self.saves_failed = 0

    # ------------------------------------------------------------------
    def save(self, state, save_dir, tag, blocking=True, save_latest=True,
             payload_in_caller=False, commit_gate=None):
        """Write ``state`` under ``save_dir/tag``. Blocking mode returns the
        commit result; async mode returns True immediately after handing the
        (already host-resident) tree to the writer thread.

        ``payload_in_caller`` is the multi-host async shape: the payload
        write (engine create/save — the device-to-host snapshot plus any
        save-side cross-process sync) runs synchronously in the caller's
        thread at the step boundary, and the background thread is restricted
        to host-side I/O (commit join, manifest, ``latest``, retention GC).
        Handing live device arrays to the writer thread would race the step
        loop's buffer donation, and the engine's save-side collectives must
        not interleave with training collectives from another thread. A
        payload failure is reported synchronously (returns False, no thread
        spawned).

        ``commit_gate`` is the cross-rank success vote: called in the
        caller's (main) thread — it runs a collective, which may not
        interleave with training collectives from another thread — and only
        a unanimous True proceeds. Success is process-local, so without the
        vote the lead would manifest/advertise a tag that failed on a peer —
        and the manifest would verify, because it inventories whatever IS on
        disk. Every rank votes even when its own stage failed (the peers are
        already blocked in the same collective), including ranks that are
        about to unwind with an exception. Placement differs by mode:
        blocking saves vote twice — on the engine commit result (durability)
        just before the manifest stage, then again after the lead's
        manifest/``latest`` flip (advertisement), so no rank returns from a
        final save while the lead is still writing; the
        ``payload_in_caller`` async shape votes once, on payload
        *submission* right after the payload stage — the engine's own async
        commit (e.g. orbax's cross-process finalize) is what fails the
        background commit closed if a rank's write later diverges.

        The lock serializes concurrent submitters (depth-1 bound: join the
        in-flight writer first, exactly one thread ever owns a write)."""
        with self._lock:
            self._join_locked()
            self.last_error = None  # status tracks the save being started
            if blocking:
                health = get_health()
                health.begin("saver")
                try:
                    return self._write_and_commit(state, save_dir, tag, save_latest,
                                                  commit_gate=commit_gate)
                finally:
                    health.end("saver")
            if payload_in_caller:
                t0 = time.perf_counter()
                local_ok, spec = True, None
                try:
                    spec = self._write_payload(state, save_dir, tag)
                except Exception as e:
                    local_ok = False
                    self._record_failure(e, f"checkpoint payload write failed for tag "
                                            f"{tag}: {e!r}; 'latest' left untouched")
                if commit_gate is not None and not commit_gate(local_ok):
                    if local_ok:
                        self._record_failure(
                            RuntimeError(f"checkpoint payload for tag {tag} failed on a "
                                         f"peer rank"),
                            f"checkpoint payload for tag {tag} failed on a peer rank; "
                            f"commit withheld, 'latest' left untouched")
                    self._abandon_payload(tag)
                    return False
                if not local_ok:
                    self._abandon_payload(tag)
                    return False
                target = self._background_commit
                args = (save_dir, tag, save_latest, spec, t0)
            else:
                target = self._background_write
                args = (state, save_dir, tag, save_latest)
            self._thread = threading.Thread(target=target, args=args,
                                            name=f"ckpt-writer-{tag}", daemon=True)
            self._thread.start()
            return True

    def flush(self, raise_on_error=False):
        """Join the in-flight save (no-op when idle); True iff the most
        recently submitted save committed cleanly. With ``raise_on_error``
        that save's stored exception is re-raised."""
        with self._lock:
            self._join_locked()
            if raise_on_error and self.last_error is not None:
                raise self.last_error
            return self.last_error is None

    def _join_locked(self):
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None

    def shutdown(self, timeout=60.0):
        """Teardown-path join with a BOUND: ``engine.destroy()`` must not
        hang forever behind a writer wedged in storage I/O (the unbounded
        ``flush()`` join is for durability-critical paths — load, the
        preemption final save — where waiting is the point). On timeout the
        writer is abandoned loudly: a warning names the tag thread,
        ``health/saver_join_timeout_total`` counts it, and the daemon thread
        is left to die with the process. Returns True iff the writer is
        fully joined (or there was none)."""
        with self._lock:
            t = self._thread
            if t is None:
                return True
            t.join(timeout=timeout)
            if t.is_alive():
                get_metrics().counter("health/saver_join_timeout_total").inc()
                get_flight_recorder().record("saver", "join_timeout",
                                             thread=t.name, timeout_s=timeout)
                logger.warning(
                    f"checkpoint writer {t.name!r} did not finish within {timeout}s at "
                    f"shutdown; abandoning the join (the daemon thread dies with the "
                    f"process, 'latest' still references the last durable tag)")
                return False
            self._thread = None
            return True

    def health_state(self):
        """The /healthz ``saver`` section: writer liveness + commit tallies."""
        t = self._thread
        return {"in_flight": bool(t is not None and t.is_alive()),
                "writer_thread": t.name if t is not None else None,
                "saves_committed": self.saves_committed,
                "saves_failed": self.saves_failed,
                "last_error": repr(self.last_error) if self.last_error else None}

    @property
    def in_flight(self):
        t = self._thread
        return t is not None and t.is_alive()

    def _record_failure(self, err=None, msg=None):
        """Failed-save accounting, in one place. Exception paths pass the
        exception but no ``msg`` — the raise itself reaches the blocking
        caller's log, and on background paths ``_run_writer`` logs it; but
        ``last_error`` must be set regardless, so a caller that caught (or
        never saw) the raise still gets the truth from ``flush()``."""
        self.saves_failed += 1
        get_metrics().counter("checkpoint/saves_failed").inc()
        # mirrored into the health/ namespace: save failures sit next to
        # stalls/stragglers on the one dashboard an operator actually watches
        get_metrics().counter("health/ckpt_save_failed_total").inc()
        if err is not None:
            self.last_error = err
        if msg:
            logger.error(msg)

    def _abandon_payload(self, tag):
        """Join (and discard) an already-submitted engine write whose commit
        stage was withheld — gate veto or local payload failure. An async
        engine otherwise still owns an in-flight write, and the next save's
        submit would collide with it; the tag is never advertised either
        way."""
        try:
            self.checkpoint_engine.commit(tag)
        except Exception:
            # the abandoned write's error must not mask the recorded one —
            # but it must not vanish either
            get_metrics().counter("health/ckpt_abandoned_commit_total").inc()

    # ------------------------------------------------------------------
    def _background_write(self, state, save_dir, tag, save_latest):
        self._run_writer(tag, lambda: self._write_and_commit(state, save_dir, tag, save_latest))

    def _background_commit(self, save_dir, tag, save_latest, spec, t0):
        self._run_writer(tag, lambda: self._commit(save_dir, tag, save_latest, spec, t0))

    def _run_writer(self, tag, fn):
        tracer = get_tracer()
        health = get_health()
        flight = get_flight_recorder()
        t0 = time.perf_counter()
        # operation-style heartbeat: the `saver` source is watched exactly
        # while a write is in flight — a writer wedged in storage I/O stops
        # beating and trips the stall watchdog past its deadline
        health.begin("saver")
        flight.record("saver", "write_begin", tag=str(tag))
        try:
            ok = fn()
            flight.record("saver", "write_end", tag=str(tag), committed=bool(ok))
            if tracer.enabled:
                tracer.complete("checkpoint/async_write", t0, time.perf_counter() - t0,
                                tid="checkpoint", args={"tag": str(tag), "committed": bool(ok)})
        except BaseException as e:  # noqa: BLE001 — a dead writer must never kill training
            self.last_error = e  # checkpoint/ failure counters bumped in the commit path
            get_metrics().counter("health/ckpt_writer_death_total").inc()
            flight.record("saver", "write_error", tag=str(tag), error=repr(e))
            if tracer.enabled:
                tracer.complete("checkpoint/async_write", t0, time.perf_counter() - t0,
                                tid="checkpoint", args={"tag": str(tag), "error": repr(e)})
            logger.error(f"async checkpoint writer died for tag {tag}: {e!r}; "
                         f"'latest' still references the previous durable tag")
        finally:
            health.end("saver")

    def _write_payload(self, state, save_dir, tag):
        """Payload stage: engine create + save. Returns the manifest tree
        spec, computed here so the commit stage never touches ``state`` — on
        the payload-in-caller path the leaves are live device arrays that
        training donates as soon as the caller returns."""
        path = os.path.join(save_dir, str(tag))
        ctx = {"path": path, "tag": str(tag)}
        fault_injection.fire("before_arrays", ctx)
        self.checkpoint_engine.create(tag)
        self.checkpoint_engine.save(state, path)
        fault_injection.fire("after_arrays", ctx)
        return tree_spec(state)

    def _write_and_commit(self, state, save_dir, tag, save_latest, commit_gate=None):
        """The one commit path (see module docstring for the protocol)."""
        t0 = time.perf_counter()
        try:
            spec = self._write_payload(state, save_dir, tag)
        except Exception as e:
            # record even though the raise carries the cause: a blocking
            # caller that catches it may still consult flush()/last_error
            self._record_failure(e)
            if commit_gate is not None:
                # the peers are already blocked in the vote collective — a
                # raising rank must still cast its (False) vote before the
                # exception unwinds, or every other rank hangs
                commit_gate(False)
            raise
        return self._commit(save_dir, tag, save_latest, spec, t0,
                            commit_gate=commit_gate)

    def _commit(self, save_dir, tag, save_latest, spec, t0, commit_gate=None):
        """Commit stage: engine commit -> durability vote (blocking mode) ->
        manifest -> ``latest`` -> retention GC -> advertisement vote
        (blocking mode). Without a gate this is host-side I/O only (plus the
        engine's async-write join) — safe off the main thread even when the
        payload was written elsewhere; a gate is only ever passed on the
        blocking path, where this runs in the caller's thread."""
        path = os.path.join(save_dir, str(tag))
        ctx = {"path": path, "tag": str(tag)}
        metrics = get_metrics()
        try:
            try:
                local_ok = bool(self.checkpoint_engine.commit(tag))
            except Exception:
                if commit_gate is not None:
                    # vote False before unwinding — peers are in the collective
                    commit_gate(False)
                raise
            ok = commit_gate(local_ok) if commit_gate is not None else local_ok
            if not ok:
                if local_ok:
                    self._record_failure(
                        RuntimeError(f"checkpoint for tag {tag} failed on a peer rank"),
                        f"checkpoint for tag {tag} failed on a peer rank; commit "
                        f"withheld, 'latest' left untouched")
                else:
                    self._record_failure(
                        RuntimeError(f"checkpoint engine refused commit for tag {tag}"),
                        f"checkpoint engine refused commit for tag {tag}; 'latest' "
                        f"left untouched")
                return False
            if self.is_lead:
                try:
                    fault_injection.fire("before_manifest", ctx)
                    man = build_manifest(path, tag, tree=spec, digests=self.digests)
                    write_manifest(path, man)
                    fault_injection.fire("after_manifest", ctx)
                    metrics.counter("checkpoint/bytes_written").inc(man["total_bytes"])
                    if save_latest:
                        fault_injection.fire("before_latest", ctx)
                        write_latest(save_dir, tag)
                    apply_retention(save_dir, self.retention, self.keep_every_n_steps,
                                    protect=(str(tag), ))
                except Exception:
                    if commit_gate is not None:
                        # cast the advertisement vote (False) before
                        # unwinding — the peers are waiting in it
                        commit_gate(False)
                    raise
            if commit_gate is not None and not commit_gate(True):
                # advertisement vote: holds every rank until the lead's
                # manifest/`latest` flip is durable — a rank returning from a
                # final (preemption) save early can get the lead gang-killed
                # mid-manifest after this rank already advertised the tag as
                # its resume point. A False here is only reachable on
                # non-lead ranks, when the lead's flip failed.
                self._record_failure(
                    RuntimeError(f"checkpoint manifest/'latest' flip for tag {tag} "
                                 f"failed on the lead rank"),
                    f"checkpoint manifest/'latest' flip for tag {tag} failed on the "
                    f"lead rank; tag not advertised")
                return False
        except Exception as e:
            self._record_failure(e)
            raise
        self.saves_committed += 1
        metrics.counter("checkpoint/saves_committed").inc()
        metrics.histogram("checkpoint/write_ms").observe((time.perf_counter() - t0) * 1e3)
        return True
