"""Preemption / maintenance-event handling.

TPU fleets deliver eviction as SIGTERM with a grace window (and Borg/GKE
maintenance notices ride the same signal). The handler only flips a flag —
signal context does no I/O — and the engine's step-boundary poll turns the
flag into one final *blocking* checkpoint followed by a clean exit
(:class:`~.errors.TrainingPreempted`, exit code 0), so the scheduler sees a
graceful shutdown and ``run_resilient``/the next incarnation resumes from
that final tag.
"""

import signal
import threading

from ...utils.logging import logger


class PreemptionHandler:
    """Flag-setting signal trap, chainable and restorable.

    ``install()`` must run on the main thread (CPython restriction);
    tests may skip signals entirely and call :meth:`request` directly.
    """

    def __init__(self, signals=(signal.SIGTERM, )):
        self.signals = tuple(signals)
        self._event = threading.Event()
        self._prev = {}
        self._installed = False

    def install(self):
        if self._installed:
            return self
        for sig in self.signals:
            self._prev[sig] = signal.signal(sig, self._on_signal)
        self._installed = True
        return self

    def uninstall(self):
        if not self._installed:
            return
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):  # non-main thread / exotic prev
                pass
        self._prev = {}
        self._installed = False

    def _on_signal(self, signum, frame):
        self.request(reason=f"signal {signum}")
        prev = self._prev.get(signum)
        if callable(prev):  # chain: whoever trapped SIGTERM before us still runs
            prev(signum, frame)

    def request(self, reason="api"):
        """Arm the preemption flag (signal handler or direct test call)."""
        if not self._event.is_set():
            logger.warning(f"preemption requested ({reason}): final checkpoint at next "
                           f"step boundary, then clean exit")
        self._event.set()

    @property
    def requested(self):
        return self._event.is_set()

    def clear(self):
        self._event.clear()

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
