"""Preemption / maintenance-event handling.

TPU fleets deliver eviction as SIGTERM with a grace window (and Borg/GKE
maintenance notices ride the same signal). The handler only flips a flag —
signal context does no I/O — and the engine's step-boundary poll turns the
flag into one final *blocking* checkpoint followed by a clean exit
(:class:`~.errors.TrainingPreempted`, exit code 0), so the scheduler sees a
graceful shutdown and ``run_resilient``/the next incarnation resumes from
that final tag.
"""

import signal
import threading

from ...utils.logging import logger


def _metrics():
    from ...monitor.metrics import get_metrics  # lazy: signal path stays import-light

    return get_metrics()


class PreemptionHandler:
    """Flag-setting signal trap, chainable and restorable.

    ``install()`` must run on the main thread (CPython restriction);
    tests may skip signals entirely and call :meth:`request` directly.
    """

    def __init__(self, signals=(signal.SIGTERM, )):
        self.signals = tuple(signals)
        self._event = threading.Event()
        self._prev = {}
        self._installed = False
        self._forwarding = False

    def install(self):
        if self._installed:
            return self
        for sig in self.signals:
            prev = signal.signal(sig, self._on_signal)
            if sig in self._prev:
                # re-install after a non-LIFO uninstall: a successor may
                # still chain to our trap. Overwriting _prev with it would
                # both cycle the chain (a._prev -> b, b._prev -> a) and drop
                # our ORIGINAL predecessor — a third-party trap whose
                # cleanup would silently never run again. Walk the successor
                # chain and hand whoever points at us our old predecessor,
                # straightening a -> successors -> original.
                node, seen = getattr(prev, "__self__", None), set()
                while isinstance(node, PreemptionHandler) and id(node) not in seen:
                    seen.add(id(node))
                    nxt = node._prev.get(sig)
                    if nxt == self._on_signal:
                        node._prev[sig] = self._prev[sig]
                        break
                    node = getattr(nxt, "__self__", None)
            self._prev[sig] = prev
        self._installed = True
        return self

    def uninstall(self):
        if not self._installed:
            return
        for sig, prev in self._prev.items():
            try:
                # only restore when the disposition is still OUR trap: if a
                # later handler chained on top of us, restoring `prev` would
                # silently detach it (non-LIFO teardown) — leave theirs in
                # place; its chain through us dead-ends harmlessly
                if signal.getsignal(sig) == self._on_signal:
                    signal.signal(sig, prev)
                else:
                    logger.warning(f"preemption trap for signal {sig} was overridden after "
                                   f"install; leaving the current handler in place")
            except (ValueError, TypeError):
                # non-main thread / exotic prev: the trap stays installed —
                # counted, because a trap that outlives its engine is exactly
                # the kind of leak a fleet debugger needs a number for
                _metrics().counter("health/preemption_uninstall_skipped_total").inc()
        # keep self._prev: if a later handler's chain still points here (it
        # restored us as ITS prev), _on_signal forwards through it
        self._installed = False

    def _on_signal(self, signum, frame):
        if self._forwarding:
            # chain cycle: re-installing after a non-LIFO uninstall can make
            # two handlers each other's predecessor (a._prev -> b, b._prev
            # -> a) — the outer frame of this delivery already ran us, so
            # forwarding again would recurse until RecursionError fires
            # inside the signal handler
            return
        self._forwarding = True
        try:
            if not self._installed:
                # uninstalled, but a successor's restored chain still reaches
                # us: act as a transparent link — forward to whoever preceded
                # us, or re-deliver with the default disposition so SIGTERM
                # still kills
                prev = self._prev.get(signum)
                if callable(prev):
                    prev(signum, frame)
                elif prev is signal.SIG_IGN:
                    pass  # the disposition we replaced ignored this signal
                else:
                    signal.signal(signum, signal.SIG_DFL)
                    signal.raise_signal(signum)
                return
            self.request(reason=f"signal {signum}")
            prev = self._prev.get(signum)
            if callable(prev):  # chain: whoever trapped SIGTERM before us still runs
                prev(signum, frame)
        finally:
            self._forwarding = False

    def request(self, reason="api"):
        """Arm the preemption flag (signal handler or direct test call)."""
        if not self._event.is_set():
            logger.warning(f"preemption requested ({reason}): final checkpoint at next "
                           f"step boundary, then clean exit")
        self._event.set()

    @property
    def requested(self):
        return self._event.is_set()

    def clear(self):
        self._event.clear()

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
