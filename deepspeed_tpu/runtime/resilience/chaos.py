"""Chaos plane: a seeded, deterministic fault scheduler over injection
points registered across the whole stack.

:mod:`fault_injection` started life as five checkpoint-stage points; this
module is its generalization — ONE hook registry any subsystem can expose a
``fire()`` point into, plus :class:`ChaosSchedule`, the deterministic storm
generator the chaos drills (``tools/chaos_drill.py``) compose with
``run_resilient`` + the stall watchdog. Production code only ever calls
:func:`fire` — a no-op dictionary probe while nothing is hooked
(``tools/check_chaos_points.py`` statically pins production modules to that
shape: no conditional imports, no test-only branches).

Registered production points (the names ``fire`` is called with):

=====================  ======================================================
``before_arrays`` ...  the five saver stage boundaries (via
                       :mod:`fault_injection`, unchanged names)
``engine/step``        the training step boundary (``ctx``: engine, step)
``comm/collective``    eager device-collective bracket (``ctx``: op)
``comm/host_collective``  blocking host-plane gather/broadcast (``ctx``: op)
``serving/driver``     each serving replica driver loop (``ctx``: replica)
``serving/handoff``    the disaggregated KV handoff, between export and
                       checksum verify (``ctx``: rid, src, dst, payloads —
                       a hook may raise OR swap a corrupted payload into
                       the list; the verify gate must catch either)
``prefetch/item``      the prefetch worker, once per assembled batch
=====================  ======================================================

:class:`ChaosSchedule` draws one pseudo-random number per (spec, fire index)
from ``crc32(seed|kind|source|index)`` — PYTHONHASHSEED-proof and
independent of wall clock, so two runs with the same seed produce the same
event log (the training drill's determinism bar). Event kinds:

* ``kill`` — raise :class:`ChaosKill` (a ``RuntimeError``: exactly what the
  elastic agent's retryable set catches) at the fired point;
* ``stall`` — sleep ``duration_s`` (> the watchdog deadline: the drill
  asserts one forensic dump per stall);
* ``straggle`` — sleep ``duration_s`` (< the deadline: latency skew only);
* ``collective_delay`` — sleep at a comm bracket;
* ``preempt`` — request preemption on the engine in ``ctx`` (the SIGTERM
  path without the signal), ending the attempt in a final blocking save +
  clean ``TrainingPreempted`` exit.
"""

import threading
import time
import zlib

from ...monitor.metrics import get_metrics
from ...utils.logging import logger


class InjectedFault(RuntimeError):
    """Base of every chaos-injected failure."""


class ChaosKill(InjectedFault):
    """Simulated worker death at an injection point (retryable by the
    elastic agent: it subclasses RuntimeError on purpose)."""


KINDS = ("kill", "stall", "straggle", "preempt", "collective_delay")

_lock = threading.Lock()
# point -> {token: hook}; insertion-ordered, so hooks run in install order
_hooks = {}
_next_token = 0
# token -> fn(point, ctx); passive listeners notified when a point with
# installed hooks is ABOUT to fire (before the hooks run, so even a kill
# fire is observed). Observers never see hook-less fires: fire()'s
# ``if not _hooks`` short-circuit stays the first line, preserving the
# zero-overhead contract for production paths with chaos disarmed.
_observers = {}


class Handle:
    """Removal handle for one installed hook; also a context manager, so
    a test can scope an injection to exactly one block::

        with chaos.inject("engine/step", hook):
            ...
    """

    __slots__ = ("point", "_token")

    def __init__(self, point, token):
        self.point = point
        self._token = token

    def remove(self):
        """Uninstall the hook (idempotent)."""
        with _lock:
            bucket = _hooks.get(self.point)
            if bucket is not None:
                bucket.pop(self._token, None)
                if not bucket:
                    _hooks.pop(self.point, None)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.remove()
        return False


def inject(point, hook):
    """Register ``hook(ctx)`` to run whenever ``point`` fires. Returns a
    :class:`Handle` (``.remove()`` / context manager)."""
    global _next_token
    with _lock:
        token = _next_token
        _next_token += 1
        _hooks.setdefault(str(point), {})[token] = hook
    return Handle(str(point), token)


class ObserverHandle:
    """Removal handle for one fire observer (idempotent; context manager)."""

    __slots__ = ("_token",)

    def __init__(self, token):
        self._token = token

    def remove(self):
        with _lock:
            _observers.pop(self._token, None)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.remove()
        return False


def observe(fn):
    """Register ``fn(point, ctx)`` to be called whenever a chaos point with
    installed hooks fires — the timeline plane's join source for 'which
    fault landed inside this request'. Observers are passive (exceptions
    swallowed, never mutate ctx) and run BEFORE the hooks, so a hook that
    raises or kills still leaves its fire on record."""
    global _next_token
    with _lock:
        token = _next_token
        _next_token += 1
        _observers[token] = fn
    return ObserverHandle(token)


def clear(points=None):
    """Remove every hook (``points=None``) or just the named points."""
    with _lock:
        if points is None:
            _hooks.clear()
        else:
            for p in points:
                _hooks.pop(p, None)


def armed(point=None):
    """True when any hook (or a hook on ``point``) is installed."""
    if point is None:
        return bool(_hooks)
    return point in _hooks


def fire(point, ctx=None):
    """Run the hooks registered on ``point`` (no-op with none installed:
    one falsy check on the module dict, no locking, no allocations). Hooks
    run in the CALLING thread — a raising hook is indistinguishable from
    the instrumented code failing there, a sleeping hook from it wedging."""
    if not _hooks:
        return
    with _lock:
        bucket = _hooks.get(point)
        hooks = list(bucket.values()) if bucket else ()
        observers = list(_observers.values()) if (hooks and _observers) else ()
    for obs in observers:
        try:
            obs(point, ctx)
        except Exception:  # noqa: BLE001 — observers are passive: a broken
            # listener must never alter the drill's failure semantics
            get_metrics().counter("health/chaos_observer_error_total").inc()
    for hook in hooks:
        hook(ctx)


class ChaosSpec:
    """One fault stream: ``kind`` events at ``source`` with probability
    ``rate`` per fire. ``duration_s`` parameterizes the sleep kinds;
    ``start_after`` skips the first N fires (grace period — e.g. don't
    kill before the first checkpoint exists); ``max_events`` bounds the
    stream (0 = unbounded)."""

    __slots__ = ("kind", "source", "rate", "duration_s", "start_after", "max_events")

    def __init__(self, kind, source, rate, duration_s=0.0, start_after=0, max_events=0):
        if kind not in KINDS:
            raise ValueError(f"unknown chaos kind {kind!r}; valid: {KINDS}")
        if not 0.0 <= float(rate) <= 1.0:
            raise ValueError(f"rate must be in [0, 1], got {rate}")
        self.kind = kind
        self.source = str(source)
        self.rate = float(rate)
        self.duration_s = float(duration_s)
        self.start_after = int(start_after)
        self.max_events = int(max_events)

    def __repr__(self):
        return (f"ChaosSpec({self.kind!r}, {self.source!r}, rate={self.rate}, "
                f"duration_s={self.duration_s}, start_after={self.start_after}, "
                f"max_events={self.max_events})")


def _draw(seed, kind, source, index):
    """Deterministic u in [0, 1) for one (spec, fire-index) decision."""
    key = f"{seed}|{kind}|{source}|{index}".encode()
    return zlib.crc32(key) / 2**32


class ChaosSchedule:
    """Seeded storm of :class:`ChaosSpec` streams over the injection
    points. ``install()`` registers one hook per distinct source;
    decisions are pure functions of ``(seed, kind, source, fire index)``,
    so a deterministic run produces a deterministic event log
    (:meth:`event_log` — what the drill compares across two runs)."""

    def __init__(self, seed, specs):
        self.seed = int(seed)
        self.specs = list(specs)
        self.events = []  # [{kind, source, index, step?, duration_s}]
        self._counters = {}  # source -> fires seen
        self._spec_counts = {}  # id(spec) -> events emitted
        self._handles = []
        self._mutex = threading.Lock()  # serving points fire from N threads

    # ------------------------------------------------------------------
    def install(self):
        if self._handles:
            return self
        by_source = {}
        for spec in self.specs:
            by_source.setdefault(spec.source, []).append(spec)
        for source, specs in by_source.items():
            self._handles.append(
                inject(source, self._make_hook(source, specs)))
        return self

    def uninstall(self):
        for h in self._handles:
            h.remove()
        self._handles = []
        return self

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    # ------------------------------------------------------------------
    def _make_hook(self, source, specs):
        def hook(ctx):
            with self._mutex:
                n = self._counters.get(source, 0)
                self._counters[source] = n + 1
                due = []
                for spec in specs:
                    if n < spec.start_after:
                        continue
                    count = self._spec_counts.get(id(spec), 0)
                    if spec.max_events and count >= spec.max_events:
                        continue
                    if _draw(self.seed, spec.kind, spec.source, n) < spec.rate:
                        self._spec_counts[id(spec)] = count + 1
                        event = {"kind": spec.kind, "source": source, "index": n,
                                 "duration_s": spec.duration_s}
                        step = (ctx or {}).get("step") if isinstance(ctx, dict) else None
                        if step is not None:
                            event["step"] = int(step)
                        self.events.append(event)
                        due.append(spec)
            # actions OUTSIDE the mutex: a sleeping stall must not serialize
            # unrelated points, and a raising kill must not poison the lock.
            # Sleep kinds run FIRST, then preempt, then kill: a stall and a
            # kill drawn on the same fire both take effect (sleep-then-die)
            # instead of the kill eating a recorded stall — and preempt
            # orders before kill because an UNARMED preempt degrades to a
            # raise itself, which must not preempt the sleeps either
            order = {"kill": 2, "preempt": 1}
            for spec in sorted(due, key=lambda s: order.get(s.kind, 0)):
                self._act(spec, source, ctx)
        return hook

    def _act(self, spec, source, ctx):
        get_metrics().counter(f"health/chaos_{spec.kind}_total").inc()
        if spec.kind == "kill":
            logger.warning(f"chaos: injected kill at {source}")
            raise ChaosKill(f"chaos kill at {source}")
        if spec.kind in ("stall", "straggle", "collective_delay"):
            time.sleep(spec.duration_s)
            return
        if spec.kind == "preempt":
            engine = (ctx or {}).get("engine") if isinstance(ctx, dict) else None
            handler = getattr(engine, "_preemption", None)
            if handler is not None:
                logger.warning(f"chaos: injected preemption at {source}")
                handler.request()
            else:
                # no handler to flip: a preempt against an unarmed engine
                # degrades to a kill so the storm still exercises a restart
                logger.warning(f"chaos: preempt at {source} with no preemption "
                               f"handler; degrading to kill")
                raise ChaosKill(f"chaos preempt (unarmed) at {source}")

    # ------------------------------------------------------------------
    def event_log(self):
        """Stable tuple view of the events for determinism comparison —
        ``(source, index, kind, step)``, sorted. Sorted because different
        SOURCES fire from different threads (the saver stages fire in the
        writer thread): per-source order is deterministic, cross-source
        interleaving is scheduling."""
        with self._mutex:
            return sorted((e["source"], e["index"], e["kind"], e.get("step"))
                          for e in self.events)

    def counts(self):
        """Events emitted per kind (``{kind: n}``)."""
        with self._mutex:
            out = {}
            for e in self.events:
                out[e["kind"]] = out.get(e["kind"], 0) + 1
            return out
