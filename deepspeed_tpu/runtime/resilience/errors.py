"""Resilience-plane error types.

Kept dependency-free so both the checkpoint engines (which raise) and the
engine/runner fallback paths (which catch) can import them without cycles.
"""


class CheckpointCorruptError(RuntimeError):
    """A checkpoint directory failed validation: missing/partial ``arrays``
    tree, torn or absent manifest, or a per-file digest mismatch. Callers on
    the auto-resume path catch this and fall back to the newest valid tag;
    everything else should treat it as data loss, not a soft miss."""


class TrainingPreempted(SystemExit):
    """Raised out of the step loop after a preemption-requested final
    checkpoint has committed. Subclasses ``SystemExit(0)`` so an unhandled
    escape is a *clean* process exit (the maintenance event contract), while
    still being catchable by ``run_resilient``/user loops that want to
    shut down gracefully themselves."""

    def __init__(self, tag=None):
        super().__init__(0)
        self.tag = tag
