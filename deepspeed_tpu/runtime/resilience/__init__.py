"""Resilience subsystem: truly-async crash-consistent checkpointing,
retention/GC, preemption handling, and auto-resume.

The robustness layer the reference gets from the Nebula service
(``deepspeed/nebula``) plus torch-elastic restarts, rebuilt TPU-native:

* :mod:`saver` — bounded background writer + manifest-gated ``latest``
  pointer (the ONLY code allowed to flip it or delete tags);
* :mod:`manifest` — per-checkpoint commit marker with byte counts and
  sha256 digests (torn writes are detectable, never loadable);
* :mod:`preemption` / :mod:`triggers` — SIGTERM → final save → clean exit,
  plus step/wall-clock auto-save cadence;
* :mod:`runner` — ``run_resilient`` wraps :class:`ElasticAgent` with
  resume-from-newest-valid-tag;
* :mod:`fault_injection` — the saver-stage face of the chaos registry
  (crash-mid-write, torn-manifest, killed-writer scenarios);
* :mod:`chaos` — the generalized injection-point registry + the seeded
  :class:`~.chaos.ChaosSchedule` storm generator the drills compose.
"""

from . import chaos  # noqa: F401
from .chaos import ChaosKill, ChaosSchedule, ChaosSpec, InjectedFault  # noqa: F401
from .errors import CheckpointCorruptError, TrainingPreempted  # noqa: F401
from .manifest import (build_manifest, is_committed, read_manifest, verify_manifest,  # noqa: F401
                       write_manifest, MANIFEST_FILE)
from .preemption import PreemptionHandler  # noqa: F401
from .runner import run_resilient  # noqa: F401
from .saver import (apply_retention, find_latest_valid, list_tags, read_latest,  # noqa: F401
                    ResilientSaver, write_latest, LATEST_FILE)
from .triggers import AutoSaveTrigger  # noqa: F401
