"""Fault-injection harness for the checkpoint commit path.

The saver calls :func:`fire` at each stage boundary of a save; tests
register hooks to simulate the real failure modes a TPU fleet produces:

* ``after_arrays``  — writer dies after the tensorstore payload, before the
  manifest (crash mid-write: directory exists, never committed);
* ``before_manifest`` / ``after_manifest`` — torn commit windows;
* ``before_latest`` — durable checkpoint whose pointer flip never happened
  (the benign window: next save supersedes it).

Hooks run *in the writer thread*, so raising :class:`InjectedCrash` is
exactly a killed writer as far as the foreground step loop can tell. A hook
may also block (e.g. on a ``threading.Event``) to hold a save in flight
while a test asserts non-blocking behavior.
"""

import threading

POINTS = ("before_arrays", "after_arrays", "before_manifest", "after_manifest", "before_latest")

_lock = threading.Lock()
_hooks = {}


class InjectedCrash(RuntimeError):
    """Simulated writer death."""


def inject(point, hook):
    """Register ``hook(ctx)`` to run when the saver reaches ``point``."""
    if point not in POINTS:
        raise ValueError(f"unknown injection point {point!r}; valid: {POINTS}")
    with _lock:
        _hooks.setdefault(point, []).append(hook)


def crash_at(point):
    """Convenience: kill the writer at ``point``."""
    inject(point, lambda ctx: (_ for _ in ()).throw(InjectedCrash(f"injected crash at {point}")))


def clear():
    with _lock:
        _hooks.clear()


def fire(point, ctx=None):
    with _lock:
        hooks = list(_hooks.get(point, ()))
    for hook in hooks:
        hook(ctx)
