"""Fault-injection harness for the checkpoint commit path.

The saver calls :func:`fire` at each stage boundary of a save; tests
register hooks to simulate the real failure modes a TPU fleet produces:

* ``after_arrays``  — writer dies after the tensorstore payload, before the
  manifest (crash mid-write: directory exists, never committed);
* ``before_manifest`` / ``after_manifest`` — torn commit windows;
* ``before_latest`` — durable checkpoint whose pointer flip never happened
  (the benign window: next save supersedes it).

Hooks run *in the writer thread*, so raising :class:`InjectedCrash` is
exactly a killed writer as far as the foreground step loop can tell. A hook
may also block (e.g. on a ``threading.Event``) to hold a save in flight
while a test asserts non-blocking behavior.

The registry itself lives in :mod:`chaos` (this module is the
saver-stage-validated face of it): :func:`inject` / :func:`crash_at` return
a removal :class:`~.chaos.Handle` usable as a context manager, so a test's
hook is scoped to its block instead of leaking through a module global
until someone remembers :func:`clear`::

    with fault_injection.crash_at("before_manifest"):
        engine.save_checkpoint(d, tag="doomed")
        engine.flush_checkpoints()
"""

from . import chaos

POINTS = ("before_arrays", "after_arrays", "before_manifest", "after_manifest", "before_latest")


class InjectedCrash(chaos.InjectedFault):
    """Simulated writer death."""


def inject(point, hook):
    """Register ``hook(ctx)`` to run when the saver reaches ``point``.
    Returns a removal handle (also a context manager)."""
    if point not in POINTS:
        raise ValueError(f"unknown injection point {point!r}; valid: {POINTS}")
    return chaos.inject(point, hook)


def crash_at(point):
    """Convenience: kill the writer at ``point``. Returns the handle."""
    return inject(point, lambda ctx: (_ for _ in ()).throw(InjectedCrash(f"injected crash at {point}")))


def clear():
    """Remove every hook on the saver stage points (the chaos registry's
    other points — engine/comm/serving/prefetch — are left alone)."""
    chaos.clear(points=POINTS)


def fire(point, ctx=None):
    chaos.fire(point, ctx)
