"""Training-time quantizer (reference ``runtime/quantize.py`` —
``Quantizer``: MoQ's progressively-tightening fake quantization applied to
the model weights every ``quantize_period`` steps, with symmetric/asymmetric
types and a mixing ratio that anneals from fp16 toward the target bits).

TPU form: a pure function over the param tree (the engine owns when to call
it), delegating the numeric core to ``compression.basic_layer`` —
symmetric/asymmetric fake-quant with straight-through semantics. The
``quantize_real_ratio`` anneal (reference ``update_fp16_ratio``) mixes the
quantized and original weights so early steps see mostly-fp values.
"""

from typing import Any, Dict

import jax
import jax.numpy as jnp

from ..compression.basic_layer import asym_quantize, sym_quantize
from ..utils.logging import logger

TWO_D_PARAMS = 6  # reference constant: params-per-layer heuristic for layer_num


class Quantizer:

    def __init__(self, q_groups: int = 1, q_mixed_fp16: bool = False, q_change_ratio: float = 0.01,
                 q_type: int = 0, q_rounding: int = 0, q_verbose: bool = False,
                 q_eigenvalue: bool = False, use_quantizer_kernel: bool = False, layer_num: int = 0):
        self.q_groups = q_groups
        self.q_mixed_fp16 = q_mixed_fp16
        self.q_change_ratio = q_change_ratio
        self.q_type = q_type  # 0 = symmetric, 1 = asymmetric
        self.q_rounding = q_rounding  # 0 nearest (stochastic not supported — disclosed)
        self.q_verbose = q_verbose
        self.q_eigenvalue = q_eigenvalue
        self.use_quantizer_kernel = use_quantizer_kernel
        self.layer_num = layer_num
        self.qsteps = 0
        self.quantize_real_ratio = 1.0

    def any_precision_switch(self):
        """Reference surface: whether the target bits change this step
        (single-target-bit schedule here — always False)."""
        return False

    def update_fp16_ratio(self):
        """Anneal the fp mixing ratio toward full quantization
        (reference ``update_fp16_ratio``)."""
        if self.q_mixed_fp16:
            self.quantize_real_ratio = max(0.0, self.quantize_real_ratio - self.q_change_ratio)

    def quantize(self, params: Dict[str, Any], overflow: bool = False, eigenvalue_enabled: bool = False,
                 target_bits: int = 8) -> Dict[str, Any]:
        """One MoQ step over the param tree: fake-quantize every >=2-D float
        weight, mixing with the original by ``quantize_real_ratio``."""
        if overflow and not eigenvalue_enabled:
            return params  # reference skips quantization on overflow steps
        self.qsteps += 1
        ratio = self.quantize_real_ratio
        qfn = sym_quantize if self.q_type == 0 else asym_quantize

        def leaf(x):
            if not hasattr(x, "ndim") or x.ndim < 2 or not jnp.issubdtype(
                    jnp.asarray(x).dtype, jnp.floating):
                return x
            q = qfn(jnp.asarray(x), bits=target_bits, groups=self.q_groups)
            return (ratio * jnp.asarray(x) + (1.0 - ratio) * q).astype(x.dtype)

        out = jax.tree_util.tree_map(leaf, params)
        self.update_fp16_ratio()
        if self.q_verbose:
            logger.info(f"MoQ step {self.qsteps}: target_bits={target_bits} "
                        f"fp_ratio={self.quantize_real_ratio:.3f}")
        return out
