"""SparseTensor (reference ``runtime/sparse_tensor.py`` — the COO wrapper
DeepSpeed uses for sparse embedding gradients so allreduce ships
indices+values instead of the dense matrix).

JAX form: immutable (index, value, dense_shape) triple with to_dense /
from_dense and an add that concatenates coordinates (duplicate rows sum on
densify — the same semantics torch sparse accumulation gives the
reference). ``jax.experimental.sparse.BCOO`` interop is provided for code
moving onto jax's native sparse support.
"""

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


class SparseTensor:
    """Row-sparse matrix: ``indices`` [nnz] row ids, ``values`` [nnz, cols]."""

    def __init__(self, indices, values, dense_size: Tuple[int, int]):
        self.indices = jnp.asarray(indices, jnp.int32)
        self.values = jnp.asarray(values)
        self.dense_size = tuple(int(s) for s in dense_size)
        assert self.values.ndim == 2 and self.values.shape[1] == self.dense_size[1]
        assert self.indices.shape[0] == self.values.shape[0]

    @classmethod
    def from_dense(cls, dense, threshold: float = 0.0):
        """Rows whose max|.| exceeds ``threshold`` become the sparse payload
        (embedding-gradient pattern: most rows are exactly zero)."""
        dense = np.asarray(dense)
        mask = np.abs(dense).max(axis=1) > threshold
        idx = np.nonzero(mask)[0]
        return cls(idx, dense[idx], dense.shape)

    def to_dense(self):
        """Duplicate row ids accumulate (torch sparse semantics)."""
        out = jnp.zeros(self.dense_size, self.values.dtype)
        return out.at[self.indices].add(self.values)

    def add(self, other: "SparseTensor") -> "SparseTensor":
        assert self.dense_size == other.dense_size, "sparse add needs matching dense shapes"
        return SparseTensor(jnp.concatenate([self.indices, other.indices]),
                            jnp.concatenate([self.values, other.values]), self.dense_size)

    def to_coo_tensor(self):
        """jax-native BCOO (reference returns a torch sparse_coo_tensor)."""
        from jax.experimental import sparse as jsparse

        rows = jnp.repeat(self.indices, self.dense_size[1])
        cols = jnp.tile(jnp.arange(self.dense_size[1], dtype=jnp.int32), self.indices.shape[0])
        coords = jnp.stack([rows, cols], axis=1)
        return jsparse.BCOO((self.values.reshape(-1), coords), shape=self.dense_size)

    def sparse_size(self):
        dense = int(np.prod(self.dense_size))
        sparse = int(self.indices.size + self.values.size)
        return sparse, dense

    @property
    def dtype(self):
        return self.values.dtype

    def __str__(self):
        s, d = self.sparse_size()
        return f"SparseTensor(nnz_rows={self.indices.shape[0]}, dense={self.dense_size}, " \
               f"payload={s}/{d})"

    __repr__ = __str__
