"""Process-local span/event bus — Chrome-trace/Perfetto JSONL emission.

The observability spine the reference spreads over ``@timed_op`` wrappers,
the flops profiler and the torch profiler hooks, unified here into one bus:

  * ``get_tracer().span("fwd")`` — a context manager emitting a Chrome-trace
    duration event (``ph:"X"``) with ``pid`` = this host process and ``tid`` =
    a logical stream (engine / comm / compile / checkpoint / serving / data).
  * ``complete``/``instant``/``counter`` — manual emission for call sites
    that cannot use a ``with`` block (async dispatch, listener callbacks).
  * JAX compile/recompile events are captured through
    ``jax.monitoring.register_event_duration_secs_listener`` and emitted as
    ``jax_compile`` duration events on the ``compile`` stream.

Output is JSONL: one Chrome-trace event object per line, each independently
``json.loads``-able (the acceptance format for ``bench.py --trace``). The
``trace_viewer`` JSON-array form for chrome://tracing or Perfetto is one
``to_chrome_trace`` call away.

Zero overhead when disabled: ``span()`` returns a shared no-op singleton
(``NULL_SPAN``), every other emitter early-returns on one attribute check, and
the compile listener is only installed on first enable.

This module must stay import-light (no package-internal imports): it is
pulled in by ``comm.comm`` during package bootstrap.
"""

import json
import os
import threading
import time

# canonical logical streams -> stable Chrome-trace tid numbers
STREAMS = ("engine", "comm", "compile", "checkpoint", "serving", "data")


class _NullSpan:
    """Shared no-op context manager returned while tracing is disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set_args(self, **kwargs):
        return self


NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "_name", "_tid", "_args", "_t0")

    def __init__(self, tracer, name, tid, args):
        self._tracer = tracer
        self._name = name
        self._tid = tid
        self._args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def set_args(self, **kwargs):
        self._args.update(kwargs)
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        self._tracer.complete(self._name, self._t0, t1 - self._t0, tid=self._tid, args=self._args)
        return False


class Tracer:
    """Buffered JSONL trace writer. One per process (see ``get_tracer``)."""

    def __init__(self):
        self.enabled = False
        self._path = None
        self._fh = None
        self._buf = []
        self._flush_every = 256
        self._lock = threading.RLock()
        self._origin = time.perf_counter()  # ts epoch: trace times are relative
        self._pid = None
        self._tids = {}
        self._opened_paths = set()  # paths truncated once this process
        # event mirror (the health plane's flight recorder): when set, every
        # emitted event is also handed to mirror.record_event — INCLUDING in
        # "tracing disabled" mode, where the spans exist only for the mirror
        self._mirror = None
        self._atexit_installed = False

    # -- configuration --------------------------------------------------
    def configure(self, enabled=None, path=None, flush_every=None, config=None):
        """Enable/point the tracer. ``config`` may be a ``TraceConfig`` block
        (``monitor_config.trace``); explicit kwargs win over it."""
        if config is not None:
            if enabled is None:
                enabled = getattr(config, "enabled", None)
            if path is None:
                path = getattr(config, "output_path", None) or None
            if flush_every is None:
                flush_every = getattr(config, "flush_every", None)
        with self._lock:
            if path is not None and path != self._path:
                self._close_fh()
                self._path = path
            if flush_every is not None:
                self._flush_every = max(1, int(flush_every))
            if enabled is not None:
                enabled = bool(enabled)
                if enabled and not self.enabled:
                    self._pid = _process_id()
                    _install_compile_listener()
                    self._install_atexit()
                    self.enabled = True
                    self._emit({"name": "process_name", "ph": "M", "ts": 0, "pid": self._pid,
                                "tid": 0, "args": {"name": "deepspeed_tpu"}})
                    # re-announce streams first seen in mirror-only mode:
                    # their thread_name metadata went to the flight ring,
                    # never to the buffer/file — without this, a trace
                    # enabled AFTER the health plane armed the mirror has
                    # tids no viewer can name
                    for stream, tid in sorted(self._tids.items()):
                        self._emit({"name": "thread_name", "ph": "M", "ts": 0,
                                    "pid": self._pid, "tid": tid,
                                    "args": {"name": stream}})
                elif not enabled and self.enabled:
                    self.flush()
                    self.enabled = False
        return self

    def set_mirror(self, mirror):
        """Install/remove the event mirror (``record_event(ev)`` duck type —
        the health plane's flight recorder). With a mirror installed the
        emitters run even while ``enabled`` is False, feeding the mirror
        only: nothing is buffered or written to the trace path."""
        with self._lock:
            if mirror is not None and self._pid is None:
                self._pid = _process_id()
            self._mirror = mirror
        return self

    def _install_atexit(self):
        """Flush/close at interpreter exit: without this, an abrupt
        ``sys.exit`` (preemption runners do exactly that) truncates the tail
        ``flush_every`` window of the JSONL artifact mid-run. Registered
        once per tracer, on first enable; ``close()`` is idempotent so an
        orderly ``drain()``/``close()`` beforehand costs nothing."""
        if self._atexit_installed:
            return
        import atexit

        atexit.register(self.close)
        self._atexit_installed = True

    # -- emission -------------------------------------------------------
    def span(self, name, tid="engine", **args):
        """Context manager for a duration event. Allocation-free no-op
        (the shared ``NULL_SPAN`` object) while disabled and unmirrored."""
        if not self.enabled and self._mirror is None:
            return NULL_SPAN
        return _Span(self, name, tid, args)

    def complete(self, name, t0, duration, tid="engine", args=None):
        """Emit a ``ph:"X"`` duration event. ``t0`` is a ``time.perf_counter``
        reading; ``duration`` is in seconds."""
        if not self.enabled and self._mirror is None:
            return
        ev = {"name": name, "ph": "X", "ts": round((t0 - self._origin) * 1e6, 3),
              "dur": round(duration * 1e6, 3), "pid": self._pid, "tid": self._tid(tid)}
        if args:
            ev["args"] = args
        self._emit(ev)

    def instant(self, name, tid="engine", **args):
        if not self.enabled and self._mirror is None:
            return
        ev = {"name": name, "ph": "i", "s": "t", "ts": self._now_us(), "dur": 0,
              "pid": self._pid, "tid": self._tid(tid)}
        if args:
            ev["args"] = args
        self._emit(ev)

    def counter(self, name, value, tid="engine"):
        if not self.enabled and self._mirror is None:
            return
        self._emit({"name": name, "ph": "C", "ts": self._now_us(), "dur": 0, "pid": self._pid,
                    "tid": self._tid(tid), "args": {"value": float(value)}})

    # -- plumbing -------------------------------------------------------
    def _now_us(self):
        return round((time.perf_counter() - self._origin) * 1e6, 3)

    def _tid(self, stream):
        # under the (reentrant) lock: the jax compile listener can fire from
        # a background thread concurrently with engine-thread spans
        with self._lock:
            tid = self._tids.get(stream)
            if tid is None:
                tid = STREAMS.index(stream) + 1 if stream in STREAMS else len(STREAMS) + 1 + len(self._tids)
                self._tids[stream] = tid
                self._emit({"name": "thread_name", "ph": "M", "ts": 0, "pid": self._pid, "tid": tid,
                            "args": {"name": stream}})
            return tid

    def _emit(self, ev):
        m = self._mirror
        if m is not None:
            m.record_event(ev)
        if not self.enabled:
            return  # mirror-only mode: nothing buffered, nothing written
        with self._lock:
            self._buf.append(ev)
            if self._path is None:
                # buffer-only mode: trim lazily at 2x the cap so the per-event
                # cost stays amortized O(1) instead of an O(cap) slice each time
                if len(self._buf) > 2 * self.MAX_BUFFERED:
                    del self._buf[:len(self._buf) - self.MAX_BUFFERED]
            elif len(self._buf) >= self._flush_every:
                self._flush_locked()

    def flush(self):
        with self._lock:
            self._flush_locked()

    # pathless-tracer memory bound: keep at most this many buffered events
    # (drain()/a later path picks them up; beyond it, oldest are dropped)
    MAX_BUFFERED = 65536

    def _flush_locked(self):
        if not self._buf:
            return
        events, self._buf = self._buf, []
        if self._path is None:
            if len(events) > self.MAX_BUFFERED:
                events = events[len(events) - self.MAX_BUFFERED:]
            self._buf = events  # nowhere to write yet; keep for a later path
            return
        if self._fh is None:
            d = os.path.dirname(os.path.abspath(self._path))
            if d:
                os.makedirs(d, exist_ok=True)
            # truncate on this process's FIRST open of a path: a stale trace
            # from a previous run would interleave near ts=0 (ts is relative
            # to each process's clock origin) and corrupt the artifact;
            # within-process reopen (flush/close cycles) appends
            mode = "a" if self._path in self._opened_paths else "w"
            self._opened_paths.add(self._path)
            self._fh = open(self._path, mode)
        for ev in events:
            self._fh.write(json.dumps(ev) + "\n")
        self._fh.flush()

    def _close_fh(self):
        if self._fh is not None:
            try:
                self._fh.close()
            finally:
                self._fh = None

    def close(self):
        with self._lock:
            self._flush_locked()
            self._close_fh()

    def drain(self):
        """Return (and clear) the buffered, not-yet-written events — the
        in-memory read path for tests and programmatic consumers."""
        with self._lock:
            events, self._buf = self._buf, []
        return events


def _process_id():
    """pid for trace events: the jax process index when distributed is up
    (stable across hosts of one job), else the OS pid."""
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return os.getpid()


# ---------------------------------------------------------------------------
# module singleton + compile-event capture
# ---------------------------------------------------------------------------
_tracer = Tracer()


def get_tracer() -> Tracer:
    return _tracer


def configure_tracer(config=None, **kwargs) -> Tracer:
    return _tracer.configure(config=config, **kwargs)


_COMPILE_LISTENER = {"installed": False}

# compile-source routing: XLA compiles happen synchronously on the thread
# that triggered them, so a THREAD-LOCAL source label attributes each
# compile event to the engine that compiled — a serving replica thread's
# bucket compile must not count under train/ (the pre-PR-14 drift). The
# default (no scope pushed) stays "train", the historical behavior.
_COMPILE_SOURCES = ("train", "serving")
_compile_tls = threading.local()

# subscribers: fn(source, event_name, duration_s) per compile event — the
# goodput plane books training compile seconds through this. Zero overhead
# while empty (one truthiness check per event).
_compile_subscribers = []


def push_compile_source(source):
    """Set this thread's compile-source label; returns the previous value
    for :func:`pop_compile_source` (nestable)."""
    if source not in _COMPILE_SOURCES:
        source = "train"
    prev = getattr(_compile_tls, "source", None)
    _compile_tls.source = source
    return prev


def pop_compile_source(prev):
    _compile_tls.source = prev


def current_compile_source():
    return getattr(_compile_tls, "source", None) or "train"


def add_compile_listener(fn):
    """Subscribe ``fn(source, event_name, duration_s)`` to compile events."""
    if fn not in _compile_subscribers:
        _compile_subscribers.append(fn)
    _install_compile_listener()


def remove_compile_listener(fn):
    try:
        _compile_subscribers.remove(fn)
    except ValueError:
        pass


def _install_compile_listener():
    """Capture XLA compile/lower durations as ``jax_compile`` trace events and
    ``<source>/compile_*`` metrics. Installed once, fires only while tracing/
    metrics are enabled or a subscriber is registered (one attribute check
    per event otherwise)."""
    if _COMPILE_LISTENER["installed"]:
        return
    try:
        import jax.monitoring as jmon

        def _on_event_duration(event, duration, **kwargs):
            if "compile" not in event and "lower" not in event:
                return
            source = current_compile_source()
            tr = _tracer
            if tr.enabled:
                now = time.perf_counter()
                tr.complete("jax_compile", now - duration, duration, tid="compile",
                            args={"source": event, "engine": source})
            from .metrics import get_metrics

            reg = get_metrics()
            if reg.enabled:
                # <source>/ namespace per tools/check_metric_names.py (the
                # old compile/* names predated the approved prefix set; the
                # old always-train/ attribution predated serving engines
                # compiling from replica threads). Names assembled outside
                # the registration call: this module is gate-allowlisted
                # for dynamic names it validates itself (_COMPILE_SOURCES).
                ev_name = source + "/compile_events"
                sec_name = source + "/compile_seconds"
                reg.counter(ev_name).inc()
                reg.counter(sec_name).inc(duration)
            if _compile_subscribers:
                for fn in list(_compile_subscribers):
                    try:
                        fn(source, event, duration)
                    except Exception:  # noqa: BLE001 — telemetry never raises
                        pass

        jmon.register_event_duration_secs_listener(_on_event_duration)
        _COMPILE_LISTENER["installed"] = True
    except Exception:  # tracing must never break program startup
        pass


def observe_latency(t0, span_name, hist_name=None, tid="serving", span_args=None, gauges=None):
    """Shared tail for instrumented latency call sites: optional histogram
    observation (milliseconds), optional gauge sets, and one trace span.
    ``gauges`` maps name -> value or callable(dt_seconds). Callers guard with
    their own enabled check; returns dt in seconds."""
    dt = time.perf_counter() - t0
    from .metrics import get_metrics

    reg = get_metrics()
    if reg.enabled:
        if hist_name:
            reg.histogram(hist_name).observe(dt * 1e3)
        for gname, gval in (gauges or {}).items():
            reg.gauge(gname).set(gval(dt) if callable(gval) else gval)
    if _tracer.enabled or _tracer._mirror is not None:
        _tracer.complete(span_name, t0, dt, tid=tid, args=span_args or {})
    return dt


def to_chrome_trace(jsonl_path, out_path):
    """Wrap a JSONL trace into the strict ``{"traceEvents": [...]}`` JSON the
    chrome://tracing legacy loader expects (Perfetto loads either form)."""
    events = []
    with open(jsonl_path) as f:
        for line in f:
            line = line.strip()
            if line:
                events.append(json.loads(line))
    with open(out_path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return out_path
