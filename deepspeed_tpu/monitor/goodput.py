"""Goodput ledger + recompile sentinel: attribute every wall-clock second.

The observability planes so far answer *what happened* (PR 1 spans), *is it
alive* (PR 5 heartbeats/stalls), *where did one request's time go* (PR 7
stage stamps) and *what does memory do* (PR 11). This module answers the
question a production operator actually asks: **of the last hour, how many
seconds were useful compute vs. input wait, exposed comm, checkpoint
blocking, compile, stall, or restart recovery?** — and its serving twin:
prefill vs decode vs verify vs idle vs stalled vs draining vs recovering,
per replica.

Two components:

  * :class:`GoodputLedger` — a wall-clock attribution ledger. One training
    ledger per process (engines attach across restarts, so the ledger spans
    the whole resilient run) and one serving ledger per replica. Categories
    are booked from the EXISTING measurement points (the PR 2 input-wait
    window, the PR 4 ``ckpt_blocked`` observation, the comm host-plane
    bracket, the compile listener, chaos/stall gaps, the resilience
    runner's failure boundary) — and the PR 7 discipline applies globally:
    :meth:`GoodputLedger.report` must sum to measured wall-clock, with any
    unclassified residual disclosed as its own ``unattributed`` bucket
    (and any double-booking disclosed as ``overbooked_s``), never silently
    absorbed.

  * :class:`RecompileSentinel` — after a declared warmup boundary
    (training: the first ``train_warmup_steps`` steps; serving:
    ``InferenceEngineV2.warmup`` completion), every further compile of a
    new (token-bucket, seq-bucket, k, sampling) program is flagged:
    counted per source and shape bucket, joined to the in-flight request
    uids (and request ids when the replica registered a resolver), and
    compile-storm bursts (K unexpected compiles inside a window) raise a
    trace instant + their own counter. The single worst silent perf killer
    in a JAX serving plane — a steady-state recompile when a request lands
    in a never-warmed bucket — becomes a named, attributed event instead
    of an unlabelled blip.

Measurement semantics (stated plainly; the conservation test enforces the
arithmetic, the README documents the physics):

  * ``compute`` (training) is the per-step residual: step wall minus the
    explicitly booked input-wait / compile / ckpt-blocked / comm-exposed /
    stall seconds inside that step window, clamped at zero.
  * ``comm_exposed`` counts BLOCKING host-plane collective time (the
    step-boundary resilience vote, object broadcasts). In-jit collective
    time is invisible to the host and rides ``compute`` — XLA overlaps it.
  * ``stall``/``stalled`` books hook-caused wedges ≥ ``stall_gap_s``,
    measured around the chaos fire points (the step boundary / the driver
    loop top — where the storm drills inject). A wedge INSIDE a forward
    books into the active category that wedged (train ``compute``, serving
    ``prefill_active``/...); the PR 5 watchdog dumps both kinds either way,
    so stall=0 here means "no injected/hook wedge", not "never wedged".
  * serving ``prefill_active``/``decode_active``/``spec_verify`` book the
    engine's own forward walltime; scheduler/gateway python overhead is
    disclosed as ``unattributed``, not laundered into an active bucket.

Everything defaults OFF with the PR 5 zero-overhead contract: no plane
object work, no threads, and one ``is not None`` / ``enabled`` check at
each hook when the ``monitor.goodput`` block is absent.

Import-light by design: stdlib + sibling monitor modules only (comm and
the health plane are reached lazily at configure time).
"""

import threading
import time
from collections import Counter as _Counter
from collections import deque

from .flight import get_flight_recorder
from .metrics import get_metrics
from .trace import get_tracer

TRAIN_CATEGORIES = ("compute", "input_wait", "comm_exposed", "ckpt_blocked",
                    "compile", "stall", "recovery", "idle")
# input_wait on the serving side: admission-path waits a request eats
# before its prefill can start — today the synchronous H2D promotion of a
# demoted prefix chain (the tiered KV cache restoring a host/disk-resident
# hit). Same semantic as the training category: time the accelerator sat
# ready while the input pipeline (here: the memory hierarchy) caught up.
SERVING_CATEGORIES = ("prefill_active", "decode_active", "spec_verify",
                      "handoff", "input_wait", "idle", "stalled", "draining",
                      "recovering")

# training categories booked directly by their sources (compile listener,
# comm hook, ckpt save path, chaos-gap detection) INSIDE a step window; the
# per-step compute residual subtracts their delta so one second is never
# booked twice
_TRAIN_EXPLICIT = ("comm_exposed", "ckpt_blocked", "compile", "stall")

# ---------------------------------------------------------------------------
# span-name -> ledger-category contract (enforced by
# tools/check_goodput_taxonomy.py, tier-1): every DURATION span an
# engine/serving/resilience module emits either maps to exactly ONE ledger
# category here, or sits on the explicit allowlist below with its reason.
# A future PR adding a time-consuming span must classify it — the gate
# fails otherwise.
# ---------------------------------------------------------------------------
SPAN_TO_CATEGORY = {
    "input_wait": "input_wait",
    "train_batch": "compute",
    "checkpoint/save": "ckpt_blocked",
    "jax_compile": "compile",
    "serving/prefill": "prefill_active",
    "serving/decode_step": "decode_active",
    "serving/decode": "decode_active",
    "serving/spec_verify": "spec_verify",
    # tiered KV cache: synchronous promotion wait on the admission path
    "serving/promote_wait": "input_wait",
    # disaggregated serving: the prefill replica's driver exporting +
    # brokering one request's KV to a decode replica — real driver seconds
    # that are neither prefill nor decode compute, so they get their own
    # category instead of contaminating pool purity
    "serving/handoff": "handoff",
}

SPAN_ALLOWLIST = (
    # request-scoped OVERLAYS (serving/reqtrace.py): re-attributions of the
    # same wall time the engine spans above book — booking them too would
    # double-count every request's seconds
    "serving/queue_wait",
    "serving/prefill_chunk",
    "serving/gateway_respond",
    "serving/decode_tail",
    # engine phase OVERLAYS (`_emit_phase`): fwd/bwd/step durations live
    # INSIDE the train_batch window the step residual already books — the
    # ledger booking them too would double-count every training second
    "fwd",
    "bwd",
    "step",
    # restore path: runs before the restarted engine's first step entry,
    # i.e. inside the interval the ledger books as recovery (or startup
    # wall before the first boundary, disclosed as unattributed)
    "checkpoint/load",
    # background writer thread: overlapped with compute by design (the
    # step-loop cost it DOES impose is the ckpt_blocked host snapshot)
    "checkpoint/async_write",
    # legacy v1 one-shot generate path — not wired to a ledger
    "serving/generate",
    # zero-duration instants (consume no wall clock)
    "serving/request_rejected",
    "preemption_exit",
    "prefix_hit",
    "cache/evict",
    "serving/admitted",
    "serving/route",
    "serving/first_token",
    "serving/request_done",
    "serving/request_shed",
    "serving/request_failed",
    # tenant metering (serving/metering.py): a starvation detection is a
    # zero-duration instant — it consumes no wall clock
    "serving/tenant_starvation",
    # control plane (serving/control/): a controller decision is a
    # zero-duration instant — it consumes no wall clock
    "control/decision",
    # timeline sub-stage OVERLAYS (serving/disagg.py, serving/reqtrace.py):
    # export -> verify -> resume-adoption decompose the same wall window
    # serving/handoff already books as `handoff` — booking them too would
    # double-count every migrated request's broker seconds
    "serving/handoff_export",
    "serving/broker_verify",
    "serving/resume_wait",
)


class GoodputLedger:
    """Wall-clock attribution for one scope (the training run, or one
    serving replica). ``book`` accumulates seconds into a category;
    :meth:`report` reconciles against measured wall clock."""

    def __init__(self, kind, name):
        assert kind in ("train", "serving")
        self.kind = kind
        self.name = name
        self.categories = TRAIN_CATEGORIES if kind == "train" else SERVING_CATEGORIES
        self._lock = threading.Lock()
        self._books = {c: 0.0 for c in self.categories}
        self._t0 = time.perf_counter()
        self._t_stop = None
        # training step bookkeeping (driven by engine.train_batch)
        self._entry_t = None
        self._last_boundary = None
        self._explicit_mark = 0.0
        self._recovery_begin = None

    # -- core ----------------------------------------------------------
    def book(self, category, seconds):
        """Accumulate ``seconds`` into ``category`` (clamped at 0)."""
        if seconds <= 0.0:
            return
        with self._lock:
            self._books[category] += seconds

    def stop(self):
        """Freeze the wall clock (replica stopped / run over)."""
        if self._t_stop is None:
            self._t_stop = time.perf_counter()
        return self

    def resume(self, category="recovering"):
        """Un-freeze after :meth:`stop`: the frozen interval books into
        ``category`` (a restarted replica was down — that wall clock is
        recovery, not a hole in the ledger)."""
        with self._lock:
            if self._t_stop is not None:
                self._books[category] += max(0.0, time.perf_counter() - self._t_stop)
                self._t_stop = None
        return self

    def wall_s(self):
        return (self._t_stop or time.perf_counter()) - self._t0

    @property
    def stopped_at(self):
        """perf_counter stamp of :meth:`stop`, or None while running."""
        return self._t_stop

    def _explicit_total_locked(self):
        return sum(self._books[c] for c in _TRAIN_EXPLICIT)

    # -- training step hooks (engine.train_batch) ----------------------
    def note_recovery_begin(self, t=None):
        """A training attempt failed (or was preempted): wall clock from
        here to the restarted engine's first step entry is ``recovery``."""
        with self._lock:
            if self._recovery_begin is None:
                self._recovery_begin = t if t is not None else time.perf_counter()

    def step_entry(self):
        """Called at ``train_batch`` entry: books the gap since the last
        step boundary as ``recovery`` (when a restart is in flight) or
        ``idle`` (the caller was doing eval/logging/whatever — from the
        run's perspective, drained time). The explicit sources keep booking
        inside this gap too (a restarted engine re-compiles; a between-steps
        save blocks) — their delta is subtracted, same as the in-step
        compute residual, so one second is never both idle/recovery AND
        compile/ckpt_blocked."""
        now = time.perf_counter()
        with self._lock:
            explicit_now = self._explicit_total_locked()
            delta = max(0.0, explicit_now - self._explicit_mark)
            self._explicit_mark = explicit_now
            rb = self._recovery_begin
            if rb is not None:
                self._books["recovery"] += max(0.0, (now - rb) - delta)
                self._recovery_begin = None
            elif self._last_boundary is not None:
                self._books["idle"] += max(0.0, (now - self._last_boundary) - delta)
            self._entry_t = now

    def step_boundary(self, input_wait_s):
        """Called at the step boundary: books this step's input wait and the
        ``compute`` residual — step wall minus input wait minus whatever
        the explicit sources (compile listener, comm hook, ckpt save,
        stall gaps) booked inside this window."""
        now = time.perf_counter()
        with self._lock:
            entry = self._entry_t if self._entry_t is not None else now
            explicit_now = self._explicit_total_locked()
            delta = max(0.0, explicit_now - self._explicit_mark)
            self._explicit_mark = explicit_now
            iw = max(0.0, float(input_wait_s))
            self._books["input_wait"] += iw
            self._books["compute"] += max(0.0, (now - entry) - iw - delta)
            self._last_boundary = now
            self._entry_t = None

    # -- reconciliation -------------------------------------------------
    def report(self):
        """Categories + the conservation verdict: ``unattributed_s`` is the
        disclosed residual (wall minus booked), ``overbooked_s`` discloses
        any double-booking (both zero-floored — exactly one is nonzero)."""
        with self._lock:
            cats = dict(self._books)
        wall = max(self.wall_s(), 0.0)
        booked = sum(cats.values())
        unattributed = max(0.0, wall - booked)
        out = {
            "kind": self.kind,
            "name": self.name,
            "wall_s": round(wall, 6),
            "categories": {c: round(v, 6) for c, v in cats.items()},
            "unattributed_s": round(unattributed, 6),
            "overbooked_s": round(max(0.0, booked - wall), 6),
        }
        if wall > 0:
            fr = {c: round(v / wall, 6) for c, v in cats.items()}
            fr["unattributed"] = round(unattributed / wall, 6)
            out["fractions"] = fr
        else:
            out["fractions"] = {}
        return out


class RecompileSentinel:
    """Post-warmup compile detector. Engines report every NEW compiled
    program (a compiled-cache miss is exactly the moment XLA compiles) via
    :meth:`note_compile` with their own warmed flag; compiles after the
    warmup boundary are flagged, attributed to their shape bucket and the
    in-flight request uids, and burst-detected into compile storms."""

    def __init__(self, storm_k=5, storm_window_s=10.0):
        self.storm_k = max(2, int(storm_k))
        self.storm_window_s = float(storm_window_s)
        self._lock = threading.Lock()
        self._scopes = {}
        self._uid_resolvers = {}  # replica name -> fn(uid) -> request id|None

    def _scope(self, source):
        sc = self._scopes.get(source)
        if sc is None:
            with self._lock:
                sc = self._scopes.setdefault(source, {
                    "warmed_at": None, "expected": 0, "unexpected": 0,
                    "by_bucket": _Counter(), "events": deque(maxlen=64),
                    "storm_times": deque(), "storms": 0, "storm_latched": False,
                })
        return sc

    def set_uid_resolver(self, name, fn):
        """Replica-registered uid -> request-id join (None removes)."""
        if fn is None:
            self._uid_resolvers.pop(name, None)
        else:
            self._uid_resolvers[name] = fn

    def resolve_rids(self, uids):
        rids = []
        for u in uids or []:
            rid = None
            for fn in list(self._uid_resolvers.values()):
                try:
                    rid = fn(u)
                except Exception:  # noqa: BLE001 — telemetry never raises
                    rid = None
                if rid is not None:
                    break
            rids.append(rid)
        return rids

    def declare_warmed(self, source):
        """Declare the warmup boundary for ``source`` ('train'/'serving'):
        recorded for reporting; the flag engines pass to
        :meth:`note_compile` is what actually arms flagging (each serving
        engine owns its own boundary)."""
        sc = self._scope(source)
        if sc["warmed_at"] is None:
            sc["warmed_at"] = time.perf_counter()

    def note_compile(self, source, bucket, warmed, uids=None, rids=None,
                     seconds=None, step=None):
        """One newly compiled program on ``source`` ('train'/'serving').
        ``warmed`` is the calling engine's own warmup-boundary verdict."""
        sc = self._scope(source)
        with self._lock:
            if not warmed:
                sc["expected"] += 1
                return
            sc["unexpected"] += 1
            sc["by_bucket"][str(bucket)] += 1
            uids = [int(u) for u in (uids or [])][:8]
            if rids is None and uids:
                rids = self.resolve_rids(uids)
            ev = {"bucket": str(bucket), "uids": uids,
                  "rids": [r for r in (rids or []) if r] or None,
                  "step": step, "t": time.perf_counter()}
            sc["events"].append(ev)
            storm = self._note_storm_locked(sc, ev["t"])
        reg = get_metrics()
        if reg.enabled:
            # literal names by branch: the check_metric_names gate reads
            # registration sites statically
            if source == "train":
                reg.counter("train/unexpected_compiles_total").inc()
                if storm:
                    reg.counter("train/compile_storms_total").inc()
            else:
                reg.counter("serving/unexpected_compiles_total").inc()
                if storm:
                    reg.counter("serving/compile_storms_total").inc()
        get_flight_recorder().record("goodput", "unexpected_compile",
                                     source=source, bucket=str(bucket),
                                     uids=uids, rids=ev["rids"])
        tr = get_tracer()
        if tr.enabled:
            tr.instant("unexpected_compile", tid="compile", source=source,
                       bucket=str(bucket), uids=uids, rids=ev["rids"], step=step)
            if storm:
                tr.instant("compile_storm", tid="compile", source=source,
                           k=self.storm_k, window_s=self.storm_window_s)

    def _note_storm_locked(self, sc, now):
        """Burst detection: K unexpected compiles inside the window fires
        ONE storm (latched until the window drains below K)."""
        times = sc["storm_times"]
        times.append(now)
        while times and now - times[0] > self.storm_window_s:
            times.popleft()
        if len(times) >= self.storm_k:
            if not sc["storm_latched"]:
                sc["storm_latched"] = True
                sc["storms"] += 1
                return True
        else:
            sc["storm_latched"] = False
        return False

    def unexpected(self, source):
        sc = self._scopes.get(source)
        return sc["unexpected"] if sc else 0

    def report(self):
        out = {}
        for source, sc in list(self._scopes.items()):
            out[source] = {
                "warmed": sc["warmed_at"] is not None,
                "expected_compiles": sc["expected"],
                "unexpected_compiles": sc["unexpected"],
                "by_bucket": dict(sc["by_bucket"]),
                "storms": sc["storms"],
                "recent": [dict(e, t=round(e["t"], 3)) for e in list(sc["events"])[-8:]],
            }
        return out


class GoodputPlane:
    """Process-global goodput state (see :func:`get_goodput`): the training
    ledger, per-replica serving ledgers, the sentinel, and the export
    wiring (health-plane gauge/state/dump providers, compile listener,
    comm host-plane hook)."""

    def __init__(self):
        self.enabled = False
        self.train_warmup_steps = 2
        self.stall_gap_s = 0.05
        self._lock = threading.Lock()
        self._training = None
        self._serving = {}
        # high-water mark of compile wall already booked: jax emits one
        # duration event PER PHASE (jaxpr trace / lower / backend compile)
        # with nested sub-traces, and threads compile concurrently — summing
        # raw durations overbooks. The ledger books the UNION of compile
        # intervals instead: each event contributes only the part of
        # [now-duration, now] past the mark.
        self._compile_mark = 0.0
        self._gauge_fn = None   # bound-method refs cached at configure time
        self._report_fn = None  # (the health clears are identity-checked)
        self.sentinel = RecompileSentinel()

    # -- configuration --------------------------------------------------
    def configure(self, config=None, **kwargs):
        """Arm the plane. ``config`` is a ``GoodputConfig`` block
        (``monitor_config.goodput``); explicit kwargs win over it."""

        def knob(name, default=None):
            if name in kwargs and kwargs[name] is not None:
                return kwargs[name]
            if config is not None:
                return getattr(config, name, default)
            return default

        enabled = knob("enabled")
        if enabled is not None and not enabled:
            self.shutdown()
            return self
        if not enabled and not self.enabled:
            return self
        self.train_warmup_steps = int(knob("train_warmup_steps",
                                           self.train_warmup_steps))
        self.stall_gap_s = float(knob("stall_gap_s", self.stall_gap_s))
        self.sentinel.storm_k = max(2, int(knob("storm_k", self.sentinel.storm_k)))
        self.sentinel.storm_window_s = float(knob("storm_window_s",
                                                  self.sentinel.storm_window_s))
        if not self.enabled:
            # the ledger's counters/fractions are served through the metrics
            # registry + health providers — the goodput block implies
            # metrics, like `trace` and `health` do
            get_metrics().enable()
            from .trace import add_compile_listener

            add_compile_listener(self._on_compile_event)
            self._set_comm_hook(self._on_host_collective)
        # health providers are (re-)registered on EVERY arm, not just the
        # first: HealthPlane.shutdown() clears all providers, so a later
        # health re-arm (drills do this) would otherwise serve /healthz and
        # forensic dumps with no goodput section while this plane reports
        # enabled (the memory plane re-registers the same way)
        from .health import get_health

        hp = get_health()
        if self._gauge_fn is None:
            # bound-method references are cached ONCE: the health clears
            # are identity-checked (rollover contract), and
            # `self.gauge_rows` makes a fresh object per attribute access
            self._gauge_fn = self.gauge_rows
            self._report_fn = self.report
        hp.set_gauge_provider("goodput", self._gauge_fn)
        hp.set_state_provider("goodput", self._report_fn)
        hp.set_dump_provider("goodput", self._report_fn)
        self.enabled = True
        return self

    def shutdown(self):
        """Disarm + drop every ledger. Idempotent."""
        if self.enabled:
            from .trace import remove_compile_listener

            remove_compile_listener(self._on_compile_event)
            self._set_comm_hook(None)
            from .health import get_health

            hp = get_health()
            hp.clear_gauge_provider("goodput", self._gauge_fn)
            hp.clear_state_provider("goodput", self._report_fn)
            hp.clear_dump_provider("goodput", self._report_fn)
        self.enabled = False
        with self._lock:
            self._training = None
            self._serving.clear()
            self._compile_mark = 0.0
        self.sentinel = RecompileSentinel(self.sentinel.storm_k,
                                          self.sentinel.storm_window_s)
        return self

    def _set_comm_hook(self, fn):
        try:
            from ..comm import comm as _comm  # lazy: comm imports monitor.trace

            _comm.goodput_comm_hook = fn
        except Exception as e:  # noqa: BLE001 — telemetry never kills runs
            self._log().warning(f"goodput: comm hook not armed: {e!r}")

    # -- ledgers ---------------------------------------------------------
    @property
    def training(self):
        """The process training ledger (created on first access while the
        plane is armed — it spans engine restarts)."""
        if not self.enabled:
            return None
        with self._lock:
            if self._training is None:
                self._training = GoodputLedger("train", "train")
            return self._training

    def serving_ledger(self, name):
        """The serving ledger for replica/engine ``name`` (created on first
        access; wall-clock origin = that first access)."""
        if not self.enabled:
            return None
        with self._lock:
            led = self._serving.get(name)
            if led is None or led._t_stop is not None:
                # a STOPPED ledger under this name belongs to a previous
                # replica generation (gateways reuse replica names "0"/"1"):
                # a new instance gets a fresh wall-clock origin — booking
                # into a frozen clock would overdraw it. A replica that
                # merely restarts keeps its own ledger reference and
                # resume()s it instead (it never re-fetches here).
                led = self._serving[name] = GoodputLedger("serving", str(name))
            return led

    def note_training_failure(self):
        """A training attempt just failed/preempted (called by the
        resilience runner): start the recovery clock."""
        with self._lock:
            led = self._training
        if led is not None:
            led.note_recovery_begin()

    # -- event feeds -----------------------------------------------------
    def _on_compile_event(self, source, event, duration):
        """Compile listener subscriber (monitor/trace.py): training-scope
        compile seconds book into the training ledger; serving compiles
        already ride the forward walltime their put/decode booked."""
        if source == "train":
            now = time.perf_counter()
            with self._lock:
                led = self._training
                # interval-union booking (see _compile_mark): nested phase
                # events and concurrent compiling threads must not book the
                # same wall second twice
                start = max(now - duration, self._compile_mark)
                seconds = max(0.0, now - start)
                self._compile_mark = max(self._compile_mark, now)
            if led is not None:
                led.book("compile", seconds)

    def _on_host_collective(self, op, duration):
        """Blocking host-plane collective bracket (comm/comm.py): the
        exposed-comm seconds of the step boundary."""
        with self._lock:
            led = self._training
        if led is not None:
            led.book("comm_exposed", duration)

    # -- export ----------------------------------------------------------
    def report(self):
        with self._lock:
            train = self._training
            serving = dict(self._serving)
        return {
            "train": train.report() if train is not None else None,
            "serving": {name: led.report() for name, led in serving.items()},
            "sentinel": self.sentinel.report(),
        }

    def gauge_rows(self):
        """Labelled Prometheus rows for the health exporter:
        ``goodput/seconds_total{scope=...,category=...}`` + fraction gauges
        + the sentinel's per-bucket unexpected-compile counts."""
        rows = []
        with self._lock:
            ledgers = ([] if self._training is None else [self._training]) \
                + list(self._serving.values())
        for led in ledgers:
            rep = led.report()
            scope = f"{led.kind}:{led.name}" if led.kind == "serving" else "train"
            cats = dict(rep["categories"])
            cats["unattributed"] = rep["unattributed_s"]
            for cat, secs in cats.items():
                rows.append(("goodput/seconds_total",
                             {"scope": scope, "category": cat}, secs))
            for cat, frac in rep.get("fractions", {}).items():
                rows.append(("goodput/fraction",
                             {"scope": scope, "category": cat}, frac))
        for source, sc in self.sentinel.report().items():
            for bucket, n in sc["by_bucket"].items():
                rows.append((f"{source}/unexpected_compiles_total",
                             {"bucket": bucket}, n))
        return rows

    @staticmethod
    def _log():
        from ..utils.logging import logger  # lazy: keep module import-light

        return logger


_plane = GoodputPlane()


def get_goodput() -> GoodputPlane:
    return _plane


def configure_goodput(config=None, **kwargs) -> GoodputPlane:
    return _plane.configure(config=config, **kwargs)


def conservation_ok(report, tolerance=0.05, max_unattributed_frac=None):
    """The PR 7 acceptance arithmetic for one ledger report: booked
    categories + disclosed unattributed must equal measured wall clock
    within ``tolerance`` (double-booking shows up as overbooked_s > the
    tolerance band and fails). By construction ``unattributed_s`` absorbs
    any under-attribution, so callers whose scope SHOULD be mostly booked
    (a step loop under load, a drill) pass ``max_unattributed_frac`` to
    make silent hook-loss a failure too — scopes with legitimate
    un-booked orchestration time (the bench engine between phases) leave
    it None and read the disclosed fraction instead."""
    wall = report["wall_s"]
    if wall <= 0:
        return False
    if max_unattributed_frac is not None and \
            report["unattributed_s"] > max_unattributed_frac * wall:
        return False
    total = sum(report["categories"].values()) + report["unattributed_s"]
    return abs(total - wall) <= tolerance * wall and \
        report["overbooked_s"] <= tolerance * wall
