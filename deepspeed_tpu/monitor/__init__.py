"""Observability package: metric sinks (``monitor.py``), the span/event
trace bus (``trace.py``) and the metrics registry (``metrics.py``).

Only the import-light trace/metrics surface is re-exported here:
``monitor.monitor`` imports the comm package (rank gating) and is imported
directly by its consumers (``runtime/engine.py``) to keep package bootstrap
cycle-free.
"""

from .trace import get_tracer, configure_tracer, to_chrome_trace, NULL_SPAN  # noqa: F401
from .metrics import (  # noqa: F401
    get_metrics, configure_metrics, compute_mfu, compute_mbu, peak_flops_per_chip,
    peak_hbm_bw_per_chip, CHIP_PEAK_FLOPS, CHIP_PEAK_HBM_BW,
    DEFAULT_LATENCY_BUCKETS_MS)
from .flight import get_flight_recorder, FlightRecorder  # noqa: F401
from .health import get_health, configure_health, HealthPlane  # noqa: F401
from .memory import get_memory, hbm_report, tree_device_bytes, MemoryAttribution  # noqa: F401
from .goodput import (  # noqa: F401
    get_goodput, configure_goodput, conservation_ok, GoodputLedger, GoodputPlane,
    RecompileSentinel, TRAIN_CATEGORIES, SERVING_CATEGORIES)
from .roofline import (  # noqa: F401
    get_roofline, configure_roofline, get_capture_manager, cost_analysis_dict,
    CaptureBusyError, CaptureManager, RooflinePlane, ExecutableCostRegistry)
