"""Roofline attribution plane + on-demand XPlane capture manager.

Five sensor planes account for every wall-clock second, HBM byte, and
tenant-owned resource (PRs 1, 5, 11, 14, 15) — none of them can say whether
the chip is running *as fast as the hardware allows*. This module joins what
XLA says a compiled executable must do (``compiled.cost_analysis()`` FLOPs
and bytes accessed — the exact mechanism ``profiling/flops_profiler.py``
uses point-wise) with what we measure it doing (the engine step boundary,
the serving forward wrappers, ``KernelAutotuner.measure``), per shape
bucket — the same bucket labels the PR 14 recompile sentinel tracks — and
renders a per-bucket verdict:

  * ``compute_bound``   — the FLOP roof binds (arithmetic intensity above
    the ridge point) and measured wall is near that roof;
  * ``bandwidth_bound`` — the HBM-bytes roof binds and measured wall is
    near it (a bandwidth-bound decode is what justifies the disaggregated
    fleet, ROADMAP 1);
  * ``overhead_bound``  — measured wall exceeds ``overhead_factor`` x the
    cost-model roof: the executable is near NEITHER roof, the gap is host
    dispatch / launch overhead, and the bucket is a re-tuner nominee
    (ROADMAP 5c);
  * ``unknown``         — cost, wall, or peaks are missing; every missing
    input is disclosed as null, never guessed (the VERDICT r4 trap: a CPU
    fallback must not price itself against a TPU roof).

Cost capture is LAZY: a compile site hands the plane its freshly-jitted
callable via :meth:`RooflinePlane.capture_executable`; the returned wrapper
records the abstract ``ShapeDtypeStruct`` signature of the FIRST real call
and the plane re-lowers (``fn.lower(*abstract).compile().cost_analysis()``)
only at report time — the serving hot path pays one flag check + one
Python-call forward per step while armed, and nothing at all when the
``monitor.roofline`` block is absent (no wrappers are ever installed; the
zero-overhead-absent contract of the trace/health/goodput planes,
test-enforced).

Second half: :class:`CaptureManager` — the shared ``jax.profiler``
start/stop broker both engines and the gateway's ``POST /v1/profile`` ride.
One capture may be in flight per process (``jax.profiler`` is global); a
bounded-duration capture writes into a hidden temp dir and atomically
renames it into place, so a reader never sees a torn artifact and a
concurrent request gets :class:`CaptureBusyError` (HTTP 409 at the
gateway), never a corrupted trace.

Import-light by design: stdlib + sibling monitor modules only; ``jax`` is
imported lazily at capture/lowering time.
"""

import os
import threading
import time

from .metrics import (compute_mbu, compute_mfu, get_metrics,
                      peak_flops_per_chip, peak_hbm_bw_per_chip)

VERDICTS = ("compute_bound", "bandwidth_bound", "overhead_bound", "unknown")


class CaptureBusyError(RuntimeError):
    """A jax.profiler capture is already in flight (one per process)."""


# ---------------------------------------------------------------------------
# on-demand XPlane capture
# ---------------------------------------------------------------------------
class CaptureManager:
    """Process-global ``jax.profiler.start_trace``/``stop_trace`` broker.

    Two modes share one in-flight flag (the profiler is process-global, so
    a training capture and a gateway capture must exclude each other):

      * manual ``start(dir)`` / ``stop()`` — the engine's
        ``tpu.profiler_trace`` step-window capture;
      * bounded :meth:`capture` — start, sleep ``duration_s``, drain, stop,
        then atomically rename the temp dir into the artifact root (the
        ``write_snapshot`` tmp+rename discipline, directory-shaped).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._busy = False
        self._n = 0

    @property
    def in_flight(self):
        return self._busy

    def _acquire(self):
        with self._lock:
            if self._busy:
                return False
            self._busy = True
            return True

    def _release(self):
        with self._lock:
            self._busy = False

    def start(self, trace_dir):
        """Begin a manual capture into ``trace_dir``. Returns False (no
        trace started) when a capture is already in flight."""
        if not self._acquire():
            return False
        try:
            import jax

            jax.profiler.start_trace(trace_dir)
        except Exception:
            self._release()
            raise
        return True

    def stop(self, drain=None):
        """End the manual capture: run ``drain()`` (flush in-flight device
        work so the trace holds whole steps), then ``stop_trace`` — which
        is what writes the artifact. stop_trace always runs, even when the
        drain raises (a partial trace beats a wedged profiler)."""
        if not self._busy:
            return
        import jax

        try:
            if drain is not None:
                drain()
        finally:
            try:
                jax.profiler.stop_trace()
            finally:
                self._release()

    def capture(self, duration_s, out_root, label="capture", max_s=60.0,
                drain=None):
        """One bounded capture: trace live traffic for ``duration_s``
        (clamped to ``max_s``) and return the final artifact directory.
        Raises :class:`CaptureBusyError` when a capture is in flight.

        Atomicity: the profiler writes into ``out_root/.tmp-...``; only a
        COMPLETE capture is renamed to its final name, so any visible
        ``label-*`` directory is a whole, loadable XPlane artifact."""
        duration_s = min(float(duration_s), float(max_s))
        if duration_s <= 0:
            raise ValueError(f"capture duration must be > 0, got {duration_s}")
        if not self._acquire():
            raise CaptureBusyError("a profiler capture is already in flight")
        try:
            import jax

            os.makedirs(out_root, exist_ok=True)
            with self._lock:
                self._n += 1
                n = self._n
            final = os.path.join(out_root, f"{label}-{os.getpid()}-{n:03d}")
            tmp = os.path.join(out_root, f".tmp-{label}-{os.getpid()}-{n:03d}")
            jax.profiler.start_trace(tmp)
            try:
                time.sleep(duration_s)
                if drain is not None:
                    drain()
            finally:
                jax.profiler.stop_trace()
            os.replace(tmp, final)
            get_metrics().counter("profile/captures_total").inc()
            return final
        finally:
            self._release()


_capture = None
_capture_lock = threading.Lock()


def get_capture_manager() -> CaptureManager:
    """The process capture broker (created on first use — a process that
    never profiles never allocates one)."""
    global _capture
    if _capture is None:
        with _capture_lock:
            if _capture is None:
                _capture = CaptureManager()
    return _capture


# ---------------------------------------------------------------------------
# executable-cost registry
# ---------------------------------------------------------------------------
def _abstract_signature(args):
    """Concrete call args -> ShapeDtypeStruct pytree (shardings preserved,
    so a sharded train step re-lowers under the same placement)."""
    import jax

    def one(x):
        if isinstance(x, jax.Array):
            try:
                return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            except Exception:
                return jax.ShapeDtypeStruct(x.shape, x.dtype)
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return x  # python scalars etc. stay literal

    return jax.tree_util.tree_map(one, args)


def cost_analysis_dict(compiled):
    """``compiled.cost_analysis()`` normalized to ONE flat dict — older jax
    wraps the result in a single-element list. The shared extraction used
    here, by ``profiling/flops_profiler.py`` and ``tools/decode_profile.py``,
    so every cost consumer in the repo reads the same keys."""
    cost = compiled.cost_analysis()
    if isinstance(cost, list):
        cost = cost[0] if cost else {}
    return dict(cost or {})


def _cost_of(fn, abstract_args, mesh=None):
    """``fn.lower(*abstract).compile().cost_analysis()`` with every failure
    mode disclosed instead of raised: a backend without cost analysis, a
    lowering that needs a live mesh, a list-wrapped result (older jax) —
    the row reports null flops/bytes plus the error string, never crashes
    (the CPU-fallback contract)."""
    try:
        import contextlib

        cm = mesh if mesh is not None else contextlib.nullcontext()
        with cm:
            compiled = fn.lower(*abstract_args).compile()
        cost = cost_analysis_dict(compiled)
        flops = cost.get("flops")
        bytes_accessed = cost.get("bytes accessed")
        return {"flops": float(flops) if flops is not None else None,
                "bytes": float(bytes_accessed) if bytes_accessed is not None else None}
    except Exception as e:  # noqa: BLE001 — telemetry never kills runs
        return {"flops": None, "bytes": None,
                "error": f"{type(e).__name__}: {str(e)[:160]}"}


class _CapturedExecutable:
    """Transparent wrapper a compile site installs over its jitted callable
    while the plane is armed: the FIRST call snapshots the abstract arg
    signature into the registry; every call forwards. Attribute access
    (``.lower`` for the AOT paths) delegates to the wrapped callable."""

    __slots__ = ("_fn", "_registry", "_bucket", "_mesh", "_seen")

    def __init__(self, fn, registry, bucket, mesh=None):
        self._fn = fn
        self._registry = registry
        self._bucket = bucket
        self._mesh = mesh
        self._seen = False

    def __call__(self, *args):
        if not self._seen:
            self._seen = True
            try:
                self._registry.register_lazy(
                    self._bucket, self._fn, _abstract_signature(args),
                    mesh=self._mesh)
            except Exception:  # noqa: BLE001 — capture must never cost a step
                pass
        return self._fn(*args)

    def __getattr__(self, name):
        return getattr(self._fn, name)


class ExecutableCostRegistry:
    """Per-bucket cost + measured-wall store. Buckets are the recompile
    sentinel's labels (``train_step``, ``put/t{t}/s{s}/...``,
    ``decode/s{s}/n{n}``, ``verify/...``, ``pallas/{kernel}/{bucket}``), so
    the sentinel, the goodput ledger, and the roofline rows all speak the
    same key space."""

    def __init__(self):
        self._lock = threading.Lock()
        # bucket -> {"thunk": callable|None, "cost": dict|None,
        #            "wall_s": float, "calls": int, "last_wall_s": float}
        self._rows = {}

    def _row(self, bucket):
        row = self._rows.get(bucket)
        if row is None:
            row = self._rows[bucket] = {"thunk": None, "cost": None,
                                        "wall_s": 0.0, "calls": 0,
                                        "last_wall_s": 0.0}
        return row

    def register_lazy(self, bucket, fn, abstract_args, mesh=None):
        """Record a cost THUNK for ``bucket``: evaluated once, at report
        time (re-lowering is off the serving hot path by design)."""
        with self._lock:
            row = self._row(bucket)
            if row["thunk"] is None and row["cost"] is None:
                row["thunk"] = lambda: _cost_of(fn, abstract_args, mesh=mesh)

    def register_cost(self, bucket, cost):
        """Record an already-computed cost dict (``{"flops":…, "bytes":…}``)
        for ``bucket`` — the autotuner/tools entry."""
        with self._lock:
            self._row(bucket)["cost"] = dict(cost)

    def note_wall(self, bucket, seconds):
        """One measured wall sample for ``bucket`` (host-observed, through
        the blocking fetch — the same window the goodput ledger books)."""
        with self._lock:
            row = self._row(bucket)
            row["wall_s"] += float(seconds)
            row["calls"] += 1
            row["last_wall_s"] = float(seconds)

    def cost(self, bucket):
        """The (possibly lazily-evaluated) cost dict for ``bucket``, or
        None when the bucket was never registered."""
        with self._lock:
            row = self._rows.get(bucket)
            thunk = row["thunk"] if row is not None else None
        if row is None:
            return None
        if row["cost"] is None and thunk is not None:
            cost = thunk()  # outside the lock: lowering can be slow
            with self._lock:
                if row["cost"] is None:
                    row["cost"] = cost
                    row["thunk"] = None
        return row["cost"]

    def buckets(self):
        with self._lock:
            return sorted(self._rows)

    def snapshot(self):
        """[(bucket, cost_or_None, wall_s, calls)] — costs forced."""
        out = []
        for b in self.buckets():
            cost = self.cost(b)
            with self._lock:
                row = self._rows[b]
                out.append((b, cost, row["wall_s"], row["calls"]))
        return out


# ---------------------------------------------------------------------------
# the plane
# ---------------------------------------------------------------------------
class RooflinePlane:
    """Process-global roofline state (see :func:`get_roofline`): the cost
    registry, the verdict math, and the export wiring (health-plane
    gauge/state/dump providers). Everything defaults OFF with the
    zero-overhead-absent contract: no registry object, no wrappers, no
    threads, one ``enabled`` check per hook."""

    def __init__(self):
        self.enabled = False
        self.overhead_factor = 2.0
        self.peak_flops = None   # None = per-chip table (null on CPU)
        self.peak_hbm_bw = None
        self.capture_dir = "/tmp/dstpu_xplane"
        self.max_capture_s = 60.0
        self._registry = None
        self._gauge_fn = None   # bound-method refs cached at configure time
        self._report_fn = None  # (the health clears are identity-checked)

    # -- configuration --------------------------------------------------
    def configure(self, config=None, **kwargs):
        """Arm the plane. ``config`` is a ``RooflineConfig`` block
        (``monitor_config.roofline``); explicit kwargs win over it."""

        def knob(name, default=None):
            if name in kwargs and kwargs[name] is not None:
                return kwargs[name]
            if config is not None:
                return getattr(config, name, default)
            return default

        enabled = knob("enabled")
        if enabled is not None and not enabled:
            self.shutdown()
            return self
        if not enabled and not self.enabled:
            return self
        self.overhead_factor = float(knob("overhead_factor", self.overhead_factor))
        self.peak_flops = knob("peak_flops", self.peak_flops)
        self.peak_hbm_bw = knob("peak_hbm_bw", self.peak_hbm_bw)
        self.capture_dir = str(knob("capture_dir", self.capture_dir))
        self.max_capture_s = float(knob("max_capture_s", self.max_capture_s))
        if self._registry is None:
            self._registry = ExecutableCostRegistry()
        # the verdict gauges are served through the metrics registry +
        # health providers — the roofline block implies metrics, like
        # `trace`/`health`/`goodput` do
        get_metrics().enable()
        # (re-)registered on EVERY arm: HealthPlane.shutdown() clears all
        # providers (the goodput plane's rollover lesson)
        from .health import get_health

        hp = get_health()
        if self._gauge_fn is None:
            self._gauge_fn = self.gauge_rows
            self._report_fn = self.report
        hp.set_gauge_provider("roofline", self._gauge_fn)
        hp.set_state_provider("roofline", self._report_fn)
        hp.set_dump_provider("roofline", self._report_fn)
        self.enabled = True
        return self

    def shutdown(self):
        """Disarm, drop the registry, and reset every knob to its default
        (a later bare re-arm must not inherit a previous run's peak
        overrides). Idempotent."""
        if self.enabled:
            from .health import get_health

            hp = get_health()
            hp.clear_gauge_provider("roofline", self._gauge_fn)
            hp.clear_state_provider("roofline", self._report_fn)
            hp.clear_dump_provider("roofline", self._report_fn)
        self.enabled = False
        self._registry = None
        self.overhead_factor = 2.0
        self.peak_flops = None
        self.peak_hbm_bw = None
        self.capture_dir = "/tmp/dstpu_xplane"
        self.max_capture_s = 60.0
        return self

    # -- capture hooks (compile sites / measurement points) ---------------
    def capture_executable(self, bucket, fn, mesh=None):
        """Wrap a freshly-jitted callable so its first call registers the
        bucket's cost signature. Called at the compiled-cache-miss sites
        (the same places that feed the recompile sentinel); callers only
        invoke it while ``enabled`` — disabled returns ``fn`` untouched."""
        if not self.enabled or self._registry is None:
            return fn
        return _CapturedExecutable(fn, self._registry, bucket, mesh=mesh)

    def note_wall(self, bucket, seconds):
        if not self.enabled or self._registry is None:
            return
        self._registry.note_wall(bucket, seconds)

    def register_fn(self, bucket, fn, *example_args, mesh=None):
        """Tools entry (``tools/decode_profile.py``): register ``bucket``'s
        cost from a jit-wrapped callable + example (or abstract) args."""
        if not self.enabled or self._registry is None:
            return
        self._registry.register_lazy(bucket, fn,
                                     _abstract_signature(tuple(example_args)),
                                     mesh=mesh)

    def register_thunk(self, bucket, thunk):
        """Autotuner entry: register cost from a no-arg measurement thunk
        (closed-over operands become lowering constants — good enough for a
        kernel's flop/byte totals)."""
        if not self.enabled or self._registry is None:
            return
        import jax

        self._registry.register_lazy(bucket, jax.jit(thunk), ())

    # -- verdict math ----------------------------------------------------
    def peaks(self):
        """(peak_flops, peak_hbm_bw) — config overrides first, then the
        per-chip tables; (None, None) on an unknown chip with no override."""
        pf = self.peak_flops if self.peak_flops else peak_flops_per_chip()
        pb = self.peak_hbm_bw if self.peak_hbm_bw else peak_hbm_bw_per_chip()
        return pf, pb

    def verdict_row(self, cost, wall_s, calls):
        """One bucket's joined row: achieved rates, MFU + MBU, the roofline
        verdict, and the gap to the roof — every unknowable field null."""
        pf, pb = self.peaks()
        flops = (cost or {}).get("flops")
        bts = (cost or {}).get("bytes")
        mean = wall_s / calls if calls else None
        row = {"flops": flops, "bytes": bts,
               "wall_s": round(wall_s, 6), "calls": calls,
               "mean_wall_s": round(mean, 6) if mean else None,
               "achieved_flops_per_s": (round(flops / mean, 3)
                                        if flops is not None and mean else None),
               "achieved_hbm_bytes_per_s": (round(bts / mean, 3)
                                            if bts is not None and mean else None),
               "mfu": None, "mbu": None,
               "verdict": "unknown", "roof_s": None, "gap_to_roof": None}
        if (cost or {}).get("error"):
            row["cost_error"] = cost["error"]
        if mean:
            mfu = compute_mfu(flops, mean, peak_flops=pf) if flops is not None else None
            mbu = compute_mbu(bts, mean, peak_bw=pb) if bts is not None else None
            row["mfu"] = round(mfu, 4) if mfu is not None else None
            row["mbu"] = round(mbu, 4) if mbu is not None else None
        # the verdict needs BOTH roofs priced: a one-sided roof could call a
        # bandwidth-bound kernel compute_bound simply because the bandwidth
        # roof was unknowable (disclose, don't guess)
        if (mean and flops is not None and bts is not None
                and pf is not None and pb is not None):
            t_flops = flops / pf
            t_bytes = bts / pb
            roof = max(t_flops, t_bytes)
            row["roof_s"] = round(roof, 9)
            row["gap_to_roof"] = round(mean / roof, 3) if roof > 0 else None
            if roof <= 0:
                pass  # degenerate cost model: stays "unknown"
            elif mean > self.overhead_factor * roof:
                row["verdict"] = "overhead_bound"
            elif t_flops >= t_bytes:
                row["verdict"] = "compute_bound"
            else:
                row["verdict"] = "bandwidth_bound"
        return row

    # -- export ----------------------------------------------------------
    def report(self):
        """The full forensic/healthz section: priced peaks + one joined row
        per bucket (cost thunks forced here, off the hot path)."""
        pf, pb = self.peaks()
        out = {"enabled": self.enabled,
               "peak_flops": pf, "peak_hbm_bw": pb,
               "overhead_factor": self.overhead_factor,
               "buckets": {}}
        if self._registry is None:
            return out
        for bucket, cost, wall_s, calls in self._registry.snapshot():
            out["buckets"][bucket] = self.verdict_row(cost, wall_s, calls)
        return out

    def gauge_rows(self):
        """Labelled rows for /metrics: ``profile/roofline_mfu{bucket=…}`` +
        ``profile/roofline_mbu{bucket=…}`` (only buckets whose utilization
        is knowable — a null never renders as 0.0)."""
        rows = []
        for bucket, row in self.report()["buckets"].items():
            if row["mfu"] is not None:
                rows.append(("profile/roofline_mfu", {"bucket": bucket}, row["mfu"]))
            if row["mbu"] is not None:
                rows.append(("profile/roofline_mbu", {"bucket": bucket}, row["mbu"]))
        return rows


_plane = RooflinePlane()


def get_roofline() -> RooflinePlane:
    return _plane


def configure_roofline(config=None, **kwargs) -> RooflinePlane:
    return _plane.configure(config=config, **kwargs)
