"""Monitor config (reference ``deepspeed/monitor/config.py``) + the
TPU-native ``trace`` block gating the span/metrics bus (``monitor/trace.py``)."""

from typing import Optional

from pydantic import Field, model_validator

from ..runtime.config_utils import DeepSpeedConfigModel


def get_monitor_config(param_dict):
    monitor_dict = {key: param_dict.get(key, {})
                    for key in ("tensorboard", "wandb", "csv_monitor", "comet", "trace",
                                "health", "goodput", "roofline")}
    # presence-enables: an EMPTY {"trace": {}} / {"health": {}} block in the
    # config means "on with defaults" (the validator can only see set
    # fields, not presence)
    for key in ("trace", "health", "goodput", "roofline"):
        if key in param_dict and not monitor_dict[key]:
            monitor_dict[key] = {"enabled": True}
    return DeepSpeedMonitorConfig(**monitor_dict)


class TensorBoardConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class WandbConfig(DeepSpeedConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed"


class CSVConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class CometConfig(DeepSpeedConfigModel):
    enabled: bool = False
    samples_log_interval: int = 100
    project: Optional[str] = None
    workspace: Optional[str] = None
    api_key: Optional[str] = None
    experiment_name: Optional[str] = None
    experiment_key: Optional[str] = None
    online: Optional[bool] = None
    mode: Optional[str] = None


class TraceConfig(DeepSpeedConfigModel):
    """``monitor.trace`` block — the Chrome-trace/Perfetto JSONL span bus and
    metrics registry (``monitor/trace.py`` / ``monitor/metrics.py``). Enabled
    by presence (same contract as ``tpu.profiler_trace``): configuring any
    field turns it on unless ``enabled`` is set explicitly. Off by default —
    the step loop then makes zero trace-related allocations."""
    enabled: bool = False
    output_path: str = "/tmp/dstpu_trace.jsonl"
    flush_every: int = Field(256, ge=1)

    @model_validator(mode="after")
    def enable_when_configured(self):
        if self.model_fields_set and "enabled" not in self.model_fields_set:
            self.enabled = True
        return self


class HealthConfig(DeepSpeedConfigModel):
    """``monitor.health`` block — the live-health plane (``monitor/health.py``
    / ``monitor/flight.py`` / ``monitor/export.py``): flight recorder, stall
    watchdog, straggler detection, and the Prometheus/JSON exporter. Enabled
    by presence (same contract as ``trace``); off by default, and every
    deadline defaults to 0 (= that source unwatched), so enabling the block
    alone arms only the flight recorder + heartbeat bookkeeping — no
    watchdog thread, no server, no behavior change to the step loop beyond
    one boolean check."""
    enabled: bool = False
    # flight recorder ring capacity (events retained for stall/exit dumps)
    flight_capacity: int = Field(4096, ge=16)
    # quarantine directory for watchdog-trip / SIGQUIT / destroy() dumps
    dump_dir: str = "/tmp/dstpu_health"
    dump_on_destroy: bool = True
    # install a SIGQUIT handler that writes a dump (faulthandler-style
    # kill -QUIT forensics); main-thread only
    sigquit_dump: bool = False
    watchdog_poll_s: float = Field(1.0, gt=0)
    # per-source stall deadlines, seconds; 0 = unwatched. The watchdog
    # thread only starts when at least one is > 0.
    deadline_train_step_s: float = Field(0.0, ge=0)
    deadline_collective_s: float = Field(0.0, ge=0)
    deadline_serving_s: float = Field(0.0, ge=0)
    deadline_saver_s: float = Field(0.0, ge=0)
    deadline_prefetch_s: float = Field(0.0, ge=0)
    # straggler trace instants fire past this skew; the skew gauge itself is
    # recorded whenever the engine's resilience vote carries the samples
    straggler_threshold_ms: float = Field(0.0, ge=0)
    # None = no HTTP server; 0 = ephemeral port; N = fixed port
    export_port: Optional[int] = Field(None, ge=0)
    export_host: str = "127.0.0.1"
    # scrape-less mode: atomically rewrite this JSON file every N steps
    snapshot_path: str = ""
    snapshot_every_steps: int = Field(50, ge=1)

    @model_validator(mode="after")
    def enable_when_configured(self):
        if self.model_fields_set and "enabled" not in self.model_fields_set:
            self.enabled = True
        return self


class GoodputConfig(DeepSpeedConfigModel):
    """``monitor.goodput`` block — the wall-clock attribution ledger +
    recompile sentinel (``monitor/goodput.py``). Enabled by presence (same
    contract as ``trace``/``health``); off by default — the step loop and
    the serving driver then pay one ``is not None`` check each, with no
    ledger objects, no threads, no per-step allocations."""
    enabled: bool = False
    # training warmup boundary: jax compiles during the first N steps are
    # expected; every compile after is flagged by the sentinel
    train_warmup_steps: int = Field(2, ge=0)
    # a step/driver-loop gap at least this long books as stall[ed] (the
    # same wedges the PR 5 watchdog dumps; shorter gaps stay in the
    # compute residual / unattributed)
    stall_gap_s: float = Field(0.05, gt=0)
    # compile-storm detection: K unexpected compiles inside the window
    # raise a `compile_storm` trace instant + counter (once per burst)
    storm_k: int = Field(5, ge=2)
    storm_window_s: float = Field(10.0, gt=0)

    @model_validator(mode="after")
    def enable_when_configured(self):
        if self.model_fields_set and "enabled" not in self.model_fields_set:
            self.enabled = True
        return self


class RooflineConfig(DeepSpeedConfigModel):
    """``monitor.roofline`` block — the executable-cost registry + roofline
    verdict plane and the on-demand XPlane capture manager
    (``monitor/roofline.py``). Enabled by presence (the ``trace``/``health``/
    ``goodput`` contract); off by default — compile sites and forward paths
    then pay one ``enabled`` check each, with no registry, no per-compile
    wrappers, and no threads (test-enforced)."""
    enabled: bool = False
    # measured wall past this multiple of the cost-model roof time verdicts
    # `overhead_bound` instead of compute/bandwidth bound: the executable is
    # not near either hardware roof, the gap is dispatch/host overhead
    overhead_factor: float = Field(2.0, gt=1.0)
    # peak overrides (FLOP/s, bytes/s per chip). None = the per-chip tables
    # in monitor/metrics.py; on an unknown chip (CPU fallback) with no
    # override, MFU/MBU report null and the verdict is `unknown` — the
    # VERDICT r4 discipline (never a misleading utilization number)
    peak_flops: Optional[float] = Field(None, gt=0)
    peak_hbm_bw: Optional[float] = Field(None, gt=0)
    # default artifact root for on-demand captures (the gateway's
    # serving.gateway.profiling block carries its own)
    capture_dir: str = "/tmp/dstpu_xplane"
    # hard bound on any single on-demand capture
    max_capture_s: float = Field(60.0, gt=0)

    @model_validator(mode="after")
    def enable_when_configured(self):
        if self.model_fields_set and "enabled" not in self.model_fields_set:
            self.enabled = True
        return self


class DeepSpeedMonitorConfig(DeepSpeedConfigModel):
    tensorboard: TensorBoardConfig = {}
    wandb: WandbConfig = {}
    csv_monitor: CSVConfig = {}
    comet: CometConfig = {}
    trace: TraceConfig = {}
    health: HealthConfig = {}
    goodput: GoodputConfig = {}
    roofline: RooflineConfig = {}

    @property
    def enabled(self):
        """Sink fan-out gate (rank-0 write_events). The trace bus is gated
        separately by ``trace.enabled`` — it has its own writer."""
        return self.tensorboard.enabled or self.wandb.enabled or self.csv_monitor.enabled or self.comet.enabled
