"""Monitor config (reference ``deepspeed/monitor/config.py``) + the
TPU-native ``trace`` block gating the span/metrics bus (``monitor/trace.py``)."""

from typing import Optional

from pydantic import Field, model_validator

from ..runtime.config_utils import DeepSpeedConfigModel


def get_monitor_config(param_dict):
    monitor_dict = {key: param_dict.get(key, {})
                    for key in ("tensorboard", "wandb", "csv_monitor", "comet", "trace")}
    # presence-enables: an EMPTY {"trace": {}} block in the config means "on
    # with defaults" (the validator can only see set fields, not presence)
    if "trace" in param_dict and not monitor_dict["trace"]:
        monitor_dict["trace"] = {"enabled": True}
    return DeepSpeedMonitorConfig(**monitor_dict)


class TensorBoardConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class WandbConfig(DeepSpeedConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed"


class CSVConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class CometConfig(DeepSpeedConfigModel):
    enabled: bool = False
    samples_log_interval: int = 100
    project: Optional[str] = None
    workspace: Optional[str] = None
    api_key: Optional[str] = None
    experiment_name: Optional[str] = None
    experiment_key: Optional[str] = None
    online: Optional[bool] = None
    mode: Optional[str] = None


class TraceConfig(DeepSpeedConfigModel):
    """``monitor.trace`` block — the Chrome-trace/Perfetto JSONL span bus and
    metrics registry (``monitor/trace.py`` / ``monitor/metrics.py``). Enabled
    by presence (same contract as ``tpu.profiler_trace``): configuring any
    field turns it on unless ``enabled`` is set explicitly. Off by default —
    the step loop then makes zero trace-related allocations."""
    enabled: bool = False
    output_path: str = "/tmp/dstpu_trace.jsonl"
    flush_every: int = Field(256, ge=1)

    @model_validator(mode="after")
    def enable_when_configured(self):
        if self.model_fields_set and "enabled" not in self.model_fields_set:
            self.enabled = True
        return self


class DeepSpeedMonitorConfig(DeepSpeedConfigModel):
    tensorboard: TensorBoardConfig = {}
    wandb: WandbConfig = {}
    csv_monitor: CSVConfig = {}
    comet: CometConfig = {}
    trace: TraceConfig = {}

    @property
    def enabled(self):
        """Sink fan-out gate (rank-0 write_events). The trace bus is gated
        separately by ``trace.enabled`` — it has its own writer."""
        return self.tensorboard.enabled or self.wandb.enabled or self.csv_monitor.enabled or self.comet.enabled
