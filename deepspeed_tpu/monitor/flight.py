"""Flight recorder — the crash-forensics half of the live-health plane.

A bounded, lock-protected ring buffer (default ~4k entries) that keeps the
LAST window of observability events in memory at all times, so a stall dump
(``monitor/health.py``), a ``SIGQUIT`` request, or ``engine.destroy()`` can
reconstruct what the process was doing right before it wedged — even when
file tracing is disabled (the production default: nobody runs a multi-day
pod job with the JSONL trace writer on, but everybody wants the tail of it
after a hang). Two feeds:

  * the :class:`~.trace.Tracer` mirrors every span/instant/counter it emits
    into the ring via ``Tracer.set_mirror`` — including in "tracing off"
    mode, where the health plane arms the mirror without arming the file
    writer;
  * explicit breadcrumbs (``record(kind, name, **fields)``) from the engine
    step loop, the serving engine, and the checkpoint writer — the
    host-level narrative the trace bus doesn't carry.

Ordering is strict: every entry gets a monotonically increasing ``seq`` under
the ring lock, the ring is lossless up to capacity, and past capacity the
oldest entries are overwritten in ``seq`` order (tested). Zero overhead when
disabled: one attribute check per call, no allocations.

Import-light by design (stdlib only): pulled in during package bootstrap via
the monitor wiring.
"""

import json
import threading
import time


class FlightRecorder:
    """Bounded in-memory event ring. One per process (see
    :func:`get_flight_recorder`)."""

    def __init__(self, capacity=4096):
        self.enabled = False
        self._lock = threading.Lock()
        self._cap = max(16, int(capacity))
        self._ring = [None] * self._cap
        self._seq = 0  # total entries ever recorded (== next seq)

    # -- configuration --------------------------------------------------
    def configure(self, enabled=None, capacity=None):
        with self._lock:
            if capacity is not None and int(capacity) != self._cap:
                # resizing drops the old window: the ring is forensic state,
                # not durable data, and a reconfigure marks a new run anyway
                self._cap = max(16, int(capacity))
                self._ring = [None] * self._cap
                self._seq = 0
            if enabled is not None:
                self.enabled = bool(enabled)
        return self

    @property
    def capacity(self):
        return self._cap

    @property
    def total_recorded(self):
        """Entries ever recorded (a ring past capacity has dropped
        ``total_recorded - capacity`` of them)."""
        return self._seq

    # -- feeds ----------------------------------------------------------
    def record(self, kind, name, **fields):
        """Explicit breadcrumb: ``kind`` is the subsystem (``engine`` /
        ``serving`` / ``saver`` / ``health``), ``name`` the event."""
        if not self.enabled:
            return
        entry = {"kind": kind, "name": name, "t_unix": time.time()}
        if fields:
            entry.update(fields)
        self._push(entry)

    def record_event(self, ev):
        """Tracer mirror feed: ``ev`` is a Chrome-trace event dict (already
        fully built by the tracer — stored as-is under a ``trace`` kind)."""
        if not self.enabled:
            return
        self._push({"kind": "trace", "ev": ev})

    def _push(self, entry):
        with self._lock:
            entry["seq"] = self._seq
            self._ring[self._seq % self._cap] = entry
            self._seq += 1

    # -- read side ------------------------------------------------------
    def dump(self):
        """The retained window, strictly ordered oldest -> newest."""
        with self._lock:
            n, cap = self._seq, self._cap
            if n <= cap:
                return [e for e in self._ring[:n]]
            start = n % cap
            return self._ring[start:] + self._ring[:start]

    def dump_jsonl(self, fh):
        """Write the ordered window to an open text file handle, one JSON
        object per line; returns the number of lines written."""
        entries = self.dump()
        for e in entries:
            fh.write(json.dumps(e, default=repr) + "\n")
        return len(entries)

    def clear(self):
        with self._lock:
            self._ring = [None] * self._cap
            self._seq = 0


_flight = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    return _flight
