"""Lightweight metrics registry: counters, gauges, fixed-bucket histograms,
and the chip peak-FLOPs table behind MFU reporting.

The registry is the quantitative half of the observability layer (the trace
bus in ``trace.py`` is the temporal half): the engine step loop and the
serving path record into it, and ``MonitorMaster.write_events`` drains
``registry.events(step)`` each logging interval alongside derived throughput
and MFU.

Zero overhead when disabled: every accessor returns the same shared no-op
metric object (no per-step allocations), verified by ``tests/test_monitor_trace.py``.

Well-known checkpoint-plane names (recorded by ``runtime/resilience/`` and
the engine; drained like every other metric): ``train/ckpt_blocked_ms``
(step-loop time lost to a save — the host-snapshot cost under async save,
the full write under sync), ``checkpoint/write_ms``,
``checkpoint/saves_committed`` / ``checkpoint/saves_failed``,
``checkpoint/bytes_written``; the matching temporal record is the
``checkpoint/async_write`` span on the trace bus's ``checkpoint`` stream.

Import-light by design (no package-internal imports at module level): pulled
in during package bootstrap via the comm/monitor wiring.
"""

import bisect
import math
import threading
from collections import deque

# ---------------------------------------------------------------------------
# metric primitives
# ---------------------------------------------------------------------------

# Prometheus-style latency buckets (upper bounds, ms); +inf is implicit.
DEFAULT_LATENCY_BUCKETS_MS = (1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 500.0,
                              1000.0, 2000.0, 5000.0, 10000.0)


class Counter:
    __slots__ = ("name", "value")

    def __init__(self, name):
        self.name = name
        self.value = 0.0

    def inc(self, n=1.0):
        self.value += n


class Gauge:
    __slots__ = ("name", "value")

    def __init__(self, name):
        self.name = name
        self.value = 0.0

    def set(self, v):
        self.value = float(v)


class Histogram:
    """Fixed-bucket histogram with exact percentiles over a bounded window.

    Bucket counts are the cheap always-on export (cumulative, Prometheus
    layout); the bounded raw window (last ``window`` observations) makes
    ``percentile`` exact for any run shorter than the window — the serving
    TTFT/decode distributions this was built for."""

    __slots__ = ("name", "buckets", "bucket_counts", "count", "total", "_raw", "window", "_lock")

    def __init__(self, name, buckets=None, window=4096):
        self.name = name
        self.buckets = tuple(sorted(buckets or DEFAULT_LATENCY_BUCKETS_MS))
        self.bucket_counts = [0] * (len(self.buckets) + 1)  # last = +inf
        self.count = 0
        self.total = 0.0
        self.window = window
        self._raw = deque(maxlen=window)  # O(1) eviction at the window edge
        # histograms take observations from background threads (the data
        # prefetch worker) while the main thread drains events(): sorting a
        # deque mid-append raises RuntimeError, so observe/read serialize on
        # a per-histogram lock (uncontended acquire ~100ns, noise vs a step)
        self._lock = threading.Lock()

    def observe(self, v):
        v = float(v)
        with self._lock:
            self.bucket_counts[bisect.bisect_left(self.buckets, v)] += 1
            self.count += 1
            self.total += v
            self._raw.append(v)

    def percentile(self, p, _sorted=None):
        """Exact p-th percentile (0..100) over the retained window (nearest-
        rank method, so every returned value is an actual observation)."""
        if _sorted is not None:
            data = _sorted
        else:
            with self._lock:
                data = sorted(self._raw)
        if not data:
            return 0.0
        rank = min(len(data), max(1, math.ceil(p / 100.0 * len(data))))
        return data[rank - 1]

    def mean(self):
        return self.total / self.count if self.count else 0.0

    def summary(self):
        with self._lock:
            data = sorted(self._raw)  # one sort shared by every quantile
            # mean from the SAME locked (count, total) read — calling
            # self.mean() here would re-read both fields unlocked and could
            # pair a new count with an old total under concurrent observe()
            count, total = self.count, self.total
        return {"count": count, "mean": total / count if count else 0.0,
                "p50": self.percentile(50, data), "p90": self.percentile(90, data),
                "p99": self.percentile(99, data)}


class _NullMetric:
    """Shared disabled-mode stand-in for all three metric kinds."""

    __slots__ = ()
    name = "<disabled>"
    value = 0.0
    count = 0
    total = 0.0

    def inc(self, n=1.0):
        ...

    def set(self, v):
        ...

    def observe(self, v):
        ...

    def percentile(self, p):
        return 0.0

    def mean(self):
        return 0.0

    def summary(self):
        return {"count": 0, "mean": 0.0, "p50": 0.0, "p90": 0.0, "p99": 0.0}


NULL_METRIC = _NullMetric()


class MetricsRegistry:

    def __init__(self, enabled=False):
        self.enabled = enabled
        self._lock = threading.RLock()
        self._counters = {}
        self._gauges = {}
        self._histograms = {}

    # -- lifecycle ------------------------------------------------------
    def enable(self):
        if not self.enabled:
            self.enabled = True
            from .trace import _install_compile_listener

            _install_compile_listener()  # compile counters ride the listener
        return self

    def disable(self):
        self.enabled = False
        return self

    def reset(self):
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()

    # -- accessors ------------------------------------------------------
    def counter(self, name) -> Counter:
        if not self.enabled:
            return NULL_METRIC
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name) -> Gauge:
        if not self.enabled:
            return NULL_METRIC
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(self, name, buckets=None, window=4096) -> Histogram:
        if not self.enabled:
            return NULL_METRIC
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name, buckets=buckets, window=window)
            return h

    # -- export ---------------------------------------------------------
    def events(self, step):
        """Flatten to ``(name, value, step)`` tuples — the exact shape
        ``MonitorMaster.write_events`` consumes."""
        if not self.enabled:
            return []
        out = []
        with self._lock:
            for c in self._counters.values():
                out.append((c.name, c.value, step))
            for g in self._gauges.values():
                out.append((g.name, g.value, step))
            for h in self._histograms.values():
                s = h.summary()
                for k in ("count", "mean", "p50", "p90", "p99"):
                    out.append((f"{h.name}/{k}", s[k], step))
        return out

    def snapshot(self):
        with self._lock:
            return {
                "counters": {c.name: c.value for c in self._counters.values()},
                "gauges": {g.name: g.value for g in self._gauges.values()},
                "histograms": {h.name: h.summary() for h in self._histograms.values()},
            }

    def to_prometheus(self):
        """The registry in Prometheus text exposition format (0.0.4) —
        counters/gauges/histograms with cumulative bucket series. The
        rendering lives in ``monitor/export.py`` (imported lazily to keep
        this module import-light for package bootstrap)."""
        from .export import render_prometheus

        return render_prometheus(self)


_registry = MetricsRegistry(enabled=False)


def get_metrics() -> MetricsRegistry:
    return _registry


def configure_metrics(enabled=None) -> MetricsRegistry:
    if enabled is not None:
        _registry.enable() if enabled else _registry.disable()
    return _registry


# ---------------------------------------------------------------------------
# MFU/MBU: chip peak-FLOPs + peak-HBM-bandwidth tables + derivation helpers
# ---------------------------------------------------------------------------

# dense bf16 peak FLOP/s per chip (published TPU specs)
CHIP_PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}

# peak HBM bandwidth, bytes/s per chip (published TPU specs) — the MBU
# denominator and the bandwidth roof of the roofline verdicts; keyed
# identically to CHIP_PEAK_FLOPS so the two tables can never disagree about
# which chip they price
CHIP_PEAK_HBM_BW = {
    "v4": 1228e9,
    "v5e": 819e9,
    "v5p": 2765e9,
    "v6e": 1640e9,
}

# jax ``device_kind`` strings -> table keys (v5e reports as "TPU v5 lite",
# v6e as "TPU v6 lite" / "TPU v6e" / Trillium)
_DEVICE_KIND_ALIASES = (
    ("v5 lite", "v5e"), ("v5litepod", "v5e"), ("v5e", "v5e"),
    ("v5p", "v5p"),
    ("v6 lite", "v6e"), ("v6e", "v6e"), ("trillium", "v6e"),
    ("v4", "v4"),
)


def _chip_key(device_kind=None):
    """Resolve ``device_kind`` (default: the local device) to a peak-table
    key, or None when the chip is unknown (CPU fallback)."""
    if device_kind is None:
        try:
            import jax

            device_kind = jax.devices()[0].device_kind
        except Exception:
            return None
    kind = str(device_kind).lower()
    for marker, key in _DEVICE_KIND_ALIASES:
        if marker in kind:
            return key
    return None


def peak_flops_per_chip(device_kind=None):
    """bf16 peak FLOP/s for ``device_kind`` (defaults to the local device).
    Returns None when the chip is unknown (CPU fallback) — callers report
    MFU as null rather than a misleading number."""
    key = _chip_key(device_kind)
    return CHIP_PEAK_FLOPS[key] if key is not None else None


def peak_hbm_bw_per_chip(device_kind=None):
    """Peak HBM bandwidth (bytes/s) for ``device_kind`` (defaults to the
    local device). Returns None when the chip is unknown — the same
    null-not-a-number contract as :func:`peak_flops_per_chip`."""
    key = _chip_key(device_kind)
    return CHIP_PEAK_HBM_BW[key] if key is not None else None


def compute_mfu(model_flops_per_step, step_time_s, n_chips=1, peak_flops=None):
    """Model FLOPs utilization: achieved model FLOP/s over the slice's peak.
    ``peak_flops`` overrides the per-chip table lookup (CPU tests, custom
    rooflines). Returns None when the peak is unknown."""
    if peak_flops is None:
        peak_flops = peak_flops_per_chip()
    if not peak_flops or step_time_s <= 0 or n_chips <= 0:
        return None
    return model_flops_per_step / step_time_s / (peak_flops * n_chips)


def compute_mbu(bytes_per_step, step_time_s, n_chips=1, peak_bw=None):
    """Model bandwidth utilization: achieved HBM bytes/s over the slice's
    peak — the :func:`compute_mfu` companion (same contract: ``peak_bw``
    overrides the table, None when the chip is unknown, so a CPU fallback
    can never report a misleading utilization)."""
    if peak_bw is None:
        peak_bw = peak_hbm_bw_per_chip()
    if not peak_bw or step_time_s <= 0 or n_chips <= 0:
        return None
    return bytes_per_step / step_time_s / (peak_bw * n_chips)
