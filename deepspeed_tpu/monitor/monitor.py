"""Metric event sinks.

Analog of the reference ``deepspeed/monitor/monitor.py:29`` — ``MonitorMaster``
fans ``write_events([(name, value, step), ...])`` out to TensorBoard / W&B /
CSV sinks. Only the process-0 host writes (rank gating identical to the
reference's ``self.enabled and rank == 0`` checks).
"""

import os
import csv as _csv

from ..comm import get_rank
from ..utils.logging import logger


class Monitor:

    def __init__(self, monitor_config):
        self.monitor_config = monitor_config
        self.enabled = getattr(monitor_config, "enabled", False)

    def write_events(self, event_list):
        raise NotImplementedError


def _import_summary_writer():
    """Prefer ``tensorboardX`` (torch-free, matches this JAX repo); fall back
    to ``torch.utils.tensorboard`` for environments that ship torch anyway.
    Returns (SummaryWriter, provider_name) or raises ImportError naming both."""
    try:
        from tensorboardX import SummaryWriter

        return SummaryWriter, "tensorboardX"
    except ImportError:
        pass
    try:
        from torch.utils.tensorboard import SummaryWriter

        return SummaryWriter, "torch.utils.tensorboard"
    except ImportError:
        raise ImportError("neither 'tensorboardX' nor 'torch.utils.tensorboard' is installed")


class TensorBoardMonitor(Monitor):

    def __init__(self, tensorboard_config):
        super().__init__(tensorboard_config)
        self.enabled = tensorboard_config.enabled and get_rank() == 0
        self.summary_writer = None
        if self.enabled:
            try:
                SummaryWriter, provider = _import_summary_writer()
                log_dir = os.path.join(tensorboard_config.output_path or "./runs", tensorboard_config.job_name)
                self.summary_writer = SummaryWriter(log_dir=log_dir)
            except Exception as e:
                # one loud warning instead of the old silent self-disable: a
                # run that asked for tensorboard must say WHY nothing appears
                logger.warning(f"TensorBoardMonitor disabled: {type(e).__name__}: {e}")
                self.enabled = False

    def write_events(self, event_list, flush=True):
        if self.enabled and self.summary_writer is not None:
            for event in event_list:
                self.summary_writer.add_scalar(*event)
            if flush:
                self.summary_writer.flush()

    def flush(self):
        if self.summary_writer is not None:
            self.summary_writer.flush()


class WandbMonitor(Monitor):

    def __init__(self, wandb_config):
        super().__init__(wandb_config)
        self.enabled = wandb_config.enabled and get_rank() == 0
        if self.enabled:
            try:
                import wandb

                wandb.init(project=wandb_config.project, group=wandb_config.group, entity=wandb_config.team)
                self._wandb = wandb
            except Exception:
                self.enabled = False

    def write_events(self, event_list):
        if self.enabled:
            for name, value, step in event_list:
                self._wandb.log({name: value}, step=int(step))


class csvMonitor(Monitor):
    """CSV sink with persistent file handles: one open file per metric for
    the life of the monitor (the old open/append/close per EVENT paid an
    open+close syscall pair per scalar per step on long runs). ``flush()``
    pushes buffered rows to disk; ``close()`` releases the handles."""

    def __init__(self, csv_config):
        super().__init__(csv_config)
        self.filenames = {}
        self._files = {}  # metric name -> (file handle, csv writer)
        self.enabled = csv_config.enabled and get_rank() == 0
        self.output_path = csv_config.output_path or "./csv_monitor"
        self.job_name = csv_config.job_name
        if self.enabled:
            os.makedirs(os.path.join(self.output_path, self.job_name), exist_ok=True)

    def _writer(self, safe_name):
        entry = self._files.get(safe_name)
        if entry is None:
            path = os.path.join(self.output_path, self.job_name, f"{safe_name}.csv")
            new = not os.path.exists(path)
            self.filenames[safe_name] = path
            fh = open(path, "a", newline="")
            w = _csv.writer(fh)
            if new:
                w.writerow(["step", safe_name])
            entry = self._files[safe_name] = (fh, w)
        return entry[1]

    def write_events(self, event_list):
        if not self.enabled:
            return
        for name, value, step in event_list:
            safe = name.replace("/", "_")
            self._writer(safe).writerow([int(step), float(value)])

    def flush(self):
        for fh, _ in self._files.values():
            fh.flush()

    def close(self):
        for fh, _ in self._files.values():
            try:
                fh.close()
            except OSError:
                pass
        self._files.clear()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class MonitorMaster(Monitor):

    def __init__(self, monitor_config):
        super().__init__(monitor_config)
        self.tb_monitor = None
        self.wandb_monitor = None
        self.csv_monitor = None
        self.enabled = monitor_config.enabled
        if get_rank() == 0:
            if monitor_config.tensorboard.enabled:
                self.tb_monitor = TensorBoardMonitor(monitor_config.tensorboard)
            if monitor_config.wandb.enabled:
                self.wandb_monitor = WandbMonitor(monitor_config.wandb)
            if monitor_config.csv_monitor.enabled:
                self.csv_monitor = csvMonitor(monitor_config.csv_monitor)

    def write_events(self, event_list):
        if get_rank() == 0:
            for m in (self.tb_monitor, self.wandb_monitor, self.csv_monitor):
                if m is not None:
                    m.write_events(event_list)

    def flush(self):
        for m in (self.tb_monitor, self.csv_monitor):
            if m is not None and hasattr(m, "flush"):
                m.flush()
