"""Live-health plane: heartbeats, stall watchdog, straggler math, dumps.

PR 1 made this repo observable *post-hoc* (Chrome-trace spans, the metrics
registry); this module is the component that NOTICES while the run is still
alive. The framework the paper targets runs multi-host pods for days, and
the three production failure shapes are all silent: a collective wedges (one
host died, the others sit in the all-reduce forever), one host straggles
(the step time is the max over hosts, and nothing reports WHO), or a
background writer stalls (the async checkpoint thread hangs in storage I/O
and ``destroy()`` joins it forever). The plane here is the TPU-native analog
of the PyTorch-distributed flight recorder + DeepSpeed comms logger +
Orbax-style heartbeating:

  * **heartbeats** — named sources (``engine`` step boundary, ``collective``
    entry/exit via the in-flight registry in ``comm/comm.py``, ``serving``
    prefill/decode, ``saver`` writer, ``prefetch`` worker) either *beat*
    (recurring-activity style: armed until disarmed) or *begin/end*
    (operation style: watched only while an op is in flight);
  * **stall watchdog** — one daemon thread (started only when some deadline
    is configured > 0) that polls heartbeat ages and, past a per-source
    deadline, dumps all-thread stacks + the in-flight collective table + the
    flight-recorder ring to a quarantine file, bumps ``health/stall_total``,
    and invokes an optional user callback. It NEVER kills the process — the
    decision to abort belongs to the operator (or the callback they gave
    us), not to telemetry;
  * **straggler detection** — :meth:`HealthPlane.note_straggler` folds the
    per-rank ``(step, step_wall_ms, input_wait_ms)`` tuples the engine
    piggybacks on its existing step-boundary resilience vote into a
    slowest-rank-vs-median skew, recorded as ``train/straggler_skew_ms``
    (gauge + histogram) and a ``straggler`` trace instant past the
    threshold;
  * **dumps** — :meth:`HealthPlane.dump` is callable on demand, fires on
    watchdog trip, on ``SIGQUIT`` (opt-in), and from ``engine.destroy()``.

Everything defaults OFF and the disabled path is one attribute check with no
locking and no allocations — the same contract as the ``trace`` block.
Import-light by design: stdlib + sibling monitor modules only (``comm`` and
the HTTP exporter are imported lazily).
"""

import os
import sys
import threading
import time
import traceback

from .flight import get_flight_recorder
from .metrics import get_metrics
from .trace import get_tracer

# config-block field -> heartbeat source name
_DEADLINE_FIELDS = {
    "deadline_train_step_s": "engine",
    "deadline_collective_s": "collective",
    "deadline_serving_s": "serving",
    "deadline_saver_s": "saver",
    "deadline_prefetch_s": "prefetch",
}


def _utcnow():
    return time.time()


class HealthPlane:
    """Process-global live-health state (see :func:`get_health`)."""

    def __init__(self):
        self.enabled = False
        self._lock = threading.Lock()
        self._hb = {}  # source -> {"last", "armed", "active", "tripped"}
        self._deadlines = {}  # source -> seconds (0/absent = unwatched)
        self._poll_s = 1.0
        self._watchdog = None
        self._watch_stop = threading.Event()
        self._server = None
        self._snapshot_path = None
        self._snapshot_every = 50
        self._providers = {}  # name -> callable() -> dict (healthz sections)
        self._ready_provider = None  # callable() -> bool (LB readiness)
        # name -> callable() -> [(metric, labels, value)] labelled gauge rows
        # appended to /metrics (the serving gateway feeds queue depth and
        # shed rate through here so admission state is actually scrapeable)
        self._gauge_providers = {}
        # name -> callable() -> dict written as one JSONL line into every
        # forensic dump (the gateway's in-flight request roster rides here)
        self._dump_providers = {}
        self._stall_callback = None
        self._dump_dir = "/tmp/dstpu_health"
        self._dump_n = 0
        self.dump_on_destroy = True
        self.straggler_threshold_ms = 0.0
        self.stall_count = 0
        self.last_dump_path = None
        self._prev_sigquit = None

    # ------------------------------------------------------------------
    # configuration / lifecycle
    # ------------------------------------------------------------------
    def configure(self, config=None, stall_callback=None, **kwargs):
        """Arm the plane. ``config`` is a ``HealthConfig`` block
        (``monitor_config.health``); explicit kwargs win over it.
        ``stall_callback(source, age_s, dump_path)`` runs after a trip dump
        (exceptions are swallowed loudly — telemetry must not kill runs)."""

        def knob(name, default=None):
            if name in kwargs and kwargs[name] is not None:
                return kwargs[name]
            if config is not None:
                return getattr(config, name, default)
            return default

        enabled = knob("enabled")
        if stall_callback is not None:
            self._stall_callback = stall_callback
        if enabled is not None and not enabled:
            self.shutdown()
            return self
        if not enabled:
            return self

        self._dump_dir = str(knob("dump_dir", self._dump_dir) or self._dump_dir)
        self.dump_on_destroy = bool(knob("dump_on_destroy", self.dump_on_destroy))
        self._poll_s = max(0.01, float(knob("watchdog_poll_s", self._poll_s)))
        self.straggler_threshold_ms = float(knob("straggler_threshold_ms",
                                                 self.straggler_threshold_ms))
        deadlines = dict(kwargs.get("deadlines") or {})
        for field, source in _DEADLINE_FIELDS.items():
            v = knob(field)
            if v is not None and source not in deadlines:
                deadlines[source] = float(v)
        self._deadlines.update(deadlines)
        self._snapshot_path = str(knob("snapshot_path", "") or "") or None
        self._snapshot_every = max(1, int(knob("snapshot_every_steps",
                                               self._snapshot_every)))

        # metrics registry carries the plane's counters/gauges and is what
        # /metrics serves — the health block implies it, like `trace` does
        get_metrics().enable()
        get_flight_recorder().configure(enabled=True,
                                        capacity=knob("flight_capacity", None))
        get_tracer().set_mirror(get_flight_recorder())
        self._configure_comm_watch(True)
        # HBM attribution rides every armed health plane: labelled
        # memory/hbm_bytes{section=...} gauges on /metrics and a `memory`
        # section in every forensic dump (engines register their byte
        # providers at construction; with none registered the rows are
        # simply absent)
        from .memory import get_memory, hbm_report

        self.set_gauge_provider("memory", get_memory().gauge_rows)
        self.set_dump_provider("memory", hbm_report)
        self.enabled = True

        if any(v and v > 0 for v in self._deadlines.values()):
            self._start_watchdog()
        port = knob("export_port")
        if port is not None:
            self._start_server(str(knob("export_host", "127.0.0.1")), int(port))
        if bool(knob("sigquit_dump", False)):
            self._install_sigquit()
        return self

    def shutdown(self):
        """Disarm everything this plane started: watchdog thread, HTTP
        server, SIGQUIT trap, tracer mirror, comm registry. Idempotent."""
        self.enabled = False
        if self._watchdog is not None:
            self._watch_stop.set()
            self._watchdog.join(timeout=5.0)
            self._watchdog = None
            self._watch_stop = threading.Event()
        if self._server is not None:
            try:
                self._server.stop()
            finally:
                self._server = None
        self._uninstall_sigquit()
        get_tracer().set_mirror(None)
        get_flight_recorder().configure(enabled=False)
        self._configure_comm_watch(False)
        with self._lock:
            self._hb.clear()
            self._deadlines.clear()
        self._providers.clear()
        self._gauge_providers.clear()
        self._dump_providers.clear()
        self._ready_provider = None
        self._snapshot_path = None
        self._stall_callback = None
        return self

    def _configure_comm_watch(self, on):
        try:
            from ..comm import comm as _comm  # lazy: comm imports monitor.trace

            reg = _comm.inflight_collectives
            if on:
                reg.on_enter = lambda: self.begin("collective")
                reg.on_exit = lambda: self.end("collective")
            else:
                reg.on_enter = reg.on_exit = None
            reg.enabled = bool(on)
        except Exception as e:  # noqa: BLE001
            # swallowed LOUDLY: an operator who set deadline_collective_s
            # must not silently lose the collective watch to a comm-module
            # failure (telemetry still must never kill the run)
            self._log().warning(f"health: collective watch not armed: {e!r}")

    # ------------------------------------------------------------------
    # heartbeats
    # ------------------------------------------------------------------
    def _entry(self, source):
        e = self._hb.get(source)
        if e is None:
            with self._lock:
                e = self._hb.setdefault(
                    source, {"last": time.perf_counter(), "armed": False,
                             "active": 0, "tripped": False})
        return e

    def beat(self, source):
        """Recurring-activity heartbeat: arms the source (watched until
        :meth:`disarm`) and resets its age + any tripped latch."""
        if not self.enabled:
            return
        e = self._entry(source)
        e["last"] = time.perf_counter()
        e["armed"] = True
        e["tripped"] = False

    def touch(self, source):
        """Reset a source's age without changing its armed state (a worker
        loop ticking inside a begin/end window)."""
        if not self.enabled:
            return
        e = self._entry(source)
        e["last"] = time.perf_counter()
        e["tripped"] = False

    def begin(self, source):
        """Operation-style heartbeat: the source is watched while at least
        one :meth:`begin` is unmatched by :meth:`end`."""
        if not self.enabled:
            return
        e = self._entry(source)
        with self._lock:
            e["active"] += 1
        e["last"] = time.perf_counter()
        e["tripped"] = False

    def end(self, source):
        if not self.enabled:
            return
        e = self._entry(source)
        with self._lock:
            e["active"] = max(0, e["active"] - 1)
        e["last"] = time.perf_counter()
        e["tripped"] = False

    def disarm(self, source):
        e = self._hb.get(source)
        if e is not None:
            e["armed"] = False

    def release(self, source):
        """Drop a dynamic (instance-qualified) source entirely — called on
        worker exit so short-lived sources (one prefetch worker per epoch)
        don't accumulate dead rows in /healthz forever."""
        with self._lock:
            self._hb.pop(source, None)

    def _deadline_for(self, source):
        """Deadline lookup with prefix fallback: instance-qualified sources
        (``prefetch:worker-3`` — one entry per worker, so a healthy sibling
        cannot mask a wedged one) inherit their family's deadline."""
        d = self._deadlines.get(source)
        if d is None and ":" in source:
            d = self._deadlines.get(source.split(":", 1)[0])
        return float(d or 0.0)

    def heartbeats(self):
        """Snapshot: source -> {age_s, armed, active, deadline_s, tripped}."""
        now = time.perf_counter()
        out = {}
        with self._lock:
            items = list(self._hb.items())
        for source, e in items:
            out[source] = {"age_s": max(0.0, now - e["last"]),
                           "armed": bool(e["armed"]), "active": int(e["active"]),
                           "deadline_s": self._deadline_for(source),
                           "tripped": bool(e["tripped"])}
        return out

    # ------------------------------------------------------------------
    # stall watchdog
    # ------------------------------------------------------------------
    def _start_watchdog(self):
        if self._watchdog is not None and self._watchdog.is_alive():
            return
        self._watch_stop = threading.Event()
        self._watchdog = threading.Thread(target=self._watch_loop,
                                          name="dstpu-health-watchdog", daemon=True)
        self._watchdog.start()

    @property
    def watchdog_alive(self):
        return self._watchdog is not None and self._watchdog.is_alive()

    def _watch_loop(self):
        # bounded wait on the stop event: the watchdog itself must never be
        # the unwatchable background loop it exists to catch
        while not self._watch_stop.wait(self._poll_s):
            try:
                self.check_once()
            except Exception as e:  # noqa: BLE001 — telemetry never kills runs
                self._log().error(f"health watchdog check failed: {e!r}")

    def check_once(self):
        """One watchdog pass (the thread's body; callable from tests)."""
        now = time.perf_counter()
        with self._lock:
            items = list(self._hb.items())
        for source, e in items:
            deadline = self._deadline_for(source)
            if deadline <= 0 or e["tripped"]:
                continue
            if not (e["armed"] or e["active"] > 0):
                continue
            age = now - e["last"]
            if age > deadline:
                e["tripped"] = True  # one trip per stall; a fresh beat re-arms
                self._on_stall(source, age)

    def _on_stall(self, source, age):
        self.stall_count += 1
        get_metrics().counter("health/stall_total").inc()
        get_metrics().counter(f"health/stall_{source}_total").inc()
        get_flight_recorder().record("health", "stall", source=source,
                                     age_s=round(age, 3))
        tr = get_tracer()
        if tr.enabled:
            tr.instant("stall", tid="engine", source=source, age_s=round(age, 3))
        path = None
        try:
            path = self.dump(f"stall_{source}",
                             extra={"stall": {"source": source, "age_s": age}})
        except Exception as e:  # noqa: BLE001
            self._log().error(f"health: stall dump failed: {e!r}")
        self._log().error(
            f"health watchdog: source '{source}' stalled for {age:.1f}s "
            f"(deadline {self._deadline_for(source)}s); quarantine dump: {path}. "
            f"The process is NOT being killed — inspect the dump / attach a debugger.")
        cb = self._stall_callback
        if cb is not None:
            try:
                cb(source, age, path)
            except Exception as e:  # noqa: BLE001
                self._log().error(f"health: stall callback raised {e!r}")

    # ------------------------------------------------------------------
    # dumps
    # ------------------------------------------------------------------
    def dump(self, reason="manual", extra=None, path=None):
        """Write the forensic bundle — all-thread stacks, the in-flight
        collective table, heartbeat ages, and the flight-recorder ring — as
        ordered JSONL. Returns the file path."""
        import json

        if path is None:
            os.makedirs(self._dump_dir, exist_ok=True)
            self._dump_n += 1
            path = os.path.join(
                self._dump_dir, f"health_{reason}_{os.getpid()}_{self._dump_n:03d}.jsonl")
        names = {t.ident: t.name for t in threading.enumerate()}
        stacks = {}
        for ident, frame in sys._current_frames().items():
            stacks[names.get(ident, f"ident-{ident}")] = [
                ln.rstrip() for ln in traceback.format_stack(frame)]
        with open(path, "w") as f:
            f.write(json.dumps({"kind": "header", "reason": reason,
                                "time_unix": _utcnow(), "pid": os.getpid(),
                                "stall_count": self.stall_count}) + "\n")
            if extra:
                f.write(json.dumps({"kind": "extra", **extra}, default=repr) + "\n")
            f.write(json.dumps({"kind": "threads", "stacks": stacks}) + "\n")
            f.write(json.dumps({"kind": "inflight_collectives",
                                "entries": self.inflight_collectives()},
                               default=repr) + "\n")
            f.write(json.dumps({"kind": "heartbeats",
                                "sources": self.heartbeats()}) + "\n")
            for name, fn in list(self._dump_providers.items()):
                # each provider guarded: a broken one costs its own section,
                # never the bundle (the dump is the last artifact of a stall)
                try:
                    f.write(json.dumps({"kind": name, **fn()}, default=repr) + "\n")
                except Exception as e:  # noqa: BLE001
                    f.write(json.dumps({"kind": name, "error": repr(e)}) + "\n")
            f.write(json.dumps({"kind": "flight_begin",
                                "entries": get_flight_recorder().total_recorded,
                                "capacity": get_flight_recorder().capacity}) + "\n")
            get_flight_recorder().dump_jsonl(f)
        get_metrics().counter("health/dumps_total").inc()
        self.last_dump_path = path
        return path

    def inflight_collectives(self):
        try:
            from ..comm import comm as _comm

            return _comm.inflight_collectives.snapshot()
        except Exception:
            return []

    # ------------------------------------------------------------------
    # straggler detection
    # ------------------------------------------------------------------
    def note_straggler(self, samples):
        """Fold per-rank ``(step, step_wall_ms, input_wait_ms)`` tuples (one
        per host, from the engine's piggybacked resilience vote) into
        slowest-rank skew: ``max(wall) - median(wall)`` in ms. Recorded as
        the ``train/straggler_skew_ms`` gauge + histogram; past
        ``straggler_threshold_ms`` also a ``straggler`` trace instant, a
        flight breadcrumb, and ``health/straggler_total``. Returns the skew."""
        walls = sorted(float(s[1]) for s in samples)
        if not walls:
            return 0.0
        n = len(walls)
        # true median (middle-two average on even n): the upper median would
        # make skew identically 0 on a 2-host pod — the straggler would be
        # its own baseline
        median = walls[n // 2] if n % 2 else 0.5 * (walls[n // 2 - 1] + walls[n // 2])
        skew = walls[-1] - median
        slowest = max(range(len(samples)), key=lambda i: float(samples[i][1]))
        reg = get_metrics()
        reg.gauge("train/straggler_skew_ms").set(skew)
        reg.histogram("train/straggler_skew_ms_hist").observe(skew)
        if self.straggler_threshold_ms > 0 and skew > self.straggler_threshold_ms:
            reg.counter("health/straggler_total").inc()
            get_flight_recorder().record("health", "straggler",
                                         skew_ms=round(skew, 3), slowest_rank=slowest)
            tr = get_tracer()
            if tr.enabled:
                tr.instant("straggler", tid="engine", skew_ms=round(skew, 3),
                           slowest_rank=slowest)
        return skew

    # ------------------------------------------------------------------
    # step-boundary hook + healthz composition
    # ------------------------------------------------------------------
    def step_boundary(self, step):
        """Engine step-boundary tick: heartbeat + breadcrumb + snapshot
        cadence. One call per train_batch while the plane is enabled."""
        if not self.enabled:
            return
        self.beat("engine")
        get_flight_recorder().record("engine", "step", step=int(step))
        if self._snapshot_path is not None and step % self._snapshot_every == 0:
            try:
                self.write_snapshot()
            except Exception as e:  # noqa: BLE001
                self._log().error(f"health: snapshot write failed: {e!r}")

    def set_state_provider(self, name, fn):
        """Register a healthz section: ``fn() -> dict`` under key ``name``
        (the engine registers step/sample counts, the saver its writer
        state). Pass ``None`` to remove."""
        if fn is None:
            self._providers.pop(name, None)
        else:
            self._providers[name] = fn

    def set_ready_provider(self, fn):
        """Register the READINESS oracle: ``fn() -> bool``, distinct from
        liveness. A live process can be not-ready (warmup still compiling,
        admission queues at their shed depth, operator-initiated drain) —
        an LB keying on ``/readyz`` takes it out of rotation without
        killing it. Pass ``None`` to remove (ready defaults back to the
        process being up). The serving gateway registers its composite
        readiness here on start."""
        self._ready_provider = fn

    def clear_ready_provider(self, fn):
        """Remove ``fn`` only if it is still the registered provider — a
        stale owner shutting down must not clobber a newer registration
        (in-process gateway rollover: B starts, then old A stops)."""
        if self._ready_provider is fn:
            self._ready_provider = None

    def clear_state_provider(self, name, fn):
        """Ownership-checked removal of a healthz section (same rollover
        hazard as :meth:`clear_ready_provider`)."""
        if self._providers.get(name) is fn:
            self._providers.pop(name, None)

    def set_gauge_provider(self, name, fn):
        """Register a labelled-gauge source for ``/metrics``: ``fn() ->
        [(metric_name, labels_dict, value), ...]`` rendered through the
        exporter's ``extra_gauges`` path. Pass ``None`` to remove."""
        if fn is None:
            self._gauge_providers.pop(name, None)
        else:
            self._gauge_providers[name] = fn

    def clear_gauge_provider(self, name, fn):
        """Ownership-checked removal (the rollover contract)."""
        if self._gauge_providers.get(name) is fn:
            self._gauge_providers.pop(name, None)

    def gauge_rows(self):
        """All provider rows, each provider guarded — a broken provider
        costs its own rows, never the scrape."""
        rows = []
        for name, fn in list(self._gauge_providers.items()):
            try:
                rows.extend(fn())
            except Exception as e:  # noqa: BLE001 — telemetry never raises
                self._log().error(f"health: gauge provider {name!r} failed: {e!r}")
        return rows

    def set_dump_provider(self, name, fn):
        """Register a forensic-dump section: ``fn() -> dict`` written as one
        ``{"kind": name, ...}`` JSONL line in every :meth:`dump` bundle —
        how a stall dump names the requests on a wedged replica. Pass
        ``None`` to remove."""
        if fn is None:
            self._dump_providers.pop(name, None)
        else:
            self._dump_providers[name] = fn

    def clear_dump_provider(self, name, fn):
        if self._dump_providers.get(name) is fn:
            self._dump_providers.pop(name, None)

    def ready(self):
        """Current readiness verdict: the provider's answer (False on any
        provider exception — a broken oracle must fail closed, not keep a
        sick replica in rotation), True when no provider is registered."""
        fn = self._ready_provider
        if fn is None:
            return True
        try:
            return bool(fn())
        except Exception:  # noqa: BLE001 — fail closed, never raise
            return False

    def healthz_payload(self):
        out = {"time_unix": _utcnow(), "enabled": self.enabled,
               "ready": self.ready(),
               "stalls": self.stall_count,
               "watchdog_alive": self.watchdog_alive,
               "heartbeats": self.heartbeats(),
               "inflight_collectives": self.inflight_collectives()}
        for name, fn in list(self._providers.items()):
            try:
                out[name] = fn()
            except Exception as e:  # noqa: BLE001
                out[name] = {"error": repr(e)}
        return out

    def write_snapshot(self, path=None):
        """Atomically rewrite the scrape-less JSON artifact (healthz payload
        + full metrics snapshot): tmp + fsync + rename, so a reader never
        sees a torn file."""
        import json

        path = path or self._snapshot_path
        if path is None:
            return None
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)
        payload = self.healthz_payload()
        payload["metrics"] = get_metrics().snapshot()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, default=repr)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path

    # ------------------------------------------------------------------
    # exporter / signal plumbing
    # ------------------------------------------------------------------
    def _start_server(self, host, port):
        if self._server is not None:
            return
        from .export import HealthHTTPServer  # lazy: http.server only on demand

        self._server = HealthHTTPServer(host, port, registry=get_metrics(),
                                        healthz_fn=self.healthz_payload,
                                        heartbeats_fn=self.heartbeats,
                                        extra_rows_fn=self.gauge_rows)
        self._server.start()

    @property
    def server(self):
        return self._server

    def _install_sigquit(self):
        import signal

        if threading.current_thread() is not threading.main_thread():
            self._log().warning("health: sigquit_dump needs the main thread; disabled")
            return
        self._prev_sigquit = signal.getsignal(signal.SIGQUIT)

        def _on_sigquit(signum, frame):
            try:
                self.dump("sigquit")
            finally:
                if callable(self._prev_sigquit):
                    self._prev_sigquit(signum, frame)

        signal.signal(signal.SIGQUIT, _on_sigquit)

    def _uninstall_sigquit(self):
        if self._prev_sigquit is None:
            return
        import signal

        try:
            if threading.current_thread() is threading.main_thread():
                signal.signal(signal.SIGQUIT, self._prev_sigquit)
        finally:
            self._prev_sigquit = None

    @staticmethod
    def _log():
        from ..utils.logging import logger  # lazy: keep module import-light

        return logger


_health = HealthPlane()


def get_health() -> HealthPlane:
    return _health


def configure_health(config=None, **kwargs) -> HealthPlane:
    return _health.configure(config=config, **kwargs)
