"""Causal request timelines: the pure assembly/attribution model.

The sensor planes each explain one axis of a request's life — reqtrace
stamps its stage boundaries (PR 7), the goodput ledger books its replica's
seconds (PR 14), the handoff ledger brokers its migration (PR 18), the
decision log records the actuations that mutated its environment (PR 19).
None of them joins. This module is the join: given one request's stamps
(all on the ``time.perf_counter`` clock) plus the overlay events the other
planes observed inside its window, it builds a contiguous segment list
that SUMS to client-observed end-to-end latency by construction, then
re-attributes overlapped time to its causal owner and names the dominant
cause.

Pure functions over plain dicts, import-light (no jax, no serving
imports): the serving-side :class:`~deepspeed_tpu.serving.timeline.
TimelineCollector` feeds it live requests; ``tools/trace_explain.py``
feeds it two captured populations and diffs them. Everything here is
unit-testable without a gateway.

Segment model
-------------

Each stamp opens the segment named for what the request was doing FROM
that instant; the segment closes at the next present stamp (``t_done``
closes the last). Migrated requests interleave both replicas' stamps on
the one shared clock, so the handoff window decomposes into its broker
sub-stages instead of hiding inside decode:

    ingress -> queue -> prefill -> decode -> handoff_export ->
    broker_verify -> handoff_install -> resume_wait -> decode_resumed

Absent stamps simply drop their segment (a shed request is one ``ingress``
segment; a fallback keeps ``decode_fallback`` from the failed broker's
exit). Because segments tile [t_recv, t_done] with no gaps, the
segments-sum acceptance (within ``tolerance`` of e2e, 2 ms floor — PR 7's
budget extended to migrated requests) checks the STAMPS, not the tiling:
a cross-clock or cross-replica skew is exactly what would break it.

Dominant-cause verdict
----------------------

Base attribution maps each segment to one of {queue, prefill, handoff,
decode}; overlays then move overlapped milliseconds to their causal owner:
measured driver stall gaps -> ``stall``, recompile-sentinel events ->
``recompile`` (the containing segment's remainder — a steady-state compile
owns the stage it landed in), and an applied control actuation whose
in-flight roster named this request flips a queue-dominated verdict to
``actuation-induced`` (the controller shrank this request's world; the
queue time is its doing). Attribution is conservative: moves never create
or destroy milliseconds, so the causes always sum to the segments.
"""

from typing import Dict, List, Optional

__all__ = ["CAUSES", "SEGMENT_CAUSE", "STAMP_ORDER", "build_segments",
           "assemble_timeline", "coverage_ok", "stage_totals",
           "explain_delta"]

# the closed verdict taxonomy (ISSUE 20)
CAUSES = ("queue", "prefill", "handoff", "decode", "recompile", "stall",
          "actuation-induced")

# (segment name, stamp that OPENS it), in causal order — the order is the
# tiebreak when two stamps land on the same perf_counter reading
STAMP_ORDER = (
    ("ingress", "t_recv"),                 # parse/validate/route
    ("queue", "t_admitted"),               # class-queue wait
    ("prefill", "t_dequeued"),             # scheduler pickup -> first token
    ("decode", "t_first_token"),           # decode on the source replica
    ("handoff_export", "t_handoff_start"),     # D2H export + manifest
    ("broker_verify", "t_handoff_export"),     # checksum verify window
    ("handoff_install", "t_handoff_verify"),   # dest install + detach
    ("resume_wait", "t_resume_enqueued"),      # dest adoption-queue wait
    ("decode_resumed", "t_resume_submitted"),  # decode on the dest replica
    ("decode_fallback", "t_handoff_done"),     # failed broker -> in place
    ("close", "t_last_token"),             # last token -> terminal
)

SEGMENT_CAUSE = {
    "ingress": "queue", "queue": "queue",
    "prefill": "prefill",
    "handoff_export": "handoff", "broker_verify": "handoff",
    "handoff_install": "handoff", "resume_wait": "handoff",
    "decode": "decode", "decode_resumed": "decode",
    "decode_fallback": "decode", "close": "decode",
}

HANDOFF_SEGMENTS = ("handoff_export", "broker_verify", "handoff_install",
                    "resume_wait")

# actuations that shrink a request's world mid-flight (tightened class
# depth, a drained/restarted replica) — the ones that can OWN queue time
_ACTUATION_ACTIONS = ("tighten", "drain", "restart", "undrain")


def build_segments(stamps: Dict[str, Optional[float]]) -> List[dict]:
    """Contiguous segments tiling [t_recv, t_done] from one request's
    stamps (absent stamps drop their segment; out-of-order stamps — a
    race, never the design — clamp to zero-duration rather than going
    negative). Each segment: ``{"name", "cause", "start_ms", "ms"}`` with
    ``start_ms`` relative to ``t_recv``."""
    t_recv = stamps.get("t_recv")
    t_done = stamps.get("t_done")
    if t_recv is None or t_done is None or t_done < t_recv:
        return []
    bounds = [(float(stamps[key]), i, name)
              for i, (name, key) in enumerate(STAMP_ORDER)
              if stamps.get(key) is not None]
    bounds.sort()  # by time, causal index as tiebreak
    segments = []
    prev_t = t_recv
    for j, (t, _i, name) in enumerate(bounds):
        t = min(max(t, prev_t), t_done)  # clamp monotonic, inside the window
        end = (min(max(bounds[j + 1][0], t), t_done)
               if j + 1 < len(bounds) else t_done)
        segments.append({"name": name,
                         "cause": SEGMENT_CAUSE.get(name, "decode"),
                         "start_ms": round((t - t_recv) * 1e3, 3),
                         "ms": round((end - t) * 1e3, 3)})
        prev_t = t
    return segments


def coverage_ok(sum_ms, e2e_ms, tolerance=0.10) -> bool:
    """The segments-sum acceptance: within ``tolerance`` of client e2e,
    with a 2 ms absolute floor (sub-ms smoke requests must not fail on
    scheduler jitter) — PR 7's budget, extended to migrated requests."""
    if sum_ms is None or e2e_ms is None:
        return False
    return abs(sum_ms - e2e_ms) <= max(tolerance * e2e_ms, 2.0)


def _overlap_ms(seg, t0_ms, t1_ms) -> float:
    a = max(seg["start_ms"], t0_ms)
    b = min(seg["start_ms"] + seg["ms"], t1_ms)
    return max(0.0, b - a)


def assemble_timeline(stamps, record=None, stalls=(), recompiles=(),
                      chaos_fires=(), actuations=(), tolerance=0.10) -> dict:
    """One request's assembled :class:`RequestTimeline` (a plain dict —
    JSON-safe end to end, it goes straight out ``GET /v1/timeline/<rid>``).

    ``stamps``     — perf_counter stage boundaries (see ``STAMP_ORDER``).
    ``record``     — the reqtrace terminal summary (joined by reference).
    ``stalls``     — [(t0, t1)] measured driver chaos-fire gaps on this
                     request's replicas (perf_counter, absolute).
    ``recompiles`` — sentinel events joined to this request
                     (``{"bucket", "t", ...}``, perf_counter ``t``).
    ``chaos_fires``— chaos events joined to this request (annotation only:
                     a stall fire's cost already arrives via ``stalls``).
    ``actuations`` — applied control decisions whose in-flight roster
                     named this request.
    """
    record = record or {}
    segments = build_segments(stamps)
    t_recv = stamps.get("t_recv")
    t_done = stamps.get("t_done")
    e2e_ms = (round((t_done - t_recv) * 1e3, 3)
              if t_recv is not None and t_done is not None else None)
    causes_ms = {}
    for seg in segments:
        causes_ms[seg["cause"]] = causes_ms.get(seg["cause"], 0.0) + seg["ms"]
    # -- overlay 1: measured stall gaps move their overlap to `stall` ------
    n_stalls = 0
    if t_recv is not None:
        for (s0, s1) in stalls:
            t0_ms = (s0 - t_recv) * 1e3
            t1_ms = (s1 - t_recv) * 1e3
            hit = False
            for seg in segments:
                ov = _overlap_ms(seg, t0_ms, t1_ms)
                if ov <= 0.0:
                    continue
                moved = min(ov, seg["ms"] - seg.get("stall_ms", 0.0))
                if moved <= 0.0:
                    continue
                seg["stall_ms"] = round(seg.get("stall_ms", 0.0) + moved, 3)
                causes_ms[seg["cause"]] -= moved
                causes_ms["stall"] = causes_ms.get("stall", 0.0) + moved
                hit = True
            n_stalls += bool(hit)
    # -- overlay 2: a recompile event owns its segment's remainder ---------
    n_recompiles = 0
    if t_recv is not None:
        for ev in recompiles:
            t_ms = (float(ev.get("t", 0.0)) - t_recv) * 1e3
            for seg in segments:
                if seg["start_ms"] <= t_ms <= seg["start_ms"] + seg["ms"] \
                        and not seg.get("recompile"):
                    rem = max(0.0, seg["ms"] - seg.get("stall_ms", 0.0))
                    seg["recompile"] = True
                    causes_ms[seg["cause"]] -= rem
                    causes_ms["recompile"] = causes_ms.get("recompile", 0.0) + rem
                    n_recompiles += 1
                    break
    causes_ms = {k: round(v, 3) for k, v in causes_ms.items() if v > 1e-9}
    sum_ms = round(sum(seg["ms"] for seg in segments), 3) if segments else None
    # -- verdict -----------------------------------------------------------
    dominant_cause = (max(causes_ms, key=causes_ms.get) if causes_ms else None)
    applied = [a for a in actuations
               if a.get("applied") and any(tag in str(a.get("action", ""))
                                           for tag in _ACTUATION_ACTIONS)]
    if dominant_cause == "queue" and applied:
        # the controller shrank this request's world while it waited: the
        # queue time is actuation-induced, not organic back-pressure
        dominant_cause = "actuation-induced"
    by_ms = sorted(segments, key=lambda s: s["ms"], reverse=True)
    handoff_gap_ms = round(sum(s["ms"] for s in segments
                               if s["name"] in HANDOFF_SEGMENTS), 3)
    tl = {
        "request_id": record.get("request_id"),
        "handoff_state": record.get("handoff_state"),
        "migrated": record.get("handoff_state") == "migrated",
        "e2e_ms": e2e_ms,
        "sum_ms": sum_ms,
        "coverage_ok": coverage_ok(sum_ms, e2e_ms, tolerance),
        "segments": segments,
        "causes_ms": causes_ms,
        "critical_path": [{"name": s["name"], "ms": s["ms"]} for s in by_ms[:5]],
        "dominant_segment": by_ms[0]["name"] if by_ms else None,
        "dominant_cause": dominant_cause,
        "stalls": n_stalls,
        "recompiles": n_recompiles,
        "chaos_fires": list(chaos_fires),
        "actuations": [{"policy": a.get("policy"), "action": a.get("action"),
                        "reason": a.get("reason")} for a in applied],
        "record": record,
    }
    if handoff_gap_ms > 0.0 or tl["migrated"]:
        tl["handoff_gap_ms"] = handoff_gap_ms
    return tl


# ---------------------------------------------------------------------------
# population diff: the differential-explain model (tools/trace_explain.py)
# ---------------------------------------------------------------------------
def stage_totals(timeline) -> Dict[str, float]:
    """Per-stage milliseconds of ONE timeline (segments with the same name
    merge — a request can re-enter ``decode`` around a fallback)."""
    out = {}
    for seg in timeline.get("segments", ()):
        out[seg["name"]] = out.get(seg["name"], 0.0) + seg["ms"]
    return out


def _population(timelines):
    stages, causes, e2es = {}, {}, []
    for tl in timelines:
        if tl.get("e2e_ms") is None:
            continue
        e2es.append(tl["e2e_ms"])
        for name, ms in stage_totals(tl).items():
            stages[name] = stages.get(name, 0.0) + ms
        for cause, ms in (tl.get("causes_ms") or {}).items():
            causes[cause] = causes.get(cause, 0.0) + ms
    return len(e2es), sum(e2es), stages, causes


def explain_delta(base_timelines, cur_timelines) -> dict:
    """Diff two timeline populations: the per-stage (and per-cause) delta
    of MEAN contribution per request, and which stage owns the end-to-end
    delta. A stage absent from one population contributes 0 there (a
    migration stage appearing only in the regressed round is itself the
    attribution). ``dominant_stage`` is the largest mover in the delta's
    own direction — a regression names the stage that grew, a speedup the
    stage that shrank."""
    nb, e2e_b, st_b, ca_b = _population(base_timelines)
    nc, e2e_c, st_c, ca_c = _population(cur_timelines)
    out = {"n_base": nb, "n_cur": nc, "delta_e2e_ms": None,
           "by_stage": {}, "by_cause": {}, "dominant_stage": None,
           "dominant_cause": None}
    if nb == 0 or nc == 0:
        return out
    delta_e2e = e2e_c / nc - e2e_b / nb
    out["base_e2e_mean_ms"] = round(e2e_b / nb, 3)
    out["cur_e2e_mean_ms"] = round(e2e_c / nc, 3)
    out["delta_e2e_ms"] = round(delta_e2e, 3)

    def diff(base_map, cur_map):
        rows = {}
        for name in sorted(set(base_map) | set(cur_map)):
            mb = base_map.get(name, 0.0) / nb
            mc = cur_map.get(name, 0.0) / nc
            d = mc - mb
            rows[name] = {"base_mean_ms": round(mb, 3),
                          "cur_mean_ms": round(mc, 3),
                          "delta_ms": round(d, 3),
                          "share": (round(d / delta_e2e, 3)
                                    if abs(delta_e2e) > 1e-9 else None)}
        return rows

    out["by_stage"] = diff(st_b, st_c)
    out["by_cause"] = diff(ca_b, ca_c)
    sign = 1.0 if delta_e2e >= 0 else -1.0
    if out["by_stage"]:
        out["dominant_stage"] = max(
            out["by_stage"], key=lambda n: sign * out["by_stage"][n]["delta_ms"])
    if out["by_cause"]:
        out["dominant_cause"] = max(
            out["by_cause"], key=lambda n: sign * out["by_cause"][n]["delta_ms"])
    return out
