"""Telemetry exporter: Prometheus text rendering + the /metrics and
/healthz endpoints.

The registry (``monitor/metrics.py``) is in-process state; a pod running for
days needs that state visible to an EXTERNAL scraper that keeps working when
the step loop stops making progress — which is precisely when in-band
logging goes quiet. Three surfaces, all stdlib:

  * :func:`render_prometheus` — the registry in Prometheus text exposition
    format 0.0.4: counters (``_total`` suffix convention), gauges, and
    histograms as cumulative ``_bucket{le="..."}`` series + ``_sum`` /
    ``_count``. Metric names are sanitized into the legal charset (slashes
    become underscores, original name preserved in ``# HELP``); label values
    go through :func:`escape_label_value` (backslash, quote, newline).
  * :class:`HealthHTTPServer` — an opt-in daemon-thread
    ``http.server.ThreadingHTTPServer`` serving ``GET /metrics`` (Prometheus
    text, including per-source heartbeat-age gauges), ``GET /healthz``
    (the health plane's JSON payload: last-heartbeat ages, current step,
    in-flight collectives, saver state, and a ``ready`` field distinct
    from liveness) and ``GET /readyz`` (same payload, but the status code
    follows ``ready`` — 200/503 — so a load balancer can drain a replica
    that is alive but not taking traffic). Port 0 binds an ephemeral port
    (``server.port`` reports the real one).
  * snapshot mode lives on the health plane itself
    (``HealthPlane.write_snapshot``): an atomically-rewritten JSON file for
    scrape-less deployments (cron + object store instead of a Prometheus).

Import-light: stdlib + sibling monitor modules only.
"""

import json
import re
import threading

_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_NAME_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")

METRIC_PREFIX = "dstpu_"


def sanitize_metric_name(name, prefix=METRIC_PREFIX):
    """Fold an internal metric name (``train/step_time_ms``) into the legal
    Prometheus charset ``[a-zA-Z_:][a-zA-Z0-9_:]*``, prefixed."""
    out = _NAME_SANITIZE.sub("_", str(name))
    out = prefix + out
    if not _NAME_OK.match(out):  # pathological: name was all-invalid chars
        out = prefix + "metric_" + out[len(prefix):]
    return out


def escape_label_value(value):
    """Prometheus label-value escaping: backslash, double-quote, newline."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def escape_help(text):
    """HELP-line escaping: backslash and newline only (quotes are legal)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v):
    """Float formatting: integers render bare (Prometheus-idiomatic counts),
    +Inf/NaN in the spec spelling."""
    f = float(v)
    if f != f:
        return "NaN"
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(registry, extra_gauges=None):
    """Render a ``MetricsRegistry`` snapshot as Prometheus text format.

    ``extra_gauges``: optional ``[(name, labels_dict, value), ...]`` rows
    appended as gauges (the health exporter feeds heartbeat ages through
    here so the label-escaping path is exercised by real output)."""
    snap = registry.snapshot()
    lines = []

    def header(pname, raw, kind):
        lines.append(f"# HELP {pname} {escape_help(raw)}")
        lines.append(f"# TYPE {pname} {kind}")

    for raw, value in sorted(snap["counters"].items()):
        pname = sanitize_metric_name(raw)
        if not pname.endswith("_total"):
            pname += "_total"
        header(pname, raw, "counter")
        lines.append(f"{pname} {_fmt(value)}")
    for raw, value in sorted(snap["gauges"].items()):
        pname = sanitize_metric_name(raw)
        header(pname, raw, "gauge")
        lines.append(f"{pname} {_fmt(value)}")

    # histograms need the live objects (bucket bounds + counts), not the
    # percentile summary the snapshot carries
    with registry._lock:
        hists = list(registry._histograms.values())
    for h in sorted(hists, key=lambda h: h.name):
        pname = sanitize_metric_name(h.name)
        header(pname, h.name, "histogram")
        with h._lock:
            bucket_counts = list(h.bucket_counts)
            bounds = h.buckets
            count, total = h.count, h.total
        acc = 0
        for bound, c in zip(bounds, bucket_counts[:-1]):
            acc += c
            lines.append(f'{pname}_bucket{{le="{_fmt(bound)}"}} {acc}')
        lines.append(f'{pname}_bucket{{le="+Inf"}} {count}')
        lines.append(f"{pname}_sum {_fmt(total)}")
        lines.append(f"{pname}_count {count}")

    # group rows by metric family first: the text format allows exactly ONE
    # TYPE line per family, and interleaved families (two heartbeat sources
    # alternating age/armed rows) would otherwise emit duplicates that a
    # real Prometheus scraper rejects wholesale
    by_family = {}
    for name, labels, value in (extra_gauges or ()):
        by_family.setdefault(name, []).append((labels, value))
    for name, rows in by_family.items():
        pname = sanitize_metric_name(name)
        header(pname, name, "gauge")
        for labels, value in rows:
            body = ",".join(f'{k}="{escape_label_value(v)}"'
                            for k, v in sorted(labels.items()))
            lines.append(f"{pname}{{{body}}} {_fmt(value)}" if body
                         else f"{pname} {_fmt(value)}")
    return "\n".join(lines) + "\n"


def heartbeat_gauge_rows(heartbeats):
    """Heartbeat snapshot -> ``extra_gauges`` rows for the /metrics text."""
    rows = []
    for source, hb in sorted(heartbeats.items()):
        rows.append(("health/heartbeat_age_seconds", {"source": source},
                     hb["age_s"]))
        rows.append(("health/heartbeat_armed", {"source": source},
                     1.0 if (hb["armed"] or hb["active"] > 0) else 0.0))
    return rows


# ---------------------------------------------------------------------------
# HTTP endpoints
# ---------------------------------------------------------------------------
class HealthHTTPServer:
    """Tiny stdlib exporter: ``/metrics`` (Prometheus text 0.0.4) and
    ``/healthz`` (JSON). Daemon serving thread; ``stop()`` shuts it down."""

    def __init__(self, host, port, registry, healthz_fn, heartbeats_fn=None,
                 extra_rows_fn=None):
        self.registry = registry
        self.healthz_fn = healthz_fn
        self.heartbeats_fn = heartbeats_fn
        # additional labelled gauge rows appended per scrape — the health
        # plane routes its registered gauge providers (serving admission
        # queue depth / shed rate) through here
        self.extra_rows_fn = extra_rows_fn
        self._host, self._want_port = host, int(port)
        self._httpd = None
        self._thread = None

    def start(self):
        import http.server

        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # no stderr chatter per scrape
                pass

            def _send(self, code, ctype, body):
                data = body.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        extra = list(heartbeat_gauge_rows(outer.heartbeats_fn())
                                     if outer.heartbeats_fn else ())
                        if outer.extra_rows_fn is not None:
                            extra.extend(outer.extra_rows_fn())
                        self._send(200, "text/plain; version=0.0.4; charset=utf-8",
                                   render_prometheus(outer.registry,
                                                     extra_gauges=extra or None))
                    elif path == "/healthz":
                        self._send(200, "application/json",
                                   json.dumps(outer.healthz_fn(), default=repr))
                    elif path == "/readyz":
                        # readiness ≠ liveness: the payload's `ready` field
                        # (the health plane's ready provider — warmup done,
                        # admission queues below shed depth, not draining)
                        # drives the STATUS code, so an LB health check can
                        # pull a replica from rotation without killing it
                        payload = outer.healthz_fn()
                        code = 200 if payload.get("ready", True) else 503
                        self._send(code, "application/json",
                                   json.dumps(payload, default=repr))
                    else:
                        self._send(404, "text/plain; charset=utf-8",
                                   "not found: /metrics, /healthz or /readyz\n")
                except (BrokenPipeError, ConnectionResetError):
                    pass  # scraper went away mid-response

        self._httpd = http.server.ThreadingHTTPServer((self._host, self._want_port),
                                                      Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="dstpu-health-http", daemon=True)
        self._thread.start()
        return self

    @property
    def port(self):
        """The bound port (differs from the requested one when it was 0)."""
        return self._httpd.server_address[1] if self._httpd else None

    @property
    def url(self):
        return f"http://{self._host}:{self.port}" if self._httpd else None

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
