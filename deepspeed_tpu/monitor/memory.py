"""Engine-wide HBM capacity attribution.

The serving and training engines each know what THEY put on the device
(params, optimizer/ZeRO shards, the KV block pool, a speculative draft
engine), but nobody could answer "what fraction of HBM is params vs KV pool
vs cold cache" without reading five subsystems — the question every
capacity decision (ROADMAP items 1/2/4: KV spill pool sizing, disaggregated
pools, multi-tenant packing) starts from. This module is the one ledger:

  * components register byte providers at construction
    (:meth:`MemoryAttribution.register`: ``fn(owner) -> {section: bytes}``,
    owner held by WEAK reference so a discarded engine never leaks through
    telemetry — dead providers are pruned at the next report);
  * :func:`hbm_report` folds every live provider into a section
    decomposition (``params`` / ``optimizer`` / ``kv_block_pool`` /
    ``spec_draft_engine`` / ...), reconciled against
    ``jax.local_devices()`` memory stats where the backend exposes them
    (TPU; CPU reports null device stats, never a made-up number) — the
    remainder shows up as ``unattributed_bytes`` ("other": XLA temp
    buffers, compiled executables, anything not yet registered;

and three export paths, all existing PR 1/5 surfaces: the health exporter
renders :meth:`MemoryAttribution.gauge_rows` as labelled
``memory/hbm_bytes{section=...}`` gauges on ``/metrics``, every forensic
stall dump gains a ``memory`` section (registered by
``HealthPlane.configure``), and ``bench.py`` prints the report as the final
JSON's ``memory{...}`` block.

Import-light (stdlib only at module level; jax imported lazily per report).
"""

import threading
import weakref


def tree_device_bytes(tree) -> int:
    """Bytes the array leaves of a pytree occupy on THIS HOST's devices.

    Sharded jax arrays are summed over their addressable shards — the same
    denominator ``device_memory_stats`` reports — so a ZeRO-3 param tree on
    an N-host pod attributes one host's shard bytes, not N× the global
    logical size (and a replicated array counts once per local device
    holding a copy, exactly as the backend's ``bytes_in_use`` does). Host
    numpy arrays and anything else exposing ``nbytes`` fall back to their
    full size; non-array leaves count zero."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is not None:
            try:
                total += sum(int(s.data.nbytes) for s in shards)
                continue
            except Exception:
                pass
        total += int(getattr(leaf, "nbytes", 0) or 0)
    return total


def device_memory_stats():
    """Per-host device memory stats summed over ``jax.local_devices()``:
    ``{bytes_in_use, bytes_limit, peak_bytes_in_use, n_devices}`` — or None
    when the backend exposes none (CPU), so callers report null rather than
    inventing a denominator."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return None
    agg = {"bytes_in_use": 0, "bytes_limit": 0, "peak_bytes_in_use": 0,
           "n_devices": 0}
    seen = False
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats or "bytes_in_use" not in stats:
            continue
        seen = True
        agg["n_devices"] += 1
        agg["bytes_in_use"] += int(stats.get("bytes_in_use", 0))
        agg["bytes_limit"] += int(stats.get("bytes_limit", 0))
        agg["peak_bytes_in_use"] += int(stats.get("peak_bytes_in_use", 0))
    return agg if seen else None


class MemoryAttribution:
    """Process-global provider registry (see :func:`get_memory`)."""

    def __init__(self):
        self._lock = threading.Lock()
        # name -> (weakref(owner), fn); fn(owner) -> {section: bytes}
        self._providers = {}

    def register(self, name, fn, owner) -> None:
        """Register ``fn(owner) -> {section: bytes}`` under a unique
        ``name``. ``owner`` is weakly referenced: when it is collected the
        provider self-prunes — engines without a destroy() (the serving
        engine) can register fire-and-forget."""
        with self._lock:
            self._providers[name] = (weakref.ref(owner), fn)

    def unregister(self, name) -> None:
        with self._lock:
            self._providers.pop(name, None)

    def sections(self):
        """Live section decomposition: bytes summed per section across every
        provider whose owner is still alive (dead ones pruned here)."""
        with self._lock:
            items = list(self._providers.items())
        out = {}
        dead = []
        for name, (ref, fn) in items:
            owner = ref()
            if owner is None:
                dead.append(name)
                continue
            try:
                for section, nbytes in fn(owner).items():
                    out[section] = out.get(section, 0) + int(nbytes)
            except Exception:  # a broken provider costs its rows, never the report
                continue
        if dead:
            with self._lock:
                for name in dead:
                    self._providers.pop(name, None)
        return out

    def report(self) -> dict:
        """The full attribution: per-section bytes, the accounted total, the
        backend's own in-use/limit numbers where available, and the
        unattributed remainder (XLA temporaries, executables, anything not
        registered — the honest "other")."""
        sections = self.sections()
        accounted = sum(sections.values())
        device = device_memory_stats()
        out = {"sections": sections, "accounted_bytes": accounted,
               "device": device, "unattributed_bytes": None}
        if device is not None:
            out["unattributed_bytes"] = max(0, device["bytes_in_use"] - accounted)
        return out

    def gauge_rows(self):
        """Labelled gauges for the health exporter's ``/metrics``."""
        rows = [("memory/hbm_bytes", {"section": s}, v)
                for s, v in sorted(self.sections().items())]
        device = device_memory_stats()
        if device is not None:
            rows.append(("memory/device_bytes_in_use", {}, device["bytes_in_use"]))
            rows.append(("memory/device_bytes_limit", {}, device["bytes_limit"]))
        return rows


_memory = MemoryAttribution()


def get_memory() -> MemoryAttribution:
    return _memory


def hbm_report() -> dict:
    """Module-level convenience: the current process-wide HBM attribution
    (what ``bench.py`` prints and every forensic dump carries)."""
    return _memory.report()
