"""Top-level model namespace (reference ``deepspeed/model_implementations``:
DeepSpeedTransformer containers). The TPU-native model zoo lives in
``deepspeed_tpu.models``; this module re-exports it under the reference
package name."""

from ..models import *  # noqa: F401,F403
from ..models.transformer import TransformerConfig, TransformerLM  # noqa: F401
