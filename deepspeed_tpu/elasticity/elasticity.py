"""Elastic batch-size math (reference ``deepspeed/elasticity/elasticity.py``:
``_get_compatible_gpus_v01:83`` / ``_get_compatible_gpus_v02:126`` /
``compute_elastic_config:233``).

Given a max global batch, the set of allowed micro-batch sizes and a chip
range, enumerate the (global batch, chip-count) combinations that keep
batch = micro * gas * chips exact — so a job restarted on a different slice
size picks a new valid batch without changing the effective math. v0.2 adds
the model-parallel-aware variant: chips are consumed in groups of
mp_size * pp_size (the TPU analog: devices per model replica)."""

from typing import List, Optional, Tuple

from ..utils.logging import logger

LATEST_ELASTICITY_VERSION = 0.2
MINIMUM_DEEPSPEED_VERSION = "0.3.8"


class ElasticityError(Exception):
    """Base error (reference same name)."""


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


def get_valid_gpus(batch_size: int, micro_batches: List[int], min_valid_gpus: int,
                   max_valid_gpus: int) -> List[int]:
    """All chip counts that evenly tile batch_size with some micro batch
    (reference ``_get_valid_gpus``)."""
    valid = set()
    for mb in micro_batches:
        if batch_size % mb != 0:
            continue
        max_gpus = batch_size // mb
        for i in range(1, max_gpus + 1):
            if max_gpus % i == 0 and min_valid_gpus <= i <= max_valid_gpus:
                valid.add(i)
    return sorted(valid)


def get_compatible_gpus_v01(micro_batches: List[int],
                            max_acceptable_batch_size: int,
                            min_gpus: int = 1,
                            max_gpus: int = 10000,
                            prefer_larger: bool = True) -> Tuple[int, List[int]]:
    """v0.1 (reference :83): pick the batch size <= max with the most valid
    chip counts (ties broken toward larger/smaller batch per prefer_larger)."""
    if not micro_batches:
        raise ElasticityConfigError("micro_batches must be non-empty")
    # candidates are micro * 2^k ladders (reference :98-104) — power-of-two
    # scaling keeps the valid chip sets aligned with slice sizes
    candidates = set()
    for mb in micro_batches:
        b = mb
        while b <= max_acceptable_batch_size:
            candidates.add(b)
            b *= 2
    candidate_batch_sizes = sorted(candidates)
    best_batch, best_gpus = None, []
    for batch in (reversed(candidate_batch_sizes) if prefer_larger else candidate_batch_sizes):
        gpus = get_valid_gpus(batch, micro_batches, min_gpus, max_gpus)
        if len(gpus) > len(best_gpus):
            best_batch, best_gpus = batch, gpus
    if best_batch is None:
        raise ElasticityConfigError(
            f"no valid batch <= {max_acceptable_batch_size} for micro batches {micro_batches}")
    return best_batch, best_gpus


def get_compatible_gpus_v02(micro_batches: List[int],
                            max_acceptable_batch_size: int,
                            current_num_gpus: int,
                            min_gpus: int = 1,
                            max_gpus: int = 10000,
                            prefer_larger: bool = True,
                            num_gpus_per_node: int = 1,
                            model_parallel_size: int = 1) -> Tuple[int, List[int], int]:
    """v0.2 (reference :126): chips are consumed in model-replica groups of
    ``model_parallel_size``; returns (batch, valid dp counts, micro batch)."""
    if current_num_gpus % model_parallel_size != 0:
        raise ElasticityIncompatibleWorldSize(
            f"world size {current_num_gpus} not divisible by model parallel size {model_parallel_size}")
    dp_size = current_num_gpus // model_parallel_size
    batch, valid_dp = get_compatible_gpus_v01(micro_batches, max_acceptable_batch_size,
                                              max(1, min_gpus // model_parallel_size),
                                              max(1, max_gpus // model_parallel_size), prefer_larger)
    if dp_size not in valid_dp:
        raise ElasticityIncompatibleWorldSize(
            f"dp size {dp_size} (world {current_num_gpus} / mp {model_parallel_size}) not in valid set {valid_dp}")
    mbs = _micro_batch_for(batch, dp_size, micro_batches, prefer_larger)
    return batch, valid_dp, mbs


def _micro_batch_for(batch, dp_size, micro_batches, prefer_larger):
    options = [mb for mb in micro_batches if batch % (mb * dp_size) == 0]
    if not options:
        raise ElasticityIncompatibleWorldSize(f"no micro batch fits batch={batch} dp={dp_size}")
    return max(options) if prefer_larger else min(options)


def elasticity_enabled(ds_config: dict) -> bool:
    return bool(ds_config.get("elasticity", {}).get("enabled", False))


def ensure_immutable_elastic_config(runtime_elastic_config_dict, requested):
    """Reference guard: the scheduler-time elastic config must match the
    runtime one, else restarts silently change batch math."""
    if runtime_elastic_config_dict != requested:
        raise ElasticityConfigError("elastic config changed between scheduling and runtime")


def compute_elastic_config(ds_config: dict, target_deepspeed_version: str = "0", world_size: int = 0,
                           return_microbatch: bool = False):
    """Reference ``compute_elastic_config:233``: resolve the final
    (batch, valid chip counts[, micro batch]) from a user config dict."""
    ec = dict(ds_config.get("elasticity", {}))
    if not ec.get("enabled", False):
        raise ElasticityConfigError("elasticity not enabled in config")
    version = float(ec.get("version", LATEST_ELASTICITY_VERSION))
    micro_batches = list(ec.get("micro_batch_sizes", [2, 4, 6]))
    max_batch = int(ec.get("max_train_batch_size", 2000))
    min_gpus, max_gpus = int(ec.get("min_gpus", 1)), int(ec.get("max_gpus", 10000))
    prefer_larger = bool(ec.get("prefer_larger_batch_size", True))

    if version >= 0.2 and world_size > 0:
        mp = int(ec.get("model_parallel_size", 1)) * int(ec.get("pipe_parallel_size", 1))
        batch, valid_dp, mbs = get_compatible_gpus_v02(micro_batches, max_batch, world_size,
                                                       min_gpus, max_gpus, prefer_larger,
                                                       model_parallel_size=mp)
        logger.info(f"elasticity v{version}: batch={batch} valid_dp={valid_dp} micro={mbs}")
        return (batch, valid_dp, mbs) if return_microbatch else (batch, valid_dp)

    batch, valid = get_compatible_gpus_v01(micro_batches, max_batch, min_gpus, max_gpus, prefer_larger)
    if world_size > 0 and world_size not in valid:
        raise ElasticityIncompatibleWorldSize(f"world size {world_size} not in valid set {valid}")
    if return_microbatch:
        mbs = _micro_batch_for(batch, world_size or valid[-1], micro_batches, prefer_larger)
        return batch, valid, mbs
    return batch, valid
