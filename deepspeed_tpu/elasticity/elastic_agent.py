"""Elastic training agent.

Reference ``elasticity/elastic_agent.py:28`` ``DSElasticAgent`` extends
torch-elastic's ``LocalElasticAgent``: on worker failure within
``max_restarts`` it re-rendezvous and restarts workers, letting the batch
math re-resolve for the surviving world size.

TPU analog: slice membership is fixed per jax.distributed init, so elasticity
means *restart the step loop on a re-initialized mesh* — the agent wraps the
user's train function, detects device/process loss (a retryable exception
from a dead ICI peer), recomputes the elastic batch config for the new chip
count, and re-invokes with checkpoint (or warm host-snapshot) resume. The
checkpoint-based resume is exactly the recovery path the reference uses,
minus torch-elastic's rendezvous store (jax.distributed re-init plays that
role)."""

import time
from typing import Callable, Optional, Tuple

from .elasticity import compute_elastic_config, ElasticityIncompatibleWorldSize
from ..utils.logging import logger


def default_retryable_exceptions() -> Tuple[type, ...]:
    """Worker-loss exception types worth a restart. XLA/jax surface peer
    loss inconsistently across versions and transports — a dead ICI peer
    can come back as a plain ``RuntimeError``, a ``jaxlib``
    ``XlaRuntimeError``, or a ``jax.errors.JaxRuntimeError`` — so the set
    is built from whatever this jax exposes (getattr, not try/except: the
    absence of a symbol is expected version skew, not a failure)."""
    import jax

    retryable = [RuntimeError]
    errors_mod = getattr(jax, "errors", None)
    for name in ("JaxRuntimeError", "XlaRuntimeError"):
        exc = getattr(errors_mod, name, None)
        if isinstance(exc, type) and issubclass(exc, BaseException) \
                and not issubclass(exc, RuntimeError):
            retryable.append(exc)
    return tuple(retryable)


class ElasticAgent:

    def __init__(self, ds_config: dict, max_restarts: int = 3, restart_delay_s: float = 5.0,
                 backoff_factor: float = 1.0, retryable_exceptions=None,
                 restart_window_s: float = 0.0):
        self.ds_config = ds_config
        self.max_restarts = max_restarts
        self.restart_delay_s = restart_delay_s
        # exponential restart backoff (delay * factor**(restart-1)): a
        # re-crashing worker on a sick host shouldn't hot-loop the fleet
        self.backoff_factor = backoff_factor
        # which exception types count as recoverable worker loss (anything
        # else — a real bug, an OOM loop — propagates immediately)
        self.retryable_exceptions = (tuple(retryable_exceptions)
                                     if retryable_exceptions is not None
                                     else default_retryable_exceptions())
        # restart-budget decay: an attempt that stayed healthy for at least
        # this long before failing RESETS restart_count — a transient blip
        # every few hours must not consume the lifetime budget a crash loop
        # is meant to exhaust (torch-elastic's rolling-window semantics).
        # 0 = never decay (the old behavior).
        self.restart_window_s = float(restart_window_s)
        self.restart_count = 0

    def resolve_batch_config(self, world_size: int):
        """New (train_batch, micro_batch) for the current chip count. dp is
        the number of model replicas (world / mp / pp) — the v0.2 micro batch
        is chosen for that dp, so gas must use it too."""
        batch, _valid, micro = compute_elastic_config(self.ds_config, world_size=world_size,
                                                      return_microbatch=True)
        ec = self.ds_config.get("elasticity", {})
        mp = int(ec.get("model_parallel_size", 1)) * int(ec.get("pipe_parallel_size", 1))
        dp = max(1, world_size // mp)
        gas = batch // (micro * dp)
        assert micro * gas * dp == batch, \
            f"inconsistent elastic config: {micro}*{gas}*{dp} != {batch}"
        return {"train_batch_size": batch, "train_micro_batch_size_per_gpu": micro,
                "gradient_accumulation_steps": gas}

    def run(self, train_fn: Callable[[dict], None], world_size_fn: Optional[Callable[[], int]] = None):
        """Invoke ``train_fn(batch_config)`` with elastic restarts (reference
        ``_invoke_run:118`` polling loop collapsed to exception-driven
        restarts — peer loss surfaces as one of ``retryable_exceptions``)."""
        if world_size_fn is None:
            import jax

            world_size_fn = lambda: len(jax.devices())
        while True:
            world = world_size_fn()
            try:
                cfg = self.resolve_batch_config(world)
            except ElasticityIncompatibleWorldSize as e:
                raise RuntimeError(f"no elastic config for world size {world}: {e}")
            logger.info(f"elastic agent: starting with world={world} config={cfg} "
                        f"(restart {self.restart_count}/{self.max_restarts})")
            t_start = time.monotonic()
            try:
                return train_fn(cfg)
            except self.retryable_exceptions as e:
                healthy_s = time.monotonic() - t_start
                if (self.restart_window_s > 0 and self.restart_count > 0
                        and healthy_s >= self.restart_window_s):
                    logger.info(f"elastic agent: attempt ran healthy for {healthy_s:.1f}s "
                                f"(>= window {self.restart_window_s}s); restart budget reset")
                    self.restart_count = 0
                self.restart_count += 1
                if self.restart_count > self.max_restarts:
                    logger.error(f"elastic agent: exceeded {self.max_restarts} restarts; giving up")
                    raise
                delay = self.restart_delay_s * self.backoff_factor**(self.restart_count - 1)
                logger.warning(f"elastic agent: worker failure ({e}); re-resolving in {delay:.1f}s")
                time.sleep(delay)
